"""The Accelerator façade.

Parity: reference accelerator.py (class Accelerator:162) — prepare (1173),
backward (2007), accumulate (1017), no_sync (902), clip_grad_norm_ (2131),
gather/gather_for_metrics (2209/2241), save_state/load_state (2729/2894),
autocast (3189), unwrap_model (2374), save_model (2590), set_trigger/
check_trigger (2037/2063), free_memory (3027).

The training-loop inversion (SURVEY §7 hard part #1): the reference lets the
user's eager loop drive torch autograd; XLA wants the step as a traced
function. The seam chosen here keeps the loop shape but makes the *loss a
function*:

    model, optimizer, loader, scheduler = accelerator.prepare(...)
    for batch in loader:
        with accelerator.accumulate(model):
            loss = accelerator.backward(loss_fn, batch)   # jit value_and_grad
            accelerator.clip_grad_norm_(model, 1.0)
            optimizer.step()                              # jit optax update
            scheduler.step()
            optimizer.zero_grad()

Each piece is a cached jit-compiled function over sharded global arrays, so
the eager Python between them costs microseconds. For peak throughput,
``accelerator.compiled_step(loss_fn)`` fuses grad+clip+update (+ a lax.scan
microbatch loop for accumulation) into one XLA program.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from functools import partial
from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .data_loader import BaseDataLoader, prepare_data_loader, skip_first_batches
from .logging import get_logger
from .optimizer import AcceleratedOptimizer, clip_by_global_norm, clip_by_value, scaled_optimizer_update
from .ops import operations as ops
from .parallel.sharding import PartitionRules, infer_shardings, replicated, shard_tree
from .resilience import Resilience, ResilienceConfig
from .resilience.guards import next_guard_state, zero_guard_state
from .scheduler import AcceleratedScheduler
from .state import AcceleratorState, GradientState, PartialState
from .state import distributed_is_initialized as _distributed_is_initialized
from .telemetry import Telemetry, TelemetryConfig
from .utils.dataclasses import (
    CompilationConfig,
    FP8RecipeKwargs,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    InitProcessGroupKwargs,
    KwargsHandler,
    LossScaleKwargs,
    MixedPrecisionPolicy,
    ModelParallelPlugin,
    ParallelismConfig,
    PrecisionType,
    ProjectConfiguration,
)
from .utils.environment import parse_int_from_env
from .utils.random import next_rng_key, set_seed

logger = get_logger(__name__)

# distinguishes "argument omitted" from an explicit None (= clear the setting)
_UNSET = object()


class ParamBox:
    """Shared mutable holder so model and optimizer see one params tree."""

    def __init__(self, value: Any):
        self.value = value


class ProfileCapture(str):
    """What ``Accelerator.profile()`` yields: the log dir (it IS a str, so
    existing ``os.walk(capture)`` call sites keep working) plus per-device
    memory snapshots bracketing the trace — the cheapest answer to "did the
    profiled region leak/spike HBM?" without opening the trace."""

    memory_before: list = []
    memory_after: list = []


class PreparedModel:
    """A model bound to sharded parameters.

    Callable like the original module; parameters live as global sharded
    arrays in a box shared with the optimizer. ``unwrap_model`` returns the
    original module; ``model.params`` is the live tree.
    """

    def __init__(self, module: Any, box: ParamBox, params_shardings: Any, policy: MixedPrecisionPolicy):
        self.module = module
        self.box = box
        self.params_shardings = params_shardings
        self.policy = policy
        self._jit_apply = None

    @property
    def params(self) -> Any:
        return self.box.value

    @params.setter
    def params(self, value: Any) -> None:
        self.box.value = value

    @property
    def apply(self) -> Callable:
        if hasattr(self.module, "apply"):
            return self.module.apply
        return self.module  # bare apply function

    def __call__(self, *args, **kwargs):
        if self._jit_apply is None:
            policy = self.policy
            apply = self.apply

            def fwd(params, *a, **kw):
                params = cast_floating(params, policy.compute_dtype)
                out = apply(params, *a, **kw)
                return cast_floating(out, policy.output_dtype)

            self._jit_apply = jax.jit(fwd)
        return self._jit_apply(self.box.value, *args, **kwargs)

    def eval_shape(self, *args, **kwargs):
        return jax.eval_shape(self.apply, self.box.value, *args, **kwargs)


def cast_floating(tree: Any, dtype) -> Any:
    def _cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(_cast, tree)


class Accelerator:
    def __init__(
        self,
        device_placement: bool = True,
        split_batches: bool = False,
        mixed_precision: Optional[str] = None,
        gradient_accumulation_steps: Optional[int] = None,
        parallelism: Optional[ParallelismConfig] = None,
        fsdp_plugin: Optional[FullyShardedDataParallelPlugin] = None,
        model_parallel_plugin: Optional[ModelParallelPlugin] = None,
        compilation_config: Optional[CompilationConfig] = None,
        gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None,
        project_config: Optional[ProjectConfiguration] = None,
        project_dir: Optional[str] = None,
        even_batches: bool = True,
        dispatch_batches: Optional[bool] = None,
        step_scheduler_with_optimizer: bool = True,
        log_with: Optional[list] = None,
        kwargs_handlers: Optional[list[KwargsHandler]] = None,
        telemetry_config: Optional[TelemetryConfig] = None,
        resilience_config: Optional[ResilienceConfig] = None,
    ):
        # -- plugin / parallelism resolution (reference accelerator.py:285-335)
        if model_parallel_plugin is not None and parallelism is None:
            parallelism = ParallelismConfig(
                fsdp=(fsdp_plugin.fsdp_size or 1) if fsdp_plugin else 1,
                tensor=model_parallel_plugin.tensor_size,
                sequence=model_parallel_plugin.sequence_size,
                pipeline=model_parallel_plugin.pipeline_size,
                expert=model_parallel_plugin.expert_size,
            )
        elif fsdp_plugin is not None and parallelism is None:
            n = jax.device_count()
            size = fsdp_plugin.fsdp_size or n
            parallelism = ParallelismConfig(fsdp=size)

        self.project_configuration = project_config or ProjectConfiguration(project_dir=project_dir)
        if project_dir is not None and self.project_configuration.project_dir is None:
            self.project_configuration.set_directories(project_dir)

        # -- kwargs handlers (reference accelerator.py:338-372)
        self.loss_scale_kwargs: Optional[LossScaleKwargs] = None
        self.fp8_recipe: Optional[FP8RecipeKwargs] = None
        for handler in kwargs_handlers or []:
            if isinstance(handler, LossScaleKwargs):
                self.loss_scale_kwargs = handler
            elif isinstance(handler, FP8RecipeKwargs):
                self.fp8_recipe = handler
            elif isinstance(handler, InitProcessGroupKwargs):
                # consumed by PartialState._bootstrap_distributed (env is the
                # transport; also covers DistributedInitKwargs). The rendezvous
                # runs ONCE — passing this after it is a silent no-op, so fail.
                # PartialState's bootstrap is also once-only (sticky _ready
                # flag): if ANY PartialState already exists, coordinator fields
                # set here would never be consumed and the job would silently
                # run single-process. Timeout-only handlers are still fine
                # late — they only matter if a rendezvous happens afterwards.
                carries_coordinator = any(
                    getattr(handler, f, None) is not None
                    for f in ("coordinator_address", "num_processes", "process_id")
                )
                if _distributed_is_initialized() or (
                    carries_coordinator and PartialState._shared_state
                ):
                    raise ValueError(
                        "InitProcessGroupKwargs/DistributedInitKwargs with "
                        "coordinator fields must be passed before any "
                        "PartialState/Accelerator is created — the distributed "
                        "bootstrap runs once, so these fields would be "
                        "silently ignored now. Construct the Accelerator with "
                        "these kwargs first (or export ACCELERATE_COORDINATOR_"
                        "ADDRESS / ACCELERATE_NUM_PROCESSES / "
                        "ACCELERATE_PROCESS_ID before the process starts)."
                    )
                if getattr(handler, "coordinator_address", None):
                    os.environ["ACCELERATE_COORDINATOR_ADDRESS"] = handler.coordinator_address
                if getattr(handler, "num_processes", None) is not None:
                    os.environ["ACCELERATE_NUM_PROCESSES"] = str(handler.num_processes)
                if getattr(handler, "process_id", None) is not None:
                    os.environ["ACCELERATE_PROCESS_ID"] = str(handler.process_id)
                if handler.timeout is not None:
                    os.environ["ACCELERATE_INIT_TIMEOUT"] = str(int(handler.timeout.total_seconds()))

        self.state = AcceleratorState(mixed_precision=mixed_precision, parallelism=parallelism)
        self.fsdp_plugin = fsdp_plugin
        # -- ZeRO update sharding (parallel/zero.py): resolve the mesh intent
        # once. zero_stage=None auto-enables on eligible meshes (data-parallel
        # axes present, model axes trivial); 0 forces the legacy replicated
        # update; >=1 demands sharding and fails loudly on an ineligible mesh.
        from .parallel.zero import zero_ineligible_reason

        requested = getattr(self.state.parallelism, "zero_stage", None)
        ineligible_reason = zero_ineligible_reason(self.mesh, fsdp_plugin)
        eligible = ineligible_reason is None
        if requested is not None and requested >= 1 and not eligible:
            raise ValueError(
                f"zero_stage={requested} requested but the update cannot be "
                f"sharded on this configuration: {ineligible_reason}. Drop "
                "zero_stage or fix the mesh."
            )
        self._zero_update_sharding = eligible and requested != 0
        # cpu_offload used to fall back to the legacy replicated path
        # SILENTLY (ROADMAP item): the mesh is ZeRO-eligible, the user asked
        # for nothing unusual, and the run quietly pays N× the optimizer
        # state. Name the fallback where someone will look — the stage<3
        # case stays quiet because that replicated-params contract is the
        # explicit, documented meaning of the flag.
        self._zero_fallback_reason = None
        if (
            requested != 0
            and not eligible
            and fsdp_plugin is not None
            and fsdp_plugin.cpu_offload
            and fsdp_plugin.stage >= 3
            and zero_ineligible_reason(self.mesh, None) is None
        ):
            self._zero_fallback_reason = ineligible_reason
            logger.warning(
                "ZeRO sharded update DISABLED — falling back to the legacy "
                f"replicated update: {ineligible_reason}. Optimizer state "
                "will be replicated on every chip (cpu_offload still moves "
                "it to host RAM between steps); drop cpu_offload to get the "
                "1/N sharded state, or pass ParallelismConfig(zero_stage=0) "
                "to silence this."
            )
        self.model_parallel_plugin = model_parallel_plugin
        self.compilation_config = compilation_config or CompilationConfig()
        if (
            fsdp_plugin is not None
            and fsdp_plugin.activation_checkpointing
            and self.compilation_config.remat_policy is None
        ):
            # FSDP plugin activation checkpointing ≙ full recompute inside each
            # layer (Megatron recompute_activations semantics; reference
            # accelerator.py:1450-1464 applies torch checkpoint wrappers
            # post-wrap), EXCEPT the flash-attention out/lse — keeping those
            # skips the kernel's second forward pass in the backward and is
            # byte-identical to "full" for paths that never hit the kernel.
            # Scan models apply this per layer (prepare_model).
            # Copy: the config object is caller-owned and may be shared.
            import dataclasses as _dc

            self.compilation_config = _dc.replace(self.compilation_config, remat_policy="save_flash")

        if self.state.mixed_precision == "fp16" and self.loss_scale_kwargs is None:
            self.loss_scale_kwargs = LossScaleKwargs()

        # -- gradient accumulation (env-overridable, set by the launcher)
        if gradient_accumulation_plugin is None:
            steps = gradient_accumulation_steps or parse_int_from_env(
                "ACCELERATE_GRADIENT_ACCUMULATION_STEPS", 1
            )
            gradient_accumulation_plugin = GradientAccumulationPlugin(num_steps=steps)
        elif gradient_accumulation_steps is not None:
            raise ValueError(
                "Pass either gradient_accumulation_steps or gradient_accumulation_plugin, not both."
            )
        self.gradient_state = GradientState(gradient_accumulation_plugin)

        self.device_placement = device_placement
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.dispatch_batches = dispatch_batches
        self.step_scheduler_with_optimizer = step_scheduler_with_optimizer

        seed = parse_int_from_env("ACCELERATE_SEED")
        if seed is not None:
            set_seed(seed)

        self.log_with = log_with
        self._models: list[PreparedModel] = []
        self._optimizers: list[AcceleratedOptimizer] = []
        self._schedulers: list[AcceleratedScheduler] = []
        self._dataloaders: list[BaseDataLoader] = []
        self._custom_objects: list = []
        self._grad_fns: dict[tuple, Callable] = {}
        self._accum_step = 0
        self.step = 0
        self.trackers: list = []
        self._save_model_hooks: list = []
        self._load_model_hooks: list = []

        self.flag_tensor = None

        # -- telemetry hub (telemetry/hub.py): step timing, compile capture,
        # memory watermarks, goodput, profiler windows. Constructed here so
        # compiles during prepare() are already attributed; near-zero cost
        # until the user calls telemetry.step()/flush().
        self.telemetry = Telemetry(accelerator=self, config=telemetry_config)
        self._profile_active = False
        if self._zero_fallback_reason is not None and self.telemetry.enabled:
            # the warning above is for the console; the record is for the
            # telemetry stream (a fleet of silent fallbacks is a query away)
            self.telemetry.write_record(
                "zero",
                {
                    "event": "fallback_replicated",
                    "reason": self._zero_fallback_reason,
                },
            )
        # -- resilience hub (resilience/hub.py): numerical guards fused into
        # compiled_step, the chaos fault-injection harness, and retry
        # observability. Inert (and compiled programs unchanged) unless a
        # config is passed or ACCELERATE_RESILIENCE / ACCELERATE_CHAOS_* is
        # set — constructed after telemetry so its records have a sink.
        self.resilience = Resilience(accelerator=self, config=resilience_config)
        if self.telemetry.enabled:
            import weakref

            from . import data_loader as _dl

            # weakly bound: the module-level hook (last Accelerator wins)
            # must not pin a dead Accelerator's goodput ledger for the
            # process lifetime — same lifecycle rule as the compile
            # tracker's weak-set dispatcher
            goodput_ref = weakref.ref(self.telemetry.goodput)

            def _record_rewind(seconds: float, batches: int) -> None:
                goodput = goodput_ref()
                if goodput is not None:
                    goodput.record("dataloader_rewind", seconds)

            _dl.rewind_seconds_hook = _record_rewind

    # ------------------------------------------------------------------
    # topology passthrough (reference properties)
    # ------------------------------------------------------------------

    @property
    def distributed_type(self):
        return self.state.distributed_type

    @property
    def num_processes(self) -> int:
        return self.state.num_processes

    @property
    def process_index(self) -> int:
        return self.state.process_index

    @property
    def local_process_index(self) -> int:
        return self.state.local_process_index

    @property
    def is_main_process(self) -> bool:
        return self.state.is_main_process

    @property
    def is_local_main_process(self) -> bool:
        return self.state.is_local_main_process

    @property
    def is_last_process(self) -> bool:
        return self.state.is_last_process

    @property
    def mesh(self):
        return self.state.mesh

    @property
    def device(self):
        return self.state.device

    @property
    def mixed_precision(self) -> str:
        return self.state.mixed_precision

    @property
    def gradient_accumulation_steps(self) -> int:
        return self.gradient_state.num_steps

    @gradient_accumulation_steps.setter
    def gradient_accumulation_steps(self, value: int) -> None:
        self.gradient_state.plugin_kwargs.update({"num_steps": value})

    @property
    def sync_gradients(self) -> bool:
        return self.gradient_state.sync_gradients

    @property
    def project_dir(self) -> Optional[str]:
        return self.project_configuration.project_dir

    @property
    def use_distributed(self) -> bool:
        return self.state.use_distributed

    def print(self, *args, **kwargs) -> None:
        self.state.print(*args, **kwargs)

    def wait_for_everyone(self) -> None:
        self.state.wait_for_everyone()

    @contextmanager
    def main_process_first(self):
        with self.state.main_process_first():
            yield

    @contextmanager
    def split_between_processes(self, inputs, apply_padding: bool = False):
        with self.state.split_between_processes(inputs, apply_padding=apply_padding) as piece:
            yield piece

    def on_main_process(self, fn):
        return self.state.on_main_process(fn)

    def on_last_process(self, fn):
        return self.state.on_last_process(fn)

    def on_process(self, fn=None, process_index: int = 0):
        return self.state.on_process(fn, process_index=process_index)

    # ------------------------------------------------------------------
    # prepare
    # ------------------------------------------------------------------

    def _partition_rules(self, module: Any) -> PartitionRules:
        rules: list[tuple[str, tuple]] = []
        if self.model_parallel_plugin is not None and self.model_parallel_plugin.partition_rules:
            rules.extend(self.model_parallel_plugin.partition_rules)
        if hasattr(module, "partition_rules"):
            rules.extend(module.partition_rules())
        # ZeRO stage 1/2: parameters replicated over fsdp, only optimizer state
        # shards (prepare_optimizer derives that layout via with_fsdp_applied)
        stage3 = self.fsdp_plugin is None or self.fsdp_plugin.stage >= 3
        return PartitionRules(rules, fsdp_plugin=self.fsdp_plugin, apply_fsdp_to_params=stage3)

    def prepare_model(self, model: Any, params: Any = None, device_placement: Optional[bool] = None) -> PreparedModel:
        """Bind a model to sharded global parameters.

        ``model`` is anything with ``.apply(params, ...)`` (our models, flax
        linen modules) or a bare apply function; ``params`` may be given, or
        the model must expose ``.init(rng)``.
        """
        if isinstance(model, PreparedModel):
            return model
        if params is None:
            if hasattr(model, "init"):
                params = model.init(next_rng_key())
            else:
                raise ValueError(
                    "prepare_model needs parameters: pass params= or give the model an init(rng) method."
                )
        rules = self._partition_rules(model)
        shardings = infer_shardings(params, self.mesh, rules)
        if self._zero_update_sharding:
            # ZeRO storage layout: each parameter additionally split over the
            # data-parallel axes (1/N params + 1/N optimizer state per chip;
            # shardings_like propagates this to the moments automatically).
            # Every step opens with the all-gathers for its forward and closes
            # with reduce-scatter + sharded update (parallel/zero.py).
            from .parallel.sharding import zero_update_shardings

            shardings = zero_update_shardings(params, shardings, self.mesh)
        if device_placement if device_placement is not None else self.device_placement:
            params = shard_tree(params, shardings)
        from .utils.constants import MESH_AXIS_PIPELINE, MESH_AXIS_SEQUENCE

        # Assign (or clear) the mesh-dependent hooks unconditionally: the model
        # object may be re-prepared under a different Accelerator/mesh, and a
        # stale pipeline_fn/attention_fn closes over the old mesh.
        if hasattr(model, "attention_fn"):
            # bidirectional models (Bert: causal_attention=False) get a
            # non-causal ring and skip the causal-only flash kernel
            causal = getattr(model, "causal_attention", True)
            if self.mesh.shape.get(MESH_AXIS_SEQUENCE, 1) > 1:
                # sequence axis active: swap in exact ring attention so K/V
                # blocks rotate over ICI instead of being all-gathered
                from .parallel.ring_attention import make_ring_attention

                model.attention_fn = make_ring_attention(self.mesh, causal=causal)
            elif (
                self.compilation_config.flash_attention_min_seq
                and jax.default_backend() == "tpu"
            ):
                # long sequences stream through the Pallas flash kernel; short
                # ones keep the XLA einsum path (per-shape dispatch). v2 covers
                # non-causal (Bert/T5-encoder), padding masks, and additive
                # bias, so every attention_fn model gets the hook.
                from .ops.flash_attention import make_auto_attention

                model.attention_fn = make_auto_attention(
                    self.compilation_config.flash_attention_min_seq, causal=causal
                )
            else:
                model.attention_fn = None
        if self.state.mixed_precision == "fp8":
            # fp8 = e4m3 per-tensor-scaled projection matmuls (ops/fp8). A
            # model without the dot_fn hook cannot honor it — fail loudly
            # instead of silently training in bf16.
            if not hasattr(model, "dot_fn"):
                raise NotImplementedError(
                    f"mixed_precision='fp8' needs a model with fp8-capable "
                    f"projections (a `dot_fn` hook, like the model zoo's "
                    f"Llama/Bert); {type(model).__name__} has none. Use 'bf16' "
                    "or add the hook."
                )
            from .ops.fp8 import fp8_dot, make_fp8_dot

            model.dot_fn = (
                make_fp8_dot(margin=self.fp8_recipe.margin) if self.fp8_recipe is not None else fp8_dot
            )
        elif hasattr(model, "dot_fn"):
            model.dot_fn = None
        if not hasattr(model, "pipeline_fn") and self.mesh.shape.get(MESH_AXIS_PIPELINE, 1) > 1:
            # still mathematically correct (layers replicate over the axis),
            # but the user asked for pipeline parallelism and gets none — say so
            logger.warning(
                f"{type(model).__name__} has no pipeline_fn/pipeline_layer hook: "
                "the pipeline axis will hold replicated layers (no schedule, no "
                "memory savings). Implement the hook (models/llama.py) or drop "
                "the pipeline axis."
            )
        if hasattr(model, "pipeline_fn"):
            if self.mesh.shape.get(MESH_AXIS_PIPELINE, 1) > 1:
                from .parallel.pipeline import make_pipeline_layers_fn

                # default 4 microbatches per stage: GPipe bubble (P-1)/(M+P-1)
                # drops from ~(P-1)/(2P-1) ≈ 45% at M=P to <20% at M=4P
                num_micro = (
                    self.model_parallel_plugin.num_microbatches
                    if self.model_parallel_plugin is not None and self.model_parallel_plugin.num_microbatches > 0
                    else 4 * self.mesh.shape[MESH_AXIS_PIPELINE]
                )
                virtual = (
                    self.model_parallel_plugin.virtual_pipeline_stages
                    if self.model_parallel_plugin is not None
                    else 1
                )
                # the model's own per-layer function drives the schedule
                # (reads self.dot_fn at trace time, so fp8 stays wired).
                # With a sequence axis the schedule goes manual over BOTH
                # axes (the model declares its sequence dims) and the layers
                # must use the manual-region ring attention.
                seq_dims = None
                if self.mesh.shape.get(MESH_AXIS_SEQUENCE, 1) > 1:
                    seq_dims = getattr(model, "pipeline_seq_dims", None)
                    if hasattr(model, "attention_fn"):
                        from .parallel.ring_attention import make_local_ring_attention

                        model.attention_fn = make_local_ring_attention(
                            causal=getattr(model, "causal_attention", True)
                        )
                model.pipeline_fn = make_pipeline_layers_fn(
                    model.config, self.mesh, num_micro,
                    layer_fn=model.pipeline_layer, virtual_stages=virtual,
                    seq_dims=seq_dims,
                    const_kinds=getattr(model, "pipeline_const_kinds", None),
                )
                if hasattr(model, "enc_pipeline_layer"):
                    # encoder-decoder models pipeline each stack separately
                    # (t5: the encoder schedule completes, then the decoder
                    # schedule runs with enc_out as a per-microbatch input)
                    model.enc_pipeline_fn = make_pipeline_layers_fn(
                        model.config, self.mesh, num_micro,
                        layer_fn=model.enc_pipeline_layer, virtual_stages=virtual,
                        const_kinds=getattr(model, "enc_pipeline_const_kinds", None),
                    )
            else:
                model.pipeline_fn = None
                if hasattr(model, "enc_pipeline_fn"):
                    model.enc_pipeline_fn = None
        layer_policy = self.compilation_config.checkpoint_policy()
        if hasattr(model, "remat_layers"):
            # scan-structured models apply the remat policy per layer (the
            # scan carry is always saved; the policy decides what survives
            # inside a layer) instead of the outer loss-fn wrap, which for
            # dot-saving policies would keep every attention score across all
            # layers alive at once. The pipeline branch bypasses the scan, so
            # those models keep the outer wrap. Always assign — the model
            # object may be re-prepared under a different Accelerator config.
            model.remat_layers = (
                layer_policy
                if layer_policy is not None and getattr(model, "pipeline_fn", None) is None
                else False
            )
        prepared = PreparedModel(model, ParamBox(params), shardings, self.state.precision_policy)
        self._models.append(prepared)
        return prepared

    def prepare_optimizer(self, tx: Any, model: Optional[PreparedModel] = None) -> AcceleratedOptimizer:
        if isinstance(tx, AcceleratedOptimizer):
            return tx
        if model is None:
            if not self._models:
                raise ValueError("Prepare (or pass) the model before its optimizer.")
            model = self._models[-1]
        opt_reference_shardings = None
        cpu_offload = False
        if self.fsdp_plugin is not None:
            cpu_offload = self.fsdp_plugin.cpu_offload
            if self.fsdp_plugin.stage < 3:
                # ZeRO stage 1/2: optimizer state shards over fsdp even though
                # the params are replicated (weight-update sharding)
                from .parallel.sharding import infer_shardings

                rules = self._partition_rules(model.module).with_fsdp_applied()
                opt_reference_shardings = infer_shardings(model.params, self.mesh, rules)
        if self._zero_update_sharding:
            # the sharded update runs tx on 1/N shards, which is exact only
            # for transforms that do not couple leaves (adam/sgd families);
            # a clip_by_global_norm inside the chain would reduce over the
            # local shard and train silently differently — fail loudly with
            # the two fixes spelled out instead
            from .parallel.zero import tx_couples_across_leaves

            if tx_couples_across_leaves(tx, model.params):
                raise ValueError(
                    "This optimizer transform couples gradient leaves (e.g. "
                    "an optax.clip_by_global_norm inside the chain), which "
                    "the ZeRO sharded update would compute over each chip's "
                    "1/N shard. Use accelerator.clip_grad_norm_() (exact "
                    "cross-shard norm inside the step) or opt out with "
                    "ParallelismConfig(zero_stage=0)."
                )
        optimizer = AcceleratedOptimizer(
            tx,
            model.box,
            model.params_shardings,
            scaler=self.loss_scale_kwargs if self.state.precision_policy.requires_loss_scaling else None,
            opt_reference_shardings=opt_reference_shardings,
            cpu_offload=cpu_offload,
        )
        optimizer.telemetry = self.telemetry if self.telemetry.enabled else None
        if self.telemetry.enabled:
            # per-chip residency of the state just allocated: under the ZeRO
            # sharded update this is 1/N of the replicated layout — recorded
            # so the saving is a telemetry number, not a claim
            from .telemetry.memory import state_bytes_per_chip

            self.telemetry.write_record(
                "memory",
                {
                    "event": "optimizer_state_allocated",
                    "opt_state_bytes_per_chip": state_bytes_per_chip(optimizer.opt_state),
                    "zero_update_sharding": self._zero_update_sharding,
                },
            )
        self._optimizers.append(optimizer)
        return optimizer

    def prepare_scheduler(self, schedule_fn: Callable[[int], float]) -> AcceleratedScheduler:
        if isinstance(schedule_fn, AcceleratedScheduler):
            return schedule_fn
        scheduler = AcceleratedScheduler(
            schedule_fn,
            optimizer=self._optimizers[-1] if self._optimizers else None,
            step_with_optimizer=self.step_scheduler_with_optimizer,
            split_batches=self.split_batches,
        )
        self._schedulers.append(scheduler)
        return scheduler

    def prepare_data_loader(self, loader: Any, device_placement: Optional[bool] = None, **loader_kwargs) -> BaseDataLoader:
        """``loader_kwargs`` (batch_size, shuffle, seed, collate_fn, drop_last,
        use_seedable_sampler) pass through to ``prepare_data_loader`` when a
        raw dataset is handed in."""
        if isinstance(loader, BaseDataLoader) and loader_kwargs:
            raise ValueError(
                "This loader is already prepared; the extra options "
                f"{sorted(loader_kwargs)} would be silently ignored. Pass the "
                "raw dataset instead to reconfigure it."
            )
        # per-call kwargs override the Accelerator-level loader defaults
        merged = dict(
            split_batches=self.split_batches,
            even_batches=self.even_batches,
            dispatch_batches=self.dispatch_batches,
        )
        merged.update(loader_kwargs)
        prepared = prepare_data_loader(
            loader,
            device_placement=device_placement if device_placement is not None else self.device_placement,
            **merged,
        )
        self._dataloaders.append(prepared)
        return prepared

    def _is_model_like(self, obj: Any) -> bool:
        return isinstance(obj, PreparedModel) or hasattr(obj, "apply") and not self._is_optimizer_like(obj)

    @staticmethod
    def _is_optimizer_like(obj: Any) -> bool:
        # optax GradientTransformation is a NamedTuple of (init, update)
        return hasattr(obj, "init") and hasattr(obj, "update") and not hasattr(obj, "apply")

    @staticmethod
    def _is_loader_like(obj: Any) -> bool:
        return (
            isinstance(obj, BaseDataLoader)
            or hasattr(obj, "__getitem__")
            and hasattr(obj, "__len__")
            or hasattr(obj, "__iter__")
            and not callable(obj)
        )

    def prepare(self, *args: Any, device_placement: Optional[list] = None) -> Any:
        """Prepare objects in their natural order (reference accelerator.py:1173).

        Dispatch by duck type: models (``.apply``/``.init``), optax
        transformations (``.init``+``.update``), dataloaders/datasets
        (iterable or indexable), schedule callables (int → float).
        """
        result = []
        # pass 1: models (optimizers bind to the model prepared before them)
        prepared_map: dict[int, Any] = {}
        for i, obj in enumerate(args):
            if isinstance(obj, PreparedModel) or (hasattr(obj, "apply") and hasattr(obj, "init") and not self._is_optimizer_like(obj)):
                prepared_map[i] = self.prepare_model(obj)
        for i, obj in enumerate(args):
            if i in prepared_map:
                continue
            if self._is_optimizer_like(obj):
                prepared_map[i] = self.prepare_optimizer(obj)
            elif isinstance(obj, (BaseDataLoader,)) or self._is_loader_like(obj):
                prepared_map[i] = self.prepare_data_loader(obj)
            elif callable(obj):
                # Last duck-type bucket: only SCHEDULE-shaped callables (one
                # required argument — the step count) may fall through here. A
                # loss function silently wrapped in AcceleratedScheduler fails
                # confusingly much later (reference's prepare dispatches on
                # nn.Module/Optimizer/DataLoader types, accelerator.py:1178) —
                # reject with the fix spelled out instead.
                import inspect

                try:
                    required = [
                        p
                        for p in inspect.signature(obj).parameters.values()
                        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                        and p.default is p.empty
                    ]
                    schedule_shaped = len(required) <= 1
                except (TypeError, ValueError):  # builtins without signatures
                    schedule_shaped = True
                if not schedule_shaped:
                    raise TypeError(
                        f"prepare() got a callable ({getattr(obj, '__name__', obj)!r}) "
                        f"taking {len(required)} required arguments — a learning-rate "
                        "schedule takes one (the step count). If this is a loss "
                        "function, pass it to backward()/compiled_step() instead; "
                        "for a custom schedule call prepare_scheduler() explicitly."
                    )
                prepared_map[i] = self.prepare_scheduler(obj)
            else:
                prepared_map[i] = obj
        result = tuple(prepared_map[i] for i in range(len(args)))
        return result if len(result) != 1 else result[0]

    # ------------------------------------------------------------------
    # the step: backward / clip / accumulate
    # ------------------------------------------------------------------

    _GRAD_FN_CACHE_LIMIT = 16

    def _effective_remat_policy(self, model: PreparedModel):
        """Models with built-in per-layer remat don't get the outer loss-fn
        jax.checkpoint wrap (it would re-save what the layers already handle)."""
        if getattr(model.module, "remat_layers", False):
            return None
        return self.compilation_config.checkpoint_policy()

    def _get_grad_fn(self, loss_fn: Callable, model: PreparedModel, has_aux: bool) -> Callable:
        # key holds a strong reference to loss_fn: ids of collected objects are
        # reused, so an id()-only key could serve a stale compiled grad fn.
        key = (loss_fn, id(model), has_aux)
        if key not in self._grad_fns:
            policy = self.state.precision_policy
            remat_policy = self._effective_remat_policy(model)

            def scaled_loss(params, batch, scale):
                compute_params = cast_floating(params, policy.compute_dtype)
                compute_batch = cast_floating(batch, policy.compute_dtype)
                fn = loss_fn
                if remat_policy is not None:
                    fn = jax.checkpoint(fn, policy=remat_policy)
                out = fn(compute_params, compute_batch)
                if has_aux:
                    loss, aux = out
                    return (loss.astype(jnp.float32) * scale, aux)
                return out.astype(jnp.float32) * scale

            grad_fn = jax.value_and_grad(scaled_loss, has_aux=has_aux)

            @partial(jax.jit, static_argnums=())
            def run(params, batch, scale):
                value, grads = grad_fn(params, batch, scale)
                # NOTE(zero): gradients are deliberately NOT constrained to
                # the ZeRO storage layout here. GSPMD already lays them out
                # like the (folded) params they mirror, and forcing the
                # constraint trips this XLA version's "involuntary full
                # rematerialization" resharding path, which we have measured
                # miscomputing (same bug class as the donated FSDP fused
                # step the ZeRO program replaced). The fused path gets its
                # layout from explicit collectives instead.
                return value, grads

            if len(self._grad_fns) >= self._GRAD_FN_CACHE_LIMIT:
                evicted = next(iter(self._grad_fns))
                del self._grad_fns[evicted]
                logger.warning_once(
                    "backward() has compiled more than "
                    f"{self._GRAD_FN_CACHE_LIMIT} distinct loss functions — pass a "
                    "stable callable (not a fresh lambda per step) to avoid "
                    "recompiling every step."
                )
            self._grad_fns[key] = run
        return self._grad_fns[key]

    def backward(self, loss_fn: Callable, batch: Any = None, model: Optional[PreparedModel] = None, has_aux: bool = False, **kwargs):
        """Compute gradients of ``loss_fn(params, batch)`` and accumulate them.

        Replaces ``loss.backward()`` (reference accelerator.py:2007): the loss
        is passed as a *function* because XLA differentiates traced programs,
        not materialized scalars. Loss is divided by the accumulation window
        via the optimizer's mean (reference divides the loss, 2025-2027 — same
        result, fewer casts). Returns the (unscaled) loss value; with
        ``has_aux`` returns (loss, aux).
        """
        if model is None:
            if not self._models:
                raise ValueError("backward() needs a prepared model.")
            model = self._models[-1]
        # route grads to the optimizer bound to THIS model's params (multi-model
        # setups like GANs prepare several pairs)
        optimizer = next((opt for opt in self._optimizers if opt._box is model.box), None)
        if optimizer is None:
            raise ValueError(
                "backward() computed gradients but no optimizer is prepared for "
                "this model, so they would be silently dropped. Call "
                "prepare(optimizer) first, or use jax.grad on your loss function "
                "directly if you only want gradients."
            )
        scale = optimizer.scale if optimizer.scale is not None else jnp.float32(1.0)
        run = self._get_grad_fn(loss_fn, model, has_aux)
        value, grads = run(model.params, batch, scale)
        optimizer.accumulate_grads(grads)
        if has_aux:
            loss, aux = value
            return loss / scale, aux
        return value / scale

    def clip_grad_norm_(self, model_or_max_norm=_UNSET, max_norm=_UNSET, norm_type: int = 2):
        """Register gradient clipping for subsequent optimizer steps.

        Signature accepts (parameters, max_norm) reference-style or just
        (max_norm). Clipping happens inside the jitted update using the
        *accumulated* gradient — identical semantics to clipping after
        unscale (reference accelerator.py:2131-2180). The setting is sticky
        (applies to every later step); pass an explicit ``None`` to clear it.
        """
        if norm_type != 2:
            raise ValueError("Only the L2 grad norm is supported under XLA.")
        if max_norm is _UNSET:
            max_norm = model_or_max_norm
        if max_norm is _UNSET:
            raise ValueError("clip_grad_norm_ needs max_norm")
        for optimizer in self._optimizers:
            optimizer.set_clip_grad_norm(None if max_norm is None else float(max_norm))

    def clip_grad_value_(self, model_or_clip_value=_UNSET, clip_value=_UNSET):
        """Register elementwise gradient clamping to [-clip_value, clip_value]
        (reference accelerator.py:2183, torch.nn.utils.clip_grad_value_
        semantics). Accepts (parameters, clip_value) reference-style or just
        (clip_value). Applied inside the jitted update on the accumulated,
        unscaled gradient, before any clip_grad_norm_. The setting is sticky
        (applies to every later step); pass an explicit ``None`` to clear it.
        Prefer clip_grad_norm_ at scale — value clipping changes the gradient
        direction."""
        if clip_value is _UNSET:
            clip_value = model_or_clip_value
        if clip_value is _UNSET:
            raise ValueError("clip_grad_value_ needs clip_value")
        for optimizer in self._optimizers:
            optimizer.set_clip_grad_value(None if clip_value is None else float(clip_value))

    def _do_sync(self) -> None:
        if self.gradient_state.sync_with_dataloader and self.gradient_state.end_of_dataloader:
            self._accum_step = 0
            self.gradient_state._set_sync_gradients(True)
        else:
            self._accum_step += 1
            sync = (self._accum_step % self.gradient_state.num_steps == 0) or self.gradient_state.sync_each_batch
            self.gradient_state._set_sync_gradients(sync)

    @contextmanager
    def accumulate(self, *models):  # noqa: ARG002 - models accepted for parity
        """Gradient-accumulation window (reference accelerator.py:1017)."""
        self._do_sync()
        yield

    @contextmanager
    def no_sync(self, model=None):  # noqa: ARG002
        """Force-accumulate context (reference accelerator.py:902). Under SPMD
        there is no DDP hook to suppress; this just marks the step as
        non-syncing so optimizer.step()/zero_grad() no-op."""
        previous = self.gradient_state.sync_gradients
        self.gradient_state._set_sync_gradients(False)
        try:
            yield
        finally:
            self.gradient_state._set_sync_gradients(previous)

    @contextmanager
    def join_uneven_inputs(self, joinables, even_batches: Optional[bool] = None):  # noqa: ARG002
        """Parity shim (reference accelerator.py:1053): even_batches padding in
        the loaders already guarantees equal step counts, so there is nothing
        to join; the context simply yields."""
        yield

    @contextmanager
    def autocast(self, autocast_handler=None):  # noqa: ARG002
        """Parity shim (reference accelerator.py:3189): the dtype policy is
        applied functionally inside jitted functions, not via a context."""
        yield

    @contextmanager
    def profile(
        self,
        log_dir: Optional[str] = None,
        port: Optional[int] = None,
        host_metadata: Optional[dict] = None,
    ):
        """Capture a ``jax.profiler`` device trace for the enclosed steps
        (SURVEY §5.1: the reference has only Megatron timers; XLA gives full
        timeline traces). View with TensorBoard or Perfetto::

            with accelerator.profile("/tmp/trace") as capture:
                for batch in loader:
                    step(batch)
            print(capture.memory_after)

        ``port`` additionally starts the jax profiler server (for live
        ``tensorboard --logdir`` capture against a running job); the server is
        stopped on exit. ``host_metadata`` (plus process/device coordinates)
        is written to ``host_metadata.json`` next to the trace so pod-wide
        trace collections stay attributable. Yields a :class:`ProfileCapture`
        (a ``str`` of the log dir, with per-device memory snapshots taken on
        entry and exit as attributes). Not reentrant: nesting would interleave
        two traces into one corrupt capture, so it raises instead.
        """
        if self._profile_active:
            raise RuntimeError(
                "accelerator.profile() is already active — jax supports one "
                "trace at a time, and nesting would corrupt the capture. "
                "Close the outer profile() first."
            )
        from .utils.environment import get_device_memory_info
        from .telemetry.step_timer import drain_local_devices

        if log_dir is None:
            log_dir = os.path.join(self.project_configuration.logging_dir or ".", "profile")
        capture = ProfileCapture(log_dir)
        capture.memory_before = get_device_memory_info()
        server_started = False
        if port is not None:
            try:
                jax.profiler.start_server(port)
                server_started = True
            except Exception as e:  # port in use / older jax: trace still works
                logger.warning(f"profile(): could not start profiler server on port {port}: {e}")
        os.makedirs(log_dir, exist_ok=True)
        meta = {
            "process_index": self.process_index,
            "local_process_index": self.local_process_index,
            "num_processes": self.num_processes,
            "device_kind": getattr(jax.local_devices()[0], "device_kind", None),
            **(host_metadata or {}),
        }
        try:
            import json as _json

            with open(os.path.join(log_dir, "host_metadata.json"), "w") as f:
                _json.dump(meta, f, indent=2, default=str)
        except OSError:
            pass  # metadata is best-effort; the trace is the payload
        jax.profiler.start_trace(log_dir)
        # the guard flips only once the trace is live: a failed start_trace
        # must not leave the accelerator permanently "profiling"
        self._profile_active = True
        try:
            yield capture
        finally:
            try:
                # drain async dispatch on EVERY device so the trace covers the
                # final step's work on the whole mesh, not just device 0
                drain_local_devices()
                jax.profiler.stop_trace()
                if server_started:
                    try:
                        jax.profiler.stop_server()
                    except Exception:
                        pass
            finally:
                # release the guard even when the stop path raises (full disk
                # under the trace dir, wedged device): a failed stop must not
                # leave the accelerator permanently "profiling"
                capture.memory_after = get_device_memory_info()
                self._profile_active = False
                # a trace is non-step overhead; keep step-time samples honest
                self.telemetry.timer.discard_window()

    # ------------------------------------------------------------------
    # program analysis (analysis/: the correctness-tooling layer)
    # ------------------------------------------------------------------

    def _sharding_intent(self) -> bool:
        """Whether this configuration declares state sharding — if so, a
        large input resolving to full replication is a regression (ERROR),
        not the expected data-parallel layout (INFO). ZeRO update sharding is
        declared intent: parameters AND optimizer state must arrive sharded,
        so the replication audit asserts it rather than inventorying it."""
        if self._zero_update_sharding:
            return True
        p = getattr(self.state, "parallelism", None)
        if p is None:
            return False
        model_axes = (p.fsdp, p.pipeline, p.expert, p.sequence, p.tensor)
        return any(int(size or 1) > 1 for size in model_axes)

    def analyze(
        self,
        loss_fn: Optional[Callable] = None,
        batch: Any = None,
        *,
        step: Optional[Callable] = None,
        model: Optional[PreparedModel] = None,
        compile: bool = True,
        label: str = "compiled_step",
        write_record: bool = True,
        contracts_dir: Optional[str] = None,
        **audit_kwargs,
    ):
        """Audit the fused step program (docs/analysis.md).

        Lowers the exact program ``compiled_step`` runs — pass either a
        ``step`` previously returned by :meth:`compiled_step`, or the same
        ``loss_fn`` you would hand it — plus one representative ``batch``
        (real arrays or ``jax.ShapeDtypeStruct``), and runs the full program
        audit: donation aliasing, fp64 leaks, baked-in constants, collective
        inventory, replication. Returns an
        :class:`~.analysis.AnalysisReport`; the summary also lands as a
        ``{"kind": "analysis"}`` record in ``telemetry.jsonl``.

        ``compile=True`` (default) compiles a second AOT executable so the
        post-GSPMD properties (real collectives, executable alias table,
        memory + schedule passes) are audited — costs one extra XLA compile
        of the step. ``contracts_dir`` additionally checks the report against
        the program's checked-in contract (``<contracts_dir>/<label>.json``)
        and appends any ``CONTRACT_DRIFT`` findings — the differential gate.
        """
        from .analysis import audit_lowered

        if step is None:
            if loss_fn is None:
                raise ValueError("analyze() needs a loss_fn (or a step= from compiled_step)")
            step = self.compiled_step(loss_fn, model=model)
        if not hasattr(step, "lower"):
            raise ValueError(
                "analyze() needs the step returned by compiled_step() (it "
                "carries the program); got a plain callable."
            )
        if batch is None:
            raise ValueError("analyze() needs a representative batch (arrays or ShapeDtypeStructs)")
        report = audit_lowered(
            step.lower(batch),
            compile=compile,
            label=label,
            sharded_intent=audit_kwargs.pop("sharded_intent", self._sharding_intent()),
            **audit_kwargs,
        )
        if contracts_dir is not None:
            from .analysis.contracts import gate_reports

            gate_reports([report], contracts_dir)
        if write_record and self.telemetry.enabled:
            self.telemetry.write_record("analysis", {"analysis": report.to_dict()})
        return report

    # ------------------------------------------------------------------
    # fused fast path
    # ------------------------------------------------------------------

    def compiled_step(
        self,
        loss_fn: Callable,
        model: Optional[PreparedModel] = None,
        clip_grad_norm: Optional[float] = None,
        clip_grad_value: Optional[float] = None,
        donate: bool = True,
    ):
        """One fused jit program: grads (+ scan over microbatches) → clip → update.

        Returns ``step(batch) -> loss``. The batch's leading dim is split into
        ``gradient_accumulation_steps`` microbatches inside the program via
        ``lax.scan`` — no eager Python between microbatches, buffers donated.
        This is what the reference's whole hot loop (SURVEY §3.3) compiles down
        to, and the path benchmarks should use.

        ``donate=False`` keeps params/opt_state undonated — for debugging
        against the pre-step state, and for the analyzer's seeded
        dropped-donation regression (tests/test_contracts.py), at the cost of
        a second resident copy of the whole training state.
        """
        if model is None:
            model = self._models[-1]
        optimizer = next((opt for opt in self._optimizers if opt._box is model.box), None)
        if optimizer is None:
            raise ValueError("compiled_step needs an optimizer prepared for this model.")
        policy = self.state.precision_policy
        num_micro = self.gradient_state.num_steps
        tx = optimizer.tx
        remat_policy = self._effective_remat_policy(model)
        scaler_cfg = optimizer.scaler  # fp16 dynamic loss scaling (None otherwise)

        def loss_of(params, batch, scale):
            fn = loss_fn
            if remat_policy is not None:
                fn = jax.checkpoint(fn, policy=remat_policy)
            loss = fn(cast_floating(params, policy.compute_dtype), cast_floating(batch, policy.compute_dtype))
            loss = loss.astype(jnp.float32)
            # scale is None (STATIC) without an fp16 scaler: a traced scale of
            # 1.0 cannot be folded by XLA, and the matching grads/scale divide
            # below would read+write the whole gradient tree every step
            # (~0.9 GB on bert-base ≈ 3 ms — the round-2..4 bert regression)
            return loss if scale is None else loss * scale

        def loss_and_grads(params, batch, scale):
            if num_micro > 1:
                def micro(carry, mb):
                    grads_acc, loss_acc = carry
                    loss, grads = jax.value_and_grad(loss_of)(params, mb, scale)
                    return (jax.tree.map(jnp.add, grads_acc, grads), loss_acc + loss), None

                zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
                micro_batches = jax.tree.map(
                    lambda x: x.reshape((num_micro, x.shape[0] // num_micro) + x.shape[1:]), batch
                )
                (grads, loss), _ = jax.lax.scan(micro, (zeros, jnp.float32(0.0)), micro_batches)
                grads = jax.tree.map(lambda g: g / num_micro, grads)
                loss = loss / num_micro
                return loss, grads
            return jax.value_and_grad(loss_of)(params, batch, scale)

        # -- resilience (resilience/): when the hub is armed, the numerical
        # guard's finite verdict + skip/escalate policy fuse into the program
        # and the chaos harness can poison loss/grads at scheduled steps.
        # With the hub inert (the default) the plain program below is built
        # unchanged — zero cost, bit-identical behavior.
        resilience = getattr(self, "resilience", None)
        res_on = resilience is not None and resilience.enabled
        guard = resilience.guard if res_on else None
        gpolicy = guard.policy if guard is not None else None
        chaos = resilience.chaos if res_on else None
        chaos_nan = bool(chaos is not None and chaos.nan_steps)

        def step_impl(params, opt_state, batch, scale, growth_tracker):
            loss, grads = loss_and_grads(params, batch, scale)
            if scale is not None:
                grads = jax.tree.map(lambda g: g / scale, grads)
            grads = clip_by_value(grads, clip_grad_value)
            # the global norm is a full gradient-tree reduction — compute it
            # only for consumers (the clip, or the scaler's finite check)
            gnorm = None
            if clip_grad_norm is not None or scaler_cfg is not None:
                grads, gnorm = clip_by_global_norm(grads, clip_grad_norm)

            # unscale the reported loss with the scale it was computed under,
            # before the scaler bookkeeping below mutates `scale`
            if scale is not None:
                loss = loss / scale
            params, opt_state, scale, growth_tracker, skipped = scaled_optimizer_update(
                tx, params, opt_state, grads, gnorm, scale, growth_tracker, scaler_cfg
            )
            # pin output layouts: keeps the ZeRO stage-1/2 replicated-params
            # invariant and the moment shardings stable under GSPMD propagation,
            # via in-program constraints so buffer donation stays usable
            params = jax.lax.with_sharding_constraint(params, model.params_shardings)
            opt_state = jax.lax.with_sharding_constraint(opt_state, optimizer._opt_state_device_shardings)
            return params, opt_state, loss, scale, growth_tracker, skipped

        # NOTE: parallel/zero.py's guarded_step_impl mirrors this ladder for
        # the sharded update — a semantic change to skip/escalate/backoff
        # belongs in both places (the resilience suite pins each).
        def guarded_step_impl(params, opt_state, batch, scale, growth_tracker, gstate, corrupt):
            loss, grads = loss_and_grads(params, batch, scale)
            if chaos_nan:
                # scheduled poisoning lands where a real blowup would: in the
                # traced program, before the guard's verdict
                poison = jnp.where(corrupt != 0, jnp.float32(jnp.nan), jnp.float32(1.0))
                if chaos.nan_target == "loss":
                    loss = loss * poison
                else:
                    grads = jax.tree.map(lambda g: g * poison, grads)
            if scale is not None:
                grads = jax.tree.map(lambda g: g / scale, grads)
            grads = clip_by_value(grads, clip_grad_value)
            # the guard's verdict needs the global norm regardless of clip
            # settings — one reduction covers every gradient leaf
            grads, gnorm = clip_by_global_norm(grads, None)
            finite = jnp.isfinite(loss) & jnp.isfinite(gnorm) if guard is not None else None
            escalating = guard is not None and gpolicy.escalate_clip is not None
            if clip_grad_norm is not None or escalating:
                base = (
                    jnp.float32(clip_grad_norm)
                    if clip_grad_norm is not None
                    else jnp.float32(jnp.inf)
                )
                if escalating:
                    # for escalate_steps after a bad step the clip tightens
                    esc = jnp.minimum(jnp.float32(gpolicy.escalate_clip), base)
                    limit = jnp.where(gstate["escalate"] > 0, esc, base)
                else:
                    limit = base
                factor = jnp.minimum(1.0, limit / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * factor, grads)
            if scale is not None:
                loss = loss / scale
            if guard is not None and gpolicy.skip_nonfinite:
                def _apply(args):
                    p, o, s, gt = args
                    return scaled_optimizer_update(tx, p, o, grads, gnorm, s, gt, scaler_cfg)

                def _skip(args):
                    p, o, s, gt = args
                    if scaler_cfg is not None:
                        # a guard skip IS the overflow case the scaler's
                        # backoff exists for — keep its dynamics intact
                        s = s * scaler_cfg.backoff_factor
                        gt = jnp.int32(0)
                    return p, o, s, gt, jnp.asarray(True)

                params, opt_state, scale, growth_tracker, skipped = jax.lax.cond(
                    finite, _apply, _skip, (params, opt_state, scale, growth_tracker)
                )
            else:
                params, opt_state, scale, growth_tracker, skipped = scaled_optimizer_update(
                    tx, params, opt_state, grads, gnorm, scale, growth_tracker, scaler_cfg
                )
            if guard is not None:
                gstate = next_guard_state(gstate, finite, gpolicy.escalate_steps)
            params = jax.lax.with_sharding_constraint(params, model.params_shardings)
            opt_state = jax.lax.with_sharding_constraint(opt_state, optimizer._opt_state_device_shardings)
            return params, opt_state, loss, scale, growth_tracker, skipped, gstate

        donate_argnums = (0, 1) if donate else ()
        if self._zero_update_sharding:
            # ZeRO sharded update (parallel/zero.py): the program opens with
            # the param all-gathers (hidden behind forward compute), closes
            # with reduce-scatter → sharded adamw on 1/N state. Signature-
            # identical to the replicated jit below, so lower()/step() and
            # the analysis seam serve both implementations unchanged.
            from .parallel.zero import build_zero_step

            jitted = build_zero_step(
                mesh=self.mesh,
                loss_fn=loss_fn,
                tx=tx,
                params_shardings=model.params_shardings,
                opt_state_shardings=optimizer._opt_state_device_shardings,
                batch_sharding=self.state.data_sharding(),
                compute_cast=lambda tree: cast_floating(tree, policy.compute_dtype),
                num_micro=num_micro,
                remat_policy=remat_policy,
                scaler_cfg=scaler_cfg,
                clip_grad_norm=clip_grad_norm,
                clip_grad_value=clip_grad_value,
                guard_policy=gpolicy if guard is not None else None,
                chaos_nan_target=chaos.nan_target if chaos_nan else None,
                resilience_on=res_on,
                donate=donate,
            )
        else:
            jitted = jax.jit(
                guarded_step_impl if res_on else step_impl, donate_argnums=donate_argnums
            )

        if self.telemetry.enabled:
            # {"kind": "kernels"} at step build (the serving engine writes the
            # same kind at its first step): names whether the fused adamw
            # kernel (ops/fused_adamw.py) is in this step's update — a fleet
            # operator greps one record kind for kernel coverage everywhere
            self.telemetry.write_record(
                "kernels",
                {
                    "program": "train_step",
                    "fused_adamw": "pallas" if getattr(tx, "fused_apply", None) else None,
                    "zero_update_sharding": self._zero_update_sharding,
                },
            )

        def lower(batch):
            """AOT-lower the fused program against the LIVE params/opt_state —
            the program-audit entry point (``Accelerator.analyze``): traces
            the exact program ``step`` runs, without executing a step."""
            scale_in = optimizer.scale if scaler_cfg is not None else None
            growth_in = optimizer.growth_tracker if scaler_cfg is not None else None
            opt_state_in = optimizer.opt_state
            if optimizer.cpu_offload:
                opt_state_in = jax.device_put(opt_state_in, optimizer._opt_state_device_shardings)
            if res_on:
                gstate_in = (
                    guard.state
                    if guard is not None and guard.state is not None
                    else zero_guard_state()
                )
                return jitted.lower(
                    model.params, opt_state_in, batch, scale_in, growth_in, gstate_in, np.int32(0)
                )
            return jitted.lower(model.params, opt_state_in, batch, scale_in, growth_in)

        def step(batch):
            # no scaler → scale stays a STATIC None (empty pytree through jit):
            # every scaling op is elided at trace time instead of shipping a
            # runtime 1.0 the compiler cannot fold
            scale = optimizer.scale if scaler_cfg is not None else None
            growth = optimizer.growth_tracker if scaler_cfg is not None else None
            opt_state_in = optimizer.opt_state
            if optimizer.cpu_offload:
                opt_state_in = jax.device_put(opt_state_in, optimizer._opt_state_device_shardings)
            if optimizer.telemetry is not None:
                # abstract signature (shapes/dtypes only — no host sync): when
                # the hub later observes a steady-state recompile, the diff of
                # the last two signatures names the leaf that forced it
                optimizer.telemetry.note_step_signature(batch)
            if res_on:
                step_idx = resilience.begin_step()  # chaos stall/SIGTERM fire here
                corrupt = np.int32(0)
                if chaos_nan and chaos.corrupt_target(step_idx) is not None:
                    corrupt = np.int32(1)
                if guard is not None and guard.state is None:
                    guard.arm(model, optimizer)
                gstate_in = guard.state if guard is not None else zero_guard_state()
                params, opt_state, loss, scale, growth, skipped, gstate_out = jitted(
                    model.params, opt_state_in, batch, scale, growth, gstate_in, corrupt
                )
                if guard is not None:
                    guard.state = gstate_out
            else:
                params, opt_state, loss, scale, growth, skipped = jitted(
                    model.params, opt_state_in, batch, scale, growth
                )
            model.params = params
            optimizer.opt_state = opt_state
            if optimizer.cpu_offload:
                optimizer.opt_state = jax.device_put(opt_state, optimizer._opt_state_shardings)
            if scaler_cfg is not None:
                optimizer.scale, optimizer.growth_tracker = scale, growth
            # lazy device scalar; step_was_skipped converts — so the scheduler
            # sees overflow-skipped steps exactly as on the eager path
            optimizer._skipped = skipped
            optimizer._step_count += 1
            if optimizer.telemetry is not None:
                optimizer.telemetry._on_optimizer_step()
            if guard is not None:
                # fence-cadence host check: snapshot refresh / LKG restore.
                # Off the cadence this is two integer ops — no host sync.
                guard.after_step(model, optimizer)
            return loss

        # analysis seam: the returned step carries its program (analysis/
        # program.py audits the jitted fn via lower(); tests pin donation)
        step.jitted = jitted
        step.lower = lower
        step.donate_argnums = donate_argnums
        return step

    # ------------------------------------------------------------------
    # gather / metrics
    # ------------------------------------------------------------------

    def gather(self, tensor):
        return ops.gather(tensor)

    def gather_for_metrics(self, input_data, use_gather_object: bool = False):
        """Gather + drop the duplicate samples the even-batch padding added on
        the final batch (reference accelerator.py:2241-2301)."""
        if use_gather_object:
            data = ops.gather_object(input_data)
        else:
            data = ops.gather(input_data)
        # GradientState defaults are safe with no active loader
        # (end_of_dataloader=False, remainder=-1), so no exception guard: a
        # shape bug here should surface, not silently return duplicated samples.
        remainder = self.gradient_state.remainder
        if self.gradient_state.end_of_dataloader and remainder > 0:
            data = ops.recursively_apply(lambda t: t[:remainder], data)
        return data

    def reduce(self, tensor, reduction: str = "mean", scale: float = 1.0):
        return ops.reduce(tensor, reduction=reduction, scale=scale)

    def pad_across_processes(self, tensor, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
        return ops.pad_across_processes(tensor, dim=dim, pad_index=pad_index, pad_first=pad_first)

    # ------------------------------------------------------------------
    # trigger primitive (coordinated breakpoints, reference 2037-2094)
    # ------------------------------------------------------------------

    def set_trigger(self) -> None:
        self.flag_tensor = np.ones((), dtype=np.int32)

    def check_trigger(self) -> bool:
        flag = self.flag_tensor if self.flag_tensor is not None else np.zeros((), dtype=np.int32)
        total = ops.reduce(flag, reduction="sum")
        if float(total) >= 1:
            self.flag_tensor = None
            return True
        return False

    # ------------------------------------------------------------------
    # model/unwrap/save
    # ------------------------------------------------------------------

    def unwrap_model(self, model: PreparedModel, keep_fp32_wrapper: bool = True):  # noqa: ARG002
        return model.module if isinstance(model, PreparedModel) else model

    def get_state_dict(self, model: PreparedModel, unwrap: bool = True):  # noqa: ARG002
        """Full (host-replicated numpy) state dict — the ZeRO-3 consolidation
        analogue (reference accelerator.py:3096)."""
        return ops.to_numpy(model.params)

    def save_model(self, model: PreparedModel, save_directory: str, max_shard_size: str = "10GB", safe_serialization: bool = True):
        from .checkpointing import save_model_weights

        save_model_weights(
            model.params, save_directory, max_shard_size=max_shard_size, safe_serialization=safe_serialization
        )

    def register_for_checkpointing(self, *objects) -> None:
        invalid = [o for o in objects if not (hasattr(o, "state_dict") and hasattr(o, "load_state_dict"))]
        if invalid:
            raise ValueError(
                f"All objects must have state_dict/load_state_dict methods; got invalid: {invalid}"
            )
        self._custom_objects.extend(objects)

    def register_save_state_pre_hook(self, hook: Callable):
        self._save_model_hooks.append(hook)
        return _RemovableHandle(self._save_model_hooks, hook)

    def register_load_state_pre_hook(self, hook: Callable):
        self._load_model_hooks.append(hook)
        return _RemovableHandle(self._load_model_hooks, hook)

    def save_state(self, output_dir: Optional[str] = None, **save_model_kwargs):
        """Save model/optimizer/scheduler/scaler/RNG/custom state.

        Atomic by default (``atomic=False`` opts out): staged into
        ``<output_dir>.tmp`` with a checksummed ``manifest.json`` and renamed
        into place only once complete, so a kill mid-save never corrupts an
        existing checkpoint (fault_tolerance.py documents the protocol).
        """
        from .checkpointing import save_accelerator_state

        return save_accelerator_state(self, output_dir, **save_model_kwargs)

    def load_state(self, input_dir: Optional[str] = None, **load_model_kwargs):
        """Restore state saved by ``save_state``. ``input_dir="auto"`` loads
        the newest checkpoint under the project's checkpoints dir whose
        manifest VALIDATES — torn or uncommitted dirs are skipped, so a run
        killed mid-save auto-resumes from the last complete state."""
        from .checkpointing import load_accelerator_state

        return load_accelerator_state(self, input_dir, **load_model_kwargs)

    def checkpoint_manager(self, checkpoint_dir: Optional[str] = None, **manager_kwargs):
        """A ``fault_tolerance.CheckpointManager`` bound to this accelerator:
        periodic atomic saves + rotation, SIGTERM-boundary saves inside the
        spot-VM grace window, and ``resume("auto")`` with exact dataloader
        rewind. See docs/fault_tolerance.md for the canonical loop."""
        from .fault_tolerance import CheckpointManager

        return CheckpointManager(self, checkpoint_dir=checkpoint_dir, **manager_kwargs)

    def elastic_coordinator(self, loss_fn: Callable, model: Optional[PreparedModel] = None, **kwargs):
        """A ``resilience.elastic.ElasticCoordinator`` driving this
        accelerator's compiled step with in-memory host-loss recovery:
        buddy-redundant ZeRO shards, live mesh shrink/regrow, and the
        chaos-drilled degradation ladder (buddy reshard → checkpoint reload
        → fail loudly). Pass ``membership=MembershipService(...)`` (or run
        under ``pod-launch --elastic --membership_dir``) to arm the
        epoch-fenced failure detector that NAMES the lost host — heartbeat
        silence, step-stamp stalls, and supervisor-published deaths all
        resolve to a concrete ``reshard(lost_host=...)``. See
        docs/resilience.md § Elastic training / § Failure detection &
        membership."""
        from .resilience.elastic import ElasticCoordinator

        return ElasticCoordinator(self, loss_fn, model=model, **kwargs)

    def skip_first_batches(self, dataloader, num_batches: int = 0):
        return skip_first_batches(dataloader, num_batches)

    def free_memory(self, *objects):
        """Release prepared-object references (reference accelerator.py:3027)."""
        self._models.clear()
        self._optimizers.clear()
        self._schedulers.clear()
        self._dataloaders.clear()
        self._grad_fns.clear()
        self._accum_step = 0
        import gc

        gc.collect()
        return objects

    def clear(self, *objects):
        return self.free_memory(*objects)

    # ------------------------------------------------------------------
    # tracking (full implementation in tracking.py)
    # ------------------------------------------------------------------

    def init_trackers(self, project_name: str, config: Optional[dict] = None, init_kwargs: Optional[dict] = None):
        from .tracking import filter_trackers

        self.trackers = filter_trackers(
            self.log_with, self.project_configuration.logging_dir, project_name, config, init_kwargs
        )

    def log(self, values: dict, step: Optional[int] = None, log_kwargs: Optional[dict] = None):
        if self.is_main_process:
            for tracker in self.trackers:
                tracker.log(values, step=step, **((log_kwargs or {}).get(tracker.name, {})))

    def end_training(self) -> None:
        # resilience first (its final guard check + summary record must land
        # before the telemetry sink closes), then telemetry's final flush
        # fans out through the trackers below. Collective when multi-host
        # (like this method generally: call end_training on every process).
        self.resilience.finish()
        self.telemetry.finish()
        for tracker in self.trackers:
            tracker.finish()

    def get_tracker(self, name: str, unwrap: bool = False):
        for tracker in self.trackers:
            if tracker.name == name:
                return tracker.tracker if unwrap else tracker
        raise ValueError(f"{name} is not an active tracker")

    def __deepcopy__(self, memo):
        # An Accelerator wraps process-global singletons; copying must not
        # fork them (reference accelerator.py:3268).
        return self


class _RemovableHandle:
    def __init__(self, hook_list: list, hook):
        self._list = hook_list
        self._hook = hook

    def remove(self) -> None:
        if self._hook in self._list:
            self._list.remove(self._hook)
