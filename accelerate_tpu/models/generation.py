"""Autoregressive generation with a static KV cache.

TPU-first: the decode step is one jit program with *static shapes* — the cache
is pre-allocated at ``max_len`` and written via ``dynamic_update_slice``, so
XLA compiles exactly two programs (prefill, decode) per (model, shape), cached
on the model instance and reused across ``generate`` calls. The per-token path
is what the reference's big-model-inference benchmark measures
(benchmarks/big_model_inference.py per-token seconds).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .attention import rotary_embedding
from .config import TransformerConfig
from .llama import Llama, decoder_layer, rms_norm


def init_cache(config: TransformerConfig, batch: int, max_len: int, dtype=jnp.bfloat16) -> dict:
    """Pre-allocated KV cache: stacked [L, B, T, KV, D] for the layer scan."""
    L, kv, d = config.num_layers, config.kv_heads, config.dim_per_head
    return {
        "k": jnp.zeros((L, batch, max_len, kv, d), dtype),
        "v": jnp.zeros((L, batch, max_len, kv, d), dtype),
        "length": jnp.zeros((), jnp.int32),
    }


def forward_with_cache(model: Llama, params: dict, input_ids: jax.Array, cache: dict):
    """Run ``input_ids`` (prefill block or single token) against the cache.

    Returns (logits for the LAST position [B, V], updated cache).
    """
    cfg = model.config
    b, s = input_ids.shape
    length = cache["length"]
    h = jnp.take(params["embed_tokens"], input_ids, axis=0)
    positions = length + jnp.arange(s)[None, :]
    cos, sin = rotary_embedding(positions, cfg.dim_per_head, cfg.rope_theta, dtype=h.dtype)

    # positions <= current are attendable: causal within the block, full over cache
    t = cache["k"].shape[2]
    query_pos = length + jnp.arange(s)
    key_pos = jnp.arange(t)
    mask = (key_pos[None, :] <= query_pos[:, None])[None, None]  # [1,1,S,T]

    def body(carry, xs):
        h = carry
        lp, k_cache, v_cache = xs
        h, new_cache = decoder_layer(
            cfg, h, lp, cos, sin, mask,
            cache={"k": k_cache, "v": v_cache, "length": length},
            dot_fn=getattr(model, "dot_fn", None),
        )
        return h, (new_cache["k"], new_cache["v"])

    h, (k_cache, v_cache) = jax.lax.scan(body, h, (params["layers"], cache["k"], cache["v"]))
    h = rms_norm(h, params["final_norm"], cfg.norm_eps)
    head = params["embed_tokens"].T if cfg.tie_embeddings else params["lm_head"]
    logits = h[:, -1] @ head.astype(h.dtype)
    new_cache = {"k": k_cache, "v": v_cache, "length": length + s}
    return logits.astype(jnp.float32), new_cache


def _jit_for(model, name: str, build):
    """Per-model jit cache so repeated generate() calls reuse compilations;
    dot_fn-invalidated (see utils/jit_cache.py)."""
    from ..utils.jit_cache import dot_keyed_jit

    return dot_keyed_jit(model, "_jit_cache", name, build)


def generate(
    model,
    params: dict,
    input_ids,  # [B, S] prompt
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_token_id: Optional[int] = None,
    return_device: bool = False,
) -> "np.ndarray | jax.Array":
    """Greedy (temperature=0) or sampled generation. Returns [B, S+new] ids.

    ``return_device=True`` returns the concatenated ids as a DEVICE array with
    no host fetch (and no eos truncation, which is host-side) — benchmarks use
    it so the clock can stop on ``block_until_ready`` instead of paying the
    transport's fixed device→host fetch latency inside the timed region.

    Works for any causal model implementing the decode protocol —
    ``init_cache(batch, max_len, dtype)`` + ``forward_with_cache(params, ids,
    cache) -> (last logits, cache)`` (GPT2 here) — with the llama family's
    protocol provided by this module."""
    if return_device and eos_token_id is not None:
        raise ValueError(
            "return_device=True skips eos truncation (a host-side operation); "
            "pass one or the other, or truncate after fetching."
        )
    input_ids = jnp.asarray(input_ids, jnp.int32)
    b, s = input_ids.shape
    max_len = s + max_new_tokens
    dtype = params["embed_tokens"].dtype
    if hasattr(model, "forward_with_cache"):
        cache = model.init_cache(b, max_len, dtype=dtype)
        fwc = model.forward_with_cache
    else:
        cache = init_cache(model.config, b, max_len, dtype=dtype)
        fwc = lambda p, ids, c: forward_with_cache(model, p, ids, c)  # noqa: E731

    prefill = _jit_for(model, "prefill", lambda: jax.jit(lambda p, ids, c: fwc(p, ids, c)))
    logits, cache = prefill(params, input_ids, cache)

    greedy = temperature <= 0.0

    def sample(logits, key):
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(key, logits / temperature, axis=-1).astype(jnp.int32)

    if rng is None:
        rng = jax.random.key(0)
    keys = jax.random.split(rng, max_new_tokens)
    first = sample(logits, keys[0])

    def decode_loop(params, cache, first, keys):
        def step(carry, key):
            cache, token = carry
            logits, cache = fwc(params, token[:, None], cache)
            nxt = sample(logits, key)
            return (cache, nxt), nxt

        return jax.lax.scan(step, (cache, first), keys)

    if max_new_tokens > 1:
        # temperature is baked into the traced program — key the cache on it
        decode = _jit_for(model, f"decode_g{greedy}_t{temperature}", lambda: jax.jit(decode_loop))
        (_, _), rest = decode(params, cache, first, keys[1:])
        tokens = jnp.concatenate([first[:, None], rest.T], axis=1)
    else:
        tokens = first[:, None]
    if return_device:
        return jnp.concatenate([input_ids, tokens], axis=1)
    out = np.concatenate([np.asarray(input_ids), np.asarray(tokens)], axis=1)
    if eos_token_id is not None:
        # truncate after first EOS per row (host-side cosmetic)
        for row in range(b):
            hits = np.where(out[row, s:] == eos_token_id)[0]
            if hits.size:
                out[row, s + hits[0] + 1 :] = eos_token_id
    return out
