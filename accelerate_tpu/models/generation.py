"""Autoregressive generation with a static KV cache.

TPU-first: the decode step is one jit program with *static shapes* — the cache
is pre-allocated at ``max_len`` and written via ``dynamic_update_slice``, so
XLA compiles exactly two programs (prefill, decode) per (model, shape), cached
on the model instance and reused across ``generate`` calls. The per-token path
is what the reference's big-model-inference benchmark measures
(benchmarks/big_model_inference.py per-token seconds).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .attention import rotary_embedding
from .config import TransformerConfig
from .llama import Llama, decoder_layer, rms_norm


def init_cache(config: TransformerConfig, batch: int, max_len: int, dtype=jnp.bfloat16) -> dict:
    """Pre-allocated KV cache: stacked [L, B, T, KV, D] for the layer scan."""
    L, kv, d = config.num_layers, config.kv_heads, config.dim_per_head
    return {
        "k": jnp.zeros((L, batch, max_len, kv, d), dtype),
        "v": jnp.zeros((L, batch, max_len, kv, d), dtype),
        "length": jnp.zeros((), jnp.int32),
    }


def forward_with_cache(model: Llama, params: dict, input_ids: jax.Array, cache: dict):
    """Run ``input_ids`` (prefill block or single token) against the cache.

    Returns (logits for the LAST position [B, V], updated cache).
    """
    cfg = model.config
    b, s = input_ids.shape
    length = cache["length"]
    h = jnp.take(params["embed_tokens"], input_ids, axis=0)
    positions = length + jnp.arange(s)[None, :]
    cos, sin = rotary_embedding(positions, cfg.dim_per_head, cfg.rope_theta, dtype=h.dtype)

    # paged-kernel decode (serving engine, use_kernels=True): the cache's
    # "k"/"v" are the page POOL (scanned per layer) and "attend" masks inside
    # the kernel against "table"/"length" — no [S, T] mask to build here
    extra = {key: cache[key] for key in ("table", "attend") if key in cache}
    if extra:
        mask = None
    else:
        # positions <= current are attendable: causal within the block, full over cache
        t = cache["k"].shape[2]
        query_pos = length + jnp.arange(s)
        key_pos = jnp.arange(t)
        mask = (key_pos[None, :] <= query_pos[:, None])[None, None]  # [1,1,S,T]

    def body(carry, xs):
        h = carry
        lp, k_cache, v_cache = xs
        h, new_cache = decoder_layer(
            cfg, h, lp, cos, sin, mask,
            cache={"k": k_cache, "v": v_cache, "length": length, **extra},
            dot_fn=getattr(model, "dot_fn", None),
        )
        return h, (new_cache["k"], new_cache["v"])

    h, (k_cache, v_cache) = jax.lax.scan(body, h, (params["layers"], cache["k"], cache["v"]))
    h = rms_norm(h, params["final_norm"], cfg.norm_eps)
    head = params["embed_tokens"].T if cfg.tie_embeddings else params["lm_head"]
    logits = h[:, -1] @ head.astype(h.dtype)
    new_cache = {"k": k_cache, "v": v_cache, "length": length + s}
    return logits.astype(jnp.float32), new_cache


def forward_window_with_cache(model: Llama, params: dict, input_ids: jax.Array, cache: dict):
    """Speculative-verify window forward: like :func:`forward_with_cache`
    but returns logits for EVERY position ``[B, S, V]`` — the target model
    scores a whole k+1-token candidate window in one step and the engine
    needs the greedy token after each window position to find the longest
    agreeing prefix.

    Paged-attend protocol only: the causal mask inside the window lives in
    the ``attend`` hook (kernel or gathered reference), not here, so a cache
    without one cannot be scored correctly."""
    if "attend" not in cache:
        raise ValueError(
            "forward_window_with_cache requires the paged 'attend' protocol "
            "(the in-window causal mask lives in the attend hook)"
        )
    cfg = model.config
    b, s = input_ids.shape
    length = cache["length"]
    h = jnp.take(params["embed_tokens"], input_ids, axis=0)
    positions = length + jnp.arange(s)[None, :]
    cos, sin = rotary_embedding(positions, cfg.dim_per_head, cfg.rope_theta, dtype=h.dtype)
    extra = {key: cache[key] for key in ("table", "attend") if key in cache}

    def body(carry, xs):
        h = carry
        lp, k_cache, v_cache = xs
        h, new_cache = decoder_layer(
            cfg, h, lp, cos, sin, None,
            cache={"k": k_cache, "v": v_cache, "length": length, **extra},
            dot_fn=getattr(model, "dot_fn", None),
        )
        return h, (new_cache["k"], new_cache["v"])

    h, (k_cache, v_cache) = jax.lax.scan(body, h, (params["layers"], cache["k"], cache["v"]))
    h = rms_norm(h, params["final_norm"], cfg.norm_eps)
    head = params["embed_tokens"].T if cfg.tie_embeddings else params["lm_head"]
    logits = h @ head.astype(h.dtype)  # all positions, not just the last
    new_cache = {"k": k_cache, "v": v_cache, "length": length + s}
    return logits.astype(jnp.float32), new_cache


def resolve_window_protocol(model):
    """The window-forward half of the decode protocol: ``forward_window(
    params, ids, cache) -> (all-position logits [B, S, V], cache)``.

    Mirrors :func:`resolve_decode_protocol`: models that implement
    ``forward_window_with_cache`` themselves (GPT2) contribute their method;
    the llama family's (incl. GQA) lives in this module. The serving
    engine's speculative verify drives models exclusively through this."""
    if hasattr(model, "forward_window_with_cache"):
        return model.forward_window_with_cache
    return lambda p, ids, c: forward_window_with_cache(model, p, ids, c)


def _jit_for(model, name, build):
    """Per-model jit cache so repeated generate() calls reuse compilations;
    dot_fn-invalidated (see utils/jit_cache.py)."""
    from ..utils.jit_cache import dot_keyed_jit

    return dot_keyed_jit(model, "_jit_cache", name, build)


def resolve_decode_protocol(model):
    """``(init_cache, forward_with_cache)`` for any causal model.

    Models that implement the decode protocol themselves (GPT2) contribute
    their own methods; the llama family's protocol lives in this module.
    Both ``generate`` and the serving engine (``serving/``) drive models
    exclusively through this pair, so a new family only has to implement the
    protocol once to get batch generation AND continuous-batching serving.
    """
    if hasattr(model, "forward_with_cache"):
        return model.init_cache, model.forward_with_cache
    return (
        lambda batch, max_len, dtype=jnp.bfloat16: init_cache(model.config, batch, max_len, dtype=dtype),
        lambda p, ids, c: forward_with_cache(model, p, ids, c),
    )


def make_sampler(temperature: float):
    """Greedy (temperature<=0) or categorical token sampler over last-position
    logits [..., V] → int32 ids. Shared by generate() and the serving engine
    so the two paths can never sample differently at the same temperature."""
    greedy = temperature <= 0.0

    def sample(logits, key):
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(key, logits / temperature, axis=-1).astype(jnp.int32)

    return sample


def generate(
    model,
    params: dict,
    input_ids,  # [B, S] prompt
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_token_id: Optional[int] = None,
    return_device: bool = False,
) -> "np.ndarray | jax.Array":
    """Greedy (temperature=0) or sampled generation. Returns [B, S+new] ids.

    ``return_device=True`` returns the concatenated ids as a DEVICE array with
    no host fetch — benchmarks use it so the clock can stop on
    ``block_until_ready`` instead of paying the transport's fixed device→host
    fetch latency inside the timed region.

    ``eos_token_id`` carries a per-row done mask through the decode scan:
    once a row emits EOS, every later position feeds and emits EOS (a no-op
    token), so finished rows stop contributing fresh decode work and the
    output arrives already EOS-filled — on device, so it composes with
    ``return_device``.

    Works for any causal model implementing the decode protocol —
    ``init_cache(batch, max_len, dtype)`` + ``forward_with_cache(params, ids,
    cache) -> (last logits, cache)`` (GPT2 here) — with the llama family's
    protocol provided by this module."""
    input_ids = jnp.asarray(input_ids, jnp.int32)
    b, s = input_ids.shape
    max_len = s + max_new_tokens
    dtype = params["embed_tokens"].dtype
    cache_init, fwc = resolve_decode_protocol(model)
    cache = cache_init(b, max_len, dtype=dtype)

    prefill = _jit_for(model, "prefill", lambda: jax.jit(lambda p, ids, c: fwc(p, ids, c)))
    logits, cache = prefill(params, input_ids, cache)

    greedy = temperature <= 0.0
    sample = make_sampler(temperature)

    if rng is None:
        rng = jax.random.key(0)
    keys = jax.random.split(rng, max_new_tokens)
    first = sample(logits, keys[0])

    def decode_loop(params, cache, first, keys):
        def step(carry, key):
            cache, token, done = carry
            logits, cache = fwc(params, token[:, None], cache)
            nxt = sample(logits, key)
            if eos_token_id is not None:
                nxt = jnp.where(done, jnp.int32(eos_token_id), nxt)
                done = done | (nxt == eos_token_id)
            return (cache, nxt, done), nxt

        done = (
            first == eos_token_id if eos_token_id is not None else jnp.zeros(first.shape, bool)
        )
        return jax.lax.scan(step, (cache, first, done), keys)

    if max_new_tokens > 1:
        # temperature and the eos mask are baked into the traced program —
        # key the cache on both
        decode = _jit_for(
            model, f"decode_g{greedy}_t{temperature}_e{eos_token_id}", lambda: jax.jit(decode_loop)
        )
        (_, _, _), rest = decode(params, cache, first, keys[1:])
        tokens = jnp.concatenate([first[:, None], rest.T], axis=1)
    else:
        tokens = first[:, None]
    out = jnp.concatenate([input_ids, tokens], axis=1)
    return out if return_device else np.asarray(out)
