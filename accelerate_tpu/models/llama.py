"""Llama-family decoder, TPU-first.

Design (vs the reference's torch models, which it only orchestrates):
- parameters are a flat pytree with layers *stacked* on a leading L axis so the
  whole stack runs as one ``lax.scan`` — O(1) XLA program size in depth, and
  partition specs apply uniformly to every layer.
- attention/MLP projections carry explicit TP partition rules (megatron-style
  column/row split) that the sharding engine folds with the fsdp axis.
- activations get sharding constraints (batch over data axes, sequence over
  the ``sequence`` axis) so GSPMD propagates the layout end to end.
- bf16-friendly: RMSNorm and softmax accumulate in fp32.

Capability parity: the model families the reference's examples/benchmarks
exercise via transformers (GPT-J/NeoX/OPT/Llama — benchmarks/README.md:31-37,
tests/fsdp Llama-7B) are covered by this one parametric family (config.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils.constants import (
    MESH_AXIS_DATA,
    MESH_AXIS_EXPERT,
    MESH_AXIS_FSDP,
    MESH_AXIS_SEQUENCE,
    MESH_AXIS_TENSOR,
)
from .attention import apply_rotary, dense_init, dot_product_attention, dropout, rotary_embedding
from .config import TransformerConfig, get_config

BATCH_AXES = (MESH_AXIS_DATA, MESH_AXIS_FSDP)


def _constrain(x: jax.Array, *spec) -> jax.Array:
    """Best-effort sharding constraint (no-op outside a mesh context)."""
    try:
        return jax.lax.with_sharding_constraint(x, P(*spec))
    except (ValueError, RuntimeError):
        return x


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return (x * weight.astype(jnp.float32)).astype(dtype)


def decoder_layer(
    cfg: TransformerConfig,
    h: jax.Array,  # [B, S, H]
    lp: dict,  # one layer's params
    cos: jax.Array,
    sin: jax.Array,
    mask: Optional[jax.Array],
    causal: bool = True,
    cache: Optional[dict] = None,  # {"k","v"} [B, T, KV, D] + write offset "length"
    dropout_rngs: tuple = (None, None),
    dropout_rate: float = 0.0,
    attention_fn=None,  # e.g. ring attention for sequence-sharded activations
    kv_mask=None,  # raw [B, S] validity mask for attention_fn implementations
    dot_fn=None,  # e.g. ops.fp8.fp8_dot for fp8 projection compute
    return_aux: bool = False,  # also return the MoE load-balance loss term
):
    """The one llama decoder layer used by every execution path (training
    scan, KV-cache decode, streamed big-model inference). Returns
    (h, updated_cache_or_None), plus the per-layer MoE aux loss (0 for dense
    layers) when ``return_aux``."""
    from .attention import dropout, resolve_dot  # local import to avoid cycle at module load

    dot = resolve_dot(dot_fn)
    b, s = h.shape[:2]
    nh, nkv, d = cfg.num_heads, cfg.kv_heads, cfg.dim_per_head
    x = rms_norm(h, lp["attn_norm"], cfg.norm_eps)
    q = dot(x, lp["wq"]).reshape(b, s, nh, d)
    k = dot(x, lp["wk"]).reshape(b, s, nkv, d)
    v = dot(x, lp["wv"]).reshape(b, s, nkv, d)
    q = apply_rotary(q, cos, sin)
    k = apply_rotary(k, cos, sin)
    new_cache = None
    if cache is not None and "attend" in cache:
        # paged-kernel decode (serving engine, use_kernels=True): the cache
        # carries one layer of the page POOL plus this slot's table row, and
        # ``attend`` (ops/paged_attention.py) reads the pool directly — no
        # gathered view, no in-layer cache write. The new token's K/V return
        # as the cache delta; the engine scatters them into the pool.
        attn = cache["attend"](q, k, v, cache)
        new_cache = {"k": k, "v": v, "length": cache["length"]}
    elif cache is not None:
        k_cache = jax.lax.dynamic_update_slice(cache["k"], k.astype(cache["k"].dtype), (0, cache["length"], 0, 0))
        v_cache = jax.lax.dynamic_update_slice(cache["v"], v.astype(cache["v"].dtype), (0, cache["length"], 0, 0))
        attn = dot_product_attention(q, k_cache.astype(q.dtype), v_cache.astype(q.dtype), mask=mask)
        new_cache = {"k": k_cache, "v": v_cache, "length": cache["length"]}
    elif attention_fn is not None:
        attn = attention_fn(q, k, v, kv_mask)
    else:
        attn = dot_product_attention(q, k, v, mask=mask, causal=causal)
    attn_out = dot(attn.reshape(b, s, nh * d), lp["wo"])
    if dropout_rngs[0] is not None:
        attn_out = dropout(attn_out, dropout_rate, dropout_rngs[0])
    h = h + attn_out
    x = rms_norm(h, lp["mlp_norm"], cfg.norm_eps)
    aux = jnp.zeros((), jnp.float32)
    if "router" in lp:
        # MoE decoder (config.num_experts > 1): top-k routed expert MLP over
        # the `expert` mesh axis; Llama.apply sums the per-layer balance loss
        from .moe import routed_mlp

        mlp_out, aux = routed_mlp(
            x, lp["router"], lp["moe_up"], lp["moe_down"],
            top_k=cfg.moe_top_k, capacity_factor=cfg.moe_capacity_factor,
        )
    else:
        gated = jax.nn.silu(dot(x, lp["w_gate"])) * dot(x, lp["w_up"])
        mlp_out = dot(gated, lp["w_down"])
    if dropout_rngs[1] is not None:
        mlp_out = dropout(mlp_out, dropout_rate, dropout_rngs[1])
    h = h + mlp_out
    if return_aux:
        return h, new_cache, aux
    return h, new_cache


class Llama:
    """(init, apply) pair for a llama-style causal LM."""

    def __init__(self, config: TransformerConfig | str):
        self.config = get_config(config) if isinstance(config, str) else config
        assert self.config.arch == "llama"
        # Swapped in by Accelerator.prepare_model when the mesh has a sequence
        # axis (ring attention) or a pipeline axis (GPipe layer schedule).
        self.attention_fn = None
        self.pipeline_fn = None
        # fp8 projection compute (ops/fp8.fp8_dot), set by prepare_model when
        # mixed_precision="fp8"; None = plain matmul in the compute dtype.
        self.dot_fn = None
        # Per-layer activation checkpointing, set by Accelerator.prepare_model:
        # falsy = off; a jax.checkpoint policy callable (or True for
        # save-nothing) decides what survives inside each scanned layer — the
        # carried layer input is always saved, so save-nothing gives Megatron
        # "recompute_activations" semantics.
        self.remat_layers = False

    # -- parameters --------------------------------------------------------

    def init(self, rng: jax.Array) -> dict:
        # One compiled program instead of ~10 per-tensor RNG dispatches — on
        # remote-attached TPUs each dispatch is a round trip. The jit wrapper
        # is cached on the instance so repeated init() reuses the compile.
        if not hasattr(self, "_init_jit"):
            self._init_jit = jax.jit(self._init)
        return self._init_jit(rng)

    def _init(self, rng: jax.Array) -> dict:
        cfg = self.config
        h, i, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
        d, nh, nkv, L = cfg.dim_per_head, cfg.num_heads, cfg.kv_heads, cfg.num_layers
        keys = iter(jax.random.split(rng, 16))
        dense = dense_init
        # key consumption order is part of the format: embed → attention →
        # mlp → lm_head, so dense-model seeds reproduce across versions
        params = {
            "embed_tokens": jax.random.normal(next(keys), (v, h), jnp.float32) * 0.02,
            "layers": {
                "attn_norm": jnp.ones((L, h), jnp.float32),
                "wq": dense(next(keys), (L, h, nh * d), h),
                "wk": dense(next(keys), (L, h, nkv * d), h),
                "wv": dense(next(keys), (L, h, nkv * d), h),
                "wo": dense(next(keys), (L, nh * d, h), nh * d),
                "mlp_norm": jnp.ones((L, h), jnp.float32),
            },
            "final_norm": jnp.ones((h,), jnp.float32),
        }
        if cfg.num_experts > 1:
            E = cfg.num_experts
            params["layers"]["router"] = dense(next(keys), (L, h, E), h)
            params["layers"]["moe_up"] = dense(next(keys), (L, E, h, i), h)
            params["layers"]["moe_down"] = dense(next(keys), (L, E, i, h), i)
        else:
            params["layers"]["w_gate"] = dense(next(keys), (L, h, i), h)
            params["layers"]["w_up"] = dense(next(keys), (L, h, i), h)
            params["layers"]["w_down"] = dense(next(keys), (L, i, h), i)
        if not cfg.tie_embeddings:
            params["lm_head"] = dense(next(keys), (h, v), h)
        return params

    # -- sharding ----------------------------------------------------------

    def partition_rules(self) -> list[tuple[str, tuple]]:
        """Megatron-style TP: attention split by heads, MLP by intermediate;
        row-parallel projections bring activations back (GSPMD inserts the
        reduce). Leading dim of stacked layers is never sharded (scan axis)."""
        from ..utils.constants import MESH_AXIS_PIPELINE

        t = MESH_AXIS_TENSOR
        p = MESH_AXIS_PIPELINE  # stacked-layer leading dim; size-1 axis = no-op
        return [
            (r"embed_tokens", (t, None)),          # vocab-parallel embedding
            (r"layers/(wq|wk|wv)", (p, None, t)),  # column-parallel
            (r"layers/wo", (p, t, None)),          # row-parallel
            (r"layers/(w_gate|w_up)", (p, None, t)),
            (r"layers/w_down", (p, t, None)),
            # MoE: experts over the expert axis, TP inside each expert
            (r"layers/router", (p, None, None)),
            (r"layers/moe_up", (p, MESH_AXIS_EXPERT, None, t)),
            (r"layers/moe_down", (p, MESH_AXIS_EXPERT, t, None)),
            (r"layers/(attn_norm|mlp_norm)", (p, None)),
            (r"final_norm", (None,)),
            (r"lm_head", (None, t)),
        ]

    # -- forward -----------------------------------------------------------

    def apply(
        self,
        params: dict,
        input_ids: jax.Array,  # [B, S] int32
        attention_mask: Optional[jax.Array] = None,  # [B, S] 1=real
        positions: Optional[jax.Array] = None,
        dropout_rng: Optional[jax.Array] = None,
        return_aux: bool = False,  # also return the summed MoE balance loss
    ) -> jax.Array:
        """Logits [B, S, V]. Pass ``dropout_rng`` to enable config.dropout_rate
        residual dropout during training; ``return_aux`` adds the summed MoE
        load-balance loss as a second output (0 for dense configs)."""
        cfg = self.config
        b, s = input_ids.shape
        d, nh, nkv = cfg.dim_per_head, cfg.num_heads, cfg.kv_heads

        h = jnp.take(params["embed_tokens"], input_ids, axis=0)
        h = _constrain(h, BATCH_AXES, MESH_AXIS_SEQUENCE, None)
        if positions is None:
            positions = jnp.arange(s)[None, :]
        elif positions.ndim == 1:
            # normalize to [1, S]: a 1-D table would make cos/sin 2-D, and a
            # seq length equal to the batch would then read as per-microbatch
            # to the pipeline schedule's leading-dim inference
            positions = positions[None, :]
        cos, sin = rotary_embedding(positions, d, cfg.rope_theta, dtype=h.dtype)

        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)  # [B,1,1,T]

        use_dropout = dropout_rng is not None and cfg.dropout_rate > 0.0
        if use_dropout:
            layer_rngs = jax.random.split(dropout_rng, cfg.num_layers * 2).reshape(cfg.num_layers, 2)

        def layer(h, xs):
            lp = xs[0] if use_dropout else xs
            rngs = tuple(xs[1]) if use_dropout else (None, None)
            h, _, aux = decoder_layer(
                cfg, h, lp, cos, sin, mask, causal=True,
                dropout_rngs=rngs, dropout_rate=cfg.dropout_rate,
                attention_fn=self.attention_fn, kv_mask=attention_mask,
                dot_fn=self.dot_fn, return_aux=True,
            )
            h = _constrain(h, BATCH_AXES, MESH_AXIS_SEQUENCE, None)
            return h, aux

        total_aux = jnp.zeros((), jnp.float32)
        if self.pipeline_fn is not None:
            # dropout rngs fold in per (layer, microbatch) inside the schedule
            # (pipeline.fold_pipeline_dropout_rng); the MoE balance loss is
            # accumulated per executed chunk and psum-reduced over the axis.
            # cos/sin are broadcast consts when batch-invariant (positions
            # default) and per-microbatch consts for per-row positions. The
            # raw [B, S] mask rides along for the flash-attention hook.
            h, total_aux = self.pipeline_fn(
                params["layers"], h, mask, cos, sin, attention_mask,
                dropout_rng=dropout_rng if use_dropout else None,
            )
        else:
            xs = (params["layers"], layer_rngs) if use_dropout else params["layers"]
            body = (
                jax.checkpoint(layer, policy=self.remat_layers if callable(self.remat_layers) else None)
                if self.remat_layers
                else layer
            )
            h, aux_per_layer = jax.lax.scan(body, h, xs)
            total_aux = aux_per_layer.sum()
        h = rms_norm(h, params["final_norm"], cfg.norm_eps)
        head = params["embed_tokens"].T if cfg.tie_embeddings else params["lm_head"]
        logits = h @ head.astype(h.dtype)
        if return_aux:
            return logits, total_aux
        return logits

    # sequence dimension of the pipeline activations and side inputs
    # (mask, cos, sin, kv_mask) — lets the schedule combine with a sequence
    # axis (ring attention inside each stage)
    pipeline_seq_dims = {"h": 1, "consts": (3, 1, 1, 1)}
    # cos/sin stay shape-inferred (batch-invariant [1, S, D/2] with default
    # positions, per-row [B, S, D/2] otherwise); mask/kv_mask are batched
    pipeline_const_kinds = ("mb", None, None, "mb")

    # -- pipeline hook (parallel/pipeline.make_pipeline_layers_fn) -----------

    def pipeline_layer(self, lp, h, rng, mask, cos, sin, kv_mask=None):
        """One decoder layer in the pipeline schedule's ``layer_fn`` contract:
        ``(lp, h, rng, *consts) -> (h, aux)``. ``rng`` is the schedule's
        per-(layer, microbatch) folded key (None when dropout is off);
        ``aux`` is the MoE balance loss term (0 for dense layers). The
        ``attention_fn`` hook applies inside the pipeline too: the flash
        kernel on TPU, or — when the mesh also has a sequence axis — the
        manual-region ring (make_local_ring_attention), which prepare_model
        swaps in because the schedule is then manual over both axes."""
        rngs = (None, None) if rng is None else tuple(jax.random.split(rng))
        h, _, aux = decoder_layer(
            self.config, h, lp, cos, sin, mask, causal=True,
            dropout_rngs=rngs, dropout_rate=self.config.dropout_rate,
            attention_fn=self.attention_fn, kv_mask=kv_mask,
            dot_fn=self.dot_fn, return_aux=True,
        )
        return h, aux

    # -- streaming protocol (big_modeling.StreamedModel full-sequence path) --

    def stream_prefix(self, resident, input_ids, attention_mask=None):
        cfg = self.config
        input_ids = jnp.asarray(input_ids, jnp.int32)
        b, s = input_ids.shape
        h = jnp.take(resident["embed_tokens"], input_ids, axis=0)
        cos, sin = rotary_embedding(jnp.arange(s)[None, :], cfg.dim_per_head, cfg.rope_theta, dtype=h.dtype)
        mask = None
        if attention_mask is not None:
            mask = jnp.asarray(attention_mask)[:, None, None, :].astype(bool)
        return (h, cos, sin, mask)

    def stream_layer(self, carry, lp):
        h, cos, sin, mask = carry
        h, _ = decoder_layer(self.config, h, lp, cos, sin, mask, causal=True, dot_fn=self.dot_fn)
        return (h, cos, sin, mask)

    def stream_suffix(self, resident, carry):
        h, _, _, _ = carry
        cfg = self.config
        h = rms_norm(h, resident["final_norm"], cfg.norm_eps)
        head = resident["embed_tokens"].T if cfg.tie_embeddings else resident["lm_head"]
        return (h @ head.astype(h.dtype)).astype(jnp.float32)

    # -- streamed decode protocol (big_modeling.StreamedModel.generate) ------

    def init_layer_cache(self, batch: int, max_len: int, dtype=jnp.bfloat16) -> dict:
        cfg = self.config
        return {
            "k": jnp.zeros((batch, max_len, cfg.kv_heads, cfg.dim_per_head), dtype),
            "v": jnp.zeros((batch, max_len, cfg.kv_heads, cfg.dim_per_head), dtype),
        }

    def decode_prefix(self, resident, input_ids, length, max_len: int):
        cfg = self.config
        input_ids = jnp.asarray(input_ids, jnp.int32)
        b, s = input_ids.shape
        h = jnp.take(resident["embed_tokens"], input_ids, axis=0)
        positions = length + jnp.arange(s)[None, :]
        cos, sin = rotary_embedding(positions, cfg.dim_per_head, cfg.rope_theta, dtype=h.dtype)
        q_pos = length + jnp.arange(s)
        mask = (jnp.arange(max_len)[None, :] <= q_pos[:, None])[None, None]
        return (h, cos, sin, mask)

    def stream_layer_cached(self, carry, lp, cache, length):
        h, cos, sin, mask = carry
        h, nc = decoder_layer(
            self.config, h, lp, cos, sin, mask,
            cache={"k": cache["k"], "v": cache["v"], "length": length},
            dot_fn=self.dot_fn,
        )
        return (h, cos, sin, mask), {"k": nc["k"], "v": nc["v"]}

    def decode_suffix(self, resident, carry):
        h, _, _, _ = carry
        cfg = self.config
        h = rms_norm(h, resident["final_norm"], cfg.norm_eps)
        head = resident["embed_tokens"].T if cfg.tie_embeddings else resident["lm_head"]
        return (h[:, -1] @ head.astype(h.dtype)).astype(jnp.float32)

    # -- loss helper -------------------------------------------------------

    @staticmethod
    def loss_fn(model: "Llama"):
        """Next-token cross-entropy over a batch {input_ids, [attention_mask]};
        MoE configs add the router load-balance loss."""
        moe = model.config.num_experts > 1

        def fn(params, batch):
            input_ids = batch["input_ids"]
            if moe:
                logits, aux = model.apply(
                    params, input_ids, batch.get("attention_mask"), return_aux=True
                )
            else:
                logits = model.apply(params, input_ids, batch.get("attention_mask"))
                aux = 0.0
            targets = input_ids[:, 1:]
            logits = logits[:, :-1].astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
            if "attention_mask" in batch:
                w = batch["attention_mask"][:, 1:].astype(jnp.float32)
                return (nll * w).sum() / jnp.maximum(w.sum(), 1.0) + aux
            return nll.mean() + aux

        return fn
