"""T5-family encoder-decoder, TPU-first.

Capability parity: the reference ships a T5 big-model-inference walkthrough
(examples/inference/t5.py:1-64, pippy PP over an encoder-decoder) and its
benchmark table's T0pp-11B row (benchmarks/README.md:35) is a T5 derivative.
This is that family rebuilt on the stacked-layer/scan design of
models/llama.py: cross-attention, T5 relative-position buckets, unscaled
attention (the 1/sqrt(d) factor is folded into the init, as in the paper),
RMSNorm, ReLU feed-forward, shared embeddings with d_model^-0.5 logit scaling.

Streaming layout: the DECODER stack is the ``layers`` tree — during
generation the decoder runs once per token while the encoder runs once per
sequence, so the decoder is what big-model dispatch streams through the HBM
window; the encoder rides with the resident components (still host-placeable
via the device map — ``resident_tree`` streams them per call). Cross-attention
K/V are recomputed from the carried encoder output each step instead of being
cached: a streamed model is DMA-bound, and the recompute keeps the per-layer
cache layout identical to the causal families'.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..utils.constants import MESH_AXIS_SEQUENCE, MESH_AXIS_TENSOR
from .attention import dense_init, dropout, resolve_dot
from .config import TransformerConfig, get_config
from .llama import BATCH_AXES, _constrain, rms_norm

NEG_INF = -1e30


def relative_position_bucket(
    relative_position: jax.Array, bidirectional: bool, num_buckets: int, max_distance: int
) -> jax.Array:
    """T5 relative-position bucketing (Raffel et al. 2020 §2.1): exact buckets
    up to num_buckets/2, log-spaced beyond, clamped at max_distance."""
    ret = jnp.zeros_like(relative_position)
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / math.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


def relative_bias(
    table: jax.Array,  # [num_buckets, n_heads]
    q_positions: jax.Array,  # [S_q]
    k_positions: jax.Array,  # [S_k]
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> jax.Array:
    """[1, n_heads, S_q, S_k] additive attention bias."""
    rel = k_positions[None, :] - q_positions[:, None]  # [S_q, S_k]
    buckets = relative_position_bucket(rel, bidirectional, num_buckets, max_distance)
    bias = table[buckets]  # [S_q, S_k, n_heads]
    return jnp.transpose(bias, (2, 0, 1))[None].astype(jnp.float32)


def t5_attention(q, k, v, bias, mask) -> jax.Array:
    """Unscaled dot-product attention with an additive position bias.

    q [B,Sq,N,D], k/v [B,Sk,N,D]; bias [1,N,Sq,Sk] fp32 or None;
    mask broadcastable to [B,1,Sq,Sk] bool (True = attend) or None.
    """
    scores = jnp.einsum("bqnd,bknd->bnqk", q, k).astype(jnp.float32)
    if bias is not None:
        scores = scores + bias
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bnqk,bknd->bqnd", p, v)


class T5:
    """(init, apply) pair for a T5-style seq2seq LM (shared embeddings)."""

    is_encoder_decoder = True

    def __init__(self, config: TransformerConfig | str):
        self.config = get_config(config) if isinstance(config, str) else config
        assert self.config.arch == "t5"
        # hooks set by Accelerator.prepare_model (see models/llama.py).
        # The two stacks pipeline separately: the encoder schedule runs to
        # completion, then the decoder schedule runs with the encoder output
        # riding along as a per-microbatch side input (cross-attention).
        self.remat_layers = False
        self.dot_fn = None
        self.pipeline_fn = None  # decoder stack (params["layers"])
        self.enc_pipeline_fn = None  # encoder stack (params["encoder"])
        # attention hook: engaged only when it declares supports_bias (the
        # flash auto-attention does; ring hooks don't carry T5's additive
        # relative-position bias and are skipped — einsum stays exact)
        self.attention_fn = None

    # -- parameters --------------------------------------------------------

    def init(self, rng: jax.Array) -> dict:
        if not hasattr(self, "_init_jit"):
            self._init_jit = jax.jit(self._init)
        return self._init_jit(rng)

    def _init(self, rng: jax.Array) -> dict:
        cfg = self.config
        h, i, v, L = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size, cfg.num_layers
        inner = cfg.num_heads * cfg.dim_per_head
        keys = iter(jax.random.split(rng, 24))
        dense = dense_init
        return {
            "shared_embed": jax.random.normal(next(keys), (v, h), jnp.float32) * 0.02,
            "enc_rel_bias": jax.random.normal(next(keys), (cfg.rel_buckets, cfg.num_heads), jnp.float32) * 0.1,
            "dec_rel_bias": jax.random.normal(next(keys), (cfg.rel_buckets, cfg.num_heads), jnp.float32) * 0.1,
            "encoder": {
                "attn_norm": jnp.ones((L, h), jnp.float32),
                "wq": dense(next(keys), (L, h, inner), h),
                "wk": dense(next(keys), (L, h, inner), h),
                "wv": dense(next(keys), (L, h, inner), h),
                "wo": dense(next(keys), (L, inner, h), inner),
                "mlp_norm": jnp.ones((L, h), jnp.float32),
                "wi": dense(next(keys), (L, h, i), h),
                "wo_ff": dense(next(keys), (L, i, h), i),
            },
            "enc_final_norm": jnp.ones((h,), jnp.float32),
            # the DECODER stack is named "layers": it is what generation
            # streams through the big-model HBM window (module docstring)
            "layers": {
                "self_norm": jnp.ones((L, h), jnp.float32),
                "self_wq": dense(next(keys), (L, h, inner), h),
                "self_wk": dense(next(keys), (L, h, inner), h),
                "self_wv": dense(next(keys), (L, h, inner), h),
                "self_wo": dense(next(keys), (L, inner, h), inner),
                "cross_norm": jnp.ones((L, h), jnp.float32),
                "cross_wq": dense(next(keys), (L, h, inner), h),
                "cross_wk": dense(next(keys), (L, h, inner), h),
                "cross_wv": dense(next(keys), (L, h, inner), h),
                "cross_wo": dense(next(keys), (L, inner, h), inner),
                "mlp_norm": jnp.ones((L, h), jnp.float32),
                "wi": dense(next(keys), (L, h, i), h),
                "wo_ff": dense(next(keys), (L, i, h), i),
            },
            "dec_final_norm": jnp.ones((h,), jnp.float32),
        }

    # -- sharding ----------------------------------------------------------

    def partition_rules(self) -> list[tuple[str, tuple]]:
        """Megatron TP: q/k/v/wi column-parallel, output projections
        row-parallel; the relative-bias tables replicate (tiny). Stacked
        leading dims shard over the pipeline axis (size-1 = no-op)."""
        from ..utils.constants import MESH_AXIS_PIPELINE

        t = MESH_AXIS_TENSOR
        p = MESH_AXIS_PIPELINE
        return [
            (r"shared_embed", (t, None)),
            (r"rel_bias", (None, None)),
            (r"(encoder|layers)/.*w[qkv]$", (p, None, t)),
            (r"(encoder|layers)/.*wo$", (p, t, None)),
            (r"(encoder|layers)/wi", (p, None, t)),
            (r"(encoder|layers)/wo_ff", (p, t, None)),
            (r"(encoder|layers)/.*norm", (p, None)),
            (r"norm", (None,)),
        ]

    # -- layer bodies -------------------------------------------------------

    def _attn(self, q, k, v, bias, mask, kv_mask, causal: bool, use_hook: bool = True):
        """Self/cross attention through the hook when it can carry the bias
        (flash kernel path), else the exact einsum. ``mask`` is the 4-D
        broadcast mask for the einsum; ``kv_mask`` the raw [B, S] validity
        the kernel wants (None = nothing masked beyond causality).
        ``use_hook=False`` forces the einsum — callers that only hold the
        4-D mask (streamed decoder layers) must not drop padding by handing
        the hook a None kv_mask."""
        fn = self.attention_fn
        if use_hook and fn is not None and getattr(fn, "supports_bias", False):
            return fn(q, k, v, kv_mask, bias=bias, scale=1.0, causal=causal)
        return t5_attention(q, k, v, bias, mask)

    def _enc_layer(self, h, lp, bias, mask, rngs=(None, None), kv_mask=None):
        cfg = self.config
        dot = resolve_dot(self.dot_fn)
        b, s = h.shape[:2]
        nh, d = cfg.num_heads, cfg.dim_per_head
        x = rms_norm(h, lp["attn_norm"], cfg.norm_eps)
        q = dot(x, lp["wq"]).reshape(b, s, nh, d)
        k = dot(x, lp["wk"]).reshape(b, s, nh, d)
        v = dot(x, lp["wv"]).reshape(b, s, nh, d)
        attn = self._attn(q, k, v, bias, mask, kv_mask, causal=False)
        attn_out = dot(attn.reshape(b, s, nh * d), lp["wo"])
        if rngs[0] is not None:
            attn_out = dropout(attn_out, cfg.dropout_rate, rngs[0])
        h = h + attn_out
        x = rms_norm(h, lp["mlp_norm"], cfg.norm_eps)
        mlp_out = dot(jax.nn.relu(dot(x, lp["wi"])), lp["wo_ff"])
        if rngs[1] is not None:
            mlp_out = dropout(mlp_out, cfg.dropout_rate, rngs[1])
        return h + mlp_out

    def _dec_layer(
        self, h, lp, self_bias, self_mask, enc_out, enc_mask,
        rngs=(None, None, None), cache=None, length=None, kv_masks=(None, None),
        use_hook: bool = True,
    ):
        """One decoder layer: self-attn (+rel bias) → cross-attn → FF.

        ``cache`` holds {"k","v"} [B, T, N, D] self-attention KV plus the
        write offset ``length`` during incremental decode. Cross-attention
        K/V are always computed from ``enc_out`` (module docstring).
        """
        cfg = self.config
        dot = resolve_dot(self.dot_fn)
        b, s = h.shape[:2]
        nh, d = cfg.num_heads, cfg.dim_per_head
        x = rms_norm(h, lp["self_norm"], cfg.norm_eps)
        q = dot(x, lp["self_wq"]).reshape(b, s, nh, d)
        k = dot(x, lp["self_wk"]).reshape(b, s, nh, d)
        v = dot(x, lp["self_wv"]).reshape(b, s, nh, d)
        new_cache = None
        if cache is not None:
            k_cache = jax.lax.dynamic_update_slice(cache["k"], k.astype(cache["k"].dtype), (0, length, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(cache["v"], v.astype(cache["v"].dtype), (0, length, 0, 0))
            attn = t5_attention(q, k_cache.astype(q.dtype), v_cache.astype(q.dtype), self_bias, self_mask)
            new_cache = {"k": k_cache, "v": v_cache}
        else:
            attn = self._attn(q, k, v, self_bias, self_mask, kv_masks[0], causal=True, use_hook=use_hook)
        attn_out = dot(attn.reshape(b, s, nh * d), lp["self_wo"])
        if rngs[0] is not None:
            attn_out = dropout(attn_out, cfg.dropout_rate, rngs[0])
        h = h + attn_out

        x = rms_norm(h, lp["cross_norm"], cfg.norm_eps)
        q = dot(x, lp["cross_wq"]).reshape(b, s, nh, d)
        ek = dot(enc_out, lp["cross_wk"]).reshape(b, enc_out.shape[1], nh, d)
        ev = dot(enc_out, lp["cross_wv"]).reshape(b, enc_out.shape[1], nh, d)
        cross = self._attn(q, ek, ev, None, enc_mask, kv_masks[1], causal=False, use_hook=use_hook)
        cross_out = dot(cross.reshape(b, s, nh * d), lp["cross_wo"])
        if rngs[1] is not None:
            cross_out = dropout(cross_out, cfg.dropout_rate, rngs[1])
        h = h + cross_out

        x = rms_norm(h, lp["mlp_norm"], cfg.norm_eps)
        mlp_out = dot(jax.nn.relu(dot(x, lp["wi"])), lp["wo_ff"])
        if rngs[2] is not None:
            mlp_out = dropout(mlp_out, cfg.dropout_rate, rngs[2])
        h = h + mlp_out
        return (h, new_cache) if cache is not None else h

    # -- forward -----------------------------------------------------------

    def encode(
        self,
        params: dict,
        input_ids: jax.Array,  # [B, S] int32
        attention_mask: Optional[jax.Array] = None,  # [B, S] 1=real
        dropout_rng: Optional[jax.Array] = None,
        use_hooks: bool = True,
    ) -> jax.Array:
        """Encoder hidden states [B, S, H] (final-norm applied).

        ``use_hooks=False`` bypasses the mesh-bound ``enc_pipeline_fn`` hook:
        the streaming executor runs single-device, and a stale shard_map
        schedule from an earlier prepare_model would be traced into its jitted
        programs (mirrors Bert/GPT2's ``use_attention_hook=False``).
        """
        cfg = self.config
        b, s = input_ids.shape
        h = jnp.take(params["shared_embed"], input_ids, axis=0)
        h = _constrain(h, BATCH_AXES, MESH_AXIS_SEQUENCE, None)
        positions = jnp.arange(s)
        bias = relative_bias(
            params["enc_rel_bias"], positions, positions,
            bidirectional=True, num_buckets=cfg.rel_buckets, max_distance=cfg.rel_max_distance,
        )
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        use_dropout = dropout_rng is not None and cfg.dropout_rate > 0.0
        if use_hooks and self.enc_pipeline_fn is not None:
            h, _ = self.enc_pipeline_fn(
                params["encoder"], h, mask, bias,
                dropout_rng=dropout_rng if use_dropout else None,
            )
            return rms_norm(h, params["enc_final_norm"], cfg.norm_eps)
        if use_dropout:
            layer_rngs = jax.random.split(dropout_rng, cfg.num_layers * 2).reshape(cfg.num_layers, 2)

        def layer(h, xs):
            lp = xs[0] if use_dropout else xs
            rngs = tuple(xs[1]) if use_dropout else (None, None)
            h = self._enc_layer(h, lp, bias, mask, rngs, kv_mask=attention_mask)
            return _constrain(h, BATCH_AXES, MESH_AXIS_SEQUENCE, None), None

        xs = (params["encoder"], layer_rngs) if use_dropout else params["encoder"]
        body = (
            jax.checkpoint(layer, policy=self.remat_layers if callable(self.remat_layers) else None)
            if self.remat_layers
            else layer
        )
        h, _ = jax.lax.scan(body, h, xs)
        return rms_norm(h, params["enc_final_norm"], cfg.norm_eps)

    def apply(
        self,
        params: dict,
        input_ids: jax.Array,  # [B, S_enc] int32 encoder inputs
        decoder_input_ids: jax.Array,  # [B, S_dec] int32 (shifted-right labels)
        attention_mask: Optional[jax.Array] = None,
        decoder_attention_mask: Optional[jax.Array] = None,
        dropout_rng: Optional[jax.Array] = None,
    ) -> jax.Array:
        """Decoder logits [B, S_dec, V]."""
        cfg = self.config
        use_dropout = dropout_rng is not None and cfg.dropout_rate > 0.0
        enc_rng = dec_rng = None
        if use_dropout:
            enc_rng, dec_rng = jax.random.split(dropout_rng)
        enc_out = self.encode(params, input_ids, attention_mask, dropout_rng=enc_rng)

        b, s = decoder_input_ids.shape
        h = jnp.take(params["shared_embed"], decoder_input_ids, axis=0)
        h = _constrain(h, BATCH_AXES, None, None)
        positions = jnp.arange(s)
        self_bias = relative_bias(
            params["dec_rel_bias"], positions, positions,
            bidirectional=False, num_buckets=cfg.rel_buckets, max_distance=cfg.rel_max_distance,
        )
        causal = (positions[None, :] <= positions[:, None])[None, None]  # [1,1,S,S]
        if decoder_attention_mask is not None:
            self_mask = causal & decoder_attention_mask[:, None, None, :].astype(bool)
        else:
            self_mask = causal
        enc_mask = None
        if attention_mask is not None:
            enc_mask = attention_mask[:, None, None, :].astype(bool)
        if self.pipeline_fn is not None:
            # enc_out/enc_mask/self_mask are per-microbatch side inputs
            # (leading dim == batch); self_bias is batch-invariant broadcast
            h, _ = self.pipeline_fn(
                params["layers"], h, self_bias, self_mask, enc_out, enc_mask,
                dropout_rng=dec_rng if use_dropout else None,
            )
        else:
            if use_dropout:
                layer_rngs = jax.random.split(dec_rng, cfg.num_layers * 3).reshape(cfg.num_layers, 3)

            def layer(h, xs):
                lp = xs[0] if use_dropout else xs
                rngs = tuple(xs[1]) if use_dropout else (None, None, None)
                h = self._dec_layer(
                    h, lp, self_bias, self_mask, enc_out, enc_mask, rngs,
                    kv_masks=(decoder_attention_mask, attention_mask),
                )
                return _constrain(h, BATCH_AXES, None, None), None

            xs = (params["layers"], layer_rngs) if use_dropout else params["layers"]
            body = (
                jax.checkpoint(layer, policy=self.remat_layers if callable(self.remat_layers) else None)
                if self.remat_layers
                else layer
            )
            h, _ = jax.lax.scan(body, h, xs)
        h = rms_norm(h, params["dec_final_norm"], cfg.norm_eps)
        return self._lm_logits(params, h)

    # -- pipeline hooks (parallel/pipeline.make_pipeline_layers_fn) ----------

    # declared side-input kinds (pipeline.py const_kinds): decoder self_bias
    # is batch-invariant [1, N, S, S]; self_mask varies ([1,1,S,S] causal-only
    # vs [B,1,S,S] with a decoder mask) so it stays shape-inferred
    pipeline_const_kinds = ("bcast", None, "mb", "mb")
    enc_pipeline_const_kinds = ("mb", "bcast")

    def enc_pipeline_layer(self, lp, h, rng, mask, bias):
        """Encoder-stack ``layer_fn``: (lp, h, rng, *consts) -> (h, aux).
        The raw key validity is recovered from the [B,1,1,S] const so the
        flash hook stays engaged inside pipeline stages."""
        rngs = (None, None) if rng is None else tuple(jax.random.split(rng))
        kv_mask = None if mask is None else mask[:, 0, 0, :]
        h = self._enc_layer(h, lp, bias, mask, rngs, kv_mask=kv_mask)
        return h, jnp.zeros((), jnp.float32)

    def pipeline_layer(self, lp, h, rng, self_bias, self_mask, enc_out, enc_mask):
        """Decoder-stack ``layer_fn``: cross-attention reads the encoder
        output carried as a per-microbatch side input. The consts hold only
        4-D masks (causality folded in), so the attention hook is bypassed —
        the einsum path is exact for the decoder's short sequences."""
        rngs = (None, None, None) if rng is None else tuple(jax.random.split(rng, 3))
        h = self._dec_layer(
            h, lp, self_bias, self_mask, enc_out, enc_mask, rngs, use_hook=False
        )
        return h, jnp.zeros((), jnp.float32)

    def _lm_logits(self, params, h):
        # tied head with the T5 d_model^-0.5 rescale (the paper folds the
        # attention 1/sqrt(d) into init; the output head keeps this factor)
        cfg = self.config
        h = h * (cfg.hidden_size ** -0.5)
        return (h @ params["shared_embed"].T.astype(h.dtype)).astype(jnp.float32)

    def shift_right(self, labels: jax.Array) -> jax.Array:
        """Teacher-forcing decoder inputs: [start, l0, l1, ...] (reference HF
        convention — labels feed the loss, their shift feeds the decoder)."""
        start = jnp.full((labels.shape[0], 1), self.config.decoder_start_token_id, labels.dtype)
        return jnp.concatenate([start, labels[:, :-1]], axis=1)

    # -- streaming protocol (big_modeling.StreamedModel full-sequence path) --
    # carry = (dec_h, self_bias, self_mask, enc_out, enc_mask)

    def stream_prefix(self, resident, input_ids, decoder_input_ids, attention_mask=None, decoder_attention_mask=None):
        cfg = self.config
        input_ids = jnp.asarray(input_ids, jnp.int32)
        decoder_input_ids = jnp.asarray(decoder_input_ids, jnp.int32)
        enc_out = self.encode(resident, input_ids, attention_mask, use_hooks=False)
        b, s = decoder_input_ids.shape
        h = jnp.take(resident["shared_embed"], decoder_input_ids, axis=0)
        positions = jnp.arange(s)
        self_bias = relative_bias(
            resident["dec_rel_bias"], positions, positions,
            bidirectional=False, num_buckets=cfg.rel_buckets, max_distance=cfg.rel_max_distance,
        )
        self_mask = (positions[None, :] <= positions[:, None])[None, None]
        if decoder_attention_mask is not None:
            self_mask = self_mask & jnp.asarray(decoder_attention_mask)[:, None, None, :].astype(bool)
        enc_mask = None
        if attention_mask is not None:
            enc_mask = jnp.asarray(attention_mask)[:, None, None, :].astype(bool)
        return (h, self_bias, self_mask, enc_out, enc_mask)

    def stream_layer(self, carry, lp):
        h, self_bias, self_mask, enc_out, enc_mask = carry
        # use_hook=False: the carry holds only 4-D masks, and a stale or
        # kv_mask-less hook would drop padding (see _attn)
        h = self._dec_layer(h, lp, self_bias, self_mask, enc_out, enc_mask, use_hook=False)
        return (h, self_bias, self_mask, enc_out, enc_mask)

    def stream_suffix(self, resident, carry):
        h = carry[0]
        h = rms_norm(h, resident["dec_final_norm"], self.config.norm_eps)
        return self._lm_logits(resident, h)

    # -- streamed decode protocol (big_modeling.Seq2SeqStreamedModel.generate) --

    def init_layer_cache(self, batch: int, max_len: int, dtype=jnp.bfloat16) -> dict:
        cfg = self.config
        return {
            "k": jnp.zeros((batch, max_len, cfg.num_heads, cfg.dim_per_head), dtype),
            "v": jnp.zeros((batch, max_len, cfg.num_heads, cfg.dim_per_head), dtype),
        }

    def decode_prefix(self, resident, current, length, max_len: int, enc_out=None, enc_mask=None):
        """Decode carry for ``current`` decoder tokens at offset ``length``.

        ``enc_out``/``enc_mask`` come from the one-time encoder pass that
        Seq2SeqStreamedModel.generate runs before the decode loop.
        """
        cfg = self.config
        current = jnp.asarray(current, jnp.int32)
        b, s = current.shape
        h = jnp.take(resident["shared_embed"], current, axis=0)
        q_pos = length + jnp.arange(s)
        k_pos = jnp.arange(max_len)
        self_bias = relative_bias(
            resident["dec_rel_bias"], q_pos, k_pos,
            bidirectional=False, num_buckets=cfg.rel_buckets, max_distance=cfg.rel_max_distance,
        )
        self_mask = (k_pos[None, :] <= q_pos[:, None])[None, None]
        return (h, self_bias, self_mask, enc_out, enc_mask)

    def stream_layer_cached(self, carry, lp, cache, length):
        h, self_bias, self_mask, enc_out, enc_mask = carry
        h, nc = self._dec_layer(
            h, lp, self_bias, self_mask, enc_out, enc_mask,
            cache={"k": cache["k"], "v": cache["v"]}, length=length,
        )
        return (h, self_bias, self_mask, enc_out, enc_mask), nc

    def decode_suffix(self, resident, carry):
        h = carry[0]
        h = rms_norm(h, resident["dec_final_norm"], self.config.norm_eps)
        return self._lm_logits(resident, h)[:, -1]

    # -- loss --------------------------------------------------------------

    @staticmethod
    def loss_fn(model: "T5"):
        """Seq2seq CE over {input_ids, labels, attention_mask?,
        decoder_attention_mask?}; decoder inputs are the shifted labels unless
        ``decoder_input_ids`` is given explicitly."""

        def fn(params, batch):
            labels = batch["labels"]
            decoder_input_ids = batch.get("decoder_input_ids")
            if decoder_input_ids is None:
                decoder_input_ids = model.shift_right(labels)
            logits = model.apply(
                params,
                batch["input_ids"],
                decoder_input_ids,
                batch.get("attention_mask"),
                batch.get("decoder_attention_mask"),
                dropout_rng=batch.get("dropout_rng"),
            ).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
            mask = batch.get("decoder_attention_mask")
            if mask is not None:
                w = mask.astype(jnp.float32)
                return (nll * w).sum() / jnp.maximum(w.sum(), 1.0)
            return nll.mean()

        return fn
