"""Mixture-of-Experts block with expert parallelism over the ``expert`` mesh
axis.

Capability parity: the reference only plumbs MoE config through to DeepSpeed
(``set_moe_leaf_modules``, reference accelerator.py:1594-1595,
dataclasses.py:977) — the experts themselves live in DeepSpeed's CUDA MoE
layer. Here the block is first-class and TPU-native: GShard/Switch-style
dense dispatch — top-k routing, capacity-bounded one-hot dispatch/combine
einsums — with the expert dimension of every tensor sharded over the
``expert`` mesh axis, so XLA emits the device all-to-alls that DeepSpeed
does by hand.

Design notes (MXU/ICI-first):
- Routing and dispatch are einsums over static shapes: no gather/scatter, no
  dynamic shapes, everything tiles onto the MXU.
- ``with_sharding_constraint`` pins the per-expert activations to the expert
  axis; with the expert weights sharded the same way, the dispatch einsum
  becomes an all-to-all over ICI and each device computes only its experts.
- Tokens over capacity are *dropped* (their combine weight is zero) exactly
  as in Switch/GShard; the auxiliary load-balance loss keeps the router from
  collapsing onto few experts.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..utils.constants import MESH_AXIS_EXPERT
from .attention import dense_init


class MoEBlock:
    """Top-k-routed expert MLP: ``[B, S, H] -> [B, S, H]`` (+ aux loss).

    Usable standalone or as the MLP of a transformer layer. ``init``/
    ``apply``/``partition_rules`` follow the model-zoo protocol so
    ``Accelerator.prepare_model`` shards it directly.
    """

    def __init__(
        self,
        hidden_size: int,
        intermediate_size: int,
        num_experts: int,
        top_k: int = 2,
        capacity_factor: float = 1.25,
        aux_loss_weight: float = 0.01,
    ):
        if top_k > num_experts:
            raise ValueError(f"top_k={top_k} > num_experts={num_experts}")
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = aux_loss_weight

    def init(self, rng: jax.Array) -> dict:
        h, f, e = self.hidden_size, self.intermediate_size, self.num_experts
        k_router, k_up, k_down = jax.random.split(rng, 3)
        return {
            "router": dense_init(k_router, (h, e), h),
            "w_up": dense_init(k_up, (e, h, f), h),
            "w_down": dense_init(k_down, (e, f, h), f),
        }

    def partition_rules(self) -> list[tuple[str, tuple]]:
        ex = MESH_AXIS_EXPERT
        return [
            (r"router", (None, None)),  # replicated: every token routes everywhere
            (r"w_(up|down)", (ex, None, None)),
        ]

    def capacity(self, num_tokens: int) -> int:
        """Per-expert token slots (Switch Transformer capacity formula)."""
        return max(int(math.ceil(self.top_k * num_tokens / self.num_experts * self.capacity_factor)), 1)

    def apply(self, params: dict, x: jax.Array, return_aux: bool = False):
        """Route each token to its top-k experts and combine their outputs.

        Returns ``y`` (same shape as ``x``) or ``(y, aux_loss)`` with the
        GShard load-balance auxiliary loss.
        """
        y, aux = routed_mlp(
            x,
            params["router"],
            params["w_up"],
            params["w_down"],
            top_k=self.top_k,
            capacity_factor=self.capacity_factor,
            aux_loss_weight=self.aux_loss_weight,
        )
        return (y, aux) if return_aux else y


def routed_mlp(
    x: jax.Array,  # [B, S, H]
    router: jax.Array,  # [H, E]
    w_up: jax.Array,  # [E, H, F]
    w_down: jax.Array,  # [E, F, H]
    top_k: int = 2,
    capacity_factor: float = 1.25,
    aux_loss_weight: float = 0.01,
) -> tuple[jax.Array, jax.Array]:
    """GShard dense-dispatch expert MLP — the core shared by ``MoEBlock`` and
    the llama-family MoE layers. Returns ``(y, aux_load_balance_loss)``."""
    b, s, h = x.shape
    e = router.shape[-1]
    k = top_k
    if k > e:
        raise ValueError(f"top_k={k} > num_experts={e}")
    t = b * s
    c = max(int(math.ceil(k * t / e * capacity_factor)), 1)
    tokens = x.reshape(t, h)

    # routing stays fp32 (GShard/Switch convention): near-tied logits in bf16
    # flip top-k selections
    router_logits = tokens.astype(jnp.float32) @ router.astype(jnp.float32)  # [T, E]
    probs = jax.nn.softmax(router_logits, axis=-1)

    # top-k selection; gates renormalized over the selected experts
    gate_vals, expert_idx = jax.lax.top_k(probs, k)  # [T, k]
    gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

    # capacity assignment: position of each (token, choice) in its
    # expert's queue, computed with one-hot cumsums (static shapes)
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [T, k, E]
    # priority: choice 0 of every token beats choice 1 of any token
    flat_choice = onehot.transpose(1, 0, 2).reshape(k * t, e)  # [k*T, E]
    position = (jnp.cumsum(flat_choice, axis=0) - 1.0) * flat_choice  # [k*T, E]
    within_cap = (position < c) & (flat_choice > 0)
    position = position.reshape(k, t, e).transpose(1, 0, 2)  # [T, k, E]
    within_cap = within_cap.reshape(k, t, e).transpose(1, 0, 2)

    cap_onehot = jax.nn.one_hot(position.astype(jnp.int32), c, dtype=jnp.float32)  # [T,k,E,C]
    cap_onehot = cap_onehot * within_cap[..., None]
    dispatch = (onehot[..., None] * cap_onehot).sum(axis=1)  # [T, E, C]
    combine = (gate_vals[..., None, None] * onehot[..., None] * cap_onehot).sum(axis=1)

    # expert compute: dispatch/combine einsums become all-to-alls under
    # the expert-axis sharding of the [E, ...] tensors
    expert_in = jnp.einsum("tec,th->ech", dispatch.astype(x.dtype), tokens)
    expert_in = _constrain_expert(expert_in)
    h1 = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", expert_in, w_up.astype(x.dtype)))
    expert_out = jnp.einsum("ecf,efh->ech", h1, w_down.astype(x.dtype))
    expert_out = _constrain_expert(expert_out)
    y = jnp.einsum("tec,ech->th", combine.astype(x.dtype), expert_out).reshape(b, s, h)

    # load-balance loss (GShard eq. 4): E * Σ_e mean_prob_e * dispatch_frac_e
    dispatch_frac = (onehot[:, 0].sum(0) / t).astype(jnp.float32)  # first-choice counts
    mean_prob = probs.mean(0)
    aux = aux_loss_weight * e * jnp.sum(dispatch_frac * mean_prob)
    return y, aux


def _constrain_expert(value: jax.Array) -> jax.Array:
    """Pin the leading expert dim to the expert mesh axis.

    The constraint is built against the *concrete* Accelerator mesh (a bare
    PartitionSpec needs an ambient mesh context, which plain ``jax.jit`` with
    NamedSharding-typed arguments never establishes). Skipped only when no
    topology singleton exists (plain eager use); a genuine sharding error —
    e.g. num_experts not divisible by the expert axis — then surfaces."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..state import PartialState

    if not PartialState._shared_state:  # no Accelerator/mesh in this process
        return value
    if getattr(value.aval, "vma", ()):
        # inside a shard_map manual region (the pipeline schedule): a
        # NamedSharding constraint would mix Manual and Auto axis types and
        # be rejected. The expert layout still holds — GSPMD propagates it
        # from the moe_up/moe_down parameter shardings.
        return value
    mesh = PartialState().mesh
    if mesh.shape.get(MESH_AXIS_EXPERT, 1) <= 1:
        return value
    sharding = NamedSharding(mesh, P(MESH_AXIS_EXPERT, *([None] * (value.ndim - 1))))
    return jax.lax.with_sharding_constraint(value, sharding)
