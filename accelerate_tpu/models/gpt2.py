"""GPT-2-family causal LM, TPU-first.

Capability parity: the reference's big-model benchmark and inference examples
exercise GPT-2-lineage checkpoints (GPT-J/GPT-NeoX in benchmarks/README.md:
31-34, examples/inference/pippy/gpt2.py). Architecturally distinct from the
llama family: learned absolute position embeddings (no RoPE), LayerNorm with
bias (no RMSNorm), a plain GELU MLP (no gating), biases on every projection,
and tied input/output embeddings.

Same TPU-first design as models/llama.py: stacked layers on a leading L axis
run as one ``lax.scan``; megatron-style TP partition rules; activation
sharding constraints; fp32 norm/softmax accumulation under bf16. Implements
the stream protocol (stream_prefix/stream_layer/stream_suffix) so
``dispatch_model`` offloads it like any other model.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..utils.constants import MESH_AXIS_SEQUENCE, MESH_AXIS_TENSOR
from .attention import dense_init, dot_product_attention, dropout, resolve_dot
from .bert import layer_norm
from .config import TransformerConfig, get_config
from .llama import BATCH_AXES, _constrain


class GPT2:
    """(init, apply) pair for a GPT-2-style causal LM (tied embeddings)."""

    def __init__(self, config: TransformerConfig | str):
        self.config = get_config(config) if isinstance(config, str) else config
        assert self.config.arch == "gpt2"
        # hooks set by Accelerator.prepare_model (see models/llama.py)
        self.remat_layers = False
        self.dot_fn = None
        self.attention_fn = None  # ring/flash attention for the training path
        self.pipeline_fn = None  # GPipe layer schedule when the mesh has a pipeline axis

    # -- parameters --------------------------------------------------------

    def init(self, rng: jax.Array) -> dict:
        if not hasattr(self, "_init_jit"):
            self._init_jit = jax.jit(self._init)
        return self._init_jit(rng)

    def _init(self, rng: jax.Array) -> dict:
        cfg = self.config
        h, i, v, L = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size, cfg.num_layers
        keys = iter(jax.random.split(rng, 12))
        dense = dense_init
        return {
            "embed_tokens": jax.random.normal(next(keys), (v, h), jnp.float32) * 0.02,
            "embed_positions": jax.random.normal(next(keys), (cfg.max_seq_len, h), jnp.float32) * 0.01,
            "layers": {
                "attn_norm_scale": jnp.ones((L, h), jnp.float32),
                "attn_norm_bias": jnp.zeros((L, h), jnp.float32),
                "wqkv": dense(next(keys), (L, h, 3 * h), h),
                "bqkv": jnp.zeros((L, 3 * h), jnp.float32),
                "wo": dense(next(keys), (L, h, h), h),
                "bo": jnp.zeros((L, h), jnp.float32),
                "mlp_norm_scale": jnp.ones((L, h), jnp.float32),
                "mlp_norm_bias": jnp.zeros((L, h), jnp.float32),
                "w_up": dense(next(keys), (L, h, i), h),
                "b_up": jnp.zeros((L, i), jnp.float32),
                "w_down": dense(next(keys), (L, i, h), i),
                "b_down": jnp.zeros((L, h), jnp.float32),
            },
            "final_norm_scale": jnp.ones((h,), jnp.float32),
            "final_norm_bias": jnp.zeros((h,), jnp.float32),
        }

    # -- sharding ----------------------------------------------------------

    def partition_rules(self) -> list[tuple[str, tuple]]:
        """TP: fused qkv and MLP-up column-parallel, output projections
        row-parallel; stacked leading dim is the scan axis (pipeline rule)."""
        from ..utils.constants import MESH_AXIS_PIPELINE

        t = MESH_AXIS_TENSOR
        p = MESH_AXIS_PIPELINE
        return [
            (r"embed_tokens", (t, None)),
            (r"embed_positions", (None, None)),
            (r"layers/wqkv", (p, None, t)),
            (r"layers/bqkv", (p, t)),
            (r"layers/wo", (p, t, None)),
            (r"layers/w_up", (p, None, t)),
            (r"layers/b_up", (p, t)),
            (r"layers/w_down", (p, t, None)),
            (r"layers/(attn_norm|mlp_norm|bo|b_down)", (p, None)),
            (r"final_norm", (None,)),
        ]

    # -- one transformer block (shared by apply, streaming, and KV decode) --

    def _block(self, h: jax.Array, lp: dict, mask, rngs=(None, None), cache=None, kv_mask=None, use_attention_hook=True):
        """Returns ``h`` (no cache) or ``(h, new_cache)`` when ``cache`` holds
        {"k","v"} [B, T, N, D] plus the write offset "length". ``kv_mask`` is
        the raw [B, S] validity mask for ``attention_fn`` implementations
        (ring/flash attention); ``use_attention_hook=False`` forces the plain
        masked path (streaming executor — see models/bert.py)."""
        cfg = self.config
        dot = resolve_dot(self.dot_fn)
        b, s, _ = h.shape
        nh = cfg.num_heads
        d = cfg.hidden_size // nh
        x = layer_norm(h, lp["attn_norm_scale"], lp["attn_norm_bias"], cfg.norm_eps)
        qkv = dot(x, lp["wqkv"]) + lp["bqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (t.reshape(b, s, nh, d) for t in (q, k, v))
        new_cache = None
        if cache is not None and "attend" in cache:
            # paged-kernel decode: attention reads the page pool directly
            # (ops/paged_attention.py); the engine scatters the returned
            # new-token K/V — see models/llama.py decoder_layer
            attn = cache["attend"](q, k, v, cache)
            new_cache = {"k": k, "v": v}
        elif cache is not None:
            k_cache = jax.lax.dynamic_update_slice(
                cache["k"], k.astype(cache["k"].dtype), (0, cache["length"], 0, 0)
            )
            v_cache = jax.lax.dynamic_update_slice(
                cache["v"], v.astype(cache["v"].dtype), (0, cache["length"], 0, 0)
            )
            attn = dot_product_attention(q, k_cache.astype(q.dtype), v_cache.astype(q.dtype), mask=mask)
            new_cache = {"k": k_cache, "v": v_cache}
        elif use_attention_hook and self.attention_fn is not None:
            attn = self.attention_fn(q, k, v, kv_mask)
        else:
            attn = dot_product_attention(q, k, v, mask=mask, causal=True)
        attn_out = dot(attn.reshape(b, s, nh * d), lp["wo"]) + lp["bo"]
        if rngs[0] is not None:
            attn_out = dropout(attn_out, cfg.dropout_rate, rngs[0])
        h = h + attn_out
        x = layer_norm(h, lp["mlp_norm_scale"], lp["mlp_norm_bias"], cfg.norm_eps)
        mlp_out = dot(jax.nn.gelu(dot(x, lp["w_up"]) + lp["b_up"]), lp["w_down"]) + lp["b_down"]
        if rngs[1] is not None:
            mlp_out = dropout(mlp_out, cfg.dropout_rate, rngs[1])
        h = h + mlp_out
        return h if cache is None else (h, new_cache)

    # -- KV-cache decode protocol (models/generation.py) --------------------

    def init_cache(self, batch: int, max_len: int, dtype=jnp.bfloat16) -> dict:
        cfg = self.config
        if max_len > cfg.max_seq_len:
            raise ValueError(
                f"prompt + max_new_tokens = {max_len} exceeds max_seq_len "
                f"{cfg.max_seq_len} (learned positions would silently clamp)"
            )
        L, nh = cfg.num_layers, cfg.num_heads
        d = cfg.hidden_size // nh
        return {
            "k": jnp.zeros((L, batch, max_len, nh, d), dtype),
            "v": jnp.zeros((L, batch, max_len, nh, d), dtype),
            "length": jnp.zeros((), jnp.int32),
        }

    def forward_with_cache(self, params: dict, input_ids: jax.Array, cache: dict):
        """(last-position logits [B, V], updated cache) — the decode protocol
        generation.generate drives (prefill block or single token). One copy
        of the math: built from decode_prefix/stream_layer_cached/
        decode_suffix, scanned over the stacked layers."""
        b, s = input_ids.shape
        length = cache["length"]
        # paged-kernel decode threads the pool's table + attend hook through
        # (see models/llama.py decoder_layer); max_len only shapes the mask,
        # which the kernel path computes internally from table/length
        extra = {key: cache[key] for key in ("table", "attend") if key in cache}
        max_len = self.config.max_seq_len if extra else cache["k"].shape[2]
        carry = self.decode_prefix(params, input_ids, length, max_len=max_len)

        def body(carry, xs):
            lp, k_cache, v_cache = xs
            carry, nc = self.stream_layer_cached(
                carry, lp, {"k": k_cache, "v": v_cache, **extra}, length
            )
            return carry, (nc["k"], nc["v"])

        carry, (k_cache, v_cache) = jax.lax.scan(body, carry, (params["layers"], cache["k"], cache["v"]))
        logits = self.decode_suffix(params, carry)
        return logits, {"k": k_cache, "v": v_cache, "length": length + s}

    def forward_window_with_cache(self, params: dict, input_ids: jax.Array, cache: dict):
        """Speculative-verify window forward: all-position logits [B, S, V]
        (models/generation.py resolve_window_protocol). Paged-attend only —
        the in-window causal mask lives in the attend hook, and the learned
        positions beyond max_seq_len that jnp.take would clamp are never
        emitted (the engine's per-slot window limit caps at capacity)."""
        if "attend" not in cache:
            raise ValueError(
                "forward_window_with_cache requires the paged 'attend' protocol "
                "(the in-window causal mask lives in the attend hook)"
            )
        b, s = input_ids.shape
        length = cache["length"]
        extra = {key: cache[key] for key in ("table", "attend") if key in cache}
        carry = self.decode_prefix(params, input_ids, length, max_len=self.config.max_seq_len)

        def body(carry, xs):
            lp, k_cache, v_cache = xs
            carry, nc = self.stream_layer_cached(
                carry, lp, {"k": k_cache, "v": v_cache, **extra}, length
            )
            return carry, (nc["k"], nc["v"])

        carry, (k_cache, v_cache) = jax.lax.scan(body, carry, (params["layers"], cache["k"], cache["v"]))
        h, _ = carry
        h = layer_norm(h, params["final_norm_scale"], params["final_norm_bias"], self.config.norm_eps)
        logits = (h @ params["embed_tokens"].T.astype(h.dtype)).astype(jnp.float32)
        return logits, {"k": k_cache, "v": v_cache, "length": length + s}

    # -- forward -----------------------------------------------------------

    def apply(
        self,
        params: dict,
        input_ids: jax.Array,  # [B, S] int32
        attention_mask: Optional[jax.Array] = None,  # [B, S] 1=real
        positions: Optional[jax.Array] = None,
        dropout_rng: Optional[jax.Array] = None,
    ) -> jax.Array:
        """Logits [B, S, V] (LM head = tied token embedding)."""
        cfg = self.config
        b, s = input_ids.shape
        if s > cfg.max_seq_len:
            # learned positions: jnp.take would silently CLAMP out-of-range
            # indices to the last row — fail loudly instead
            raise ValueError(f"sequence length {s} exceeds max_seq_len {cfg.max_seq_len}")
        if positions is None:
            positions = jnp.arange(s)[None, :]
        h = jnp.take(params["embed_tokens"], input_ids, axis=0) + jnp.take(
            params["embed_positions"], positions, axis=0
        )
        h = _constrain(h, BATCH_AXES, MESH_AXIS_SEQUENCE, None)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)

        use_dropout = dropout_rng is not None and cfg.dropout_rate > 0.0
        if use_dropout:
            layer_rngs = jax.random.split(dropout_rng, cfg.num_layers * 2).reshape(cfg.num_layers, 2)

        if self.pipeline_fn is not None:
            h, _ = self.pipeline_fn(
                params["layers"], h, mask, attention_mask,
                dropout_rng=dropout_rng if use_dropout else None,
            )
        else:
            def layer(h, xs):
                lp = xs[0] if use_dropout else xs
                rngs = tuple(xs[1]) if use_dropout else (None, None)
                h = self._block(h, lp, mask, rngs, kv_mask=attention_mask)
                return _constrain(h, BATCH_AXES, MESH_AXIS_SEQUENCE, None), None

            xs = (params["layers"], layer_rngs) if use_dropout else params["layers"]
            body = (
                jax.checkpoint(layer, policy=self.remat_layers if callable(self.remat_layers) else None)
                if self.remat_layers
                else layer
            )
            h, _ = jax.lax.scan(body, h, xs)
        h = layer_norm(h, params["final_norm_scale"], params["final_norm_bias"], cfg.norm_eps)
        return (h @ params["embed_tokens"].T.astype(h.dtype)).astype(jnp.float32)

    # sequence dims of the pipeline activations/side inputs (mask, kv_mask)
    pipeline_seq_dims = {"h": 1, "consts": (3, 1)}
    # both side inputs carry the batch in dim 0 — declared so the schedule
    # never has to infer from shape
    pipeline_const_kinds = ("mb", "mb")

    # -- pipeline hook (parallel/pipeline.make_pipeline_layers_fn) -----------

    def pipeline_layer(self, lp, h, rng, mask, kv_mask):
        """``layer_fn`` contract: (lp, h, rng, *consts) -> (h, aux)."""
        rngs = (None, None) if rng is None else tuple(jax.random.split(rng))
        h = self._block(h, lp, mask, rngs, kv_mask=kv_mask)
        return h, jnp.zeros((), jnp.float32)

    # -- streamed decode protocol (big_modeling.StreamedModel.generate) ------

    def init_layer_cache(self, batch: int, max_len: int, dtype=jnp.bfloat16) -> dict:
        """KV cache for ONE layer (the streamed decode keeps per-layer dicts)."""
        cfg = self.config
        if max_len > cfg.max_seq_len:
            raise ValueError(
                f"prompt + max_new_tokens = {max_len} exceeds max_seq_len "
                f"{cfg.max_seq_len} (learned positions would silently clamp)"
            )
        nh = cfg.num_heads
        d = cfg.hidden_size // nh
        return {
            "k": jnp.zeros((batch, max_len, nh, d), dtype),
            "v": jnp.zeros((batch, max_len, nh, d), dtype),
        }

    def decode_prefix(self, resident, input_ids, length, max_len: int):
        """Embeddings + causal-over-cache mask → decode carry."""
        input_ids = jnp.asarray(input_ids, jnp.int32)
        b, s = input_ids.shape
        positions = length + jnp.arange(s)[None, :]
        h = jnp.take(resident["embed_tokens"], input_ids, axis=0) + jnp.take(
            resident["embed_positions"], positions, axis=0
        )
        q_pos = length + jnp.arange(s)
        mask = (jnp.arange(max_len)[None, :] <= q_pos[:, None])[None, None]
        return (h, mask)

    def stream_layer_cached(self, carry, lp, cache, length):
        h, mask = carry
        h, nc = self._block(h, lp, mask, cache={**cache, "length": length})
        return (h, mask), nc

    def decode_suffix(self, resident, carry):
        """Last-position logits [B, V] from the decode carry."""
        h, _ = carry
        cfg = self.config
        h = layer_norm(h, resident["final_norm_scale"], resident["final_norm_bias"], cfg.norm_eps)
        return (h[:, -1] @ resident["embed_tokens"].T.astype(h.dtype)).astype(jnp.float32)

    # -- streaming protocol (big-model dispatch, big_modeling.StreamedModel) --

    def stream_prefix(self, resident, input_ids, attention_mask=None):
        input_ids = jnp.asarray(input_ids, jnp.int32)
        b, s = input_ids.shape
        if s > self.config.max_seq_len:
            # learned positions: jnp.take would silently clamp — fail loudly
            raise ValueError(f"sequence length {s} exceeds max_seq_len {self.config.max_seq_len}")
        h = jnp.take(resident["embed_tokens"], input_ids, axis=0) + jnp.take(
            resident["embed_positions"], jnp.arange(s)[None, :], axis=0
        )
        mask = None
        if attention_mask is not None:
            mask = jnp.asarray(attention_mask)[:, None, None, :].astype(bool)
        return (h, mask)

    def stream_layer(self, carry, lp):
        h, mask = carry
        return (self._block(h, lp, mask, use_attention_hook=False), mask)

    def stream_suffix(self, resident, carry):
        h, _ = carry
        cfg = self.config
        h = layer_norm(h, resident["final_norm_scale"], resident["final_norm_bias"], cfg.norm_eps)
        return (h @ resident["embed_tokens"].T.astype(h.dtype)).astype(jnp.float32)

    # -- loss --------------------------------------------------------------

    @staticmethod
    def loss_fn(model: "GPT2"):
        """Next-token CE over {input_ids, attention_mask?}."""

        def fn(params, batch):
            logits = model.apply(
                params, batch["input_ids"], batch.get("attention_mask")
            ).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
            tgt = batch["input_ids"][:, 1:]
            nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1).squeeze(-1)
            mask = batch.get("attention_mask")
            if mask is not None:
                valid = mask[:, 1:].astype(nll.dtype)
                return (nll * valid).sum() / jnp.maximum(valid.sum(), 1.0)
            return nll.mean()

        return fn
