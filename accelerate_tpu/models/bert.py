"""BERT-family encoder for sequence classification, TPU-first.

Capability parity: the reference's canonical example trains
bert-base-uncased on GLUE-MRPC (examples/nlp_example.py); this is that model
rebuilt on the stacked-layer/scan design of models/llama.py. BASELINE.json
target metric #1 (steps/sec/chip) runs on this.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..utils.constants import MESH_AXIS_TENSOR
from .attention import dense_init, dot_product_attention, dropout, resolve_dot
from .config import TransformerConfig, get_config
from .llama import BATCH_AXES, _constrain


def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = x.mean(axis=-1, keepdims=True)
    var = ((x - mean) ** 2).mean(axis=-1, keepdims=True)
    x = (x - mean) * jax.lax.rsqrt(var + eps)
    return (x * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


class Bert:
    """(init, apply) pair for an encoder with a classification head."""

    # bidirectional attention: prepare_model builds ring attention with
    # causal=False and skips the (causal-only) flash kernel
    causal_attention = False

    def __init__(self, config: TransformerConfig | str):
        self.config = get_config(config) if isinstance(config, str) else config
        assert self.config.arch == "bert"
        # per-layer activation checkpointing (see models/llama.py)
        self.remat_layers = False
        # fp8 projection compute (ops/fp8.fp8_dot), set by prepare_model
        self.dot_fn = None
        # hooks set by Accelerator.prepare_model (see models/llama.py)
        self.attention_fn = None
        self.pipeline_fn = None

    def init(self, rng: jax.Array) -> dict:
        if not hasattr(self, "_init_jit"):
            self._init_jit = jax.jit(self._init)
        return self._init_jit(rng)

    def _init(self, rng: jax.Array) -> dict:
        cfg = self.config
        h, i, v, L = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size, cfg.num_layers
        keys = iter(jax.random.split(rng, 20))
        dense = dense_init
        return {
            "embeddings": {
                "word": jax.random.normal(next(keys), (v, h), jnp.float32) * 0.02,
                "position": jax.random.normal(next(keys), (cfg.max_seq_len, h), jnp.float32) * 0.02,
                "token_type": jax.random.normal(next(keys), (cfg.type_vocab_size, h), jnp.float32) * 0.02,
                "norm_scale": jnp.ones((h,), jnp.float32),
                "norm_bias": jnp.zeros((h,), jnp.float32),
            },
            "layers": {
                "wq": dense(next(keys), (L, h, h), h),
                "bq": jnp.zeros((L, h), jnp.float32),
                "wk": dense(next(keys), (L, h, h), h),
                "bk": jnp.zeros((L, h), jnp.float32),
                "wv": dense(next(keys), (L, h, h), h),
                "bv": jnp.zeros((L, h), jnp.float32),
                "wo": dense(next(keys), (L, h, h), h),
                "bo": jnp.zeros((L, h), jnp.float32),
                "attn_norm_scale": jnp.ones((L, h), jnp.float32),
                "attn_norm_bias": jnp.zeros((L, h), jnp.float32),
                "w_up": dense(next(keys), (L, h, i), h),
                "b_up": jnp.zeros((L, i), jnp.float32),
                "w_down": dense(next(keys), (L, i, h), i),
                "b_down": jnp.zeros((L, h), jnp.float32),
                "mlp_norm_scale": jnp.ones((L, h), jnp.float32),
                "mlp_norm_bias": jnp.zeros((L, h), jnp.float32),
            },
            "pooler": {"w": dense(next(keys), (h, h), h), "b": jnp.zeros((h,), jnp.float32)},
            "classifier": {
                "w": dense(next(keys), (h, cfg.num_labels), h),
                "b": jnp.zeros((cfg.num_labels,), jnp.float32),
            },
        }

    def partition_rules(self) -> list[tuple[str, tuple]]:
        from ..utils.constants import MESH_AXIS_PIPELINE

        t = MESH_AXIS_TENSOR
        p = MESH_AXIS_PIPELINE  # stacked-layer leading dim; size-1 axis = no-op
        return [
            (r"embeddings/word", (t, None)),
            (r"layers/(wq|wk|wv|w_up)", (p, None, t)),
            (r"layers/(bq|bk|bv|b_up)", (p, t)),
            (r"layers/(wo|w_down)", (p, t, None)),
            (r"layers/.*(norm|bo|b_down)", (p, None)),
            (r"(norm|bias|bo|b_down)", (None,)),
            (r"pooler/w", (None, t)),
            (r"classifier", (None,)),
        ]

    def apply(
        self,
        params: dict,
        input_ids: jax.Array,  # [B, S]
        attention_mask: Optional[jax.Array] = None,
        token_type_ids: Optional[jax.Array] = None,
        position_ids: Optional[jax.Array] = None,
        dropout_rng: Optional[jax.Array] = None,
    ) -> jax.Array:
        """Classification logits [B, num_labels].

        Pass ``dropout_rng`` during training to enable ``config.dropout_rate``
        dropout (embeddings + each residual branch); omit it for eval.
        """
        cfg = self.config
        b, s = input_ids.shape
        if s > cfg.max_seq_len:
            # learned positions: jnp.take would silently CLAMP out-of-range
            # indices to the last row — fail loudly instead
            raise ValueError(f"sequence length {s} exceeds max_seq_len {cfg.max_seq_len}")
        nh = cfg.num_heads
        d = cfg.hidden_size // nh

        emb = params["embeddings"]
        if position_ids is None:
            position_ids = jnp.arange(s)[None, :]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        h = (
            jnp.take(emb["word"], input_ids, axis=0)
            + jnp.take(emb["position"], position_ids, axis=0)
            + jnp.take(emb["token_type"], token_type_ids, axis=0)
        )
        h = layer_norm(h, emb["norm_scale"], emb["norm_bias"], cfg.norm_eps)
        h = _constrain(h, BATCH_AXES, None, None)
        use_dropout = dropout_rng is not None and cfg.dropout_rate > 0.0
        if use_dropout:
            emb_rng, layers_rng = jax.random.split(dropout_rng)
            h = dropout(h, cfg.dropout_rate, emb_rng)
            layer_rngs = jax.random.split(layers_rng, cfg.num_layers * 2).reshape(cfg.num_layers, 2)

        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)

        if self.pipeline_fn is not None:
            h, _ = self.pipeline_fn(
                params["layers"], h, mask, attention_mask,
                dropout_rng=layers_rng if use_dropout else None,
            )
        else:
            def layer(h, xs):
                lp = xs[0] if use_dropout else xs
                rngs = tuple(xs[1]) if use_dropout else (None, None)
                h = self._block(h, lp, mask, rngs, kv_mask=attention_mask)
                return h, None

            xs = (params["layers"], layer_rngs) if use_dropout else params["layers"]
            body = (
                jax.checkpoint(layer, policy=self.remat_layers if callable(self.remat_layers) else None)
                if self.remat_layers
                else layer
            )
            h, _ = jax.lax.scan(body, h, xs)
        pooled = jnp.tanh(h[:, 0] @ params["pooler"]["w"] + params["pooler"]["b"])
        return pooled @ params["classifier"]["w"] + params["classifier"]["b"]

    # -- one encoder layer (shared by apply, streaming, and the pipeline) ----

    def _block(self, h: jax.Array, lp: dict, mask, rngs=(None, None), kv_mask=None, use_attention_hook=True):
        """One encoder layer. ``kv_mask`` is the raw [B, S] validity mask for
        ``attention_fn`` implementations (non-causal ring attention);
        ``use_attention_hook=False`` forces the plain masked path — the
        streaming executor runs single-device with a precomputed 4D mask, and
        a mesh-bound ring hook left on the model would silently drop it."""
        cfg = self.config
        dot = resolve_dot(self.dot_fn)
        b, s, _ = h.shape
        nh = cfg.num_heads
        d = cfg.hidden_size // nh
        q = (dot(h, lp["wq"]) + lp["bq"]).reshape(b, s, nh, d)
        k = (dot(h, lp["wk"]) + lp["bk"]).reshape(b, s, nh, d)
        v = (dot(h, lp["wv"]) + lp["bv"]).reshape(b, s, nh, d)
        if use_attention_hook and self.attention_fn is not None:
            attn = self.attention_fn(q, k, v, kv_mask)
        else:
            attn = dot_product_attention(q, k, v, mask=mask)
        attn_out = dot(attn.reshape(b, s, nh * d), lp["wo"]) + lp["bo"]
        if rngs[0] is not None:
            attn_out = dropout(attn_out, cfg.dropout_rate, rngs[0])
        h = layer_norm(h + attn_out, lp["attn_norm_scale"], lp["attn_norm_bias"], cfg.norm_eps)
        up = jax.nn.gelu(dot(h, lp["w_up"]) + lp["b_up"])
        mlp_out = dot(up, lp["w_down"]) + lp["b_down"]
        if rngs[1] is not None:
            mlp_out = dropout(mlp_out, cfg.dropout_rate, rngs[1])
        h = layer_norm(h + mlp_out, lp["mlp_norm_scale"], lp["mlp_norm_bias"], cfg.norm_eps)
        return h

    # sequence dims of the pipeline activations/side inputs (mask, kv_mask)
    pipeline_seq_dims = {"h": 1, "consts": (3, 1)}
    # both side inputs carry the batch in dim 0 — declared so the schedule
    # never has to infer from shape
    pipeline_const_kinds = ("mb", "mb")

    # -- pipeline hook (parallel/pipeline.make_pipeline_layers_fn) -----------

    def pipeline_layer(self, lp, h, rng, mask, kv_mask):
        """``layer_fn`` contract: (lp, h, rng, *consts) -> (h, aux)."""
        rngs = (None, None) if rng is None else tuple(jax.random.split(rng))
        h = self._block(h, lp, mask, rngs, kv_mask=kv_mask)
        return h, jnp.zeros((), jnp.float32)

    # -- streaming protocol (big-model dispatch, big_modeling.StreamedModel) --

    def stream_prefix(self, resident, input_ids, attention_mask=None, token_type_ids=None):
        """Embeddings → (hidden, mask) carry for the per-layer stream."""
        cfg = self.config
        input_ids = jnp.asarray(input_ids, jnp.int32)
        b, s = input_ids.shape
        if s > cfg.max_seq_len:
            # learned positions: jnp.take would silently clamp — fail loudly
            raise ValueError(f"sequence length {s} exceeds max_seq_len {cfg.max_seq_len}")
        emb = resident["embeddings"]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        h = (
            jnp.take(emb["word"], input_ids, axis=0)
            + jnp.take(emb["position"], jnp.arange(s)[None, :], axis=0)
            + jnp.take(emb["token_type"], jnp.asarray(token_type_ids, jnp.int32), axis=0)
        )
        h = layer_norm(h, emb["norm_scale"], emb["norm_bias"], cfg.norm_eps)
        mask = None
        if attention_mask is not None:
            mask = jnp.asarray(attention_mask)[:, None, None, :].astype(bool)
        return (h, mask)

    def stream_layer(self, carry, lp):
        """One encoder layer; identical math to the training path — ``_block``
        (including the dot_fn hook, so fp8 dispatch matches fp8 training).
        The mesh-bound attention hook is bypassed: streaming is single-device
        and the padding mask is already the 4D ``mask`` in the carry."""
        h, mask = carry
        return (self._block(h, lp, mask, use_attention_hook=False), mask)

    def stream_suffix(self, resident, carry):
        h, _ = carry
        pooled = jnp.tanh(h[:, 0] @ resident["pooler"]["w"] + resident["pooler"]["b"])
        return pooled @ resident["classifier"]["w"] + resident["classifier"]["b"]

    @staticmethod
    def loss_fn(model: "Bert"):
        """Softmax CE over {input_ids, attention_mask?, token_type_ids?, labels}."""

        def fn(params, batch):
            logits = model.apply(
                params,
                batch["input_ids"],
                batch.get("attention_mask"),
                batch.get("token_type_ids"),
            ).astype(jnp.float32)
            labels = batch["labels"]
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()

        return fn
