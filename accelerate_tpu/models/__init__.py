from .attention import dot_product_attention, rotary_embedding
from .bert import Bert
from .config import TransformerConfig, get_config, list_models, param_count, register_config
from .generation import generate
from .gpt2 import GPT2
from .llama import Llama
from .moe import MoEBlock
from .t5 import T5


_ARCHS = {"llama": Llama, "bert": Bert, "gpt2": GPT2, "t5": T5}


def build_model(name: str):
    """Registry name → model instance (e.g. "llama-7b", "bert-base")."""
    config = get_config(name)
    if config.arch not in _ARCHS:
        raise ValueError(f"Unknown arch {config.arch!r}; available: {sorted(_ARCHS)}")
    return _ARCHS[config.arch](config)
