from .config import TransformerConfig, get_config, list_models, param_count, register_config
