"""Attention primitives shared by the model zoo.

The default path is einsum attention, which XLA fuses well on TPU (softmax
rides the VPU, matmuls the MXU). A Pallas splash/ring kernel plugs in behind
the same signature for long sequences (parallel/ring_attention.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def resolve_dot(dot_fn):
    """The projection-matmul hook with its default: plain ``@`` when no
    override (e.g. ops.fp8.fp8_dot) is installed. One definition, used by
    every layer body."""
    return dot_fn if dot_fn is not None else (lambda a, w: a @ w)


def dense_init(key: jax.Array, shape: tuple, fan_in: int) -> jax.Array:
    """Scaled-normal initializer shared by the model zoo."""
    return (jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(jnp.float32)


def dropout(x: jax.Array, rate: float, rng: Optional[jax.Array]) -> jax.Array:
    """Inverted dropout; identity when ``rng`` is None (eval) or rate == 0."""
    if rng is None or rate <= 0.0:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))


def rotary_embedding(positions: jax.Array, head_dim: int, theta: float = 10000.0, dtype=jnp.float32):
    """RoPE cos/sin tables for ``positions`` [..., S] → two [..., S, D/2] arrays."""
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions[..., None].astype(jnp.float32) * freqs
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rotary(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Apply RoPE to [..., S, N, D] given [..., S, D/2] tables."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    cos = cos[..., None, :]  # broadcast over heads
    sin = sin[..., None, :]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def grouped_scores(q: jax.Array, k: jax.Array) -> jax.Array:
    """[B,S,N,D] x [B,T,KV,D] -> [B,N,S,T] attention logits; GQA query
    heads grouped onto their shared KV head (h reads kv head h // group) —
    the ONE definition of the head-grouping convention for every einsum
    attention path (model zoo, flash fallback, ring fallback)."""
    b, s, n, d = q.shape
    t, kv = k.shape[1], k.shape[2]
    if n != kv:
        group = n // kv
        qg = q.reshape(b, s, kv, group, d)
        return jnp.einsum("bskgd,btkd->bkgst", qg, k).reshape(b, n, s, t)
    return jnp.einsum("bsnd,btnd->bnst", q, k)


def grouped_output(p: jax.Array, v: jax.Array) -> jax.Array:
    """[B,N,S,T] probabilities x [B,T,KV,D] values -> [B,S,N,D] (GQA twin
    of :func:`grouped_scores`)."""
    b, n, s, t = p.shape
    kv, d = v.shape[2], v.shape[3]
    if n != kv:
        group = n // kv
        pg = p.reshape(b, kv, group, s, t)
        return jnp.einsum("bkgst,btkd->bskgd", pg, v).reshape(b, s, n, d)
    return jnp.einsum("bnst,btnd->bsnd", p, v)


def dot_product_attention(
    q: jax.Array,  # [B, S, N, D]
    k: jax.Array,  # [B, T, K, D]
    v: jax.Array,  # [B, T, K, D]
    mask: Optional[jax.Array] = None,  # [B, 1, S, T] or broadcastable, True = attend
    causal: bool = False,
    scale: Optional[float] = None,
    bias: Optional[jax.Array] = None,  # [1|B, N, S, T] additive (T5 rel bias)
) -> jax.Array:
    """Grouped-query attention; softmax in fp32 for stability."""
    b, s, n, d = q.shape
    t = k.shape[1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    logits = grouped_scores(q * scale, k).astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        causal_mask = jnp.tril(jnp.ones((s, t), dtype=bool), k=t - s)
        logits = jnp.where(causal_mask[None, None], logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return grouped_output(probs, v)
