"""Model configurations and the built-in registry.

The model zoo is pure JAX: parameters are pytrees of jnp arrays, models are
(init, apply) function pairs. This keeps abstract init (`jax.eval_shape`),
partition-rule matching (by pytree path), and checkpoint IO trivial — no
module-system indirection between the framework and XLA.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional


@dataclass(frozen=True)
class TransformerConfig:
    """One config for both decoder (llama-style) and encoder (bert-style) stacks."""

    arch: str = "llama"  # "llama" | "bert" | "gpt2" | "t5"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None  # grouped-query attention; None = num_heads
    head_dim: Optional[int] = None  # None = hidden_size // num_heads
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    # encoder-only extras
    type_vocab_size: int = 2
    num_labels: int = 2
    dropout_rate: float = 0.0
    # mixture-of-experts (decoder): num_experts > 1 swaps the gated MLP for a
    # top-k routed expert MLP sharded over the `expert` mesh axis
    num_experts: int = 1
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    # encoder-decoder (t5) extras: relative-position bias bucketing and the
    # decoder's BOS (t5 starts generation from the pad token)
    rel_buckets: int = 32
    rel_max_distance: int = 128
    decoder_start_token_id: int = 0

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def dim_per_head(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    def replace(self, **kwargs) -> "TransformerConfig":
        return replace(self, **kwargs)


_REGISTRY: dict[str, TransformerConfig] = {
    # llama family (decoder)
    "llama-tiny": TransformerConfig(
        arch="llama", vocab_size=1024, hidden_size=128, intermediate_size=352,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256,
    ),
    "llama-125m": TransformerConfig(
        arch="llama", vocab_size=32000, hidden_size=768, intermediate_size=2048,
        num_layers=12, num_heads=12, max_seq_len=2048,
    ),
    "llama-1b": TransformerConfig(
        arch="llama", vocab_size=32000, hidden_size=2048, intermediate_size=5504,
        num_layers=22, num_heads=16, max_seq_len=2048,
    ),
    "llama-7b": TransformerConfig(
        arch="llama", vocab_size=32000, hidden_size=4096, intermediate_size=11008,
        num_layers=32, num_heads=32, max_seq_len=4096,
    ),
    "llama-13b": TransformerConfig(
        arch="llama", vocab_size=32000, hidden_size=5120, intermediate_size=13824,
        num_layers=40, num_heads=40, max_seq_len=4096,
    ),
    "llama-70b": TransformerConfig(
        arch="llama", vocab_size=32000, hidden_size=8192, intermediate_size=28672,
        num_layers=80, num_heads=64, num_kv_heads=8, max_seq_len=4096,
    ),
    # moe variant of the decoder family (expert-parallel MLP)
    "llama-moe-tiny": TransformerConfig(
        arch="llama", vocab_size=1024, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256,
        num_experts=4, moe_top_k=2, moe_capacity_factor=2.0,
    ),
    # gpt2 family (decoder, learned positions + LayerNorm + tied embeddings) —
    # the reference's big-model benchmark lineage (GPT-J/NeoX, README.md:31-34)
    "gpt2-tiny": TransformerConfig(
        arch="gpt2", vocab_size=1024, hidden_size=128, intermediate_size=512,
        num_layers=2, num_heads=4, max_seq_len=256, tie_embeddings=True,
    ),
    "gpt2-124m": TransformerConfig(
        arch="gpt2", vocab_size=50257, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, max_seq_len=1024, tie_embeddings=True,
    ),
    "gpt2-355m": TransformerConfig(
        arch="gpt2", vocab_size=50257, hidden_size=1024, intermediate_size=4096,
        num_layers=24, num_heads=16, max_seq_len=1024, tie_embeddings=True,
    ),
    "gpt2-774m": TransformerConfig(
        arch="gpt2", vocab_size=50257, hidden_size=1280, intermediate_size=5120,
        num_layers=36, num_heads=20, max_seq_len=1024, tie_embeddings=True,
    ),
    "gpt2-1.5b": TransformerConfig(
        arch="gpt2", vocab_size=50257, hidden_size=1600, intermediate_size=6400,
        num_layers=48, num_heads=25, max_seq_len=1024, tie_embeddings=True,
    ),
    # t5 family (encoder-decoder) — reference examples/inference/t5.py and the
    # T0pp-11B row of benchmarks/README.md:35. num_layers counts layers PER
    # stack (encoder and decoder are symmetric); v1.0 geometry (ReLU FF, tied
    # embeddings with d_model^-0.5 logit scaling).
    "t5-tiny": TransformerConfig(
        arch="t5", vocab_size=1024, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, head_dim=32, max_seq_len=256,
        tie_embeddings=True, rel_buckets=8, rel_max_distance=32,
    ),
    "t5-small": TransformerConfig(
        arch="t5", vocab_size=32128, hidden_size=512, intermediate_size=2048,
        num_layers=6, num_heads=8, head_dim=64, max_seq_len=512, tie_embeddings=True,
    ),
    "t5-base": TransformerConfig(
        arch="t5", vocab_size=32128, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, head_dim=64, max_seq_len=512, tie_embeddings=True,
    ),
    "t5-large": TransformerConfig(
        arch="t5", vocab_size=32128, hidden_size=1024, intermediate_size=4096,
        num_layers=24, num_heads=16, head_dim=64, max_seq_len=512, tie_embeddings=True,
    ),
    "t5-3b": TransformerConfig(
        arch="t5", vocab_size=32128, hidden_size=1024, intermediate_size=16384,
        num_layers=24, num_heads=32, head_dim=128, max_seq_len=512, tie_embeddings=True,
    ),
    "t5-11b": TransformerConfig(
        arch="t5", vocab_size=32128, hidden_size=1024, intermediate_size=65536,
        num_layers=24, num_heads=128, head_dim=128, max_seq_len=512, tie_embeddings=True,
    ),
    # bert family (encoder) — nlp_example parity (BERT-base MRPC)
    "bert-tiny": TransformerConfig(
        arch="bert", vocab_size=1024, hidden_size=128, intermediate_size=512,
        num_layers=2, num_heads=2, max_seq_len=128,
    ),
    "bert-base": TransformerConfig(
        arch="bert", vocab_size=30522, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, max_seq_len=512, norm_eps=1e-12,
    ),
    "bert-large": TransformerConfig(
        arch="bert", vocab_size=30522, hidden_size=1024, intermediate_size=4096,
        num_layers=24, num_heads=16, max_seq_len=512, norm_eps=1e-12,
    ),
}


def config_from_hf_json(source) -> TransformerConfig:
    """Map a HF ``config.json`` (dict, file path, or directory containing
    one) to a :class:`TransformerConfig` — no weights needed.

    Parity: reference commands/estimate.py:215-299 builds a meta-device model
    for any Hub repo from its config alone; this is the offline analogue for
    the four zoo families (llama/mistral, gpt2, bert, t5).
    """
    import json
    import os

    if isinstance(source, str):
        path = source
        if os.path.isdir(path):
            path = os.path.join(path, "config.json")
        with open(path) as f:
            cfg = json.load(f)
    else:
        cfg = dict(source)

    mt = cfg.get("model_type", "")
    arch = {"llama": "llama", "mistral": "llama", "gpt2": "gpt2", "bert": "bert", "t5": "t5"}.get(mt)
    if arch is None:
        raise ValueError(
            f"Unsupported model_type {mt!r} in config.json — supported: "
            "llama, mistral, gpt2, bert, t5"
        )
    if arch == "llama":
        return TransformerConfig(
            arch="llama",
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            intermediate_size=cfg["intermediate_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=cfg["num_attention_heads"],
            num_kv_heads=cfg.get("num_key_value_heads"),
            head_dim=cfg.get("head_dim"),
            max_seq_len=cfg.get("max_position_embeddings", 2048),
            rope_theta=cfg.get("rope_theta", 10000.0),
            norm_eps=cfg.get("rms_norm_eps", 1e-5),
            tie_embeddings=cfg.get("tie_word_embeddings", False),
        )
    if arch == "gpt2":
        h = cfg["n_embd"]
        return TransformerConfig(
            arch="gpt2",
            vocab_size=cfg["vocab_size"],
            hidden_size=h,
            intermediate_size=cfg.get("n_inner") or 4 * h,
            num_layers=cfg["n_layer"],
            num_heads=cfg["n_head"],
            max_seq_len=cfg.get("n_positions", 1024),
            norm_eps=cfg.get("layer_norm_epsilon", 1e-5),
            tie_embeddings=True,
        )
    if arch == "bert":
        return TransformerConfig(
            arch="bert",
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            intermediate_size=cfg["intermediate_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=cfg["num_attention_heads"],
            max_seq_len=cfg.get("max_position_embeddings", 512),
            type_vocab_size=cfg.get("type_vocab_size", 2),
            norm_eps=cfg.get("layer_norm_eps", 1e-12),
        )
    # t5: symmetric stacks only (num_layers counts layers PER stack)
    dec = cfg.get("num_decoder_layers", cfg["num_layers"])
    if dec != cfg["num_layers"]:
        raise ValueError(
            f"asymmetric t5 stacks (encoder {cfg['num_layers']}, decoder {dec}) "
            "are not supported"
        )
    return TransformerConfig(
        arch="t5",
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["d_model"],
        intermediate_size=cfg["d_ff"],
        num_layers=cfg["num_layers"],
        num_heads=cfg["num_heads"],
        head_dim=cfg.get("d_kv", 64),
        max_seq_len=cfg.get("n_positions", 512),
        norm_eps=cfg.get("layer_norm_epsilon", 1e-6),
        tie_embeddings=cfg.get("tie_word_embeddings", True),
        rel_buckets=cfg.get("relative_attention_num_buckets", 32),
        rel_max_distance=cfg.get("relative_attention_max_distance", 128),
        decoder_start_token_id=cfg.get("decoder_start_token_id", 0),
    )


def get_config(name: str) -> TransformerConfig:
    if name not in _REGISTRY:
        raise KeyError(f"Unknown model {name!r}; available: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def register_config(name: str, config: TransformerConfig) -> None:
    _REGISTRY[name] = config


def list_models() -> list[str]:
    return sorted(_REGISTRY)


def train_flops_per_token(config: TransformerConfig, seq_len: int | None = None) -> float:
    """Training FLOPs per token: the standard 6·N dense estimate (fwd + bwd)
    plus 12·L·H·S for the self-attention score/context matmuls, which the
    parameter count does not see. Shared by MFU derivation in telemetry and
    the benchmark suite so the two can never disagree."""
    seq = seq_len if seq_len is not None else config.max_seq_len
    dense = 6.0 * param_count(config)
    attention = 12.0 * config.num_layers * config.hidden_size * seq
    return dense + attention


def train_flops_per_step(config: TransformerConfig, batch_size: int, seq_len: int) -> float:
    """Training FLOPs for one optimizer step over ``batch_size`` sequences."""
    return batch_size * seq_len * train_flops_per_token(config, seq_len)


def param_count(config: TransformerConfig) -> int:
    """Exact parameter count without materializing anything."""
    h, i, v = config.hidden_size, config.intermediate_size, config.vocab_size
    d, nh, nkv = config.dim_per_head, config.num_heads, config.kv_heads
    if config.arch == "llama":
        if config.num_experts > 1:
            mlp = h * config.num_experts + config.num_experts * 2 * h * i  # router + experts
        else:
            mlp = 3 * h * i  # gate, up, down
        per_layer = (
            h * (nh * d)          # q
            + 2 * h * (nkv * d)   # k, v
            + (nh * d) * h        # o
            + mlp
            + 2 * h               # two rmsnorms
        )
        total = v * h + config.num_layers * per_layer + h  # embed + layers + final norm
        if not config.tie_embeddings:
            total += h * v  # lm head
        return total
    if config.arch == "gpt2":
        embed = v * h + config.max_seq_len * h  # token + learned positions (tied head)
        per_layer = (
            h * 3 * h + 3 * h     # fused qkv with bias
            + h * h + h           # o with bias
            + h * i + i           # mlp up
            + i * h + h           # mlp down
            + 4 * h               # two layernorms (scale+bias)
        )
        return embed + config.num_layers * per_layer + 2 * h  # + final layernorm
    if config.arch == "t5":
        inner = nh * d
        attn = 4 * h * inner  # q, k, v (h→inner) + o (inner→h): equal byte counts
        ff = 2 * h * i
        enc_layer = attn + ff + 2 * h  # two rmsnorms
        dec_layer = 2 * attn + ff + 3 * h  # self + cross attention, three norms
        rel = 2 * config.rel_buckets * nh  # one table per stack
        return (
            v * h  # shared embedding (tied head)
            + config.num_layers * (enc_layer + dec_layer)
            + rel
            + 2 * h  # encoder + decoder final norms
        )
    if config.arch == "bert":
        embed = v * h + config.max_seq_len * h + config.type_vocab_size * h + 2 * h
        per_layer = (
            4 * (h * h + h)       # q,k,v,o with bias
            + h * i + i           # mlp up
            + i * h + h           # mlp down
            + 4 * h               # two layernorms (scale+bias)
        )
        pooler = h * h + h
        classifier = h * config.num_labels + config.num_labels
        return embed + config.num_layers * per_layer + pooler + classifier
    raise ValueError(f"unknown arch {config.arch}")
