"""Big-model inference: abstract init → device map → streamed execution.

Parity: reference big_modeling.py + hooks.py (§2.5 of SURVEY):
- init_empty_weights (big_modeling.py:56) → ``jax.eval_shape`` abstract init:
  zero bytes allocated, exact shapes/dtypes.
- infer_auto_device_map + dispatch_model (305) + AlignDevicesHook (hooks.py:
  212) → ``dispatch_model`` here returns a ``StreamedModel`` that keeps
  resident components on the TPU and streams cpu/disk layers through HBM with
  an async double buffer. No forward-patching: streaming is explicit in the
  run loop, and the per-layer compute is ONE jit program reused by every
  layer (static shapes — the XLA analogue of the hook's device juggling).
- cpu_offload / disk_offload (169/249) → thin wrappers over dispatch_model.
- load_checkpoint_and_dispatch (498) → same pipeline from a weights file.

Transfer design: each offloaded layer is *packed into one contiguous host
buffer* at dispatch time, so streaming a layer is a single DMA (the reference
moves every tensor separately through AlignDevicesHook — hooks.py:328-358);
unpacking into the nine weight views happens on-device inside the jitted
layer program, where slicing is HBM-bandwidth cheap. Layers stream and
execute in GROUPS (one jit program per group) to amortize per-program
dispatch latency; the group size is derived from ``stream_window_bytes``.

Memory invariant (benchmarks/README.md:44-46): device HBM holds the resident
components + at most two streamed layer *groups* (double buffer) — bounded by
``stream_window_bytes`` (default ``DEFAULT_STREAM_WINDOW_BYTES``); host RAM
holds only the offloaded components (memmap-backed when from disk).
"""

from __future__ import annotations

import os
from typing import Any, Mapping, Optional, Union

import numpy as np

import jax
import jax.numpy as jnp

from .logging import get_logger
from .models.config import TransformerConfig
from .models.llama import Llama
from .utils.modeling import _iter_flat as _flat_items, check_device_map, infer_auto_device_map
from .utils.offload import load_offloaded_weight, offload_weight, save_offload_index

logger = get_logger(__name__)

# default HBM budget for the double-buffered streamed-layer window
DEFAULT_STREAM_WINDOW_BYTES = 512 << 20

# kept for llama HF-name mapping stability; the packer itself is generic
LAYER_KEYS = ("attn_norm", "wq", "wk", "wv", "wo", "mlp_norm", "w_gate", "w_up", "w_down")


def init_empty_weights(model) -> Any:
    """Abstract parameters: shapes/dtypes with zero allocation.

    The reference monkey-patches nn.Module registration onto the meta device
    (big_modeling.py:121-166); functional init makes this a one-liner.
    """
    return jax.eval_shape(model.init, jax.random.key(0))


init_on_device = init_empty_weights  # parity alias


def _np_dtype(dtype) -> np.dtype:
    """numpy dtype for a jnp scalar type WITHOUT a device round trip.

    ``np.asarray(jnp.zeros((), dtype))`` would run a device op and fetch it —
    on tunneled TPU transports a single device→host fetch permanently drops
    host→device DMA to ~10 MB/s, wrecking the streaming path that follows.
    """
    return np.dtype(dtype)


def _device_put_packed(buf):
    """One DMA per buffer; quantized layers are (int8 data, fp sidecar) pairs."""
    if isinstance(buf, tuple):
        return tuple(jax.device_put(jnp.asarray(part)) for part in buf)
    return jax.device_put(jnp.asarray(buf))


def _bytes_view(buf) -> list[np.ndarray]:
    """Raw little-endian byte views of a packed host buffer (no copy for
    plain buffers; quantized (q, f) pairs yield two views)."""
    parts = buf if isinstance(buf, tuple) else (buf,)
    return [np.asarray(part).view(np.uint8).ravel() for part in parts]


def _bitcast_u8(u8: jax.Array, dtype) -> jax.Array:
    """Reinterpret a device uint8 buffer as ``dtype`` (on-device, free at
    HBM bandwidth — the XLA analogue of np.view)."""
    itemsize = _np_dtype(dtype).itemsize
    if itemsize == 1:
        return jax.lax.bitcast_convert_type(u8, dtype)
    return jax.lax.bitcast_convert_type(u8.reshape(-1, itemsize), dtype)


def _unflatten(flat: dict[str, Any]) -> dict:
    out: dict = {}
    for key, value in flat.items():
        node = out
        parts = key.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return out


class LayerPacker:
    """Fixed layout of one transformer layer in a single contiguous buffer.

    Works on ANY stacked-layers pytree (leaves shaped [L, ...]): the layout
    is derived from the tree itself, not from a model family (reference
    hooks.py:212 works on arbitrary modules — this is the analogue). Ordering
    is the sorted flattened key order, identical on pack and unpack.
    """

    def __init__(self, stacked_layers: Any, dtype):
        self.dtype = dtype
        self.shapes: dict[str, tuple] = {
            key: tuple(leaf.shape[1:]) for key, leaf in _flat_items(stacked_layers)
        }
        self.offsets: dict[str, tuple[int, int]] = {}
        offset = 0
        for key, shape in self.shapes.items():
            size = int(np.prod(shape)) if shape else 1
            self.offsets[key] = (offset, size)
            offset += size
        self.total = offset

    @classmethod
    def for_config(cls, cfg: TransformerConfig, dtype) -> "LayerPacker":
        """Layout from a llama config without materializing params (bench)."""
        h, i = cfg.hidden_size, cfg.intermediate_size
        nh, nkv, d = cfg.num_heads, cfg.kv_heads, cfg.dim_per_head
        shapes = {
            "attn_norm": (1, h), "mlp_norm": (1, h),
            "wq": (1, h, nh * d), "wk": (1, h, nkv * d), "wv": (1, h, nkv * d),
            "wo": (1, nh * d, h), "w_gate": (1, h, i), "w_up": (1, h, i), "w_down": (1, i, h),
        }
        return cls({k: np.empty(s, np.int8) for k, s in shapes.items()}, dtype)

    def pack(self, layer: Mapping[str, Any]) -> np.ndarray:
        np_dtype = _np_dtype(self.dtype)
        buf = np.empty((self.total,), np_dtype)
        flat = dict(_flat_items(layer))
        for key, (offset, size) in self.offsets.items():
            buf[offset : offset + size] = np.asarray(flat[key], np_dtype).ravel()
        return buf

    def unpack(self, buf: jax.Array) -> dict[str, jax.Array]:
        """On-device view extraction (static slices; used inside jit)."""
        out = {}
        for key, (offset, size) in self.offsets.items():
            out[key] = buf[offset : offset + size].reshape(self.shapes[key])
        return _unflatten(out)

    @property
    def layer_nbytes(self) -> int:
        """Packed byte footprint of one layer (group-buffer layout unit)."""
        return int(self.total * _np_dtype(self.dtype).itemsize)

    def from_bytes(self, u8: jax.Array) -> dict:
        """Unpack one layer from its raw byte slice of a group buffer
        (on-device bitcast; used inside jit)."""
        return self.unpack(_bitcast_u8(u8, self.dtype))


class _LayerStreamer:
    """Shared streaming machinery: packed layer buffers on device/host/disk,
    iterated with an async double buffer (device_put of layer i+1 is issued
    before layer i's compute is awaited — the H2D copy rides DMA while the
    MXU works)."""

    def __init__(
        self,
        model,
        layer_buffers,
        layer_on_device,
        packer: LayerPacker,
        dtype,
        stream_window_bytes: int = DEFAULT_STREAM_WINDOW_BYTES,
    ):
        self.model = model
        self.layer_buffers = layer_buffers  # packed 1D host buffers (np/memmap) or device arrays
        self.layer_on_device = layer_on_device
        self.packer = packer
        self.dtype = dtype
        self.hf_device_map: dict[str, str] = {}
        # Layers are streamed and EXECUTED in groups: one jitted program per
        # group instead of per layer. Remote/tunneled TPU transports pay tens
        # of ms of dispatch latency per program — per-layer dispatch dominates
        # decode otherwise. The group size is bounded by the HBM streaming
        # window: peak streaming memory ≈ 2 × group_size × layer_bytes
        # (double buffer), kept under ``stream_window_bytes``.
        self.stream_window_bytes = stream_window_bytes
        layer_bytes = self._layer_bytes()
        per_group = max(1, (stream_window_bytes // 2) // max(layer_bytes, 1))
        self.group_size = int(min(per_group, max(len(layer_buffers), 1)))

    def _layer_bytes(self) -> int:
        """Packed on-device footprint of one layer buffer."""
        return self.packer.layer_nbytes

    def _put(self, buf):
        return _device_put_packed(buf)

    def _put_group(self, idx: list[int]):
        """Stage one group: the offloaded layers' packed bytes concatenate
        into ONE contiguous uint8 host buffer and ride ONE async H2D DMA —
        remote/tunneled transports pay a fixed latency per transfer, so G
        per-layer puts (2G for quantized (q, f) pairs) cost G× the latency
        of one group put for the same bytes. Splitting back into per-layer
        params happens on device inside the jitted group program
        (packer.from_bytes — static slices + bitcast, HBM-bandwidth cheap).

        Returns ``(u8, resident, pattern)``: the group DMA (None when every
        layer is already on device), the device-resident packed buffers, and
        the static resident/streamed pattern that keys the group program.
        """
        pattern = tuple(bool(self.layer_on_device[i]) for i in idx)
        resident = tuple(self.layer_buffers[i] for i in idx if self.layer_on_device[i])
        host_parts: list[np.ndarray] = []
        for i in idx:
            if not self.layer_on_device[i]:
                host_parts.extend(_bytes_view(self.layer_buffers[i]))
        if not host_parts:
            return None, resident, pattern
        host = host_parts[0] if len(host_parts) == 1 else np.concatenate(host_parts)
        return jax.device_put(jnp.asarray(host)), resident, pattern

    def _group_indices(self) -> list[list[int]]:
        L = len(self.layer_buffers)
        g = self.group_size
        return [list(range(i, min(i + g, L))) for i in range(0, L, g)]

    def _iter_device_layer_groups(self):
        """Yield staged groups, double-buffering: group i's compute is
        dispatched (async) by the caller right after the yield, so group
        i+1's host-side concatenation AND its H2D DMA overlap group i's
        on-device execution."""
        groups = self._group_indices()
        if not groups:
            return
        staged = self._put_group(groups[0])
        for gi in range(len(groups)):
            yield staged
            staged = self._put_group(groups[gi + 1]) if gi + 1 < len(groups) else None


class QuantizedLayerPacker:
    """Layer packer with weight-only int8/int4 quantization (reference
    utils/bnb.py:44 load_and_quantize_model): matrix leaves are quantized per
    output channel into one contiguous int8 buffer; vectors (norms, biases)
    and the per-channel scales ride in a float32 sidecar buffer. ``unpack``
    dequantizes on device inside the jitted layer program (W8A16/W4A16)."""

    def __init__(self, stacked_layers: Any, dtype, bits: int = 8, skip: Optional[list[str]] = None):
        from .utils.quantization import quantize_weight  # noqa: F401 - used in pack

        self.dtype = dtype
        self.bits = bits
        skip = skip or []
        self.shapes: dict[str, tuple] = {
            key: tuple(leaf.shape[1:]) for key, leaf in _flat_items(stacked_layers)
        }
        self.quant_keys = [
            k for k, shape in self.shapes.items() if len(shape) >= 2 and not any(s in k for s in skip)
        ]
        self.full_keys = [k for k in self.shapes if k not in self.quant_keys]

        self.q_offsets: dict[str, tuple[int, int]] = {}
        offset = 0
        for key in self.quant_keys:
            shape = self.shapes[key]
            size = int(np.prod(shape))
            if bits == 4:
                size //= 2
            self.q_offsets[key] = (offset, size)
            offset += size
        self.q_total = offset

        self.f_offsets: dict[str, tuple[int, int]] = {}
        offset = 0
        for key in self.full_keys:
            size = int(np.prod(self.shapes[key])) if self.shapes[key] else 1
            self.f_offsets[key] = (offset, size)
            offset += size
        for key in self.quant_keys:  # per-output-channel scales
            size = self.shapes[key][-1]
            self.f_offsets[f"{key}@scale"] = (offset, size)
            offset += size
        self.f_total = offset

    def pack(self, layer: Mapping[str, Any]):
        from .utils.quantization import quantize_weight

        flat = dict(_flat_items(layer))
        qbuf = np.empty((self.q_total,), np.int8)
        fbuf = np.empty((self.f_total,), np.float32)
        for key in self.quant_keys:
            q, scale = quantize_weight(np.asarray(flat[key]), bits=self.bits)
            offset, size = self.q_offsets[key]
            qbuf[offset : offset + size] = q.ravel()
            f_off, f_size = self.f_offsets[f"{key}@scale"]
            fbuf[f_off : f_off + f_size] = scale
        for key in self.full_keys:
            offset, size = self.f_offsets[key]
            fbuf[offset : offset + size] = np.asarray(flat[key], np.float32).ravel()
        return (qbuf, fbuf)

    @property
    def layer_nbytes(self) -> int:
        """Packed byte footprint (int8 data + fp32 sidecar) of one layer."""
        return int(self.q_total + self.f_total * 4)

    def from_bytes(self, u8: jax.Array) -> dict:
        """Unpack one quantized layer from its byte slice of a group buffer:
        the int8 data and the fp32 sidecar ride ONE buffer (one DMA), split
        and bitcast on device inside the jitted program."""
        q = _bitcast_u8(u8[: self.q_total], jnp.int8)
        f = _bitcast_u8(u8[self.q_total :], jnp.float32)
        return self.unpack((q, f))

    def unpack(self, bufs, quantized_resident: bool = False) -> dict:
        """Unpack one layer. ``quantized_resident=True`` (the kernel-layer
        serving path, ops/quant_matmul.py) keeps 2-D matrix leaves PACKED as
        :class:`~.utils.quantization.QuantizedWeight` instead of
        dequantizing — the fused dequant-matmul then reads them 1
        byte/element and the bf16 shadow never exists. Non-matrix leaves
        and >2-D leaves (MoE expert stacks, consumed by einsum rather than
        the ``dot_fn`` hook) dequantize exactly as before. The buffer
        layout is sliced in ONE place for both modes, so the packed path
        can never drift from the shadowed one."""
        from .utils.quantization import QuantizedWeight, dequantize_weight

        qbuf, fbuf = bufs
        out = {}
        for key in self.quant_keys:
            shape = self.shapes[key]
            offset, size = self.q_offsets[key]
            stored_shape = (shape[0] // 2,) + shape[1:] if self.bits == 4 else shape
            q = qbuf[offset : offset + size].reshape(stored_shape)
            f_off, f_size = self.f_offsets[f"{key}@scale"]
            scale = fbuf[f_off : f_off + f_size]
            if quantized_resident and len(shape) == 2:
                out[key] = QuantizedWeight(q, scale, self.bits, self.dtype)
            else:
                out[key] = dequantize_weight(q, scale, self.bits, self.dtype)
        for key in self.full_keys:
            offset, size = self.f_offsets[key]
            out[key] = fbuf[offset : offset + size].reshape(self.shapes[key]).astype(self.dtype)
        return _unflatten(out)


class StreamedModel(_LayerStreamer):
    """Generic streaming executor for any model exposing the stream protocol:

    - ``stream_prefix(resident, *args, **kwargs) -> carry`` (a pytree)
    - ``stream_layer(carry, layer_params) -> carry``
    - ``stream_suffix(resident, carry) -> output``

    where ``resident`` is the param tree minus ``layers``. The per-layer
    compute is ONE jit program reused by every layer; non-resident layers
    stream through HBM with the async double buffer. This replaces the
    reference's forward-patched AlignDevicesHook on arbitrary modules
    (hooks.py:212-382) without touching the model's code.
    """

    def __init__(
        self, model, resident_flat, layer_buffers, layer_on_device, packer, dtype,
        stream_window_bytes: int = DEFAULT_STREAM_WINDOW_BYTES,
        host_shadow: Optional[dict] = None,
    ):
        super().__init__(
            model, layer_buffers, layer_on_device, packer, dtype,
            stream_window_bytes=stream_window_bytes,
        )
        self.config = getattr(model, "config", None)
        # flat {component: array-or-host-buffer} dict; public because tools
        # and benchmarks introspect resident placement
        self.resident = self._resident_flat = resident_flat
        self._group_fns: dict = {}
        # host copies of device-placed buffers: lets evict() free the HBM
        # without a device→host fetch (see _place_components)
        self._host_shadow = host_shadow or {"resident": {}, "layers": {}}
        self._evicted = False
        # another model's offload hook, run before this model executes
        # (cpu_offload_with_hook pipeline-of-models chaining)
        self._prev_hook: Optional["UserOffloadHook"] = None

    # -- evict / restore (reference cpu_offload_with_hook, big_modeling.py:
    # 215-302: run model A, evict, run model B within one HBM budget) --------

    def evict(self) -> "StreamedModel":
        """Drop every device-resident buffer back to its host copy, freeing
        the HBM this model holds. The placement map is unchanged — the next
        :meth:`restore` (or any execution, which restores implicitly)
        re-uploads exactly the original resident set."""
        if self._evicted:
            return self
        for key, host in self._host_shadow["resident"].items():
            live = self._resident_flat[key]
            if isinstance(live, jax.Array):
                live.delete()
            self._resident_flat[key] = host
        for i, packed in self._host_shadow["layers"].items():
            live = self.layer_buffers[i]
            for part in live if isinstance(live, tuple) else (live,):
                if isinstance(part, jax.Array):
                    part.delete()
            self.layer_buffers[i] = packed
            self.layer_on_device[i] = False
        self._evicted = True
        return self

    def restore(self) -> "StreamedModel":
        """Re-upload the originally device-placed buffers after an evict."""
        if not self._evicted:
            return self
        for key in self._host_shadow["resident"]:
            self._resident_flat[key] = jax.device_put(jnp.asarray(self._resident_flat[key]))
        for i in self._host_shadow["layers"]:
            self.layer_buffers[i] = _device_put_packed(self.layer_buffers[i])
            self.layer_on_device[i] = True
        self._evicted = False
        return self

    def _before_execute(self):
        """Pipeline-of-models choreography: evict the previous model in the
        chain, then make sure this one is resident."""
        if self._prev_hook is not None:
            self._prev_hook.offload()
        if self._evicted:
            self.restore()

    def resident_tree(self) -> dict:
        """Nested resident params, streaming host/disk leaves to the device."""
        return _unflatten(
            {
                key: value if isinstance(value, jax.Array) else self._put(np.asarray(value))
                for key, value in self._resident_flat.items()
            }
        )

    def _jit_cache(self, store_name: str, key, build):
        """Per-concern jit cache, dot_fn-invalidated (utils/jit_cache.py)."""
        from .utils.jit_cache import dot_keyed_jit

        return dot_keyed_jit(self, store_name, key, build, dot_holder=self.model)

    def _iter_group_layers(self, pattern, u8, resident_bufs):
        """Per-layer param trees of one staged group, inside jit: resident
        buffers unpack directly; streamed layers slice the group's byte
        buffer at static offsets and bitcast (packer.from_bytes)."""
        packer = self.packer
        nbytes = packer.layer_nbytes
        ri = off = 0
        for is_resident in pattern:
            if is_resident:
                yield packer.unpack(resident_bufs[ri])
                ri += 1
            else:
                yield packer.from_bytes(u8[off : off + nbytes])
                off += nbytes

    def _get_group_fn(self, pattern: tuple):
        stream_layer = self.model.stream_layer
        iter_layers = self._iter_group_layers

        def build():
            @jax.jit
            def group_fn(carry, u8, resident_bufs):
                for lp in iter_layers(pattern, u8, resident_bufs):
                    carry = stream_layer(carry, lp)
                return carry

            return group_fn

        return self._jit_cache("_group_fns", pattern, build)

    def __call__(self, *args, **kwargs):
        self._before_execute()
        resident = self.resident_tree()
        carry = self.model.stream_prefix(resident, *args, **kwargs)
        for u8, res, pattern in self._iter_device_layer_groups():
            carry = self._get_group_fn(pattern)(carry, u8, res)
        return self.model.stream_suffix(resident, carry)

    # -- streamed KV-cache decode (models exposing the decode protocol:
    #    init_layer_cache / decode_prefix / stream_layer_cached / decode_suffix)

    def _get_decode_prelude(self, max_len: int):
        model = self.model

        def build():
            @jax.jit
            def prelude(resident, current, length):
                carry = model.decode_prefix(resident, current, length, max_len)
                return carry, length + current.shape[1]

            return prelude

        return self._jit_cache("_decode_preludes", max_len, build)

    def _get_decode_group_fn(self, pattern: tuple):
        model = self.model
        iter_layers = self._iter_group_layers

        def build():
            @jax.jit
            def fn(carry, u8, resident_bufs, caches, length):
                new_caches = []
                for lp, c in zip(iter_layers(pattern, u8, resident_bufs), caches):
                    carry, nc = model.stream_layer_cached(carry, lp, c, length)
                    new_caches.append(nc)
                return carry, tuple(new_caches)

            return fn

        return self._jit_cache("_decode_group_fns", pattern, build)

    def _get_decode_tail(self, sampled: bool):
        model = self.model

        def build():
            @jax.jit
            def tail(resident, carry, rng, temperature):
                logits = model.decode_suffix(resident, carry)
                if sampled:
                    rng, sub = jax.random.split(rng)
                    nxt = jax.random.categorical(sub, logits / temperature, axis=-1)
                else:
                    nxt = jnp.argmax(logits, axis=-1)
                return nxt.astype(jnp.int32), rng

            return tail

        return self._jit_cache("_decode_tails", sampled, build)

    def generate(
        self,
        input_ids,
        max_new_tokens: int = 20,
        temperature: float = 0.0,
        rng=None,
        return_device: bool = False,
    ):
        """Streamed KV-cache decode for any model implementing the decode
        protocol: grouped fetch-free decode — tokens accumulate on device
        and convert to numpy in one transfer at the end."""
        if not hasattr(self.model, "stream_layer_cached"):
            raise TypeError(
                f"{type(self.model).__name__} has no streamed-decode protocol "
                "(init_layer_cache/decode_prefix/stream_layer_cached/decode_suffix)"
            )
        self._before_execute()
        input_ids = jnp.asarray(input_ids, jnp.int32)
        b, s = input_ids.shape
        max_len = s + max_new_tokens
        L = len(self.layer_buffers)
        caches = [self.model.init_layer_cache(b, max_len, self.dtype) for _ in range(L)]
        if rng is None:
            rng = jax.random.key(0)
        temp = jnp.asarray(max(temperature, 1e-6), jnp.float32)
        resident = self.resident_tree()
        prelude = self._get_decode_prelude(max_len)
        tail = self._get_decode_tail(temperature > 0.0)
        groups = self._group_indices()

        tokens = [input_ids]
        current = input_ids
        length = jnp.zeros((), jnp.int32)
        for _ in range(max_new_tokens):
            carry, new_length = prelude(resident, current, length)
            for idx, (u8, res, pattern) in zip(groups, self._iter_device_layer_groups()):
                gcaches = tuple(caches[i] for i in idx)
                carry, new_caches = self._get_decode_group_fn(pattern)(
                    carry, u8, res, gcaches, length
                )
                for i, nc in zip(idx, new_caches):
                    caches[i] = nc
            nxt, rng = tail(resident, carry, rng, temp)
            length = new_length
            current = nxt[:, None]
            tokens.append(current)
        out = jnp.concatenate(tokens, axis=1)
        return out if return_device else np.asarray(out)


# kept as a name for the causal-LM dispatch result (historical API); all
# machinery lives on StreamedModel via the model's stream/decode protocols
StreamedCausalLM = StreamedModel


class Seq2SeqStreamedModel(StreamedModel):
    """Streaming executor for encoder-decoder models (T5 family).

    Reference parity: examples/inference/t5.py (pippy PP over T5). The
    full-sequence ``__call__`` path is inherited unchanged (the model's
    stream_prefix runs the encoder). ``generate`` differs from the causal
    loop: ``input_ids`` are ENCODER inputs, run once through a jitted
    resident-encoder program; the decode loop then streams the decoder stack
    per token starting from ``config.decoder_start_token_id``, with the
    encoder output carried into every layer's cross-attention.
    """

    def _get_encoder_fn(self, s_enc: int, has_mask: bool):
        model = self.model

        def build():
            # use_hooks=False: the model may carry a stale mesh-bound
            # enc_pipeline_fn from an earlier prepare_model; the streaming
            # executor is single-device and must not trace that schedule
            if has_mask:
                return jax.jit(
                    lambda resident, ids, am: model.encode(resident, ids, am, use_hooks=False)
                )
            return jax.jit(lambda resident, ids: model.encode(resident, ids, use_hooks=False))

        return self._jit_cache("_encoder_fns", (s_enc, has_mask), build)

    def _get_seq2seq_prelude(self, max_len: int):
        model = self.model

        def build():
            @jax.jit
            def prelude(resident, current, length, enc_out, enc_mask):
                carry = model.decode_prefix(
                    resident, current, length, max_len, enc_out=enc_out, enc_mask=enc_mask
                )
                return carry, length + current.shape[1]

            return prelude

        return self._jit_cache("_decode_preludes", max_len, build)

    def generate(
        self,
        input_ids,
        max_new_tokens: int = 20,
        temperature: float = 0.0,
        rng=None,
        return_device: bool = False,
        attention_mask=None,
    ):
        """Streamed seq2seq decode: one encoder pass, then fetch-free
        KV-cached decoder streaming (tokens accumulate on device). Returns
        the DECODER sequence [B, 1 + max_new_tokens] (start token included)."""
        self._before_execute()
        input_ids = jnp.asarray(input_ids, jnp.int32)
        b = input_ids.shape[0]
        max_len = 1 + max_new_tokens
        L = len(self.layer_buffers)
        caches = [self.model.init_layer_cache(b, max_len, self.dtype) for _ in range(L)]
        if rng is None:
            rng = jax.random.key(0)
        temp = jnp.asarray(max(temperature, 1e-6), jnp.float32)
        resident = self.resident_tree()

        has_mask = attention_mask is not None
        enc_fn = self._get_encoder_fn(input_ids.shape[1], has_mask)
        if has_mask:
            attention_mask = jnp.asarray(attention_mask, jnp.int32)
            enc_out = enc_fn(resident, input_ids, attention_mask)
            enc_mask = attention_mask[:, None, None, :].astype(bool)
        else:
            enc_out = enc_fn(resident, input_ids)
            enc_mask = jnp.ones((b, 1, 1, input_ids.shape[1]), bool)

        prelude = self._get_seq2seq_prelude(max_len)
        tail = self._get_decode_tail(temperature > 0.0)
        groups = self._group_indices()

        current = jnp.full((b, 1), self.config.decoder_start_token_id, jnp.int32)
        tokens = [current]
        length = jnp.zeros((), jnp.int32)
        for _ in range(max_new_tokens):
            carry, new_length = prelude(resident, current, length, enc_out, enc_mask)
            for idx, (u8, res, pattern) in zip(groups, self._iter_device_layer_groups()):
                gcaches = tuple(caches[i] for i in idx)
                carry, new_caches = self._get_decode_group_fn(pattern)(
                    carry, u8, res, gcaches, length
                )
                for i, nc in zip(idx, new_caches):
                    caches[i] = nc
            nxt, rng = tail(resident, carry, rng, temp)
            length = new_length
            current = nxt[:, None]
            tokens.append(current)
        out = jnp.concatenate(tokens, axis=1)
        return out if return_device else np.asarray(out)


def _place_components(params, device_map, offload_dir, dtype, quantization=None):
    """Shared placement: resident leaves + packed per-layer buffers.

    Also returns ``host_shadow`` — host copies of every DEVICE-placed buffer,
    kept so :meth:`StreamedModel.evict` can free the HBM without a
    device→host fetch (a single D2H fetch permanently degrades H2D DMA on
    tunneled transports; the weights already exist on the host here).
    """
    np_dtype = _np_dtype(dtype)

    resident: dict[str, Any] = {}
    host_shadow: dict[str, Any] = {"resident": {}, "layers": {}}
    for key, leaf in _flat_items({k: v for k, v in params.items() if k != "layers"}):
        target = device_map.get(key.replace("/", "."), "device")
        host = np.asarray(leaf, np_dtype)
        if target == "device":
            resident[key] = jax.device_put(jnp.asarray(host))
            host_shadow["resident"][key] = host
        elif target == "cpu":
            resident[key] = host
        elif target == "disk":
            if offload_dir is None:
                raise ValueError(f"device_map places {key} on disk — pass offload_dir")
            os.makedirs(offload_dir, exist_ok=True)
            disk_name = key.replace("/", ".")
            disk_meta = offload_weight(host, disk_name, offload_dir, {})
            resident[key] = load_offloaded_weight(
                os.path.join(offload_dir, f"{disk_name}.dat"), disk_meta[disk_name]
            )
        else:
            raise ValueError(f"Unknown target {target!r} for {key}")

    if quantization is not None:
        packer: Any = QuantizedLayerPacker(
            params["layers"], dtype, bits=quantization.bits, skip=quantization.skip_modules
        )
    else:
        packer = LayerPacker(params["layers"], dtype)
    stacked = {k: np.asarray(v) for k, v in _flat_items(params["layers"])}
    num_layers = next(iter(stacked.values())).shape[0]
    layer_buffers: list[Any] = []
    layer_on_device: list[bool] = []
    disk_index: dict = {}

    def _to_disk(packed, name):
        nonlocal disk_index
        parts = packed if isinstance(packed, tuple) else (packed,)
        loaded = []
        for j, part in enumerate(parts):
            part_name = f"{name}.{j}" if len(parts) > 1 else name
            disk_index = offload_weight(part, part_name, offload_dir, disk_index)
            loaded.append(
                load_offloaded_weight(os.path.join(offload_dir, f"{part_name}.dat"), disk_index[part_name])
            )
        return tuple(loaded) if isinstance(packed, tuple) else loaded[0]

    for i in range(num_layers):
        layer = {k: v[i] for k, v in stacked.items()}
        target = device_map.get(f"layers.{i}", "device")
        packed = packer.pack(layer)
        if target == "device":
            layer_buffers.append(_device_put_packed(packed))
            layer_on_device.append(True)
            host_shadow["layers"][i] = packed
        elif target == "cpu":
            layer_buffers.append(packed)
            layer_on_device.append(False)
        elif target == "disk":
            if offload_dir is None:
                raise ValueError("device_map places layers on disk — pass offload_dir")
            os.makedirs(offload_dir, exist_ok=True)
            layer_buffers.append(_to_disk(packed, f"layers.{i}.packed"))
            layer_on_device.append(False)
        else:
            raise ValueError(f"Unknown target {target!r} for layers.{i}")
    if disk_index:
        save_offload_index(disk_index, offload_dir)
    return resident, packer, layer_buffers, layer_on_device, host_shadow


def dispatch_model(
    model: Any,
    params: Any,
    device_map: dict[str, str] | str = "auto",
    max_memory: Optional[dict] = None,
    offload_dir: Optional[str] = None,
    dtype=jnp.bfloat16,
    quantization=None,  # utils.quantization.QuantizationConfig → W8A16/W4A16 layers
    stream_window_bytes: int = DEFAULT_STREAM_WINDOW_BYTES,  # HBM budget for streamed layer groups
):
    """Place components per ``device_map`` and return the streaming executor.

    Parity: reference dispatch_model (big_modeling.py:305) + hook attachment.
    Any model implementing the stream protocol (``stream_prefix`` /
    ``stream_layer`` / ``stream_suffix``) gets a ``StreamedModel``; models
    with the decode protocol additionally get KV-cache ``generate``.
    """
    if not isinstance(model, Llama) and not hasattr(model, "stream_layer"):
        raise TypeError(
            f"{type(model).__name__} cannot be dispatched: implement the stream "
            "protocol (stream_prefix/stream_layer/stream_suffix) or use a "
            "llama-family model."
        )
    dtype_bytes: float = _np_dtype(dtype).itemsize
    # auto placement sizes layers at their QUANTIZED footprint (resident
    # components stay full precision), or capacity is mis-estimated 2-4x
    layer_dtype_bytes = quantization.bits / 8 if quantization is not None else None
    if isinstance(device_map, str):
        device_map = infer_auto_device_map(
            model, max_memory=max_memory, dtype_bytes=dtype_bytes, layer_dtype_bytes=layer_dtype_bytes
        )
    check_device_map(model, device_map)

    resident, packer, layer_buffers, layer_on_device, host_shadow = _place_components(
        params, device_map, offload_dir, dtype, quantization=quantization
    )

    cls = Seq2SeqStreamedModel if getattr(model, "is_encoder_decoder", False) else StreamedModel
    dispatched = cls(
        model, resident, layer_buffers, layer_on_device, packer, dtype,
        stream_window_bytes=stream_window_bytes, host_shadow=host_shadow,
    )
    dispatched.hf_device_map = dict(device_map)
    return dispatched


def make_layered_device_map(model, layer_target: str) -> dict[str, str]:
    """Device map sending every ``layers.*`` entry to ``layer_target``
    (device/cpu/disk) and every other component to the device — the placement
    rule behind cpu_offload/disk_offload, exported for scripts that want the
    same split explicitly."""
    from .utils.modeling import named_component_sizes

    return {
        key: (layer_target if key.startswith("layers.") else "device")
        for key in named_component_sizes(model)
    }


def cpu_offload(model: Any, params: Any, dtype=jnp.bfloat16):
    """Everything streamed from host RAM (reference big_modeling.py:169)."""
    return dispatch_model(model, params, make_layered_device_map(model, "cpu"), dtype=dtype)


def disk_offload(model: Any, params: Any, offload_dir: str, dtype=jnp.bfloat16):
    """Everything streamed from disk memmaps (reference big_modeling.py:249)."""
    return dispatch_model(model, params, make_layered_device_map(model, "disk"), offload_dir=offload_dir, dtype=dtype)


class UserOffloadHook:
    """User handle to evict a dispatched model (reference UserCpuOffloadHook,
    hooks.py). ``offload()`` frees the model's HBM; the model restores itself
    automatically on its next execution."""

    def __init__(self, streamed: StreamedModel):
        self.model = streamed

    def offload(self) -> None:
        self.model.evict()

    def remove(self) -> None:
        """Detach the chained previous-model hook (parity with the reference's
        remove_hook_from_module semantics)."""
        self.model._prev_hook = None


def cpu_offload_with_hook(
    model: Any,
    params: Any,
    dtype=jnp.bfloat16,
    prev_module_hook: Optional[UserOffloadHook] = None,
) -> tuple[StreamedModel, UserOffloadHook]:
    """Pipeline-of-models offload (reference big_modeling.py:215-302).

    Unlike :func:`cpu_offload` — which streams every layer on every forward —
    the model here is dispatched fully DEVICE-resident and *stays* resident
    across executions; it only leaves the HBM when the returned hook's
    ``offload()`` runs. Chain hooks through ``prev_module_hook`` to run
    several models alternately inside one HBM budget::

        lm1, hook1 = cpu_offload_with_hook(model1, params1)
        lm2, hook2 = cpu_offload_with_hook(model2, params2, prev_module_hook=hook1)
        lm1(x)          # model1 uploads
        lm2(y)          # model1 evicts first, then model2 uploads
        hook2.offload() # free model2 explicitly

    Construction is HBM-free (reference semantics: the model sits on CPU
    until its first forward): the dispatched model starts in the EVICTED
    state with an all-device restore target, so chaining N models never
    holds more than the executing one resident.
    """
    from .utils.modeling import named_component_sizes

    # place everything on the host, then mark the whole set as the evicted
    # image of an all-device placement — the first execution restores it
    all_cpu = {key: "cpu" for key in named_component_sizes(model)}
    dispatched = dispatch_model(model, params, all_cpu, dtype=dtype)
    dispatched._host_shadow = {
        "resident": dict(dispatched._resident_flat),
        "layers": {i: buf for i, buf in enumerate(dispatched.layer_buffers)},
    }
    dispatched._evicted = True
    dispatched._prev_hook = prev_module_hook
    return dispatched, UserOffloadHook(dispatched)


def load_checkpoint_and_dispatch(
    model: Any,
    checkpoint: str,
    device_map: dict[str, str] | str = "auto",
    max_memory: Optional[dict] = None,
    offload_dir: Optional[str] = None,
    dtype=jnp.bfloat16,
    stream_window_bytes: int = DEFAULT_STREAM_WINDOW_BYTES,
) -> StreamedModel:
    """Load weights and dispatch (big_modeling.py:498) for any model
    implementing the stream protocol. Accepts the native flat layout
    ("layers/wq" stacked tensors) for every family; llama models additionally
    accept the HuggingFace/torch layout
    ("model.layers.0.self_attn.q_proj.weight" …), translated (transpose +
    restack) by utils/hf_import.py."""
    from .utils.hf_import import load_checkpoint_in_model

    params = load_checkpoint_in_model(model, checkpoint)
    return dispatch_model(
        model, params, device_map=device_map, max_memory=max_memory, offload_dir=offload_dir,
        dtype=dtype, stream_window_bytes=stream_window_bytes,
    )


def load_and_quantize_model(
    model: Any,
    quantization_config,
    weights_location: Optional[str] = None,
    params: Any = None,
    device_map: dict[str, str] | str = "auto",
    max_memory: Optional[dict] = None,
    offload_dir: Optional[str] = None,
    dtype=jnp.bfloat16,
    stream_window_bytes: int = DEFAULT_STREAM_WINDOW_BYTES,
):
    """Reference utils/bnb.py:44 — load a checkpoint and dispatch with layer
    weights quantized to int8/int4 (per-output-channel scales, dequantized on
    device inside the jitted layer program)."""
    if params is None:
        if weights_location is None:
            raise ValueError("Pass weights_location (a checkpoint) or params.")
        from .utils.hf_import import load_checkpoint_in_model

        params = load_checkpoint_in_model(model, weights_location)
    return dispatch_model(
        model,
        params,
        device_map=device_map,
        max_memory=max_memory,
        offload_dir=offload_dir,
        dtype=dtype,
        quantization=quantization_config,
        stream_window_bytes=stream_window_bytes,
    )
