"""LocalSGD: independent per-worker updates with periodic parameter averaging.

Capability parity: reference local_sgd.py:19-102 — under DDP ``no_sync``,
each rank steps its own replica and every ``local_sgd_steps`` steps the
params are all-reduce-averaged (``_sync_and_avg_model_params``, :94-102).

TPU-native shape: in SPMD the gradient all-reduce is fused into the compiled
step, so "skipping sync" is not a flag — it is a *different program*. Here
each data-parallel worker gets its own parameter replica as a leading
``[W, ...]`` axis sharded over the ``data`` mesh axis; the local step is the
user's update ``vmap``-ed over that axis (no cross-worker communication —
XLA partitions the batched program so each device updates only its slice),
and the periodic sync is a mean over the worker axis (XLA emits the
all-reduce). Communication therefore drops from every-step gradient
all-reduce to one parameter average per ``local_sgd_steps`` — the actual
point of LocalSGD on DCN-connected topologies.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import optax

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..state import AcceleratorState
from ..utils.constants import MESH_AXIS_DATA


class LocalSGD:
    """Context manager running a model's training with per-worker replicas.

    Usage (API parity with the reference, adapted to the functional step)::

        with LocalSGD(accelerator, model, optimizer_tx, local_sgd_steps=8) as lsgd:
            for batch in loader:
                loss = lsgd.step(loss_fn, batch)   # local update on each worker
        # on exit: replicas averaged and written back to model.params

    ``optimizer_tx`` is a raw optax transformation — each worker keeps its
    own optimizer state (matching the reference, which leaves per-rank
    optimizer state unsynced and averages only params).
    """

    def __init__(
        self,
        accelerator=None,
        model=None,
        optimizer_tx=None,
        local_sgd_steps: int = 8,
        enabled: bool = True,
    ):
        if model is None or optimizer_tx is None:
            raise ValueError("LocalSGD needs a prepared model and an optax transformation.")
        self.accelerator = accelerator
        self.model = model
        self.tx = optimizer_tx
        # enabled=False = true synchronized training in the same loop
        # (reference local_sgd.py:45): no replicas at all — one update on the
        # full batch. (Syncing replicas every step is only equivalent for
        # linear optimizers like SGD; Adam moments built on 1/W shards would
        # diverge, so the disabled path avoids the worker axis entirely.)
        self.enabled = enabled
        self.local_sgd_steps = max(int(local_sgd_steps), 1)
        self.mesh = accelerator.mesh if accelerator is not None else AcceleratorState().mesh
        self.num_workers = self.mesh.shape.get(MESH_AXIS_DATA, 1)
        self._counter = 0
        self._step_fns: dict = {}  # keyed by loss_fn object (cf. Accelerator._grad_fns)
        self._sync_fn = None
        self._params_w = None
        self._opt_w = None

    # -- worker-axis plumbing ------------------------------------------------

    def _worker_sharding(self, leaf_ndim: int) -> NamedSharding:
        return NamedSharding(self.mesh, P(MESH_AXIS_DATA, *([None] * leaf_ndim)))

    def _stack(self, tree: Any) -> Any:
        w = self.num_workers
        return jax.tree.map(
            lambda x: jax.device_put(
                jnp.broadcast_to(x[None], (w,) + tuple(x.shape)), self._worker_sharding(x.ndim)
            ),
            tree,
        )

    def __enter__(self) -> "LocalSGD":
        self._counter = 0
        if not self.enabled:
            self._params_w = self.model.params
            self._opt_w = self.tx.init(self.model.params)
            return self
        self._params_w = self._stack(self.model.params)
        self._opt_w = jax.vmap(self.tx.init)(self._params_w)
        return self

    def __exit__(self, *exc) -> None:
        if self._params_w is None:
            return
        if self.enabled:
            self._sync()
            # write the averaged replica back onto the model's own shardings
            averaged = jax.tree.map(lambda x: x[0], self._params_w)
            self.model.params = jax.device_put(averaged, self.model.params_shardings)
        else:
            self.model.params = jax.device_put(self._params_w, self.model.params_shardings)
        self._params_w = self._opt_w = None

    # -- the local step ------------------------------------------------------

    def _build_step(self, loss_fn: Callable):
        tx = self.tx
        w = self.num_workers

        def one_worker(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        if not self.enabled:  # plain synchronous update, no worker axis
            return jax.jit(one_worker)

        @jax.jit
        def step(params_w, opt_w, batch):
            # [B, ...] -> [W, B/W, ...]: each worker sees only its shard
            batch_w = jax.tree.map(
                lambda x: x.reshape((w, x.shape[0] // w) + x.shape[1:]), batch
            )
            return jax.vmap(one_worker)(params_w, opt_w, batch_w)

        return step

    def step(self, loss_fn: Callable, batch: Any) -> jax.Array:
        """One independent update per worker; mean loss returned. Syncs every
        ``local_sgd_steps`` calls (reference LocalSGD.step, local_sgd.py:81);
        with ``enabled=False`` every step syncs — plain synchronous SGD."""
        if self._params_w is None:
            raise RuntimeError("LocalSGD.step() outside the context manager.")
        if loss_fn not in self._step_fns:
            self._step_fns[loss_fn] = self._build_step(loss_fn)
        self._params_w, self._opt_w, losses = self._step_fns[loss_fn](self._params_w, self._opt_w, batch)
        self._counter += 1
        if self.enabled and self._counter % self.local_sgd_steps == 0:
            self._sync()
        return losses.mean()

    def _sync(self) -> None:
        """Average the replicas (reference _sync_and_avg_model_params)."""
        if self._sync_fn is None:
            self._sync_fn = jax.jit(
                lambda p: jax.tree.map(lambda x: jnp.broadcast_to(x.mean(0)[None], x.shape), p)
            )
        self._params_w = self._sync_fn(self._params_w)

    @property
    def params(self) -> Any:
        """Current (possibly diverged) per-worker replicas [W, ...]."""
        return self._params_w
