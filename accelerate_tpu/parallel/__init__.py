from .sharding import (
    PartitionRules,
    fsdp_auto_spec,
    infer_shardings,
    param_path,
    replicated,
    shard_tree,
    shardings_like,
)
from .local_sgd import LocalSGD
from .redistribute import (
    EpochFence,
    RedistributeConfig,
    RedistributeError,
    RedistributePlan,
    RedistributeStageFailure,
    plan_redistribute,
    redistribute,
)
