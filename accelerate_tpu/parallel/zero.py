"""ZeRO-style sharded weight update: reduce-scatter → sharded adamw → all-gather.

The replicated data-parallel update makes every chip do the same work on the
same bytes: all-reduce the full gradient, hold a full copy of the optimizer
state, apply the full update. ZeRO (arXiv 2004.13336) observes the update is
*elementwise*, so it decomposes exactly: reduce-scatter gradients over the
data-parallel axes (each chip receives the fully-reduced values for its 1/N
shard — same wire bytes as the all-reduce's reduce phase, 1/N the critical-
path payload), run the optimizer on that shard with 1/N optimizer state, and
all-gather parameters where the next forward consumes them. SimpleFSDP
(arXiv 2411.00284) lands the same decomposition compiler-side.

This module builds that step as ONE fused program over a fully-manual
``shard_map`` region, because GSPMD cannot be coaxed into it on every
backend: with auto partitioning, a sharded-update constraint lowers to
all-reduce + dynamic-slice on backends without a reduce-scatter creation
pass (XLA:CPU — measured, not assumed), which keeps the full gradient on the
critical path. Explicit ``psum_scatter`` / ``all_gather`` emit the real
collectives everywhere. Parameters are *stored* in the folded 1/N layout
(`sharding.zero_update_shardings`), so each step opens with the all-gathers
for its own forward — scheduled at the top of the program where every later
layer's compute is independent work for them to hide behind, which is where
the latency-hiding the schedule pass (analysis/schedule.py) verifies comes
from — and closes with the reduce-scatter + sharded update, leaving the
updated shards in place for the next step to gather.

Bit-exactness (pinned by tests/test_zero.py): every rescale the
decomposition introduces is a power-of-two (device counts, loss scales), so
scaling commutes exactly through the linear backward and the rank-ordered
collective reductions; the sharded update is then elementwise-identical to
the replicated one. The gradient *computation* itself is traced per-device
instead of auto-partitioned, which XLA may fuse differently — reassociation-
level (last-bit) differences, same as any compiler version bump. (On this
container's XLA:CPU the manual program is in fact the *more* faithful one:
the auto-partitioned fused FSDP step returns a loss that deviates from the
float64 reference by ~4e-3 relative, the manual program by <1e-7 —
tests/test_zero.py pins the f64 anchor.)
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.constants import (
    MESH_AXIS_EXPERT,
    MESH_AXIS_PIPELINE,
    MESH_AXIS_SEQUENCE,
    MESH_AXIS_TENSOR,
)
from .sharding import zero_batch_axes

# mesh axes that carry *model* parallelism: the manual region would have to
# re-implement their collectives (TP partial sums, ring attention, pipeline
# schedules), so ZeRO auto-enables only when they are all trivial
_MODEL_AXES = (MESH_AXIS_TENSOR, MESH_AXIS_SEQUENCE, MESH_AXIS_PIPELINE, MESH_AXIS_EXPERT)


def zero_ineligible_reason(mesh: Mesh, fsdp_plugin=None) -> Optional[str]:
    """Why the ZeRO sharded update cannot replace the replicated one on this
    configuration (None = eligible): it needs at least one nontrivial
    data-parallel axis, no model-parallel axes (their collectives live
    inside the auto-partitioned forward), and no legacy stage-1/2 FSDP or
    cpu-offload configuration (those keep params replicated / state in host
    RAM by explicit contract). The reason string is what the fallback
    warning and telemetry record name, so a run silently training on the
    legacy path is a grep away."""
    if not zero_batch_axes(mesh):
        return "no nontrivial data/fsdp mesh axis to shard the update over"
    model = [a for a in _MODEL_AXES if mesh.shape.get(a, 1) > 1]
    if model:
        return (
            f"model-parallel axes {model} are nontrivial (their collectives "
            "live inside the auto-partitioned forward)"
        )
    if fsdp_plugin is not None and fsdp_plugin.stage < 3:
        return (
            f"FullyShardedDataParallelPlugin(stage={fsdp_plugin.stage}) keeps "
            "parameters replicated by explicit contract"
        )
    if fsdp_plugin is not None and fsdp_plugin.cpu_offload:
        return (
            "cpu_offload keeps optimizer state in host RAM, which the fused "
            "sharded-update program does not support yet (ROADMAP: ZeRO "
            "cpu_offload composition)"
        )
    return None


def zero_eligible(mesh: Mesh, fsdp_plugin=None) -> bool:
    """Whether the ZeRO sharded update can replace the replicated one on this
    mesh (see :func:`zero_ineligible_reason` for the criteria)."""
    return zero_ineligible_reason(mesh, fsdp_plugin) is None


def tx_couples_across_leaves(tx, params_tree: Any) -> bool:
    """Probe whether an optax transform couples gradient leaves — the
    property that breaks the ZeRO decomposition. The sharded update runs
    ``tx`` on 1/N shards, which is exact only for elementwise transforms
    (adam/sgd families); a transform that reads ACROSS leaves (an
    ``optax.clip_by_global_norm`` inside the chain) would compute its
    reduction over the local shard and silently train differently. The probe
    runs two updates on a tiny surrogate tree with the real tree's
    STRUCTURE (so path/label-keyed transforms behave normally), bumping a
    single element of the last leaf, and reports coupling if anything the
    bump cannot reach elementwise moved: the first leaf's update (cross-leaf
    coupling — a chained clip_by_global_norm) or the last leaf's OTHER
    element (within-leaf reductions — LAMB/LARS trust ratios, adafactor's
    RMS clipping). Costs two (2,)-element updates at prepare time; probe
    failures (exotic transforms that reject the surrogate) report False —
    the documented contract still applies."""
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(params_tree)
    if not leaves:
        return False
    try:
        tiny = jax.tree_util.tree_unflatten(
            treedef, [jnp.ones((2,), jnp.float32) for _ in leaves]
        )
        base = jax.tree_util.tree_unflatten(
            treedef, [jnp.full((2,), 0.5, jnp.float32) for _ in leaves]
        )
        bumped_leaves = [jnp.full((2,), 0.5, jnp.float32) for _ in leaves]
        bumped_leaves[-1] = jnp.asarray([0.5, 64.0], jnp.float32)
        bumped = jax.tree_util.tree_unflatten(treedef, bumped_leaves)
        # advance the state one step first: several transforms normalize the
        # very first update into a shape-independent form (adafactor's
        # g/sqrt(g^2) = ±1), which would blind a from-init probe
        _, state = tx.update(base, tx.init(tiny), tiny)
        up_a, _ = tx.update(base, state, tiny)
        up_b, _ = tx.update(bumped, state, tiny)
        flat_a = jax.tree_util.tree_leaves(up_a)
        flat_b = jax.tree_util.tree_leaves(up_b)
        if not np.array_equal(np.asarray(flat_a[-1])[0], np.asarray(flat_b[-1])[0]):
            return True  # within-leaf reduction reached the un-bumped element
        return len(leaves) > 1 and not np.array_equal(
            np.asarray(flat_a[0]), np.asarray(flat_b[0])
        )
    except Exception as e:
        # an unprobeable transform is NOT proven elementwise — say so where
        # someone will look instead of silently reporting "no coupling"
        from ..logging import get_logger

        get_logger(__name__).warning(
            f"ZeRO elementwise-update probe could not run on "
            f"{type(tx).__name__} ({e!r}); proceeding on the documented "
            "contract that the transform is elementwise — if it reduces "
            "across gradient elements, pass ParallelismConfig(zero_stage=0)."
        )
        return False


def _sharded_dims(spec, mesh: Optional[Mesh] = None) -> list[tuple[int, tuple[str, ...]]]:
    """(dim, axes) pairs for a PartitionSpec. With a mesh, size-1 axes are
    dropped: a collective over a trivial axis is an exact no-op, but XLA
    still materializes it as a singleton-group op that pollutes the
    collective inventory and the schedule pass."""
    out = []
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        if mesh is not None:
            axes = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
        if axes:
            out.append((dim, axes))
    return out


def gather_full(x: jax.Array, spec, mesh: Optional[Mesh] = None) -> jax.Array:
    """Inside the manual region: local param shard → full parameter, one
    tiled all-gather per sharded dim (axis-tuple order matches the
    PartitionSpec split order, so this is the exact inverse of the storage
    placement)."""
    for dim, axes in _sharded_dims(spec, mesh):
        x = jax.lax.all_gather(x, axes, axis=dim, tiled=True)
    return x


def make_grad_reducer(pspecs: Any, batch_axes: tuple[str, ...], mesh: Optional[Mesh] = None):
    """Returns ``reduce(grads_tree) -> shard_tree``: per-leaf reduce-scatter
    into the parameter's storage layout (summing over the batch axes), with a
    plain psum for leaves whose spec consumed no batch axis (the un-foldable
    small leaves — their update stays replicated). Gradients must already
    carry the 1/N batch prescale: the scatter then sums exactly the terms the
    replicated all-reduce would."""

    def _leaf(g, spec):
        consumed: list[str] = []
        for dim, axes in _sharded_dims(spec, mesh):
            if any(a in batch_axes for a in axes):
                g = jax.lax.psum_scatter(g, axes, scatter_dimension=dim, tiled=True)
                consumed.extend(a for a in axes if a in batch_axes)
        rest = tuple(a for a in batch_axes if a not in consumed)
        if rest:
            g = jax.lax.psum(g, rest)
        return g

    return lambda grads: jax.tree.map(_leaf, grads, pspecs)


def sharded_global_norm(grads: Any, pspecs: Any, batch_axes: tuple[str, ...], mesh: Mesh):
    """Global L2 norm of a gradient tree living in the storage layout. A
    leaf's elements are disjoint across the batch axes its spec consumed and
    REPLICATED across the ones it didn't (partially-folded leaves exist: a
    dim divisible by fsdp but not by fsdp×data keeps only the fsdp split),
    so one uniform psum over all batch axes counts each element
    prod(missing axes) times. Pre-dividing each leaf's square-sum by that
    count — a power of two, and summing identical copies is exact scaling —
    makes the single psum come out as exactly one copy of every element."""
    total = jnp.float32(0.0)
    for g, spec in zip(
        jax.tree.leaves(grads), jax.tree.leaves(pspecs, is_leaf=lambda x: isinstance(x, P))
    ):
        consumed = {
            a for _, axes in _sharded_dims(spec, mesh) for a in axes if a in batch_axes
        }
        copies = 1
        for a in batch_axes:
            if a not in consumed:
                copies *= mesh.shape[a]
        contrib = jnp.sum(jnp.square(g.astype(jnp.float32)))
        total = total + (contrib / copies if copies > 1 else contrib)
    if batch_axes:
        total = jax.lax.psum(total, batch_axes)
    return jnp.sqrt(total)


def build_zero_step(
    *,
    mesh: Mesh,
    loss_fn: Callable,
    tx,
    params_shardings: Any,
    opt_state_shardings: Any,
    batch_sharding,
    compute_cast: Callable,
    num_micro: int = 1,
    remat_policy=None,
    scaler_cfg=None,
    clip_grad_norm: Optional[float] = None,
    clip_grad_value: Optional[float] = None,
    guard_policy=None,
    chaos_nan_target: Optional[str] = None,
    resilience_on: bool = False,
    donate: bool = True,
):
    """The fused ZeRO train-step program.

    The closing ``sharded adamw`` dispatches through
    ``optimizer.scaled_optimizer_update``: an ``optax.adamw`` lowers to the
    usual elementwise HLO chain, while ``ops.fused_adamw.fused_adamw``
    swaps in the Pallas one-read-one-write update kernel (in place via
    ``input_output_aliases``) — bit-equal at tolerance 0, so the
    update-equivalence gate below applies to both (tests/test_fused_adamw).

    Signature-compatible with ``Accelerator.compiled_step``'s jitted program:
    ``(params, opt_state, batch, scale, growth_tracker)`` — plus
    ``(guard_state, corrupt)`` when ``guard_policy``/``chaos_nan_target`` arm
    the resilience path — so the step/lower wrappers, donation audit, and
    contracts treat both implementations as one program family. Parameters
    and optimizer state enter AND leave in the folded storage layout; the
    program opens with their all-gathers (hidden behind forward compute) and
    closes with the gradient reduce-scatter + sharded update.
    """
    from jax.experimental.shard_map import shard_map

    from ..optimizer import clip_by_value as _clip_by_value
    from ..optimizer import scaled_optimizer_update
    from ..resilience.guards import next_guard_state

    batch_axes = zero_batch_axes(mesh)
    pspecs = jax.tree.map(lambda s: s.spec, params_shardings)
    ospecs = jax.tree.map(lambda s: s.spec, opt_state_shardings)
    batch_spec = batch_sharding.spec
    n_batch_shards = 1
    for a in batch_axes:
        n_batch_shards *= mesh.shape[a]
    reduce_grads = make_grad_reducer(pspecs, batch_axes, mesh)
    # the guarded program shape follows the HUB's armed state (signature
    # parity with the replicated path: gstate/corrupt thread through even
    # when only chaos stalls are scheduled), not just our own knobs
    res_on = resilience_on or guard_policy is not None or chaos_nan_target is not None

    def gather_all(params):
        return jax.tree.map(lambda p, s: gather_full(p, s, mesh), params, pspecs)

    def loss_of(full_params, local_batch, scale):
        fn = loss_fn
        if remat_policy is not None:
            fn = jax.checkpoint(fn, policy=remat_policy)
        loss = fn(compute_cast(full_params), compute_cast(local_batch))
        # 1/N batch-shard factor applied in the loss's NATIVE dtype, before
        # the f32 cast and the scale multiply — the replicated program's
        # global mean puts its 1/batch inside the compute-dtype region too,
        # so the backward sees identical cotangent magnitudes at every cast
        # boundary. That parity is what keeps GradScaler dynamics intact: the
        # f32→fp16 boundary must see the RAW scale (whose deliberate overflow
        # is the scaler's backoff probe), and since N and the scale are
        # powers of two the values match the replicated path bit-exactly.
        # scale stays a STATIC None without a scaler (same elision as the
        # replicated path).
        if n_batch_shards > 1:
            loss = loss / n_batch_shards
        loss = loss.astype(jnp.float32)
        return loss if scale is None else loss * scale

    def local_loss_and_grads(params, batch, scale):
        import math

        full = gather_all(params)
        # the region sees the LOCAL batch shard (1/N of the rows), so the
        # accumulation window's memory-saving split is re-derived locally:
        # the largest divisor of the local rows that fits num_micro. Equal-
        # size microbatch accumulation is a mean, so ANY split factor gives
        # the same gradients — only the activation working set changes (a
        # window of 4 over 8 global rows on 8 chips is 1 local row: nothing
        # left to split, one pass).
        rows = int(jax.tree.leaves(batch)[0].shape[0])
        eff_micro = math.gcd(num_micro, rows) if num_micro > 1 else 1
        if eff_micro > 1:
            def micro(carry, mb):
                grads_acc, loss_acc = carry
                loss, grads = jax.value_and_grad(loss_of)(full, mb, scale)
                return (jax.tree.map(jnp.add, grads_acc, grads), loss_acc + loss), None

            zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), full)
            micro_batches = jax.tree.map(
                lambda x: x.reshape((eff_micro, x.shape[0] // eff_micro) + x.shape[1:]),
                batch,
            )
            (grads, loss), _ = jax.lax.scan(micro, (zeros, jnp.float32(0.0)), micro_batches)
            grads = jax.tree.map(lambda g: g / eff_micro, grads)
            loss = loss / eff_micro
            return loss, grads
        return jax.value_and_grad(loss_of)(full, batch, scale)

    def prescale(grads, scale):
        # unscale BEFORE the reduce-scatter (the 1/N mean already rode the
        # loss multiplier): the scatter then sums exactly the g_i terms the
        # replicated all-reduce sums, and every factor is a power of two
        if scale is None:
            return grads
        return jax.tree.map(lambda g: g / scale, grads)

    def finish(loss, scale):
        # the 1/N loss factor makes the psum over shards the global mean
        loss = jax.lax.psum(loss, batch_axes)
        return loss if scale is None else loss / scale

    def step_impl(params, opt_state, batch, scale, growth_tracker):
        loss, grads = local_loss_and_grads(params, batch, scale)
        grads = reduce_grads(prescale(grads, scale))
        grads = _clip_by_value(grads, clip_grad_value)
        gnorm = None
        if clip_grad_norm is not None or scaler_cfg is not None:
            gnorm = sharded_global_norm(grads, pspecs, batch_axes, mesh)
            if clip_grad_norm is not None:
                factor = jnp.minimum(1.0, clip_grad_norm / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * factor, grads)
        loss = finish(loss, scale)
        params, opt_state, scale, growth_tracker, skipped = scaled_optimizer_update(
            tx, params, opt_state, grads, gnorm, scale, growth_tracker, scaler_cfg
        )
        return params, opt_state, loss, scale, growth_tracker, skipped

    # NOTE: this guard ladder (chaos poison → verdict → escalate clip →
    # skip-cond with scaler backoff → guard-state advance) deliberately
    # mirrors Accelerator.compiled_step's replicated guarded_step_impl —
    # only the norm (sharded) and the loss finish (psum) differ. A semantic
    # change to skip/escalate/backoff belongs in BOTH places; the resilience
    # test suite runs each path against the same expectations.
    def guarded_step_impl(params, opt_state, batch, scale, growth_tracker, gstate, corrupt):
        loss, grads = local_loss_and_grads(params, batch, scale)
        if chaos_nan_target is not None:
            poison = jnp.where(corrupt != 0, jnp.float32(jnp.nan), jnp.float32(1.0))
            if chaos_nan_target == "loss":
                loss = loss * poison
            else:
                grads = jax.tree.map(lambda g: g * poison, grads)
        grads = reduce_grads(prescale(grads, scale))
        grads = _clip_by_value(grads, clip_grad_value)
        # the guard's verdict needs the global norm regardless of clip
        # settings — and the GLOBAL loss: the local shard-loss can be finite
        # on some devices and not others, and a device-varying lax.cond
        # verdict would apply the update on some shards and skip it on
        # others. Both psums below make the verdict device-uniform.
        loss = finish(loss, scale)
        gnorm = sharded_global_norm(grads, pspecs, batch_axes, mesh)
        finite = jnp.isfinite(loss) & jnp.isfinite(gnorm) if guard_policy is not None else None
        escalating = guard_policy is not None and guard_policy.escalate_clip is not None
        if clip_grad_norm is not None or escalating:
            base = (
                jnp.float32(clip_grad_norm)
                if clip_grad_norm is not None
                else jnp.float32(jnp.inf)
            )
            if escalating:
                esc = jnp.minimum(jnp.float32(guard_policy.escalate_clip), base)
                limit = jnp.where(gstate["escalate"] > 0, esc, base)
            else:
                limit = base
            factor = jnp.minimum(1.0, limit / (gnorm + 1e-6))
            grads = jax.tree.map(lambda g: g * factor, grads)
        if guard_policy is not None and guard_policy.skip_nonfinite:
            def _apply(args):
                p, o, s, gt = args
                return scaled_optimizer_update(tx, p, o, grads, gnorm, s, gt, scaler_cfg)

            def _skip(args):
                p, o, s, gt = args
                if scaler_cfg is not None:
                    s = s * scaler_cfg.backoff_factor
                    gt = jnp.int32(0)
                return p, o, s, gt, jnp.asarray(True)

            params, opt_state, scale, growth_tracker, skipped = jax.lax.cond(
                finite, _apply, _skip, (params, opt_state, scale, growth_tracker)
            )
        else:
            params, opt_state, scale, growth_tracker, skipped = scaled_optimizer_update(
                tx, params, opt_state, grads, gnorm, scale, growth_tracker, scaler_cfg
            )
        if guard_policy is not None:
            gstate = next_guard_state(gstate, finite, guard_policy.escalate_steps)
        return params, opt_state, loss, scale, growth_tracker, skipped, gstate

    rep = P()
    if res_on:
        in_specs = (pspecs, ospecs, batch_spec, rep, rep, rep, rep)
        out_specs = (pspecs, ospecs, rep, rep, rep, rep, rep)
        impl = guarded_step_impl
    else:
        in_specs = (pspecs, ospecs, batch_spec, rep, rep)
        out_specs = (pspecs, ospecs, rep, rep, rep, rep)
        impl = step_impl
    # check_rep can't statically infer that psum-derived outputs are
    # replicated; the out_specs above are the semantic declaration
    smapped = shard_map(
        impl, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )
    return jax.jit(smapped, donate_argnums=(0, 1) if donate else ())


# -- sizing (the estimate-memory CLI's ZeRO column) ---------------------------


def zero_update_state_bytes(
    n_params: int, grad_dtype_bytes: float, replicas: int
) -> tuple[int, int]:
    """(optimizer_state_bytes_per_chip, gradient_bytes_per_chip) for an
    adam-family update sharded over ``replicas`` chips — the shared sizing
    formula behind `accelerate-tpu estimate-memory`'s ZeRO column (the
    training analogue of ``kv_cache_bytes`` for serving). Optimizer state is
    two fp32 moments + fp32 master params; under ZeRO each chip holds 1/N of
    both it and the reduced gradient."""
    replicas = max(int(replicas), 1)
    opt_full = n_params * 4 * 3
    grad_full = int(n_params * grad_dtype_bytes)
    return -(-opt_full // replicas), -(-grad_full // replicas)


def elastic_redundancy_bytes(
    n_params: int, param_dtype_bytes: float, replicas: int, redundancy: int = 1
) -> int:
    """Per-chip bytes of the elastic buddy mirror (resilience/elastic.py):
    ``redundancy`` extra copies of the chip's 1/N parameter shard plus its
    1/N optimizer-state shard, parked on a buddy rank so a host loss never
    destroys a shard's only copy. Gradients are recomputed after recovery
    and are not mirrored. The `estimate-memory --elastic-redundancy` column
    prices this next to the ZeRO column."""
    replicas = max(int(replicas), 1)
    opt_chip, _ = zero_update_state_bytes(n_params, param_dtype_bytes, replicas)
    param_chip = -(-int(n_params * param_dtype_bytes) // replicas)
    return max(int(redundancy), 0) * (param_chip + opt_chip)
