"""One redistribution primitive for every recovery path.

Three subsystems used to fake the same operation through host RAM: the
disaggregated KV handoff moved parked pages device→host→device, the elastic
buddy reassembly relayed ZeRO shards one leaf at a time, and ``regrow()``
round-tripped the whole state through the coordinator. All three are the
same problem — redistribute a sharded tree from mesh A's layout to mesh B's,
where A and B may not share devices — and *Memory-efficient array
redistribution through portable collective communication* (arXiv:2112.01075)
shows the general relayout decomposes into all-to-all / collective-permute /
slice stages with provably bounded per-chip scratch. This module is that
primitive, built recovery-grade:

- **Planned, then executed.** :func:`plan_redistribute` walks sharding
  metadata ONLY (``devices_indices_map``, never shard data) and decides
  everything before a byte moves: which rung (staged collectives vs the
  per-leaf host relay), which collective kind each leaf lowers to
  (``identity`` / ``collective_permute`` / ``all_to_all`` / ``device_put``),
  and how each leaf is chunked so no stage stages more than
  ``RedistributeConfig(max_scratch_bytes=)`` at once. The host-relay rung's
  plan step is the same metadata-only coverage pre-check the elastic ladder
  uses (:func:`tree_covered` lives here now) — "decided before a byte moves"
  is one piece of code, not two.

- **Bounded scratch, audited not claimed.** A leaf bigger than the scratch
  bound is moved in chunks: slice a chunk off the live source, relayout it
  to the destination sharding, and commit it into a preallocated destination
  buffer with a DONATED ``dynamic_update_slice`` — the destination buffer is
  committed state, not scratch, so the in-flight footprint is one chunk.
  The chunk-commit program is the canonical ``redistribute_stage`` contract
  program: ``analyze --self-check`` runs the PR 8 memory audit over it with
  an ``hbm_budget_bytes`` derived from the scratch bound, so the claim is
  gated, and :data:`tests/contracts/redistribute_stage.json` pins donation,
  the collective inventory, and the peak-HBM shape.

- **Transactional.** Source buffers are NEVER donated; every new leaf is
  built beside the old tree, and only after the whole tree (and the epoch
  fence, below) passes does :func:`redistribute` return it — the commit.
  A failure at any stage leaves the caller holding the intact source.

- **Chaos-drilled mid-transfer failure.** ``FaultPlan`` grows
  ``redistribute_fail_at`` / ``redistribute_fail_stage``
  (``ACCELERATE_CHAOS_REDISTRIBUTE_FAIL_AT/_STAGE``): kill stage *k* of
  transfer *n* and the ladder runs staged → host relay (re-reading the
  intact source) → fail loud NAMING the stage when the relay is disabled or
  impossible. The outcome lands in telemetry either way.

- **Epoch-fenced commit.** A zombie coordinator's in-flight transfer is
  refused at commit: :class:`EpochFence` captures the PR 14 membership epoch
  the transfer was planned under and re-reads the store at commit; a view
  that moved on raises ``StaleEpochError`` and the telemetry record says
  ``stale_epoch_write_rejected`` — the source is untouched, the new buffers
  are dropped.

- **Observable.** Every transfer writes one ``{"kind": "redistribute"}``
  record: rung, per-kind stage counts, bytes moved, peak scratch vs the
  bound, wall time, outcome, and ``trace_id`` when the transfer is
  request-scoped (the KV handoff passes the request id).

At CPU scale (the tier-1 simulation) the staged rung's relayout executes
through XLA's transfer engine (``jax.device_put``), which on a pod lowers
the same plan to ICI collectives — the plan's stage kinds are the
decomposition 2112.01075 names, recorded honestly as what WOULD run on
chips. The host-relay rung is not a test shim: it is the degenerate rung
the ladder needs anyway (dead devices cannot join a collective), so tier-1
drills both paths and a tolerance-0 bit-equality gate pins staged == relay.
"""

from __future__ import annotations

import threading
import time
from collections import Counter
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..analysis.concurrency import named_lock
from ..logging import get_logger

logger = get_logger(__name__)

# default per-chip scratch bound: 64 MiB — small enough that a recovery
# transfer can never OOM the survivors it is saving, large enough that
# CPU-scale trees move in one stage per leaf
DEFAULT_MAX_SCRATCH_BYTES = 64 << 20


class RedistributeError(RuntimeError):
    """A redistribution that could not complete on any rung. The message
    names the failing stage — fail-loud is the ladder's last rung."""


class RedistributeStageFailure(RedistributeError):
    """One staged-path stage died mid-transfer (chaos or a real collective
    failure). The source is intact (nothing is donated); callers — or
    :func:`redistribute` itself — degrade to the host relay."""

    def __init__(self, message: str, *, stage: int, kind: str, leaf: str):
        super().__init__(message)
        self.stage = int(stage)
        self.kind = kind
        self.leaf = leaf


@dataclass(frozen=True)
class RedistributeConfig:
    """Policy for one transfer.

    ``max_scratch_bytes`` bounds the bytes any single stage holds in flight
    (the chunk the staged path slices/moves/commits at a time; the largest
    leaf's host buffer on the relay rung is reported against the same bound).
    ``force_path`` pins a rung: ``"staged"`` disables the relay fallback
    (a mid-stage failure then fails loud, naming the stage), ``"relay"``
    skips the staged path entirely; ``None`` (default) lets the plan decide
    and the ladder degrade."""

    max_scratch_bytes: int = DEFAULT_MAX_SCRATCH_BYTES
    force_path: Optional[str] = None  # None | "staged" | "relay"

    def __post_init__(self):
        if self.force_path not in (None, "staged", "relay"):
            raise ValueError(
                f"force_path must be None, 'staged' or 'relay', got {self.force_path!r}"
            )
        if int(self.max_scratch_bytes) <= 0:
            raise ValueError("max_scratch_bytes must be positive")


@dataclass(frozen=True)
class Stage:
    """One unit of the decomposition: what moves, how, and how big. The
    global ``index`` is what the chaos leg targets."""

    index: int
    leaf: str
    kind: str  # identity | collective_permute | all_to_all | device_put | host_relay
    nbytes: int
    # staged-path chunking: (axis, start, size) slab of the leaf, or None
    # when the stage moves the whole leaf in one piece
    chunk: Optional[tuple[int, int, int]] = None


@dataclass
class RedistributePlan:
    """The decomposition, decided from sharding metadata before a byte
    moves. ``rung`` is the transfer path; ``covered`` (relay rung only) is
    the metadata-only coverage verdict the elastic ladder keys its rung
    decision on."""

    rung: str  # "staged" | "host_relay"
    reason: str
    stages: list[Stage] = field(default_factory=list)
    num_leaves: int = 0
    total_bytes: int = 0
    peak_scratch_bytes: int = 0
    max_scratch_bytes: int = DEFAULT_MAX_SCRATCH_BYTES
    covered: bool = True

    @property
    def stage_kinds(self) -> dict:
        return dict(Counter(s.kind for s in self.stages))


class EpochFence:
    """The PR 14 zombie fence, applied to a transfer's COMMIT: capture the
    membership epoch the transfer was planned under; :meth:`check` re-reads
    the store and raises :class:`~..resilience.membership.StaleEpochError`
    when the view moved on — the in-flight transfer belongs to a fenced-out
    coordinator and must not become live state."""

    def __init__(self, store: Any, epoch: int):
        self.store = store
        self.epoch = int(epoch)

    def check(self) -> None:
        from ..resilience.membership import EPOCH_KEY, StaleEpochError

        current = self.store.read(EPOCH_KEY)
        if current is not None and int(current.get("epoch", 0)) > self.epoch:
            raise StaleEpochError(
                "redistribute/commit", self.epoch, int(current["epoch"])
            )


# ---------------------------------------------------------------------------
# survivor-side reassembly — the host-relay rung's read path (moved from
# resilience/elastic.py: the rung decision and the relay are the fallback
# half of THIS primitive, and the elastic ladder imports them from here)
# ---------------------------------------------------------------------------


def _index_key(index: tuple, shape: tuple) -> tuple:
    """Normalize a shard's global-slice index so primary and buddy shards of
    the same region compare equal (None-bounded slices vs explicit ones)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def assemble_from_survivors(
    primary: jax.Array,
    lost_ids: "set[int]",
    buddy: Optional[jax.Array] = None,
) -> Optional[np.ndarray]:
    """Reassemble one global array on host from shards on SURVIVING devices
    only — the elastic read primitive. Shards whose device id is in
    ``lost_ids`` are never touched (the simulation's honesty guarantee: a
    dead host's HBM is unreadable). Missing regions are filled from the
    ``buddy`` copy's surviving shards; returns None when coverage is still
    incomplete (primary and buddy both lost — the caller's ladder falls
    through to the next rung)."""
    shape = tuple(primary.shape)
    out = np.empty(shape, dtype=primary.dtype)
    needed = {
        _index_key(idx, shape)
        for idx in primary.sharding.devices_indices_map(shape).values()
    }
    have: set = set()
    for source in (primary, buddy):
        if source is None:
            continue
        for shard in source.addressable_shards:
            if shard.device.id in lost_ids:
                continue
            key = _index_key(shard.index, shape)
            if key in have:
                continue
            out[shard.index] = np.asarray(shard.data)
            have.add(key)
        if needed <= have:
            return out
    return None


def _leaf_covered(primary: jax.Array, lost_ids: "set[int]", buddy=None) -> bool:
    """Coverage pre-check WITHOUT reading any shard data: do the surviving
    (primary ∪ buddy) shards tile the whole array? Walks sharding metadata
    only, so the ladder can decide its rung before moving a byte."""
    shape = tuple(primary.shape)
    needed = {
        _index_key(idx, shape)
        for idx in primary.sharding.devices_indices_map(shape).values()
    }
    have: set = set()
    for source in (primary, buddy):
        if source is None:
            continue
        for device, idx in source.sharding.devices_indices_map(shape).items():
            if device.id not in lost_ids:
                have.add(_index_key(idx, shape))
    return needed <= have


def tree_covered(primary_tree: Any, lost_ids: "set[int]", buddy_tree: Any = None) -> bool:
    """Whether every leaf of the tree survives the loss (metadata-only)."""
    if buddy_tree is None:
        flags = jax.tree.map(lambda p: _leaf_covered(p, lost_ids), primary_tree)
    else:
        flags = jax.tree.map(
            lambda p, b: _leaf_covered(p, lost_ids, b), primary_tree, buddy_tree
        )
    return all(jax.tree.leaves(flags))


def relay_tree(
    primary_tree: Any,
    lost_ids: "set[int]",
    buddy_tree: Any,
    new_shardings: Any,
) -> Any:
    """The host-relay rung: relay a state tree onto a new mesh through
    surviving shards, ONE LEAF AT A TIME — assemble the leaf on host,
    ``device_put`` it to its new sharding, drop the host copy. Peak host
    memory is bounded by the largest leaf, never the whole state (the CPU
    analogue of 2112.01075's no-full-buffer redistribution). Callers
    pre-check :func:`tree_covered`; an uncovered leaf here is a programming
    error and raises."""

    def _leaf(p, b, s):
        host = assemble_from_survivors(p, lost_ids, b)
        if host is None:
            raise RedistributeError(
                "internal: relay_tree called for a leaf whose surviving "
                "shards do not cover it (coverage must be checked first)"
            )
        return jax.device_put(host, s)

    if buddy_tree is None:
        return jax.tree.map(
            lambda p, s: _leaf(p, None, s), primary_tree, new_shardings
        )
    return jax.tree.map(_leaf, primary_tree, buddy_tree, new_shardings)


# ---------------------------------------------------------------------------
# planning: metadata only — kind classification, chunking, rung decision
# ---------------------------------------------------------------------------


def _index_multimap(shape: tuple, sharding) -> dict:
    return {
        d.id: _index_key(idx, shape)
        for d, idx in sharding.devices_indices_map(shape).items()
    }


def _leaf_kind(shape: tuple, src_sharding, dst_sharding) -> str:
    """Which collective the relayout of one leaf lowers to on a pod, per the
    2112.01075 decomposition — decided entirely from the two shardings'
    device→index maps."""
    smap = _index_multimap(shape, src_sharding)
    dmap = _index_multimap(shape, dst_sharding)
    if smap == dmap:
        return "identity"
    if smap.keys() == dmap.keys():
        if Counter(smap.values()) == Counter(dmap.values()):
            # same tiling, shards change owners: a pure device permutation
            return "collective_permute"
        return "all_to_all"  # the tiling itself changes: shards split/merge
    if set(smap) & set(dmap):
        return "all_to_all"  # overlapping device sets resharding across both
    return "device_put"  # disjoint meshes: cross-slice send/recv


def _partitions_along(sharding, shape: tuple, axis: int) -> int:
    """How many ways ``sharding`` tiles ``axis`` — chunk extents must stay a
    multiple of this, because each chunk is relaid directly onto the
    destination sharding and an uneven extent cannot be tiled."""
    try:
        return max(int(shape[axis]) // int(sharding.shard_shape(shape)[axis]), 1)
    except Exception:
        return 1


def _chunk_stages(shape: tuple, nbytes: int, max_scratch: int, dst_sharding=None):
    """Chunk a leaf along its largest axis so no stage stages more than
    ``max_scratch`` bytes, keeping every chunk a multiple of the destination
    tiling along that axis. None → the leaf moves whole (already under the
    bound, or unchunkable: a singleton axis, or a tiling whose minimal slab
    is the whole axis)."""
    if nbytes <= max_scratch or not shape or max(shape) <= 1:
        return None
    axis = int(np.argmax(shape))
    dim = int(shape[axis])
    parts = _partitions_along(dst_sharding, tuple(shape), axis) if dst_sharding is not None else 1
    row_bytes = max(nbytes // dim, 1)
    size = max(int(max_scratch // row_bytes), 1)
    # floor: one slab per destination partition of the axis — smaller cannot
    # be relaid onto the tiling, so a slab over the bound is the honest
    # minimum (the plan still reports it as peak_scratch_bytes)
    size = max((size // parts) * parts, parts)
    if size >= dim:
        return None
    return [
        (axis, start, min(size, dim - start)) for start in range(0, dim, size)
    ]


def _leaf_paths(tree: Any) -> list[str]:
    paths, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(kp) or f"[{i}]" for i, (kp, _) in enumerate(paths)]


def plan_redistribute(
    tree: Any,
    dst_shardings: Any,
    *,
    lost_device_ids: "frozenset[int] | set[int]" = frozenset(),
    buddy_tree: Any = None,
    config: Optional[RedistributeConfig] = None,
) -> RedistributePlan:
    """Decide the whole transfer from sharding metadata, before a byte
    moves. The rung decision IS the elastic ladder's: lost devices (or a
    buddy merge, which reads two source copies) force the host-relay rung —
    dead devices cannot join a collective, and the relay is the only path
    that can stitch primary+buddy shards — and its plan step is the
    :func:`tree_covered` verdict. A pure relayout (nothing lost, one source)
    takes the staged rung."""
    config = config or RedistributeConfig()
    lost = set(lost_device_ids)
    leaves = jax.tree.leaves(tree)
    paths = _leaf_paths(tree)
    total = sum(int(leaf.nbytes) for leaf in leaves)

    relay_reason = None
    if config.force_path == "relay":
        relay_reason = "forced by config"
    elif lost:
        relay_reason = f"{len(lost)} lost device(s): survivors-only host read"
    elif buddy_tree is not None:
        relay_reason = "buddy merge: two source copies stitch on host"

    if relay_reason is not None:
        covered = tree_covered(tree, lost, buddy_tree)
        stages = [
            Stage(index=i, leaf=path, kind="host_relay", nbytes=int(leaf.nbytes))
            for i, (path, leaf) in enumerate(zip(paths, leaves))
        ]
        return RedistributePlan(
            rung="host_relay",
            reason=relay_reason,
            stages=stages,
            num_leaves=len(leaves),
            total_bytes=total,
            # the relay's in-flight footprint is one leaf's host buffer
            peak_scratch_bytes=max((s.nbytes for s in stages), default=0),
            max_scratch_bytes=int(config.max_scratch_bytes),
            covered=covered,
        )

    dst_leaves = jax.tree.leaves(dst_shardings)
    if len(dst_leaves) != len(leaves):
        raise ValueError(
            f"redistribute: tree has {len(leaves)} leaves but dst_shardings "
            f"has {len(dst_leaves)}"
        )
    stages: list[Stage] = []
    index = 0
    peak = 0
    for path, leaf, dst in zip(paths, leaves, dst_leaves):
        shape = tuple(leaf.shape)
        kind = _leaf_kind(shape, leaf.sharding, dst)
        if kind == "identity":
            continue  # nothing moves; the executor re-binds the sharding
        chunks = _chunk_stages(shape, int(leaf.nbytes), int(config.max_scratch_bytes), dst)
        if chunks is None:
            stages.append(Stage(index=index, leaf=path, kind=kind, nbytes=int(leaf.nbytes)))
            peak = max(peak, int(leaf.nbytes))
            index += 1
        else:
            dim = shape[chunks[0][0]]
            for axis, start, size in chunks:
                chunk_bytes = int(leaf.nbytes) * size // dim
                stages.append(
                    Stage(
                        index=index, leaf=path, kind=kind,
                        nbytes=chunk_bytes, chunk=(axis, start, size),
                    )
                )
                peak = max(peak, chunk_bytes)
                index += 1
    return RedistributePlan(
        rung="staged",
        reason="pure relayout: every source shard readable",
        stages=stages,
        num_leaves=len(leaves),
        total_bytes=total,
        peak_scratch_bytes=peak,
        max_scratch_bytes=int(config.max_scratch_bytes),
    )


# ---------------------------------------------------------------------------
# staged execution: slice → relayout → donated commit, one chunk in flight
# ---------------------------------------------------------------------------

# program caches keyed on everything that changes the compiled program —
# steady-state transfers of the same tree shapes compile NOTHING (the bench
# asserts 0 recompiles on the second transfer)
_ZEROS_PROGRAMS: dict = {}
_SLICE_PROGRAMS: dict = {}
_UPDATE_PROGRAMS: dict = {}


def _alloc_dest(shape: tuple, dtype, sharding) -> jax.Array:
    """Preallocate the destination buffer ON its destination sharding. This
    is committed state being built, not scratch: the transfer's in-flight
    footprint stays one chunk."""
    key = (shape, jnp.dtype(dtype).name, sharding)
    fn = _ZEROS_PROGRAMS.get(key)
    if fn is None:
        fn = jax.jit(lambda: jnp.zeros(shape, dtype), out_shardings=sharding)
        _ZEROS_PROGRAMS[key] = fn
    return fn()


def _slice_chunk(leaf: jax.Array, axis: int, start: int, size: int) -> jax.Array:
    """Slice one chunk off the live (sharded) source. ``start`` rides as a
    traced argument so every body chunk shares one program."""
    key = (axis, size)
    fn = _SLICE_PROGRAMS.get(key)
    if fn is None:
        fn = jax.jit(
            lambda x, s: jax.lax.dynamic_slice_in_dim(x, s, size, axis=axis)
        )
        _SLICE_PROGRAMS[key] = fn
    return fn(leaf, jnp.int32(start))


def _update_fn(axis: int):
    def _commit(dest, chunk, start):
        return jax.lax.dynamic_update_slice_in_dim(dest, chunk, start, axis=axis)

    return _commit


def _commit_chunk(dest, chunk, axis: int, start: int, dst_sharding):
    """The canonical stage program (``redistribute_stage`` contract): commit
    one relocated chunk into the destination buffer with the buffer DONATED
    — peak HBM for the stage is the chunk plus the alias-excluded dest."""
    key = (axis, dst_sharding)
    fn = _UPDATE_PROGRAMS.get(key)
    if fn is None:
        fn = jax.jit(_update_fn(axis), donate_argnums=(0,), out_shardings=dst_sharding)
        _UPDATE_PROGRAMS[key] = fn
    return fn(dest, chunk, jnp.int32(start))


def clear_program_caches() -> None:
    """Drop the cached stage programs (tests that rebuild meshes use this —
    a NamedSharding over a dead mesh must not satisfy a fresh lookup)."""
    _ZEROS_PROGRAMS.clear()
    _SLICE_PROGRAMS.clear()
    _UPDATE_PROGRAMS.clear()


def _staged_leaf(leaf, dst_sharding, leaf_stages, fire: Callable[[Stage], None]):
    if not leaf_stages:  # identity: re-bind to the (equal-layout) dst sharding
        return jax.device_put(leaf, dst_sharding)
    if len(leaf_stages) == 1 and leaf_stages[0].chunk is None:
        fire(leaf_stages[0])
        # whole-leaf relayout in one stage: XLA's transfer engine — the ICI
        # collective the plan's `kind` names, at CPU scale
        return jax.device_put(leaf, dst_sharding)
    axis = leaf_stages[0].chunk[0]
    dest = _alloc_dest(tuple(leaf.shape), leaf.dtype, dst_sharding)
    for stage in leaf_stages:
        fire(stage)
        _, start, size = stage.chunk
        chunk = _slice_chunk(leaf, axis, start, size)
        chunk = jax.device_put(chunk, dst_sharding)
        dest = _commit_chunk(dest, chunk, axis, start, dst_sharding)
    return dest


# ---------------------------------------------------------------------------
# the transfer transaction
# ---------------------------------------------------------------------------

_SEQ_LOCK = named_lock("redistribute.seq")
_TRANSFER_SEQ = 0


def _next_seq() -> int:
    global _TRANSFER_SEQ
    with _SEQ_LOCK:
        seq = _TRANSFER_SEQ
        _TRANSFER_SEQ += 1
        return seq


def reset_transfer_seq() -> None:
    """Re-zero the process-wide transfer counter the chaos leg indexes
    (tests/bench arm ``redistribute_fail_at`` against a known sequence)."""
    global _TRANSFER_SEQ
    with _SEQ_LOCK:
        _TRANSFER_SEQ = 0


def redistribute(
    tree: Any,
    dst_shardings: Any,
    *,
    config: Optional[RedistributeConfig] = None,
    lost_device_ids: "frozenset[int] | set[int]" = frozenset(),
    buddy_tree: Any = None,
    fault_plan: Any = None,
    epoch_fence: Optional[EpochFence] = None,
    probe: Optional[Callable[[], None]] = None,
    telemetry: Any = None,
    trace_id: Optional[str] = None,
) -> Any:
    """Redistribute ``tree`` from its live shardings onto ``dst_shardings``
    and return the NEW tree — the commit. Transactional: the source is never
    donated and stays valid until the caller drops it; any failure before
    return leaves it intact.

    ``lost_device_ids`` / ``buddy_tree`` select the host-relay rung (the
    elastic shrink: survivors-only reads, buddy stitching). ``epoch_fence``
    (an :class:`EpochFence`) is checked at plan time and again at commit —
    a zombie's transfer is refused with ``StaleEpochError`` and recorded.
    ``probe`` is invoked between stages (the caller's own chaos window).
    ``fault_plan`` defaults to the module-activated chaos plan; its
    ``redistribute_fail_*`` legs kill a named stage mid-transfer, driving
    the ladder staged → host relay → fail loud."""
    from ..resilience import chaos as chaos_mod
    from ..resilience.membership import StaleEpochError

    config = config or RedistributeConfig()
    if fault_plan is None:
        fault_plan = chaos_mod.active_plan()
    seq = _next_seq()
    t0 = time.perf_counter()
    lost = set(lost_device_ids)
    plan = plan_redistribute(
        tree, dst_shardings, lost_device_ids=lost, buddy_tree=buddy_tree,
        config=config,
    )

    base = {
        "transfer": seq,
        "path": plan.rung,
        "leaves": plan.num_leaves,
        "stages": len(plan.stages),
        "stage_kinds": plan.stage_kinds,
        "bytes_moved": plan.total_bytes,
        "peak_scratch_bytes": plan.peak_scratch_bytes,
        "max_scratch_bytes": plan.max_scratch_bytes,
    }
    if trace_id is not None:
        base["trace_id"] = trace_id

    def _emit(outcome: str, **extra) -> None:
        payload = {
            **base, "outcome": outcome,
            "wall_time_s": round(time.perf_counter() - t0, 6), **extra,
        }
        if telemetry is not None and getattr(telemetry, "enabled", False):
            telemetry.write_record("redistribute", payload)

    def _fenced(new_tree):
        """The commit: nothing the caller can observe changes until the
        fence passes — a refused commit drops the new buffers unreferenced
        and the source stays live."""
        if epoch_fence is not None:
            try:
                epoch_fence.check()
            except StaleEpochError:
                _emit("stale_epoch_write_rejected")
                raise
        return new_tree

    def _fire(stage: Stage) -> None:
        if fault_plan is not None and fault_plan.redistribute_fail(
            seq, stage.index, stage.kind
        ):
            raise RedistributeStageFailure(
                f"redistribute transfer {seq} lost stage {stage.index} "
                f"({stage.kind}, leaf {stage.leaf}) mid-transfer",
                stage=stage.index, kind=stage.kind, leaf=stage.leaf,
            )
        if probe is not None:
            probe()

    if epoch_fence is not None:
        # plan-time check: a coordinator that is ALREADY fenced out must not
        # start reading shards it no longer owns
        try:
            epoch_fence.check()
        except StaleEpochError:
            _emit("stale_epoch_write_rejected")
            raise

    if plan.rung == "host_relay":
        if not plan.covered:
            _emit("failed", error="uncovered")
            raise RedistributeError(
                "redistribute: surviving shards do not cover the tree "
                f"({len(lost)} lost device(s)) — no rung can move state that "
                "no longer exists; the caller's ladder falls to its next rung"
            )
        for stage in plan.stages:
            _fire(stage)
        out = _fenced(relay_tree(tree, lost, buddy_tree, dst_shardings))
        _emit("committed")
        return out

    # -- staged rung --------------------------------------------------------
    by_leaf: dict[str, list[Stage]] = {}
    for stage in plan.stages:
        by_leaf.setdefault(stage.leaf, []).append(stage)
    paths = _leaf_paths(tree)
    leaves = jax.tree.leaves(tree)
    dst_leaves = jax.tree.leaves(dst_shardings)
    treedef = jax.tree.structure(tree)
    try:
        new_leaves = [
            _staged_leaf(leaf, dst, by_leaf.get(path, []), _fire)
            for path, leaf, dst in zip(paths, leaves, dst_leaves)
        ]
        out = _fenced(jax.tree.unflatten(treedef, new_leaves))
        _emit("committed")
        return out
    except RedistributeStageFailure as failure:
        detail = {
            "failed_stage": failure.stage,
            "failed_stage_kind": failure.kind,
            "failed_leaf": failure.leaf,
        }
        if config.force_path == "staged":
            _emit("failed", **detail)
            raise RedistributeError(
                f"staged redistribution failed at stage {failure.stage} "
                f"({failure.kind}, leaf {failure.leaf}) and the host-relay "
                "fallback is disabled (force_path='staged')"
            ) from failure
        # the ladder: the source is intact (never donated) — degrade to the
        # host relay, re-reading every source shard
        logger.warning(
            f"redistribute: stage {failure.stage} ({failure.kind}) failed — "
            "falling back to the host relay"
        )
        out = _fenced(relay_tree(tree, set(), None, dst_shardings))
        _emit("fell_back", **detail)
        return out


# ---------------------------------------------------------------------------
# the paged-transfer leg (the disagg KV handoff's wire)
# ---------------------------------------------------------------------------


def paged_transfer(
    extract: Callable[[list], tuple],
    pages: list,
    *,
    fault_plan: Any = None,
    probe: Optional[Callable[[], None]] = None,
    telemetry: Any = None,
    trace_id: Optional[str] = None,
) -> tuple:
    """The KV handoff's transfer leg, routed through the redistribution
    primitive: one stage per parked page (each page's fixed-shape block is
    the chunk, so the scratch bound is a page — the layout already IS the
    2112.01075 decomposition). ``extract`` is the source engine's jitted
    per-page read; the commit (the destination's donated adopt/copy program
    + ``release_parked`` ack) stays with the router, whose retry-then-
    re-prefill ladder is this transfer's fallback rung.

    Chaos: the ``redistribute_fail_*`` legs kill a named page-read stage
    here, and ``probe`` (the router's handoff stall/loss window) fires in
    the same mid-transfer window as before — the pre-existing drills are
    inherited unchanged. At CPU scale the page blocks stage through host
    (the relay rung, recorded honestly); on a pod the same page list drives
    device-to-device sends."""
    from ..resilience import chaos as chaos_mod
    from ..resilience.membership import StaleEpochError  # noqa: F401 - parity

    if fault_plan is None:
        fault_plan = chaos_mod.active_plan()
    seq = _next_seq()
    t0 = time.perf_counter()
    n = len(pages)
    if fault_plan is not None:
        for stage in range(n):
            if fault_plan.redistribute_fail(seq, stage, "paged_extract"):
                raise RedistributeStageFailure(
                    f"redistribute transfer {seq} lost page-read stage "
                    f"{stage} of {n} mid-transfer",
                    stage=stage, kind="paged_extract", leaf=f"page[{stage}]",
                )
    if probe is not None:
        probe()
    k_blocks, v_blocks = extract(pages)
    moved = int(k_blocks.nbytes + v_blocks.nbytes)
    if telemetry is not None and getattr(telemetry, "enabled", False):
        payload = {
            "transfer": seq,
            "path": "host_relay",
            "leaves": 2,
            "stages": n,
            "stage_kinds": {"paged_extract": n},
            "bytes_moved": moved,
            "peak_scratch_bytes": moved // max(n, 1),
            "max_scratch_bytes": moved // max(n, 1),
            "outcome": "committed",
            "wall_time_s": round(time.perf_counter() - t0, 6),
        }
        if trace_id is not None:
            payload["trace_id"] = trace_id
        telemetry.write_record("redistribute", payload)
    return k_blocks, v_blocks


# ---------------------------------------------------------------------------
# the canonical contract program (analyze --self-check / tests/contracts)
# ---------------------------------------------------------------------------

# the contract-recording geometry: a (64, 128) f32 leaf on the 8-way mesh,
# chunk bound 4 KiB → 8-row chunks — small enough to compile in the CLI gate,
# chunked enough that the stage program is the REAL multi-chunk commit path
CONTRACT_SHAPE = (64, 128)
CONTRACT_CHUNK_ROWS = 8


def canonical_redistribute_program():
    """The chunk-commit stage program the ``redistribute_stage`` contract is
    recorded from, lowered over the full device mesh. Returns ``(lowered,
    hbm_budget_bytes)``: the budget arms the PR 8 memory audit's
    ``HBM_OVER_BUDGET`` gate at destination + chunk (+ slack for XLA
    bookkeeping) — the scratch bound checked, not claimed."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devices = np.asarray(jax.devices())
    mesh = Mesh(devices.reshape(-1), ("x",))
    dst = NamedSharding(mesh, PartitionSpec(None, "x"))
    dest = jax.ShapeDtypeStruct(CONTRACT_SHAPE, jnp.float32, sharding=dst)
    chunk = jax.ShapeDtypeStruct(
        (CONTRACT_CHUNK_ROWS, CONTRACT_SHAPE[1]), jnp.float32, sharding=dst
    )
    start = jax.ShapeDtypeStruct((), jnp.int32)
    lowered = jax.jit(
        _update_fn(0), donate_argnums=(0,), out_shardings=dst
    ).lower(dest, chunk, start)
    dest_bytes = int(np.prod(CONTRACT_SHAPE)) * 4
    chunk_bytes = CONTRACT_CHUNK_ROWS * CONTRACT_SHAPE[1] * 4
    # donation aliases dest in/out, so audited peak ≈ chunk (+ index + code);
    # 2× chunk headroom keeps the gate about the BOUND, not XLA's mood
    budget = dest_bytes + 2 * chunk_bytes
    return lowered, budget
