"""Ring attention: exact attention over sequence-sharded activations.

Net-new capability (SURVEY §5.7): the reference's only sequence-parallel lever
is Megatron's LayerNorm/dropout activation sharding — it has no ring/context
parallelism, so max sequence length is bounded by one device's memory. Here
the sequence axis is a first-class mesh dimension:

- Q/K/V stay sharded over the ``sequence`` axis; nothing is ever all-gathered.
- K/V blocks rotate around the ring via ``ppermute`` (neighbor hops ride ICI),
  n-1 hops for n devices, each dispatched before the block compute so the hop
  overlaps the matmuls. GQA K/V rotate *unexpanded* (kv heads, not query
  heads), so grouped-query models keep their bandwidth advantage.
- Softmax is accumulated online (flash-attention style running max/denominator),
  so the result is *exact*, not blockwise-approximate.
- Padding masks are supported: the [B, S] key-validity mask is sharded and
  rotated alongside K/V.

Memory per device: O(S/n · S/n) score blocks instead of O(S²) — sequence
length scales linearly with the ring size.

The per-block math runs the Pallas flash kernel on TPU
(ops.flash_attention.flash_attention_block — offset-causal, masked, with a
differentiable lse output) and an identical-semantics einsum off-TPU: each
block contributes ``(numerator=out·1, max=lse, sum=1)`` to the online merge,
so the ring is exact either way.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from .compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.flash_attention import flash_attention_block
from ..utils.constants import MESH_AXIS_DATA, MESH_AXIS_FSDP, MESH_AXIS_SEQUENCE, MESH_AXIS_TENSOR

NEG_INF = -1e30


def _ring_attention_local(q, k, v, kv_valid, axis_name: str, causal: bool):
    """Body run per sequence shard inside shard_map.

    kv_valid [B, S_local] bool or None: key positions that are real.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_local, nh, d = q.shape

    perm = [(i, (i + 1) % n) for i in range(n)]
    q_offset = idx * s_local

    def accumulate(carry, r, k_cur, v_cur, valid_cur):
        o, m, l = carry
        src = (idx - r) % n  # whose K/V block we currently hold
        # the block kernel owns ALL masking: offset-causal positions (future
        # blocks cost a zero-trip loop) + rotated key validity. Its (out,
        # lse) is a normalized partial softmax: merge as (out, lse, 1).
        o_blk, lse_blk = flash_attention_block(
            q, k_cur, v_cur, valid_cur, causal=causal,
            q_offset=q_offset, kv_offset=src * s_local,
        )
        m_new = jnp.maximum(m, lse_blk)
        corr_old = jnp.exp(m - m_new)
        corr_blk = jnp.exp(lse_blk - m_new)
        o = o * corr_old[..., None] + o_blk.astype(jnp.float32) * corr_blk[..., None]
        l = l * corr_old + corr_blk
        return o, m_new, l

    def step(carry, r):
        o, m, l, k_cur, v_cur, valid_cur = carry
        # dispatch the rotation first so the hop overlaps the block compute
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        valid_next = None if valid_cur is None else jax.lax.ppermute(valid_cur, axis_name, perm)
        o, m, l = accumulate((o, m, l), r, k_cur, v_cur, valid_cur)
        return (o, m, l, k_next, v_next, valid_next), None

    o0 = jnp.zeros((b, s_local, nh, d), jnp.float32)
    m0 = jnp.full((b, s_local, nh), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, s_local, nh), jnp.float32)
    vma = getattr(q.aval, "vma", None)
    if vma:
        o0, m0, l0 = (jax.lax.pcast(x, tuple(vma), to="varying") for x in (o0, m0, l0))
        if kv_valid is not None:
            missing = tuple(set(vma) - set(getattr(kv_valid.aval, "vma", ()) or ()))
            if missing:  # e.g. an all-ones mask built inside the manual region
                kv_valid = jax.lax.pcast(kv_valid, missing, to="varying")

    if n > 1:
        # n-1 rotating rounds, then a final round with no wasted hop
        (o, m, l, k_last, v_last, valid_last), _ = jax.lax.scan(
            step, (o0, m0, l0, k, v, kv_valid), jnp.arange(n - 1)
        )
        o, m, l = accumulate((o, m, l), n - 1, k_last, v_last, valid_last)
    else:
        o, m, l = accumulate((o0, m0, l0), 0, k, v, kv_valid)
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def make_local_ring_attention(
    axis_name: str = MESH_AXIS_SEQUENCE,
    causal: bool = True,
):
    """Ring attention for code ALREADY inside a shard_map manual region over
    ``axis_name`` (the pipeline schedule with a sequence axis): operands are
    sequence-local shards, so no nested shard_map — the ring body runs
    directly. Same ``attn(q, k, v, kv_mask)`` contract as
    :func:`make_ring_attention`."""

    def attn(q, k, v, kv_mask=None):
        kv_valid = None if kv_mask is None else kv_mask.astype(bool)
        return _ring_attention_local(q, k, v, kv_valid, axis_name=axis_name, causal=causal)

    return attn


def make_ring_attention(
    mesh: Mesh,
    axis_name: str = MESH_AXIS_SEQUENCE,
    causal: bool = True,
):
    """Build a drop-in attention fn for sequence-sharded [B, S, N, D] inputs.

    Returns ``attn(q, k, v, kv_mask=None)`` where ``kv_mask`` is a [B, S]
    validity mask (1 = real token). Inputs whose sequence length does not
    divide the ring size fall back to plain (unsharded) attention — trace-time
    static shape check, so e.g. a stray eval at an odd length still works.
    """
    from ..models.attention import dot_product_attention

    batch_spec = (MESH_AXIS_DATA, MESH_AXIS_FSDP)
    qkv_spec = P(batch_spec, axis_name, MESH_AXIS_TENSOR, None)
    mask_spec = P(batch_spec, axis_name)
    ring_size = mesh.shape[axis_name]

    local = partial(_ring_attention_local, axis_name=axis_name, causal=causal)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
    )
    def ring(q, k, v, kv_valid):
        return local(q, k, v, kv_valid)

    def attn(q, k, v, kv_mask=None):
        if q.shape[1] % ring_size != 0 or q.shape[1] < ring_size:
            # indivisible length: exact fallback rather than a shard_map error
            mask = None if kv_mask is None else kv_mask[:, None, None, :].astype(bool)
            return dot_product_attention(q, k, v, mask=mask, causal=causal)
        if kv_mask is None:
            kv_valid = jnp.ones((q.shape[0], q.shape[1]), bool)
        else:
            kv_valid = kv_mask.astype(bool)
        return ring(q, k, v, kv_valid)

    return attn
