"""Pipeline parallelism: layers sharded over the ``pipeline`` mesh axis.

Parity-plus (SURVEY §2.6 PP row): the reference offers training PP only by
delegating to Megatron-LM and inference PP via pippy's fx tracing
(inference.py:126). Here PP is native: the stacked layer parameters are
sharded on their leading (layer) dimension over the ``pipeline`` axis, and a
GPipe schedule runs *inside one jit program* via ``shard_map``:

- the shard_map is manual over ONLY the ``pipeline`` axis (``axis_names``):
  tensor/fsdp/data stay in GSPMD auto mode, so Megatron-style TP matmuls and
  ZeRO-3 parameter sharding keep working *inside* each pipeline stage;
- every stage holds L/P layers; activations (and each microbatch's attention
  mask) hop stage→stage with ``ppermute`` over neighbor ICI links;
- the microbatch loop is a ``lax.scan`` over M + P - 1 ticks — stage p works
  on microbatch t-p at tick t, filling and draining like 1F1B's forward pass;
- backward is jax.grad through the scan: XLA reverses the ppermutes into the
  backward pipeline automatically (no hand-written schedule);
- each stage's compute is wrapped in ``jax.checkpoint`` so only per-tick
  boundary activations stay live.

Bubble fraction is (P-1)/(M+P-1) — pick num_microbatches >= 4*P for ~<20%
overhead, as with any GPipe-family schedule.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.constants import MESH_AXIS_PIPELINE, MESH_AXIS_SEQUENCE


def _is_narrow_float(dtype) -> bool:
    """bf16/fp16 (anything a pipeline-axis psum must be promoted around)."""
    return jnp.issubdtype(dtype, jnp.floating) and jnp.finfo(dtype).bits < 32


def make_pipeline_layers_fn(cfg, mesh: Mesh, num_microbatches: int, dot_fn=None):
    """Build ``fn(stacked_layer_params, h, cos, sin, mask) -> h`` running the
    decoder stack as a pipeline over the ``pipeline`` mesh axis.

    Constraints (v1): the ``sequence`` axis must be 1 (ring attention inside a
    pipeline stage is a follow-up); layer count must divide the pipeline
    size; cos/sin must be batch-invariant (default integer positions). The
    microbatch count adapts downward (with a warning) when it does not
    divide the batch.
    """
    from ..models.llama import decoder_layer

    if mesh.shape.get(MESH_AXIS_SEQUENCE, 1) > 1:
        raise NotImplementedError("pipeline + sequence axes combined is not supported yet")
    nstages = mesh.shape[MESH_AXIS_PIPELINE]
    if cfg.num_layers % nstages != 0:
        raise ValueError(f"num_layers={cfg.num_layers} must divide pipeline size {nstages}")
    M = num_microbatches

    def local_fn(layers, h, cos, sin, mask, dtypes=None):
        # manual over pipeline only: h/cos/sin/mask are GLOBAL here (their
        # data/tensor shardings are still handled by GSPMD in auto mode)
        idx = jax.lax.axis_index(MESH_AXIS_PIPELINE)

        def to_varying(x):
            have = set(getattr(x.aval, "vma", ()) or ())
            missing = tuple({MESH_AXIS_PIPELINE} - have)
            return jax.lax.pcast(x, missing, to="varying") if missing else x

        # Become pipeline-varying while still fp32 (fn() widens narrow floats at
        # the shard_map boundary): the transpose of this pcast is the psum that
        # carries grads back to the replicated inputs, and a bf16/fp16 psum from
        # a manual region crashes XLA's AllReducePromotion pass.
        if dtypes is not None:
            h, cos, sin = (to_varying(x).astype(d) for x, d in zip((h, cos, sin), dtypes))

        def stage(h_mb, mask_mb):
            def body(hh, lp):
                hh, _ = decoder_layer(cfg, hh, lp, cos, sin, mask_mb, causal=True, dot_fn=dot_fn)
                return hh, None

            out, _ = jax.lax.scan(body, h_mb, layers)
            return out

        stage = jax.checkpoint(stage)

        b = h.shape[0]
        # adapt the microbatch count to the actual (static) batch: the default
        # is 4 per stage for a small bubble, but a tiny batch caps it
        M_eff = min(M, b)
        while b % M_eff:
            M_eff -= 1
        if M_eff < M:  # trace-time: fires once per compiled shape
            from ..logging import get_logger

            get_logger(__name__).warning(
                f"pipeline: num_microbatches={M} cut to {M_eff} by batch {b} — "
                f"bubble fraction is {(nstages - 1) / (M_eff + nstages - 1):.0%}. "
                "Raise the batch (or pick one divisible by the microbatch "
                "count) to shrink it."
            )
        mb = h.reshape(M_eff, b // M_eff, *h.shape[1:])
        if mask is None:
            mask_mb_all = jnp.ones((M_eff, b // M_eff, 1, 1, h.shape[1]), bool)
        else:
            mask_mb_all = mask.reshape(M_eff, b // M_eff, *mask.shape[1:])
        # the loop makes these pipeline-varying (stage-dependent values); the
        # initial carry must already carry that type for scan to typecheck
        state = to_varying(jnp.zeros_like(mb[0]))
        state_mask = to_varying(jnp.ones_like(mask_mb_all[0]))
        outputs = to_varying(jnp.zeros_like(mb))
        fwd_perm = [(i, i + 1) for i in range(nstages - 1)]

        def tick(carry, t):
            state, state_mask, outputs = carry
            inject = jax.lax.dynamic_index_in_dim(mb, jnp.clip(t, 0, M_eff - 1), keepdims=False)
            inject_mask = jax.lax.dynamic_index_in_dim(
                mask_mb_all, jnp.clip(t, 0, M_eff - 1), keepdims=False
            )
            x = jnp.where(idx == 0, inject, state)
            m = jnp.where(idx == 0, inject_mask, state_mask)
            y = stage(x, m)
            out_t = t - (nstages - 1)
            collected = jax.lax.dynamic_update_slice(
                outputs, y[None].astype(outputs.dtype), (jnp.clip(out_t, 0, M_eff - 1),) + (0,) * y.ndim
            )
            valid = (out_t >= 0) & (idx == nstages - 1)
            outputs = jnp.where(valid, collected, outputs)
            if nstages > 1:
                # the mask travels with its activation through the pipeline
                state = jax.lax.ppermute(y, MESH_AXIS_PIPELINE, fwd_perm)
                state_mask = jax.lax.ppermute(m, MESH_AXIS_PIPELINE, fwd_perm)
            else:
                state, state_mask = y, m
            return (state, state_mask, outputs), None

        ticks = jnp.arange(M_eff + nstages - 1)
        (_, _, outputs), _ = jax.lax.scan(tick, (state, state_mask, outputs), ticks)
        # fan the last stage's collected outputs out to every stage; the psum is
        # exact because every other stage contributes zeros. Promote bf16/fp16 to
        # fp32 around the collective: XLA's AllReducePromotion pass crashes on a
        # low-precision all-reduce emitted from a manual shard_map region
        # ("Invalid binary instruction opcode copy"), and fp32<->bf16 round-trip
        # of bf16 values is lossless.
        out_dtype = outputs.dtype
        outputs = jnp.where(idx == nstages - 1, outputs, jnp.zeros_like(outputs))
        if _is_narrow_float(out_dtype):
            outputs = jax.lax.psum(outputs.astype(jnp.float32), MESH_AXIS_PIPELINE)
            outputs = outputs.astype(out_dtype)
        else:
            outputs = jax.lax.psum(outputs, MESH_AXIS_PIPELINE)
        return outputs.reshape(h.shape)

    def fn(stacked_layers, h, cos, sin, mask):
        if cos.shape[0] != 1:
            raise NotImplementedError("per-row positions are not supported in the pipeline schedule")
        # Replicated float operands cross the shard_map boundary in fp32: the
        # transpose of the implicit pipeline-axis broadcast of a replicated
        # input is a psum, and a bf16/fp16 psum from a manual region crashes
        # XLA's AllReducePromotion pass. Widening is lossless; compute inside
        # still runs at the caller's dtype.
        dtypes = (h.dtype, cos.dtype, sin.dtype)
        wide = tuple(
            x.astype(jnp.float32) if _is_narrow_float(x.dtype) else x for x in (h, cos, sin)
        )

        def body(l, hh, c, s, m):
            return local_fn(l, hh, c, s, m, dtypes=dtypes)

        # only the pipeline placement is manual; every other dim/axis is left
        # to GSPMD (tensor/fsdp shardings keep working inside the stage)
        layers_specs = jax.tree.map(lambda _: P(MESH_AXIS_PIPELINE), stacked_layers)
        other_specs = (P(), P(), P()) if mask is None else (P(), P(), P(), P())
        args = (stacked_layers,) + wide if mask is None else (stacked_layers,) + wide + (mask,)
        wrapped = (lambda l, hh, c, s: body(l, hh, c, s, None)) if mask is None else body
        shard_fn = shard_map(
            wrapped,
            mesh=mesh,
            in_specs=(layers_specs,) + other_specs,
            out_specs=P(),
            axis_names={MESH_AXIS_PIPELINE},
        )
        return shard_fn(*args)

    return fn
