"""Pipeline parallelism: layers sharded over the ``pipeline`` mesh axis.

Parity-plus (SURVEY §2.6 PP row): the reference offers training PP only by
delegating to Megatron-LM and inference PP via pippy's fx tracing
(inference.py:126). Here PP is native AND model-agnostic: any model exposing a
per-layer function (``pipeline_layer`` hook — llama, gpt2, bert all do) runs
its stacked layer parameters sharded on their leading (layer) dimension over
the ``pipeline`` axis, with the microbatch schedule *inside one jit program*
via ``shard_map``:

- the shard_map is manual over ONLY the ``pipeline`` axis (``axis_names``):
  tensor/fsdp/data/expert stay in GSPMD auto mode, so Megatron-style TP
  matmuls, MoE expert dispatch and ZeRO-3 parameter sharding keep working
  *inside* each pipeline stage;
- every device holds ``virtual_stages`` chunks of L/(v·P) layers (Megatron
  interleaved/virtual stages, reference dataclasses.py:1246
  ``num_layers_per_virtual_pipeline_stage``); activations hop stage→stage
  with ``ppermute`` over neighbor ICI links, wrapping P-1 → 0 between chunks;
- per-microbatch side inputs (attention masks, per-row rotary tables) do NOT
  ride the ring: they enter replicated, and each tick indexes the slice for
  the microbatch it is processing from a static schedule table;
- the schedule is computed at trace time by a deep-first greedy simulation
  (consume the ring arrival if present, else inject the next microbatch) and
  baked into per-(device, tick) index tables; a ``lax.scan`` over the ticks
  executes it. The deep-first rule guarantees each produced activation is
  consumed exactly one tick later, so one in-flight slot per device suffices;
- dropout: each tick knows its (chunk, microbatch), so per-layer rngs are
  folded in deterministically — ``fold_in(fold_in(base, layer), microbatch)``
  (see :func:`fold_pipeline_dropout_rng`). Rematerialization replays the same
  fold, so ``jax.checkpoint`` stays sound;
- auxiliary scalar losses (MoE load balance) are accumulated per executed
  chunk and psum-reduced over the pipeline axis — computed per *microbatch*
  (the GShard/Megatron convention) rather than per full batch;
- backward is jax.grad through the scan: XLA reverses the ppermutes into the
  backward pipeline automatically (no hand-written schedule);
- each chunk's compute is wrapped in ``jax.checkpoint`` so only per-tick
  boundary activations stay live.

Bubble: with v = 1 the schedule is exactly GPipe — fraction (P-1)/(M+P-1).
With v virtual stages each fill/drain tick costs 1/v of a full stage, so the
fraction drops toward (P-1)/(vM+P-1)-ish; the schedule builder reports the
exact idle fraction for the chosen (P, v, M).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .compat import HAS_PCAST, shard_map

from ..utils.constants import MESH_AXIS_PIPELINE, MESH_AXIS_SEQUENCE


def _is_narrow_float(dtype) -> bool:
    """bf16/fp16 (anything a pipeline-axis psum must be promoted around)."""
    return jnp.issubdtype(dtype, jnp.floating) and jnp.finfo(dtype).bits < 32


def fold_pipeline_dropout_rng(base: jax.Array, layer_index, microbatch) -> jax.Array:
    """The canonical dropout-rng derivation inside the pipeline schedule.

    Deterministic in (global layer index, microbatch index) so (a) forward
    recompute under ``jax.checkpoint`` replays identical masks and (b) a
    non-pipeline reference using the same fold reproduces the pipeline's
    output exactly (tests/test_pipeline.py dropout parity).
    """
    return jax.random.fold_in(jax.random.fold_in(base, layer_index), microbatch)


def build_interleaved_schedule(num_stages: int, virtual: int, num_microbatches: int):
    """Static (device, tick) tables for the interleaved forward schedule.

    Deep-first greedy: each device consumes its ring arrival when one exists
    (arrivals are always deeper in the network than fresh injections), else
    device 0 injects the next microbatch into virtual stage 0. Every
    activation produced at tick t is consumed at tick t+1 on the next device
    of the ring — one in-flight slot per device, like GPipe.

    Returns ``(chunk, use_arrival, microbatch, emit, idle_fraction)`` — the
    first four are [P, T] int arrays (-1 = not applicable at that tick);
    ``microbatch`` records WHICH microbatch a device processes at each tick
    (valid wherever ``chunk >= 0``), used for side-input indexing and
    dropout-rng folding.
    """
    Pn, v, M = num_stages, virtual, num_microbatches
    S = v * Pn
    arrive: list = [None] * Pn
    next_inject = 0
    done = 0
    chunk_rows, use_rows, mb_rows, emit_rows = [], [], [], []
    while done < M:
        send: list = [None] * Pn
        cc, uu, mm, ee = [-1] * Pn, [0] * Pn, [-1] * Pn, [-1] * Pn
        for p in range(Pn):
            if arrive[p] is not None:
                m, s = arrive[p]
                cc[p], uu[p], mm[p] = s // Pn, 1, m
                if s == S - 1:
                    ee[p] = m
                    done += 1
                else:
                    send[(p + 1) % Pn] = (m, s + 1)
            elif p == 0 and next_inject < M:
                m = next_inject
                next_inject += 1
                cc[p], mm[p] = 0, m
                if S == 1:
                    ee[p] = m
                    done += 1
                else:
                    send[1 % Pn] = (m, 1)
        arrive = send
        chunk_rows.append(cc)
        use_rows.append(uu)
        mb_rows.append(mm)
        emit_rows.append(ee)
    T = len(chunk_rows)
    tables = tuple(
        np.asarray(rows, np.int32).T  # [T, P] → [P, T]
        for rows in (chunk_rows, use_rows, mb_rows, emit_rows)
    )
    busy = int((tables[0] >= 0).sum())
    idle_fraction = 1.0 - busy / float(Pn * T)
    return (*tables, idle_fraction)


def make_pipeline_layers_fn(
    cfg,
    mesh: Mesh,
    num_microbatches: int,
    layer_fn=None,
    virtual_stages: int = 1,
    seq_dims=None,
    const_kinds=None,
):
    """Build ``fn(stacked_layer_params, h, *consts, dropout_rng=None) ->
    (h, aux)`` running a layer stack as a pipeline over the ``pipeline`` mesh
    axis, for ANY model (reference generality analogue: hooks.py:120-176 /
    accelerator.py:1421-1468 attach to arbitrary nn.Modules).

    ``layer_fn(lp, h, rng, *consts) -> (h, aux)`` is the model's single-layer
    function (the ``pipeline_layer`` hook): ``lp`` one layer's param slice,
    ``rng`` a folded dropout key or None, ``aux`` a scalar fp32 side loss
    (0 for dense layers — the MoE balance term for routed ones).

    ``consts`` are side inputs forwarded to every layer call. Each is either
    - ``None`` — passed through;
    - *per-microbatch* (leading dim == batch): split like the activations and
      indexed per tick from the schedule's microbatch table (attention masks,
      per-row position tables);
    - *broadcast* (any other shape): passed unchanged (batch-invariant rotary
      cos/sin).

    ``const_kinds`` lets the model declare each side input's kind explicitly
    (``"mb"`` / ``"bcast"`` / None = infer from shape) — the
    ``pipeline_const_kinds`` model attribute. Without a declaration the
    leading-dim==batch inference applies, which would silently slice a
    batch-invariant const whose first dim coincidentally equals the batch.

    ``virtual_stages`` > 1 gives each device that many non-contiguous layer
    chunks (Megatron interleaved schedule) — same math, smaller bubble.

    ``seq_dims`` combines the pipeline with a SEQUENCE axis (ring attention
    inside each stage): ``{"h": d, "consts": (d0, d1, ...)}`` names which
    dimension of the activations and of each side input is the sequence
    dimension (None = not sequence-sharded). The shard_map then goes manual
    over BOTH axes: activations/side inputs enter as sequence-local shards,
    and the model's layer_fn must use the manual-region ring
    (parallel.ring_attention.make_local_ring_attention — prepare_model wires
    this). Without ``seq_dims`` a sequence axis > 1 raises.

    Other constraints: layer count must divide virtual_stages × pipeline
    size. The microbatch count adapts downward (with a warning) when it does
    not divide the batch.
    """
    if layer_fn is None:
        raise TypeError(
            "make_pipeline_layers_fn needs the model's per-layer function "
            "(layer_fn=model.pipeline_layer) — the schedule is model-agnostic."
        )
    seq_size = mesh.shape.get(MESH_AXIS_SEQUENCE, 1)
    if seq_size > 1 and seq_dims is None:
        raise NotImplementedError(
            "pipeline + sequence axes need the model to declare its sequence "
            "dimensions (pipeline_seq_dims) — this model does not"
        )
    manual_axes = {MESH_AXIS_PIPELINE} | ({MESH_AXIS_SEQUENCE} if seq_size > 1 else set())
    nstages = mesh.shape[MESH_AXIS_PIPELINE]
    v = virtual_stages
    if v < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {v}")
    if const_kinds is not None:
        bad = [k for k in const_kinds if k not in (None, "mb", "bcast")]
        if bad:
            raise ValueError(f'const_kinds entries must be None, "mb" or "bcast"; got {bad}')
    if cfg.num_layers % (v * nstages) != 0:
        raise ValueError(
            f"num_layers={cfg.num_layers} must divide virtual_stages*pipeline "
            f"= {v}*{nstages}"
        )
    M = num_microbatches
    chunk_size = cfg.num_layers // (v * nstages)

    def fn(stacked_layers, h, *consts, dropout_rng=None):
        b = h.shape[0]
        # classify each side input: None / per-microbatch / broadcast.
        # Declared kinds win; the leading-dim==batch inference covers the
        # rest (a batch-invariant const whose first dim coincidentally equals
        # the batch must be declared "bcast" to avoid being sliced).
        declared = const_kinds if const_kinds is not None else (None,) * len(consts)
        if len(declared) != len(consts):
            raise ValueError(
                f"const_kinds declares {len(declared)} side inputs but the "
                f"pipeline call passed {len(consts)}"
            )
        kinds = tuple(
            "none"
            if c is None
            else (k or ("mb" if (c.ndim >= 1 and c.shape[0] == b) else "bcast"))
            for c, k in zip(consts, declared)
        )
        # Replicated float operands cross the shard_map boundary in fp32: the
        # transpose of the implicit pipeline-axis broadcast of a replicated
        # input is a psum, and a bf16/fp16 psum from a manual region crashes
        # XLA's AllReducePromotion pass. Widening is lossless; compute inside
        # still runs at the caller's dtype.
        def widen(x):
            return x.astype(jnp.float32) if _is_narrow_float(x.dtype) else x

        h_dtype = h.dtype
        const_dtypes = tuple(None if c is None else c.dtype for c in consts)
        live_consts = tuple(widen(c) for c in consts if c is not None)
        has_rng = dropout_rng is not None
        if has_rng:
            key = dropout_rng
            if not jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
                key = jax.random.wrap_key_data(key)
            rng_data = jax.random.key_data(key)

        # adapt the microbatch count to the actual (static) batch: the default
        # is 4 per stage for a small bubble, but a tiny batch caps it
        M_eff = min(M, b)
        while b % M_eff:
            M_eff -= 1
        chunk_tab, use_tab, mb_tab, emit_tab, idle = build_interleaved_schedule(
            nstages, v, M_eff
        )
        if M_eff < M:  # trace-time: fires once per compiled shape
            from ..logging import get_logger

            get_logger(__name__).warning(
                f"pipeline: num_microbatches={M} cut to {M_eff} by batch {b} — "
                f"schedule idle fraction is {idle:.0%}. Raise the batch (or "
                "pick one divisible by the microbatch count) to shrink it."
            )

        def local_fn(layers, h, *rest):
            # manual over pipeline (and optionally sequence) only: h and side
            # inputs are GLOBAL here (their data/tensor shardings are still
            # handled by GSPMD in auto mode). ``layers`` leaves arrive as
            # [v, 1, L/(v*P), ...]: chunk-major with the pipeline dim sharded
            # away — squeeze it.
            layers = jax.tree.map(lambda l: l.reshape((l.shape[0],) + l.shape[2:]), layers)
            idx = jax.lax.axis_index(MESH_AXIS_PIPELINE)
            rest = list(rest)
            rng_base = None
            if has_rng:
                rng_base = jax.random.wrap_key_data(rest.pop())

            def to_varying(x):
                if not HAS_PCAST:
                    # pre-vma jax: no replication typing in manual regions —
                    # values are already varying, shard_map transposes handle
                    # the grad psum (see compat.HAS_PCAST)
                    return x
                have = set(getattr(x.aval, "vma", ()) or ())
                missing = tuple(manual_axes - have)
                return jax.lax.pcast(x, missing, to="varying") if missing else x

            # Become pipeline-varying while still widened (fn() promoted
            # narrow floats at the shard_map boundary): the transpose of this
            # pcast is the psum that carries grads back to the replicated
            # inputs, and a bf16/fp16 psum from a manual region crashes XLA.
            h = to_varying(h).astype(h_dtype)
            if seq_size > 1:
                # layers are sequence-REPLICATED (only pipeline-sharded): the
                # pcast to sequence-varying must happen on the fp32-widened
                # values — its transpose is their grad psum over the sequence
                # axis — and only THEN downcast to the compute dtype
                layers = jax.tree.map(
                    lambda l, d: to_varying(l).astype(d), layers, layer_dtypes
                )
            consts_local: list = []
            it = iter(rest)
            for kind, dt in zip(kinds, const_dtypes):
                if kind == "none":
                    consts_local.append(None)
                    continue
                c = to_varying(next(it))
                if dt is not None and c.dtype != dt:
                    c = c.astype(dt)
                if kind == "mb":
                    c = c.reshape(M_eff, b // M_eff, *c.shape[1:])
                consts_local.append(c)

            def chunk_compute(chunk_layers, x, consts_t, c, m):
                def body(carry, xs):
                    hh, aux = carry
                    lp, j = xs
                    global_layer = (c * nstages + idx) * chunk_size + j
                    rng = (
                        fold_pipeline_dropout_rng(rng_base, global_layer, m)
                        if has_rng
                        else None
                    )
                    if has_rng and seq_size > 1:
                        # sequence shards hold DIFFERENT tokens: without this
                        # fold every shard would draw the identical dropout
                        # mask for its local block
                        rng = jax.random.fold_in(rng, jax.lax.axis_index(MESH_AXIS_SEQUENCE))
                    hh, a = layer_fn(lp, hh, rng, *consts_t)
                    return (hh, aux + a.astype(jnp.float32)), None

                # varying init: layer aux terms (MoE balance) are computed on
                # stage-dependent data, so the carry must be pipeline-varying
                (out, aux), _ = jax.lax.scan(
                    body, (x, to_varying(jnp.zeros((), jnp.float32))),
                    (chunk_layers, jnp.arange(chunk_size)),
                )
                return out, aux

            chunk_compute = jax.checkpoint(chunk_compute)

            mb_h = h.reshape(M_eff, b // M_eff, *h.shape[1:])
            # the loop makes these pipeline-varying (stage-dependent values);
            # the initial carry must already carry that type to typecheck
            state = to_varying(jnp.zeros_like(mb_h[0]))
            outputs = to_varying(jnp.zeros_like(mb_h))
            aux_acc = to_varying(jnp.zeros((), jnp.float32))
            ring = [(i, (i + 1) % nstages) for i in range(nstages)]
            chunk_arr, use_arr = jnp.asarray(chunk_tab), jnp.asarray(use_tab)
            mb_arr, emit_arr = jnp.asarray(mb_tab), jnp.asarray(emit_tab)

            def tick(carry, t):
                state, outputs, aux_acc = carry
                use = use_arr[idx, t].astype(bool)
                m = jnp.clip(mb_arr[idx, t], 0, M_eff - 1)
                inject = jax.lax.dynamic_index_in_dim(mb_h, m, keepdims=False)
                x = jnp.where(use, state, inject)
                c = jnp.clip(chunk_arr[idx, t], 0, v - 1)
                chunk_layers = jax.tree.map(
                    lambda l: jax.lax.dynamic_index_in_dim(l, c, keepdims=False), layers
                )
                # per-microbatch side inputs: pick this tick's slice from the
                # replicated table instead of shipping it around the ring
                consts_t = tuple(
                    jax.lax.dynamic_index_in_dim(cl, m, keepdims=False)
                    if kind == "mb"
                    else cl
                    for cl, kind in zip(consts_local, kinds)
                )
                y, aux = chunk_compute(chunk_layers, x, consts_t, c, m)
                # idle ticks run chunk 0 on garbage (result discarded by the
                # schedule) — their aux must not pollute the sum
                aux_acc = aux_acc + jnp.where(chunk_arr[idx, t] >= 0, aux, 0.0)
                e = emit_arr[idx, t]
                collected = jax.lax.dynamic_update_slice(
                    outputs, y[None].astype(outputs.dtype),
                    (jnp.clip(e, 0, M_eff - 1),) + (0,) * y.ndim,
                )
                outputs = jnp.where(e >= 0, collected, outputs)
                if nstages > 1:
                    state = jax.lax.ppermute(y, MESH_AXIS_PIPELINE, ring)
                else:
                    state = y
                return (state, outputs, aux_acc), None

            ticks = jnp.arange(chunk_arr.shape[1])
            (_, outputs, aux_acc), _ = jax.lax.scan(tick, (state, outputs, aux_acc), ticks)
            # fan the last virtual stage's collected outputs out to every stage
            # (only device (v*P-1) mod P == P-1 ever emits); the psum is exact
            # because every other stage contributes zeros. Promote bf16/fp16 to
            # fp32 around the collective: XLA's AllReducePromotion pass crashes
            # on a low-precision all-reduce emitted from a manual shard_map
            # region ("Invalid binary instruction opcode copy"), and
            # fp32<->bf16 round-trip of bf16 values is lossless.
            out_dtype = outputs.dtype
            outputs = jnp.where(idx == nstages - 1, outputs, jnp.zeros_like(outputs))
            if _is_narrow_float(out_dtype):
                outputs = jax.lax.psum(outputs.astype(jnp.float32), MESH_AXIS_PIPELINE)
                outputs = outputs.astype(out_dtype)
            else:
                outputs = jax.lax.psum(outputs, MESH_AXIS_PIPELINE)
            # each device accumulated the aux of its own layers only; the mean
            # over microbatches restores the full-batch scale (a sum would
            # grow the regularizer M-fold vs the non-pipeline forward)
            aux_total = jax.lax.psum(aux_acc, MESH_AXIS_PIPELINE) / M_eff
            if seq_size > 1:
                # sequence shards each saw their local tokens: mean them back
                # to the full-batch scale (and resolve the varying type for
                # the replicated out_spec)
                aux_total = jax.lax.psum(aux_total, MESH_AXIS_SEQUENCE) / seq_size
            return outputs.reshape(h.shape), aux_total

        # Rearrange stacked layers [L, ...] → [v, P, L/(v*P), ...]: virtual
        # stage s = c*P + p lands at [c, p], so sharding dim 1 over the
        # pipeline axis gives device p its v interleaved chunks.
        stacked = jax.tree.map(
            lambda l: l.reshape(v, nstages, chunk_size, *l.shape[1:]), stacked_layers
        )
        layer_dtypes = jax.tree.map(lambda l: l.dtype, stacked)
        if seq_size > 1:
            stacked = jax.tree.map(widen, stacked)
        # only the pipeline (and, with seq_dims, sequence) placement is
        # manual; every other dim/axis is left to GSPMD (tensor/fsdp/expert
        # shardings keep working inside the stage)
        def _seq_spec(ndim: int, dim) -> P:
            if seq_size <= 1 or dim is None:
                return P()
            spec = [None] * ndim
            spec[dim] = MESH_AXIS_SEQUENCE
            return P(*spec)

        layers_specs = jax.tree.map(lambda _: P(None, MESH_AXIS_PIPELINE), stacked)
        h_spec = _seq_spec(h.ndim, seq_dims["h"] if seq_dims else None)
        const_dims = tuple(seq_dims["consts"]) if seq_dims else (None,) * len(consts)
        live_specs = tuple(
            _seq_spec(c.ndim, d) for c, d in zip(consts, const_dims) if c is not None
        )
        args = (stacked, widen(h)) + live_consts
        in_specs = (layers_specs, h_spec) + live_specs
        if has_rng:
            args = args + (rng_data,)
            in_specs = in_specs + (P(),)
        shard_fn = shard_map(
            local_fn,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(h_spec, P()),
            axis_names=manual_axes,
        )
        return shard_fn(*args)

    return fn
