"""Pipeline parallelism: layers sharded over the ``pipeline`` mesh axis.

Parity-plus (SURVEY §2.6 PP row): the reference offers training PP only by
delegating to Megatron-LM and inference PP via pippy's fx tracing
(inference.py:126). Here PP is native: the stacked layer parameters are
sharded on their leading (layer) dimension over the ``pipeline`` axis, and
the microbatch schedule runs *inside one jit program* via ``shard_map``:

- the shard_map is manual over ONLY the ``pipeline`` axis (``axis_names``):
  tensor/fsdp/data stay in GSPMD auto mode, so Megatron-style TP matmuls and
  ZeRO-3 parameter sharding keep working *inside* each pipeline stage;
- every device holds ``virtual_stages`` chunks of L/(v·P) layers (Megatron
  interleaved/virtual stages, reference dataclasses.py:1246
  ``num_layers_per_virtual_pipeline_stage``); activations (and each
  microbatch's attention mask) hop stage→stage with ``ppermute`` over
  neighbor ICI links, wrapping P-1 → 0 between chunks;
- the schedule is computed at trace time by a deep-first greedy simulation
  (consume the ring arrival if present, else inject the next microbatch) and
  baked into per-(device, tick) index tables; a ``lax.scan`` over the ticks
  executes it. The deep-first rule guarantees each produced activation is
  consumed exactly one tick later, so one in-flight slot per device suffices;
- backward is jax.grad through the scan: XLA reverses the ppermutes into the
  backward pipeline automatically (no hand-written schedule);
- each chunk's compute is wrapped in ``jax.checkpoint`` so only per-tick
  boundary activations stay live.

Bubble: with v = 1 the schedule is exactly GPipe — fraction (P-1)/(M+P-1).
With v virtual stages each fill/drain tick costs 1/v of a full stage, so the
fraction drops toward (P-1)/(vM+P-1)-ish; the schedule builder reports the
exact idle fraction for the chosen (P, v, M).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.constants import MESH_AXIS_PIPELINE, MESH_AXIS_SEQUENCE


def _is_narrow_float(dtype) -> bool:
    """bf16/fp16 (anything a pipeline-axis psum must be promoted around)."""
    return jnp.issubdtype(dtype, jnp.floating) and jnp.finfo(dtype).bits < 32


def build_interleaved_schedule(num_stages: int, virtual: int, num_microbatches: int):
    """Static (device, tick) tables for the interleaved forward schedule.

    Deep-first greedy: each device consumes its ring arrival when one exists
    (arrivals are always deeper in the network than fresh injections), else
    device 0 injects the next microbatch into virtual stage 0. Every
    activation produced at tick t is consumed at tick t+1 on the next device
    of the ring — one in-flight slot per device, like GPipe.

    Returns ``(chunk, use_arrival, inject, emit, idle_fraction)`` — the first
    four are [P, T] int arrays (-1 = not applicable at that tick).
    """
    Pn, v, M = num_stages, virtual, num_microbatches
    S = v * Pn
    arrive: list = [None] * Pn
    next_inject = 0
    done = 0
    chunk_rows, use_rows, inj_rows, emit_rows = [], [], [], []
    while done < M:
        send: list = [None] * Pn
        cc, uu, ii, ee = [-1] * Pn, [0] * Pn, [-1] * Pn, [-1] * Pn
        for p in range(Pn):
            if arrive[p] is not None:
                m, s = arrive[p]
                cc[p], uu[p] = s // Pn, 1
                if s == S - 1:
                    ee[p] = m
                    done += 1
                else:
                    send[(p + 1) % Pn] = (m, s + 1)
            elif p == 0 and next_inject < M:
                m = next_inject
                next_inject += 1
                cc[p], ii[p] = 0, m
                if S == 1:
                    ee[p] = m
                    done += 1
                else:
                    send[1 % Pn] = (m, 1)
        arrive = send
        chunk_rows.append(cc)
        use_rows.append(uu)
        inj_rows.append(ii)
        emit_rows.append(ee)
    T = len(chunk_rows)
    tables = tuple(
        np.asarray(rows, np.int32).T  # [T, P] → [P, T]
        for rows in (chunk_rows, use_rows, inj_rows, emit_rows)
    )
    busy = int((tables[0] >= 0).sum())
    idle_fraction = 1.0 - busy / float(Pn * T)
    return (*tables, idle_fraction)


def make_pipeline_layers_fn(cfg, mesh: Mesh, num_microbatches: int, dot_fn=None, virtual_stages: int = 1):
    """Build ``fn(stacked_layer_params, h, cos, sin, mask) -> h`` running the
    decoder stack as a pipeline over the ``pipeline`` mesh axis.

    ``virtual_stages`` > 1 gives each device that many non-contiguous layer
    chunks (Megatron interleaved schedule) — same math, smaller bubble.

    Constraints (v1): the ``sequence`` axis must be 1 (ring attention inside a
    pipeline stage is a follow-up); layer count must divide virtual_stages ×
    pipeline size; cos/sin must be batch-invariant (default integer
    positions). The microbatch count adapts downward (with a warning) when it
    does not divide the batch.
    """
    from ..models.llama import decoder_layer

    if mesh.shape.get(MESH_AXIS_SEQUENCE, 1) > 1:
        raise NotImplementedError("pipeline + sequence axes combined is not supported yet")
    nstages = mesh.shape[MESH_AXIS_PIPELINE]
    v = virtual_stages
    if v < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {v}")
    if cfg.num_layers % (v * nstages) != 0:
        raise ValueError(
            f"num_layers={cfg.num_layers} must divide virtual_stages*pipeline "
            f"= {v}*{nstages}"
        )
    M = num_microbatches

    def local_fn(layers, h, cos, sin, mask, dtypes=None):
        # manual over pipeline only: h/cos/sin/mask are GLOBAL here (their
        # data/tensor shardings are still handled by GSPMD in auto mode).
        # ``layers`` leaves arrive as [v, 1, L/(v*P), ...]: chunk-major with
        # the pipeline dim sharded away — squeeze it.
        layers = jax.tree.map(lambda l: l.reshape((l.shape[0],) + l.shape[2:]), layers)
        idx = jax.lax.axis_index(MESH_AXIS_PIPELINE)

        def to_varying(x):
            have = set(getattr(x.aval, "vma", ()) or ())
            missing = tuple({MESH_AXIS_PIPELINE} - have)
            return jax.lax.pcast(x, missing, to="varying") if missing else x

        # Become pipeline-varying while still fp32 (fn() widens narrow floats at
        # the shard_map boundary): the transpose of this pcast is the psum that
        # carries grads back to the replicated inputs, and a bf16/fp16 psum from
        # a manual region crashes XLA's AllReducePromotion pass.
        if dtypes is not None:
            h, cos, sin = (to_varying(x).astype(d) for x, d in zip((h, cos, sin), dtypes))

        def chunk_compute(chunk_layers, h_mb, mask_mb):
            def body(hh, lp):
                hh, _ = decoder_layer(cfg, hh, lp, cos, sin, mask_mb, causal=True, dot_fn=dot_fn)
                return hh, None

            out, _ = jax.lax.scan(body, h_mb, chunk_layers)
            return out

        chunk_compute = jax.checkpoint(chunk_compute)

        b = h.shape[0]
        # adapt the microbatch count to the actual (static) batch: the default
        # is 4 per stage for a small bubble, but a tiny batch caps it
        M_eff = min(M, b)
        while b % M_eff:
            M_eff -= 1
        chunk_tab, use_tab, inj_tab, emit_tab, idle = build_interleaved_schedule(
            nstages, v, M_eff
        )
        if M_eff < M:  # trace-time: fires once per compiled shape
            from ..logging import get_logger

            get_logger(__name__).warning(
                f"pipeline: num_microbatches={M} cut to {M_eff} by batch {b} — "
                f"schedule idle fraction is {idle:.0%}. Raise the batch (or "
                "pick one divisible by the microbatch count) to shrink it."
            )
        mb = h.reshape(M_eff, b // M_eff, *h.shape[1:])
        if mask is None:
            mask_mb_all = jnp.ones((M_eff, b // M_eff, 1, 1, h.shape[1]), bool)
        else:
            mask_mb_all = mask.reshape(M_eff, b // M_eff, *mask.shape[1:])
        # the loop makes these pipeline-varying (stage-dependent values); the
        # initial carry must already carry that type for scan to typecheck
        state = to_varying(jnp.zeros_like(mb[0]))
        state_mask = to_varying(jnp.ones_like(mask_mb_all[0]))
        outputs = to_varying(jnp.zeros_like(mb))
        ring = [(i, (i + 1) % nstages) for i in range(nstages)]
        chunk_arr, use_arr = jnp.asarray(chunk_tab), jnp.asarray(use_tab)
        inj_arr, emit_arr = jnp.asarray(inj_tab), jnp.asarray(emit_tab)

        def tick(carry, t):
            state, state_mask, outputs = carry
            use = use_arr[idx, t].astype(bool)
            inj = jnp.clip(inj_arr[idx, t], 0, M_eff - 1)
            inject = jax.lax.dynamic_index_in_dim(mb, inj, keepdims=False)
            inject_mask = jax.lax.dynamic_index_in_dim(mask_mb_all, inj, keepdims=False)
            x = jnp.where(use, state, inject)
            m = jnp.where(use, state_mask, inject_mask)
            c = jnp.clip(chunk_arr[idx, t], 0, v - 1)
            chunk_layers = jax.tree.map(
                lambda l: jax.lax.dynamic_index_in_dim(l, c, keepdims=False), layers
            )
            y = chunk_compute(chunk_layers, x, m)
            e = emit_arr[idx, t]
            collected = jax.lax.dynamic_update_slice(
                outputs, y[None].astype(outputs.dtype),
                (jnp.clip(e, 0, M_eff - 1),) + (0,) * y.ndim,
            )
            outputs = jnp.where(e >= 0, collected, outputs)
            if nstages > 1:
                # the mask travels with its activation through the pipeline
                state = jax.lax.ppermute(y, MESH_AXIS_PIPELINE, ring)
                state_mask = jax.lax.ppermute(m, MESH_AXIS_PIPELINE, ring)
            else:
                state, state_mask = y, m
            return (state, state_mask, outputs), None

        ticks = jnp.arange(chunk_arr.shape[1])
        (_, _, outputs), _ = jax.lax.scan(tick, (state, state_mask, outputs), ticks)
        # fan the last virtual stage's collected outputs out to every stage
        # (only device (v*P-1) mod P == P-1 ever emits); the psum is exact
        # because every other stage contributes zeros. Promote bf16/fp16 to
        # fp32 around the collective: XLA's AllReducePromotion pass crashes on a
        # low-precision all-reduce emitted from a manual shard_map region
        # ("Invalid binary instruction opcode copy"), and fp32<->bf16 round-trip
        # of bf16 values is lossless.
        out_dtype = outputs.dtype
        outputs = jnp.where(idx == nstages - 1, outputs, jnp.zeros_like(outputs))
        if _is_narrow_float(out_dtype):
            outputs = jax.lax.psum(outputs.astype(jnp.float32), MESH_AXIS_PIPELINE)
            outputs = outputs.astype(out_dtype)
        else:
            outputs = jax.lax.psum(outputs, MESH_AXIS_PIPELINE)
        return outputs.reshape(h.shape)

    def fn(stacked_layers, h, cos, sin, mask):
        if cos.shape[0] != 1:
            raise NotImplementedError("per-row positions are not supported in the pipeline schedule")
        # Replicated float operands cross the shard_map boundary in fp32: the
        # transpose of the implicit pipeline-axis broadcast of a replicated
        # input is a psum, and a bf16/fp16 psum from a manual region crashes
        # XLA's AllReducePromotion pass. Widening is lossless; compute inside
        # still runs at the caller's dtype.
        dtypes = (h.dtype, cos.dtype, sin.dtype)
        wide = tuple(
            x.astype(jnp.float32) if _is_narrow_float(x.dtype) else x for x in (h, cos, sin)
        )

        def body(l, hh, c, s, m):
            return local_fn(l, hh, c, s, m, dtypes=dtypes)

        # Rearrange stacked layers [L, ...] → [v, P, L/(v*P), ...]: virtual
        # stage s = c*P + p lands at [c, p], so sharding dim 1 over the
        # pipeline axis gives device p its v interleaved chunks.
        chunk = cfg.num_layers // (v * nstages)
        stacked_layers = jax.tree.map(
            lambda l: l.reshape(v, nstages, chunk, *l.shape[1:]), stacked_layers
        )
        # only the pipeline placement is manual; every other dim/axis is left
        # to GSPMD (tensor/fsdp shardings keep working inside the stage)
        layers_specs = jax.tree.map(lambda _: P(None, MESH_AXIS_PIPELINE), stacked_layers)
        other_specs = (P(), P(), P()) if mask is None else (P(), P(), P(), P())
        args = (stacked_layers,) + wide if mask is None else (stacked_layers,) + wide + (mask,)
        wrapped = (lambda l, hh, c, s: body(l, hh, c, s, None)) if mask is None else body
        shard_fn = shard_map(
            wrapped,
            mesh=mesh,
            in_specs=(layers_specs,) + other_specs,
            out_specs=P(),
            axis_names={MESH_AXIS_PIPELINE},
        )
        return shard_fn(*args)

    return fn
