"""jax-version compatibility for the parallel modules.

``jax.shard_map`` became a public top-level API in newer jax; on 0.4.x only
``jax.experimental.shard_map.shard_map`` exists, and it spells the
manual-axes selection differently (``auto`` = the complement set, instead of
``axis_names``). Both call patterns used in this package — direct call and
``partial(shard_map, mesh=..., ...)`` decorator — go through this shim.
"""

from __future__ import annotations

try:  # jax >= 0.5: public API with axis_names
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # jax 0.4.x: experimental API with auto=<complement>
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None, **kwargs):
        if axis_names is not None:
            kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        # 0.4.x's replication checker false-positives on scan carries over
        # partially-auto meshes ("mismatched replication types"); jax's own
        # error message prescribes check_rep=False as the workaround.
        kwargs.setdefault("check_rep", False)
        return _experimental_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )


import jax as _jax

# Replication ("vma") typing of values inside shard_map manual regions, and
# the jax.lax.pcast that promotes replicated→varying, only exist on newer
# jax. On 0.4.x (check_rep=False) every value in a manual region is already
# treated as varying and shard_map inserts the transpose-psums itself, so
# callers skip the explicit pcast when this is False.
HAS_PCAST = hasattr(_jax.lax, "pcast")

__all__ = ["shard_map", "HAS_PCAST"]
