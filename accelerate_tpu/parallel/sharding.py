"""Partition-rule engine: from param pytrees to NamedShardings.

This is the single sharding engine that replaces the reference's four native
runtimes (DDP wrapper accelerator.py:1418, DeepSpeed ZeRO accelerator.py:1486,
FSDP accelerator.py:1421-1468, Megatron TP utils/megatron_lm.py): every
strategy is just a different assignment of array dimensions to mesh axes, and
XLA emits the matching collectives (all-gather on use, reduce-scatter on grad)
under GSPMD.

Rules are (regex, PartitionSpec-tuple) pairs matched against the pytree path
of each parameter ("layers/3/attn/wq"). First match wins. Unmatched params fall
back to the FSDP auto-rule (shard the largest divisible dim over the ``fsdp``
axis when the tensor is big enough) or replication.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..utils.constants import MESH_AXIS_FSDP
from ..utils.dataclasses import FullyShardedDataParallelPlugin


def param_path(key_path) -> str:
    """jax.tree_util key path → "a/b/0/c" string."""
    parts = []
    for entry in key_path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        elif hasattr(entry, "name"):
            parts.append(str(entry.name))
        else:
            parts.append(str(entry))
    return "/".join(parts)


def _axis_size(mesh: Mesh, axis) -> int:
    if axis is None:
        return 1
    if isinstance(axis, (tuple, list)):
        size = 1
        for a in axis:
            size *= mesh.shape[a]
        return size
    return mesh.shape[axis]


def _spec_fits(shape: tuple[int, ...], spec: Sequence, mesh: Mesh) -> bool:
    if len(spec) > len(shape):
        return False
    for dim, axis in zip(shape, spec):
        size = _axis_size(mesh, axis)
        if size > 1 and dim % size != 0:
            return False
    return True


def fsdp_auto_spec(
    shape: tuple[int, ...],
    mesh: Mesh,
    plugin: Optional[FullyShardedDataParallelPlugin] = None,
    taken_axes: Sequence[str] = (),
) -> PartitionSpec:
    """Shard the largest divisible dim over ``fsdp`` (ZeRO-3 layout).

    Mirrors the effect of FSDP's flat-param sharding / DeepSpeed ZeRO-3
    partitioning without flattening: per-tensor dim sharding composes with TP
    and keeps matmul layouts MXU-friendly.
    """
    fsdp_size = mesh.shape.get(MESH_AXIS_FSDP, 1)
    if fsdp_size <= 1:
        return PartitionSpec()
    min_size = plugin.min_weight_size if plugin is not None else 2**12
    total = int(np.prod(shape)) if shape else 0
    if total < min_size:
        return PartitionSpec()
    # prefer the largest dim not already sharded by an explicit (TP) axis
    order = sorted(range(len(shape)), key=lambda i: shape[i], reverse=True)
    for dim in order:
        if shape[dim] % fsdp_size == 0:
            spec = [None] * len(shape)
            spec[dim] = MESH_AXIS_FSDP
            return PartitionSpec(*spec)
    return PartitionSpec()


class PartitionRules:
    """Ordered (regex, spec) table with FSDP auto-fallback."""

    def __init__(
        self,
        rules: Sequence[tuple[str, tuple]] = (),
        fsdp_plugin: Optional[FullyShardedDataParallelPlugin] = None,
        combine_fsdp: bool = True,
        apply_fsdp_to_params: bool = True,
    ):
        self._raw_rules = list(rules)
        self.rules = [(re.compile(pattern), tuple(spec)) for pattern, spec in rules]
        self.fsdp_plugin = fsdp_plugin
        self.combine_fsdp = combine_fsdp
        # ZeRO stage 1/2: params stay replicated over fsdp (only optimizer
        # state shards) — the rules engine then skips the fsdp auto/fold paths
        # for parameters while with_fsdp_applied() still produces the sharded
        # layout for the optimizer-state tree.
        self.apply_fsdp_to_params = apply_fsdp_to_params

    def with_fsdp_applied(self) -> "PartitionRules":
        """Copy of these rules with fsdp sharding forced on (the optimizer-state
        layout under ZeRO stage 1/2)."""
        return PartitionRules(
            self._raw_rules, self.fsdp_plugin, combine_fsdp=self.combine_fsdp, apply_fsdp_to_params=True
        )

    def spec_for(self, path: str, shape: tuple[int, ...], mesh: Mesh) -> PartitionSpec:
        for pattern, spec in self.rules:
            if pattern.search(path):
                if not _spec_fits(shape, spec, mesh):
                    break  # rule exists but doesn't divide: fall back to auto
                spec = list(spec) + [None] * (len(shape) - len(spec))
                if (
                    self.apply_fsdp_to_params
                    and self.combine_fsdp
                    and mesh.shape.get(MESH_AXIS_FSDP, 1) > 1
                ):
                    spec = self._fold_in_fsdp(shape, spec, mesh)
                return PartitionSpec(*spec)
        if not self.apply_fsdp_to_params:
            return PartitionSpec()
        return fsdp_auto_spec(shape, mesh, self.fsdp_plugin)

    def _fold_in_fsdp(self, shape, spec, mesh) -> list:
        """Also shard an explicit-TP param over fsdp on a free dim (2D sharding,
        the megatron+zero3 combination)."""
        fsdp_size = mesh.shape[MESH_AXIS_FSDP]
        total = int(np.prod(shape)) if shape else 0
        min_size = self.fsdp_plugin.min_weight_size if self.fsdp_plugin else 2**12
        if total < min_size:
            return spec
        for dim in sorted(range(len(shape)), key=lambda i: shape[i], reverse=True):
            if spec[dim] is None and shape[dim] % fsdp_size == 0:
                spec[dim] = MESH_AXIS_FSDP
                return spec
        return spec


def infer_shardings(
    tree: Any,
    mesh: Mesh,
    rules: Optional[PartitionRules] = None,
) -> Any:
    """Tree of arrays/ShapeDtypeStructs → tree of NamedSharding."""
    rules = rules or PartitionRules()

    def _leaf(key_path, leaf):
        path = param_path(key_path)
        spec = rules.spec_for(path, tuple(leaf.shape), mesh)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(_leaf, tree)


def shard_tree(tree: Any, shardings: Any) -> Any:
    """device_put every leaf to its sharding (the actual H2D/placement step)."""
    return jax.tree.map(lambda x, s: jax.device_put(x, s), tree, shardings)


def abstract_like(tree: Any) -> Any:
    """Tree of arrays → tree of ShapeDtypeStructs: re-derive shardings for a
    NEW mesh (elastic shrink/regrow) without touching the live buffers —
    ``infer_shardings``/``shardings_like`` accept either."""
    return jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


# -- ZeRO update-sharding specs (arXiv 2004.13336; SimpleFSDP 2411.00284) ------
#
# The weight update is elementwise, so it decomposes exactly across any
# partition of the parameters: reduce-scatter the gradients over the
# data-parallel axes, update each chip's 1/N shard with 1/N optimizer state,
# and all-gather the result where the next forward needs it. These helpers
# produce the *storage* layout that decomposition implies: each parameter's
# PartitionSpec with the ZeRO axes folded onto a divisible dimension.


def zero_batch_axes(mesh: Mesh) -> tuple[str, ...]:
    """The data-parallel axes a ZeRO update shards over — every nontrivial
    batch axis (the axes ``AcceleratorState.data_sharding`` splits over)."""
    from ..utils.constants import MESH_AXIS_DATA

    return tuple(
        a for a in (MESH_AXIS_DATA, MESH_AXIS_FSDP) if mesh.shape.get(a, 1) > 1
    )


def _spec_axes(spec) -> set:
    axes = set()
    for entry in spec:
        if entry is None:
            continue
        for a in entry if isinstance(entry, tuple) else (entry,):
            axes.add(a)
    return axes


def fold_update_spec(
    shape: tuple[int, ...], spec, mesh: Mesh, zero_axes: Sequence[str]
) -> PartitionSpec:
    """Fold ``zero_axes`` into ``spec``: split one more dimension of the
    parameter over the update axes (preferring a dim that is already sharded —
    the reduce-scatter then extends the existing split — else the largest
    divisible free dim). Axes already present in the spec are skipped; a
    parameter with no divisible dim keeps its spec (its update runs
    replicated — bias-vector sized, so the state saving is negligible)."""
    spec = tuple(spec) + (None,) * (len(shape) - len(spec))
    fold = tuple(a for a in zero_axes if a not in _spec_axes(spec))
    zsize = 1
    for a in fold:
        zsize *= mesh.shape[a]
    if zsize == 1 or not shape:
        return PartitionSpec(*spec)

    def _axis_count(entry) -> int:
        if entry is None:
            return 1
        if isinstance(entry, tuple):
            return _axis_size(mesh, entry)
        return _axis_size(mesh, (entry,))

    order = sorted(
        range(len(shape)), key=lambda i: (_axis_count(spec[i]) == 1, -shape[i])
    )
    for dim in order:
        if shape[dim] % (_axis_count(spec[dim]) * zsize) == 0:
            base = (
                spec[dim]
                if isinstance(spec[dim], tuple)
                else ((spec[dim],) if spec[dim] is not None else ())
            )
            folded = list(spec)
            merged = tuple(base) + fold
            folded[dim] = merged if len(merged) > 1 else merged[0]
            return PartitionSpec(*folded)
    return PartitionSpec(*spec)


def zero_update_shardings(tree: Any, shardings: Any, mesh: Mesh) -> Any:
    """Param tree + its NamedShardings → the ZeRO-folded NamedShardings (the
    storage layout for parameters, gradients shards, and optimizer moments)."""
    axes = zero_batch_axes(mesh)

    def _leaf(leaf, sharding):
        return NamedSharding(
            mesh, fold_update_spec(tuple(leaf.shape), sharding.spec, mesh, axes)
        )

    return jax.tree.map(_leaf, tree, shardings)


def shardings_like(state_shapes: Any, params: Any, params_shardings: Any, mesh: Mesh) -> Any:
    """Shardings for an optimizer-state tree: leaves that are param-tree copies
    (Adam moments) reuse the matching param's sharding; everything else is
    replicated (step counters, scalars).

    ``state_shapes`` is a tree of ShapeDtypeStructs from
    ``jax.eval_shape(tx.init, params)``. Matching is by *tree path*: optax
    embeds whole param-tree copies inside the state (``.../mu/layers/wq``), so
    a state leaf matches the param whose path is the longest suffix of the
    state leaf's path with an equal shape. Shape-only matching would silently
    give two same-shaped params with different shardings the wrong moment
    layout (first-match-wins); path matching cannot.
    """
    by_path: dict[str, tuple[tuple, NamedSharding]] = {}

    def _collect(key_path, p_leaf, s_leaf):
        by_path[param_path(key_path)] = (tuple(p_leaf.shape), s_leaf)
        return p_leaf

    jax.tree_util.tree_map_with_path(_collect, params, params_shardings)

    def _leaf(key_path, leaf):
        if len(leaf.shape) == 0:
            return replicated(mesh)
        path = param_path(key_path)
        shape = tuple(leaf.shape)
        best = None
        for p_path, (p_shape, sharding) in by_path.items():
            if p_shape != shape:
                continue
            if path == p_path or path.endswith("/" + p_path):
                if best is None or len(p_path) > len(best[0]):
                    best = (p_path, sharding)
        if best is not None:
            return best[1]
        return replicated(mesh)

    return jax.tree_util.tree_map_with_path(_leaf, state_shapes)
