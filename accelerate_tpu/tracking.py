"""Experiment trackers.

Parity: reference tracking.py — GeneralTracker ABC (91) with
requires_logging_directory / main_process_only / lifecycle
(store_init_configuration, log, finish), concrete trackers (165-970),
filter_trackers (971).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

from .logging import get_logger
from .state import PartialState
from .utils.imports import (
    is_aim_available,
    is_clearml_available,
    is_comet_ml_available,
    is_dvclive_available,
    is_mlflow_available,
    is_tensorboard_available,
    is_wandb_available,
)

logger = get_logger(__name__)


def json_default(obj: Any):
    """``json.dumps(default=...)`` coercion for the values telemetry and
    training loops actually log: jax/numpy scalars become numbers (not the
    strings ``default=str`` produced), small arrays become lists, everything
    else degrades to ``str`` so a sink never crashes a run."""
    import numpy as np

    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if hasattr(obj, "item") and getattr(obj, "ndim", None) == 0:
        try:
            return obj.item()  # 0-d jax.Array and friends
        except Exception:
            pass
    if hasattr(obj, "tolist"):
        try:
            return obj.tolist()  # small jax arrays
        except Exception:
            pass
    if isinstance(obj, (set, frozenset)):
        return sorted(str(v) for v in obj)
    return str(obj)


def coerce_jsonable(obj: Any) -> Any:
    """Deep-coerce a tree into plain JSON types (keys stringified) — the
    fallback for payloads ``json.dumps(default=json_default)`` still rejects,
    e.g. tuple-keyed dicts or NaN-free encoders."""
    if isinstance(obj, dict):
        return {str(k): coerce_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [coerce_jsonable(v) for v in obj]
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        return obj
    coerced = json_default(obj)
    return coerce_jsonable(coerced) if isinstance(coerced, (dict, list, tuple)) else coerced


def dumps_robust(record: Any) -> str:
    """Serialize ``record`` without ever raising: numeric coercion first,
    deep sanitization if the structure itself is unserializable."""
    try:
        return json.dumps(record, default=json_default)
    except (TypeError, ValueError):
        return json.dumps(coerce_jsonable(record), default=str)


_available_trackers: dict[str, type] = {}


def register_tracker(cls):
    _available_trackers[cls.name] = cls
    return cls


def on_main_process(method):
    def wrapper(self, *args, **kwargs):
        if not getattr(self, "main_process_only", True) or PartialState().is_main_process:
            return method(self, *args, **kwargs)

    return wrapper


class GeneralTracker:
    """Base tracker API (reference tracking.py:91-163)."""

    name: str = "general"
    requires_logging_directory: bool = False
    main_process_only: bool = True

    def store_init_configuration(self, values: dict) -> None:
        raise NotImplementedError

    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        raise NotImplementedError

    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        """{name: HWC/NHWC array} (reference WandBTracker.log_images, :339).
        Optional — trackers without image support log a warning once."""
        logger.warning_once(f"Tracker {self.name} does not support log_images; skipping.")

    def log_table(
        self,
        table_name: str,
        columns: Optional[list] = None,
        data: Optional[list] = None,
        step: Optional[int] = None,
        **kwargs,
    ) -> None:
        """Tabular logging (reference WandBTracker.log_table, :360)."""
        logger.warning_once(f"Tracker {self.name} does not support log_table; skipping.")

    def finish(self) -> None:
        pass

    @property
    def tracker(self):
        return getattr(self, "writer", self)


@register_tracker
class TensorBoardTracker(GeneralTracker):
    name = "tensorboard"
    requires_logging_directory = True

    def __init__(self, run_name: str, logging_dir: str, **kwargs):
        try:
            from torch.utils import tensorboard
        except ImportError:
            import tensorboardX as tensorboard

        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir, run_name)
        self.writer = tensorboard.SummaryWriter(self.logging_dir, **kwargs)

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.writer.add_hparams(values, metric_dict={})
        self.writer.flush()
        with open(os.path.join(self.logging_dir, "hparams.json"), "w") as f:
            json.dump(values, f, default=str)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        for k, v in values.items():
            if isinstance(v, (int, float)):
                self.writer.add_scalar(k, v, global_step=step, **kwargs)
            elif isinstance(v, str):
                self.writer.add_text(k, v, global_step=step, **kwargs)
            elif isinstance(v, dict):
                self.writer.add_scalars(k, v, global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def finish(self) -> None:
        self.writer.close()


@register_tracker
class WandBTracker(GeneralTracker):
    name = "wandb"
    main_process_only = True

    def __init__(self, run_name: str, **kwargs):
        import wandb

        self.run = wandb.init(project=run_name, **kwargs)

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import wandb

        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        import wandb

        self.run.log({k: [wandb.Image(img) for img in v] if isinstance(v, (list, tuple)) else wandb.Image(v) for k, v in values.items()}, step=step, **kwargs)

    @on_main_process
    def log_table(self, table_name: str, columns=None, data=None, step: Optional[int] = None, **kwargs) -> None:
        import wandb

        self.run.log({table_name: wandb.Table(columns=columns, data=data)}, step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.run.finish()

    @property
    def tracker(self):
        return self.run


@register_tracker
class MLflowTracker(GeneralTracker):
    name = "mlflow"

    def __init__(self, run_name: str, logging_dir: Optional[str] = None, **kwargs):
        import mlflow

        self.run = mlflow.start_run(run_name=run_name, **kwargs)
        self.writer = mlflow

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import mlflow

        for k, v in values.items():
            mlflow.log_param(k, v)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        import mlflow

        metrics = {k: v for k, v in values.items() if isinstance(v, (int, float))}
        mlflow.log_metrics(metrics, step=step)

    @on_main_process
    def finish(self) -> None:
        import mlflow

        mlflow.end_run()


@register_tracker
class JSONLTracker(GeneralTracker):
    """Dependency-free tracker writing metrics as JSON lines — the default
    when no external tracker is installed (net-new; useful on TPU pods where
    hosts have no network egress)."""

    name = "jsonl"
    requires_logging_directory = True

    def __init__(self, run_name: str, logging_dir: str, **kwargs):
        self.run_name = run_name
        os.makedirs(os.path.join(logging_dir, run_name), exist_ok=True)
        self.path = os.path.join(logging_dir, run_name, "metrics.jsonl")
        self._file = open(self.path, "a")

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self._file.write(dumps_robust({"_config": values, "_time": time.time()}) + "\n")
        self._file.flush()

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        # dumps_robust: jax/numpy scalars land as numbers, and a weird value
        # degrades to a string instead of crashing the run (telemetry sinks
        # here every flush — a logging failure must never kill training)
        record = {**values, "_step": step, "_time": time.time()}
        self._file.write(dumps_robust(record) + "\n")
        self._file.flush()

    @on_main_process
    def finish(self) -> None:
        if self._file.closed:
            return
        # durability on preemption: the last flush must survive the VM dying
        # right after the run exits (GCS-fuse/NFS lose unfsynced pages)
        self._file.flush()
        try:
            os.fsync(self._file.fileno())
        except OSError:
            pass  # non-fsyncable sinks (pipes) still got the flush
        self._file.close()


@register_tracker
class CometMLTracker(GeneralTracker):
    """Comet (reference tracking.py:399-477)."""

    name = "comet_ml"

    def __init__(self, run_name: str, **kwargs):
        from comet_ml import Experiment

        self.run_name = run_name
        self.writer = Experiment(project_name=run_name, **kwargs)

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.writer.log_parameters(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        if step is not None:
            self.writer.set_step(step)
        for k, v in values.items():
            if isinstance(v, (int, float)):
                self.writer.log_metric(k, v, step=step, **kwargs)
            elif isinstance(v, str):
                self.writer.log_other(k, v, **kwargs)
            elif isinstance(v, dict):
                self.writer.log_metrics(v, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        for k, v in values.items():
            self.writer.log_image(v, name=k, step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.writer.end()


@register_tracker
class AimTracker(GeneralTracker):
    """Aim (reference tracking.py:480-576)."""

    name = "aim"
    requires_logging_directory = True

    def __init__(self, run_name: str, logging_dir: Optional[str] = None, **kwargs):
        from aim import Run

        self.writer = Run(repo=logging_dir, **kwargs)
        self.writer.name = run_name

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.writer["hparams"] = values

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        for k, v in values.items():
            self.writer.track(v, name=k, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        import aim

        for k, v in values.items():
            self.writer.track(aim.Image(v, **kwargs.get("aim_image_kw", {})), name=k, step=step)

    @on_main_process
    def finish(self) -> None:
        self.writer.close()


@register_tracker
class ClearMLTracker(GeneralTracker):
    """ClearML (reference tracking.py:724-873)."""

    name = "clearml"

    def __init__(self, run_name: str, **kwargs):
        from clearml import Task

        self.run_name = run_name
        existing = Task.current_task()
        if existing is not None:
            self.task = existing
        else:
            init_kwargs = dict(kwargs)
            init_kwargs.setdefault("project_name", run_name)
            init_kwargs.setdefault("task_name", run_name)
            self.task = Task.init(**init_kwargs)
        # only close tasks we created; an adopted external task stays open
        self._created = existing is None

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.task.connect_configuration(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        logger_obj = self.task.get_logger()
        for k, v in values.items():
            if isinstance(v, (int, float)):
                if step is None:
                    logger_obj.report_single_value(name=k, value=v, **kwargs)
                else:
                    title, _, series = k.partition("/")
                    logger_obj.report_scalar(
                        title=title, series=series or title, value=v, iteration=step, **kwargs
                    )

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        logger_obj = self.task.get_logger()
        for k, v in values.items():
            logger_obj.report_image(title=k, series=k, iteration=step, image=v, **kwargs)

    @on_main_process
    def log_table(self, table_name: str, columns=None, data=None, step: Optional[int] = None, **kwargs) -> None:
        to_report = [columns] + list(data) if columns is not None else data
        self.task.get_logger().report_table(
            title=table_name, series=table_name, table_plot=to_report, iteration=step, **kwargs
        )

    @on_main_process
    def finish(self) -> None:
        if self._created:
            self.task.close()

    @property
    def tracker(self):
        return self.task


@register_tracker
class DVCLiveTracker(GeneralTracker):
    """DVCLive (reference tracking.py:876-968)."""

    name = "dvclive"

    def __init__(self, run_name: str, live=None, **kwargs):  # noqa: ARG002 - run_name unused upstream too
        from dvclive import Live

        self.live = live if live is not None else Live(**kwargs)

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.live.log_params(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        if step is not None:
            self.live.step = step
        for k, v in values.items():
            if isinstance(v, (int, float, str)):
                self.live.log_metric(k, v, **kwargs)
        self.live.next_step()

    @on_main_process
    def finish(self) -> None:
        self.live.end()

    @property
    def tracker(self):
        return self.live


_AVAILABILITY = {
    "tensorboard": is_tensorboard_available,
    "wandb": is_wandb_available,
    "mlflow": is_mlflow_available,
    "comet_ml": is_comet_ml_available,
    "aim": is_aim_available,
    "clearml": is_clearml_available,
    "dvclive": is_dvclive_available,
    "jsonl": lambda: True,
}


def filter_trackers(
    log_with,
    logging_dir: Optional[str],
    project_name: str,
    config: Optional[dict] = None,
    init_kwargs: Optional[dict] = None,
) -> list[GeneralTracker]:
    """Resolve tracker names ("all" included) to live instances (tracking.py:971)."""
    if log_with is None:
        return []
    if not isinstance(log_with, (list, tuple)):
        log_with = [log_with]
    init_kwargs = init_kwargs or {}

    names: list[str] = []
    instances: list[GeneralTracker] = []
    for item in log_with:
        if isinstance(item, GeneralTracker):
            instances.append(item)
        elif str(item) == "all":
            names.extend(name for name, avail in _AVAILABILITY.items() if avail())
        else:
            names.append(str(item))

    for name in dict.fromkeys(names):
        if name not in _available_trackers:
            raise ValueError(f"Unknown tracker {name!r}; available: {sorted(_available_trackers)}")
        avail = _AVAILABILITY.get(name, lambda: True)
        if not avail():
            logger.warning(f"Tracker {name} requested but its package is not installed; skipping.")
            continue
        cls = _available_trackers[name]
        kwargs = dict(init_kwargs.get(name, {}))
        if cls.requires_logging_directory:
            if logging_dir is None:
                raise ValueError(f"Tracker {name} requires a logging_dir (set project_dir).")
            instances.append(cls(project_name, logging_dir=logging_dir, **kwargs))
        else:
            instances.append(cls(project_name, **kwargs))
    for tracker in instances:
        if config:
            tracker.store_init_configuration(config)
    return instances
