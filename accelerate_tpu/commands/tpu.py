"""`accelerate-tpu tpu-config` — run setup/maintenance commands on every
worker of a TPU pod.

Parity: reference commands/tpu.py:90-157 (gcloud ssh command runner with
config-file defaults, command files, and an install helper).
"""

from __future__ import annotations

import shlex
import subprocess

from .config import load_config_from_file
from .pod import build_gcloud_ssh_cmd


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "tpu-config", help="Run commands on all workers of a TPU pod (setup, installs, ...)"
    )
    parser.add_argument("--config_file", default=None, help="YAML config with tpu_name/tpu_zone/commands")
    parser.add_argument("--command", action="append", default=None, help="A command to run (repeatable)")
    parser.add_argument("--command_file", default=None, help="File with one command per line")
    parser.add_argument("--tpu_name", default=None)
    parser.add_argument("--tpu_zone", default=None)
    parser.add_argument("--worker", default="all")
    parser.add_argument("--use_alpha", action="store_true")
    parser.add_argument(
        "--install_accelerate", action="store_true",
        help="Prepend a pip install of this package on every worker",
    )
    parser.add_argument(
        "--accelerate_version", default="latest",
        help='Version to install with --install_accelerate ("latest" or an exact version)',
    )
    parser.add_argument("--debug", action="store_true", help="Print the gcloud command instead of running it")
    parser.set_defaults(func=run)
    return parser


def assemble_pod_setup_command(args, config: dict | None = None) -> str:
    """Resolve command sources (CLI > command file > YAML config) into the one
    shell line every worker executes (reference tpu.py:111-127)."""
    if config is None:
        config = load_config_from_file(args.config_file)
    commands = list(args.command or [])
    command_file = args.command_file or config.get("command_file")
    if not commands and command_file:
        with open(command_file) as f:
            commands = [line for line in f.read().splitlines() if line.strip()]
    if not commands and config.get("commands"):
        commands = list(config["commands"])
    if not commands and not args.install_accelerate:
        raise ValueError("You must specify either a command, a command file, or --install_accelerate.")

    parts = []
    if args.install_accelerate:
        if args.accelerate_version == "latest":
            parts.append("pip install -U accelerate-tpu")
        else:
            parts.append(f"pip install accelerate-tpu=={args.accelerate_version}")
    parts += commands
    return "; ".join(parts)


def run(args) -> int:
    # load_config_from_file already handles the ACCELERATE_CONFIG_FILE env
    # var, the default path, and missing files (→ {})
    config = load_config_from_file(args.config_file)
    tpu_name = args.tpu_name or config.get("tpu_name")
    tpu_zone = args.tpu_zone or config.get("tpu_zone")
    if not tpu_name or not tpu_zone:
        raise ValueError("tpu-config needs --tpu_name and --tpu_zone (or a config file providing them).")
    command = assemble_pod_setup_command(args, config)
    cmd = build_gcloud_ssh_cmd(tpu_name, tpu_zone, command, worker=args.worker, use_alpha=args.use_alpha)
    if args.debug:
        print(" ".join(shlex.quote(c) for c in cmd))
        return 0
    result = subprocess.run(cmd)
    if result.returncode == 0:
        print("Successfully ran the commands on the pod.")
    return result.returncode
