"""`accelerate-tpu analyze` — the static-analysis front door.

Two modes that compose:

1. **Source lint** (default): AST-lint the given files/directories for
   trace-time hazards in jit-traced functions — branching on traced values,
   wall clocks, host RNG, ``.item()``/``np.asarray`` host syncs, captured-
   state mutation. Exit code 1 on any ERROR finding (``--strict``: on any
   finding), so the command drops straight into CI::

       accelerate-tpu analyze train.py my_pkg/ --strict

2. **Self-check** (``--self-check``): build the repo's own bert-tiny fused
   step program, a llama-tiny serving decode program, and the routed
   (2-replica fleet) decode path, and run the full compiled-program audit
   (donation aliasing, fp64, constants, collective inventory, replication)
   over each — the same gate ``tests/test_analysis.py`` enforces, runnable
   anywhere::

       accelerate-tpu analyze --self-check

``--json`` emits the machine-readable report (findings + inventory) for
diffing across commits. The findings catalog lives in docs/analysis.md.
"""

from __future__ import annotations

import json


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "analyze",
        help="Static lint + compiled-program audit for step and decode paths",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="Python files or directories to lint (default: none — use --self-check)",
    )
    parser.add_argument(
        "--self-check", action="store_true",
        help="Audit the repo's own bert-tiny step + llama-tiny decode programs",
    )
    parser.add_argument(
        "--no-compile", action="store_true",
        help="Self-check: skip the AOT compile (trace-level audit only)",
    )
    parser.add_argument("--json", action="store_true", help="Emit the machine-readable report")
    parser.add_argument(
        "--strict", action="store_true",
        help="Exit non-zero on ANY finding (default: errors only)",
    )
    parser.set_defaults(func=run)
    return parser


def _self_check(compile: bool):
    """The analyzer pointed at this repo's own hot paths — small configs, so
    it runs on a laptop CPU in seconds and proves the plumbing end to end."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    import optax

    from .. import Accelerator
    from ..models import Bert, Llama
    from ..serving import ServingEngine

    reports = []
    accelerator = Accelerator()
    model = Bert("bert-tiny")
    accelerator.prepare_model(model)
    accelerator.prepare_optimizer(optax.adamw(1e-4))
    rng = np.random.default_rng(0)
    vocab = model.config.vocab_size
    batch = {
        "input_ids": jnp.asarray(rng.integers(0, vocab, (8, 16)), jnp.int32),
        "attention_mask": jnp.ones((8, 16), jnp.int32),
        "labels": jnp.asarray(rng.integers(0, 2, (8,)), jnp.int32),
    }
    reports.append(
        accelerator.analyze(
            Bert.loss_fn(model), batch, compile=compile, label="bert_tiny_step",
            write_record=False,
        )
    )

    llama = Llama("llama-tiny")
    lparams = llama.init(jax.random.key(0))
    engine = ServingEngine(llama, lparams, num_slots=2, max_len=32)
    reports.append(
        engine.analyze(compile=compile, write_record=False)
    )

    # the routed decode path: replication must not change the program, so a
    # 2-replica fleet's per-replica audits must come back exactly as clean
    # (donation intact on EVERY replica) as the lone engine's above
    from ..serving import ServingRouter

    router = ServingRouter(
        engine_factory=lambda: ServingEngine(llama, lparams, num_slots=2, max_len=32),
        num_replicas=2,
    )
    reports.append(router.analyze(compile=compile, write_record=False))
    return reports


def run(args) -> int:
    from ..analysis import AnalysisReport, lint_paths

    reports: list[AnalysisReport] = []
    if args.paths:
        reports.append(lint_paths(args.paths))
    if args.self_check:
        reports.extend(_self_check(compile=not args.no_compile))
    if not reports:
        print("nothing to analyze: pass paths to lint and/or --self-check")
        return 1

    total_findings = 0
    total_errors = 0
    if args.json:
        print(json.dumps([r.to_dict() for r in reports], indent=2, default=str))
    for report in reports:
        if not args.json:
            print(report.render())
            print()
        total_findings += len(report.findings)
        total_errors += len(report.errors)
    if total_errors or (args.strict and total_findings):
        return 1
    return 0
