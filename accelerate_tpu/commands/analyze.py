"""`accelerate-tpu analyze` — the static-analysis front door.

Three modes that compose:

1. **Source lint** (default): AST-lint the given files/directories for
   trace-time hazards in jit-traced functions — branching on traced values,
   wall clocks, host RNG, ``.item()``/``np.asarray`` host syncs, captured-
   state mutation. Exit code 1 on any ERROR finding (``--strict``: on any
   finding), so the command drops straight into CI::

       accelerate-tpu analyze train.py my_pkg/ --strict

   ``--races`` narrows the lint to the concurrency rule family only (bare
   ``lock.acquire()``, blocking calls inside ``with <lock>:``, unguarded
   thread-shared mutation, numpy views into async dispatch, raw
   ``threading.Lock()`` bypassing the named-lock registry)::

       accelerate-tpu analyze --races accelerate_tpu/

2. **Self-check** (``--self-check``): build the repo's own canonical
   programs — the bert-tiny fused step and a llama-tiny FSDP step (both
   compile the ZeRO sharded-update variant by default: all-gather →
   forward/backward → reduce-scatter → sharded adamw, parallel/zero.py —
   with sharded intent, so optimizer state resolving to replication is an
   ERROR, and the collective-overlap schedule as the gated observable), a
   llama-tiny serving engine
   (paged decode + every prefill chunk-span program — built with request
   tracing ATTACHED, so the gate doubles as proof that tracing adds zero
   device-program drift), and the routed 2-replica decode path — and run
   the full compiled-program audit
   (donation aliasing, fp64, constants, collective inventory, replication,
   HBM memory, collective-overlap schedule) over each. The concurrency
   drill rides along: the traced 2-replica fleet + an elastic coordinator
   run under the lock-order recorder (analysis/concurrency.py) and the
   resulting lock graph is reported — and gated, under ``--contracts``, by
   ``tests/contracts/concurrency.json``::

       accelerate-tpu analyze --self-check

3. **Contract gate** (``--contracts``, implies ``--self-check``): check
   every self-check program against its checked-in contract under
   ``tests/contracts/`` and exit 1 on drift, naming exactly which
   expectation moved and by how much. ``--update-contracts`` refreshes the
   JSON instead (churn-free: an undrifted contract stays byte-identical) —
   run it when a change *intends* to move a program property, and commit
   the diff::

       accelerate-tpu analyze --self-check --contracts            # the gate
       accelerate-tpu analyze --self-check --update-contracts    # move it

``--json`` emits the machine-readable report (findings + inventory) for
diffing across commits. The findings catalog lives in docs/analysis.md.
"""

from __future__ import annotations

import json

# the environment contracts are recorded on: an 8-way virtual CPU mesh
# (mirrors tests/conftest.py), so the CLI gate and the tier-1 self-gate audit
# the same programs with the same collective counts
_CONTRACT_CPU_DEVICES = 8


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "analyze",
        help="Static lint + compiled-program audit for step and decode paths",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="Python files or directories to lint (default: none — use --self-check)",
    )
    parser.add_argument(
        "--self-check", action="store_true",
        help="Audit the repo's own bert/llama step + serving decode programs",
    )
    parser.add_argument(
        "--no-compile", action="store_true",
        help="Self-check: skip the AOT compile (trace-level audit only)",
    )
    parser.add_argument(
        "--contracts", action="store_true",
        help="Check self-check programs against tests/contracts/*.json; exit 1 on drift",
    )
    parser.add_argument(
        "--update-contracts", action="store_true",
        help="Refresh the contract JSONs from this run instead of checking",
    )
    parser.add_argument(
        "--contracts-dir", default=None,
        help="Contract directory (default: the repo's tests/contracts)",
    )
    parser.add_argument(
        "--races", action="store_true",
        help="Lint only the concurrency rule family (bare acquires, blocking "
        "calls under locks, unguarded thread-shared state, numpy views into "
        "async dispatch, unregistered raw locks) over the given paths",
    )
    parser.add_argument("--json", action="store_true", help="Emit the machine-readable report")
    parser.add_argument(
        "--strict", action="store_true",
        help="Exit non-zero on ANY finding (default: errors only)",
    )
    parser.set_defaults(func=run)
    return parser


def _force_contract_mesh() -> None:
    """Best-effort: match the contract-recording environment (8 virtual CPU
    devices, mirroring tests/conftest.py) when running on CPU. XLA_FLAGS is
    read at backend init, so this works whenever the self-check is the first
    thing in the process to touch devices; once a backend is already live
    (or on real accelerators) it is a no-op — the contract env check then
    skips honestly instead of fabricating drift."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        return  # the caller already chose a device count
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={_CONTRACT_CPU_DEVICES}"
    ).strip()
    try:
        import jax

        # newer jax can force the count even after XLA_FLAGS was read
        jax.config.update("jax_num_cpu_devices", _CONTRACT_CPU_DEVICES)
    except Exception:
        pass


def _reset_state() -> None:
    from ..state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def canonical_bert_program():
    """The CANONICAL bert-tiny data-parallel program the ``bert_tiny_step``
    contract is recorded from: batch sharded over the mesh so the grad
    all-reduce inventory is part of the contract. ONE construction, shared
    by the CLI self-check and tests/test_contracts.py's seeded regressions —
    two hand-copied builders would let the gated program and the recorded
    program silently diverge. Returns ``(accelerator, model, batch)``."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    import optax

    from .. import Accelerator
    from ..models import Bert

    _reset_state()
    accelerator = Accelerator()
    model = Bert("bert-tiny")
    accelerator.prepare_model(model)
    accelerator.prepare_optimizer(optax.adamw(1e-4))
    rng = np.random.default_rng(0)
    vocab = model.config.vocab_size
    sharding = accelerator.state.data_sharding()
    batch = {
        "input_ids": jax.device_put(
            jnp.asarray(rng.integers(0, vocab, (8, 16)), jnp.int32), sharding
        ),
        "attention_mask": jax.device_put(jnp.ones((8, 16), jnp.int32), sharding),
        "labels": jax.device_put(
            jnp.asarray(rng.integers(0, 2, (8,)), jnp.int32), sharding
        ),
    }
    return accelerator, model, batch


def _self_check(compile: bool):
    """The analyzer pointed at this repo's own hot paths — small configs, so
    it runs on a laptop CPU in seconds and proves the plumbing end to end.
    These are the CANONICAL contract programs: tests/test_contracts.py runs
    exactly this set, so the CLI gate and the tier-1 gate can never audit
    different programs under the same contract names."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    import optax

    from .. import Accelerator, FullyShardedDataParallelPlugin, ParallelismConfig
    from ..models import Bert, Llama
    from ..serving import ServingEngine

    reports = []
    accelerator, model, batch = canonical_bert_program()
    reports.append(
        accelerator.analyze(
            Bert.loss_fn(model), batch, compile=compile, label="bert_tiny_step",
            write_record=False,
        )
    )

    # -- llama-tiny FSDP step: sharded intent, so replication regressions are
    # ERRORs, and the gather/scatter schedule is the overlap-work baseline
    _reset_state()
    fsdp_acc = Accelerator(
        parallelism=ParallelismConfig(data=1, fsdp=jax.device_count()),
        fsdp_plugin=FullyShardedDataParallelPlugin(stage=3),
    )
    llama = Llama("llama-tiny")
    fsdp_acc.prepare_model(llama)
    fsdp_acc.prepare_optimizer(optax.adamw(3e-4))

    def llama_loss(params, fbatch):
        logits = llama.apply(params, fbatch["input_ids"])[:, :-1].astype(jnp.float32)
        tgt = fbatch["input_ids"][:, 1:]
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt_logit = jnp.take_along_axis(logits, tgt[..., None], axis=-1)[..., 0]
        return (lse - tgt_logit).mean()

    rng = np.random.default_rng(0)
    fsdp_batch = {
        "input_ids": jax.device_put(
            jnp.asarray(rng.integers(0, llama.config.vocab_size, (8, 32)), jnp.int32),
            fsdp_acc.state.data_sharding(),
        )
    }
    reports.append(
        fsdp_acc.analyze(
            llama_loss, fsdp_batch, compile=compile, label="llama_tiny_fsdp_step",
            write_record=False,
        )
    )

    # -- the serving engine: paged decode + EVERY prefill chunk-span program
    # (prefill_chunk set, so the chunked-prefill span is contract-covered).
    # The engine is built TRACED on purpose: request-scoped tracing
    # (telemetry/tracing.py) is host-side stamps only, so the traced decode/
    # prefill programs must be byte-identical in contract terms to the
    # untraced ones the serving_* contracts were recorded from — any device-
    # program drift tracing ever introduced fails the gate right here
    _reset_state()
    from ..telemetry.tracing import RequestTracer

    lparams = llama.init(jax.random.key(0))
    engine_kwargs = dict(num_slots=2, max_len=64, page_size=16, prefill_chunk=16)
    engine = ServingEngine(llama, lparams, tracer=RequestTracer(), **engine_kwargs)
    reports.append(engine.analyze(compile=compile, write_record=False))

    # the KERNEL-enabled decode program (ops/paged_attention.py) is a
    # different program — Pallas page-walk attention instead of the gather —
    # with its own contract (`serving_decode_kernels`): page tables must
    # still ride as arguments (no baked constants) and donation must hold
    # with the kernel in the graph. Prefill programs are identical under
    # kernels (the kernel is decode-only), so only the decode is re-audited.
    kernel_engine = ServingEngine(llama, lparams, use_kernels=True, **engine_kwargs)
    if kernel_engine._use_decode_kernel:
        reports.append(
            kernel_engine.analyze(
                compile=compile, include_prefill=False, write_record=False
            )
        )
    else:
        from ..ops.runtime import interpret_mode

        if interpret_mode():
            # in the contract-recording environment (interpret mode) the
            # kernel engine MUST engage — a silent fallback here would drop
            # serving_decode_kernels from gating while the gate still exits
            # 0 (gate_reports only flags report-without-contract, never
            # contract-without-report). On assert-Mosaic/TPU runs the tiny
            # self-check geometry legitimately falls back and the contract's
            # env check skips it honestly.
            raise RuntimeError(
                "self-check kernel engine failed to engage the paged decode "
                f"kernel: {kernel_engine._kernel_fallback_reason}"
            )

    # -- the speculative engine: the one NEW device program speculation adds
    # is the windowed verify step (the draft's own decode/prefill programs
    # are shape-twins of the serving programs already gated above, on the
    # draft model's jit cache). `serving_speculative_verify` pins it:
    # donation must survive the window widening, and page tables + per-slot
    # emit limits must ride as ARGUMENTS — a baked table would recompile per
    # step, a baked limit would freeze the emit cap into the executable
    from ..serving import SpeculativeConfig

    draft = Llama(
        llama.config.replace(
            hidden_size=64, intermediate_size=176, num_layers=1,
            num_heads=2, num_kv_heads=2,
        )
    )
    spec_engine = ServingEngine(
        llama,
        lparams,
        speculative=SpeculativeConfig(
            draft_model=draft, draft_params=draft.init(jax.random.key(1)), k=4
        ),
        **engine_kwargs,
    )
    reports.append(spec_engine.analyze(compile=compile, write_record=False))

    # the routed decode path: replication must not change the program, so a
    # 2-replica fleet's per-replica audits must come back exactly as clean
    # (donation intact on EVERY replica) as the lone engine's above — the
    # fleet is traced too (one tracer shared across replicas, as in prod)
    from ..serving import ServingRouter

    router = ServingRouter(
        engine_factory=lambda: ServingEngine(llama, lparams, **engine_kwargs),
        num_replicas=2,
        tracer=RequestTracer(),
    )
    reports.append(router.analyze(compile=compile, write_record=False))

    # -- the redistribution stage program (parallel/redistribute.py): the
    # chunk-commit every recovery transfer's staged path runs — destination
    # DONATED so the stage's in-flight footprint is one chunk. The memory
    # audit runs with an hbm budget derived from the scratch bound, so
    # "bounded peak memory" is checked by the PR 8 pass, not claimed
    from ..analysis import audit_lowered
    from ..parallel.redistribute import canonical_redistribute_program

    lowered, budget = canonical_redistribute_program()
    reports.append(
        audit_lowered(
            lowered, compile=compile, label="redistribute_stage",
            expect_donation=True, hbm_budget_bytes=budget,
        )
    )
    return reports


def _concurrency_drill():
    """The thread-richest real paths, run under the lock-order recorder
    (analysis/concurrency.py): an elastic coordinator with the membership
    failure detector armed (2 simulated hosts), the routed 2-replica traced
    fleet, a sanitizer window, the redistribute sequencer, and the telemetry
    hub's flush path. Every named lock the codebase owns registers along the
    way, so the resulting report's lock inventory + acquisition-order graph
    is the artifact ``tests/contracts/concurrency.json`` gates: zero cycles,
    zero blocking-under-lock, exact lock set."""
    import os
    import tempfile

    import numpy as np

    import jax

    from ..analysis import HazardSanitizer, concurrency
    from ..models import Bert, Llama
    from ..parallel.redistribute import reset_transfer_seq
    from ..resilience.elastic import ElasticConfig
    from ..resilience.membership import DictStore, MembershipConfig, MembershipService
    from ..serving import ServingEngine, ServingRouter
    from ..telemetry.tracing import RequestTracer

    concurrency.reset_observations()
    prior_dir = os.environ.get("ACCELERATE_TELEMETRY_DIR")
    with tempfile.TemporaryDirectory() as tmp:
        # any telemetry the drill's subsystems emit lands in the tmp dir,
        # never the caller's cwd
        os.environ["ACCELERATE_TELEMETRY_DIR"] = tmp
        try:
            with concurrency.record():
                accelerator, model, batch = canonical_bert_program()
                membership = MembershipService(
                    DictStore(), num_hosts=2, host_index=0,
                    # long timeouts: a seconds-scale drill must never
                    # manufacture a loss detection
                    config=MembershipConfig(hang_watchdog_timeout_s=60.0),
                )
                coordinator = accelerator.elastic_coordinator(
                    Bert.loss_fn(model),
                    config=ElasticConfig(redundancy=0, num_hosts=2),
                    membership=membership,
                )
                for _ in range(2):
                    coordinator.step(batch)

                llama = Llama("llama-tiny")
                lparams = llama.init(jax.random.key(0))
                router = ServingRouter(
                    engine_factory=lambda: ServingEngine(
                        llama, lparams, num_slots=2, max_len=32, page_size=16
                    ),
                    num_replicas=2,
                    tracer=RequestTracer(),
                )
                rng = np.random.default_rng(0)
                prompts = [
                    rng.integers(0, llama.config.vocab_size, (4,)).astype(np.int32)
                    for _ in range(2)
                ]
                router.generate_many(prompts, max_new_tokens=2)

                with HazardSanitizer(label="concurrency-drill"):
                    pass
                reset_transfer_seq()
                # the hub's flush path is the satellite-6 regression target:
                # finish() must not hold hub.write across the fsync
                accelerator.telemetry.finish()
        finally:
            if prior_dir is None:
                os.environ.pop("ACCELERATE_TELEMETRY_DIR", None)
            else:
                os.environ["ACCELERATE_TELEMETRY_DIR"] = prior_dir
    return concurrency.registry().report()


def run(args) -> int:
    from ..analysis import AnalysisReport, lint_paths

    contracts_mode = args.contracts or args.update_contracts
    if contracts_mode:
        # --contracts implies --self-check even when lint paths are also
        # given: the gate is over the canonical PROGRAM set, and a paths-only
        # invocation silently checking zero contracts would read as "gate
        # passed" to the CI job that asked for it
        args.self_check = True
    if args.self_check:
        _force_contract_mesh()

    reports: list[AnalysisReport] = []
    if args.paths:
        if getattr(args, "races", False):
            from ..analysis.lint import CONCURRENCY_LINT_CODES

            reports.append(lint_paths(args.paths, only=CONCURRENCY_LINT_CODES))
        else:
            reports.append(lint_paths(args.paths))
    if args.self_check:
        reports.extend(_self_check(compile=not args.no_compile))
        # the concurrency drill rides on every self-check: the traced fleet +
        # elastic coordinator run under the lock-order recorder and the
        # resulting lock graph is a first-class report (and, under
        # --contracts, gated by tests/contracts/concurrency.json)
        reports.append(_concurrency_drill())
    if not reports:
        print("nothing to analyze: pass paths to lint and/or --self-check")
        return 1

    contract_notes = []
    if contracts_mode:
        from ..analysis.concurrency import gate_concurrency
        from ..analysis.contracts import default_contracts_dir, gate_reports

        contracts_dir = args.contracts_dir or default_contracts_dir()
        contract_notes = gate_reports(
            reports, contracts_dir, update=args.update_contracts
        )
        for report in reports:
            if report.meta.get("kind") == "concurrency":
                notes = gate_concurrency(
                    report, contracts_dir, update=args.update_contracts
                )
                report.extend(notes)
                contract_notes.extend(notes)

    total_findings = 0
    total_errors = 0
    if args.json:
        print(json.dumps([r.to_dict() for r in reports], indent=2, default=str))
    for report in reports:
        if not args.json:
            print(report.render())
            print()
        total_findings += len(report.findings)
        total_errors += len(report.errors)
    if args.update_contracts and not args.json:
        written = [f.path for f in contract_notes]
        if written:
            print(f"contracts updated ({len(written)}):")
            for path in written:
                print(f"  {path}")
        else:
            print("contracts unchanged (no expectation drifted)")
    if total_errors or (args.strict and total_findings):
        return 1
    return 0
