"""`accelerate-tpu profile` — windowed jax.profiler capture around a training
command.

Arms a step-aligned trace window via the ``ACCELERATE_PROFILE_*`` env vars
(consumed by the Telemetry hub every ``telemetry.step()``) and launches the
training script exactly like ``accelerate-tpu launch`` — same topology env
plumbing, so the two compose with configs and pods:

    accelerate-tpu profile --output-dir traces --start-step 100 --num-steps 20 \
        train.py --epochs 1

On a pod, run the same command on every host (or pass the env vars through
``pod-launch``): each host starts its trace at the SAME step number, so the
per-host timelines under ``<output-dir>/host_<i>`` line up by step rather
than by wall clock — which is what makes cross-host comparison meaningful on
a fleet with stragglers. ``--port`` additionally starts the live profiler
server inside the job for on-demand TensorBoard capture.
"""

from __future__ import annotations

import os
import subprocess
import sys


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "profile",
        help="Run a training script with a step-aligned jax.profiler trace window",
    )
    parser.add_argument("--output-dir", required=True, help="Where per-host traces land")
    parser.add_argument("--start-step", type=int, default=0, help="Step the trace starts at")
    parser.add_argument("--num-steps", type=int, default=5, help="How many steps to capture")
    parser.add_argument("--port", type=int, default=None, help="Also start the live profiler server on this port")
    # the launch-compatible topology surface (pass-through to the same env)
    parser.add_argument("--num_processes", type=int, default=None)
    parser.add_argument("--process_id", type=int, default=None)
    parser.add_argument("--coordinator_address", default=None)
    parser.add_argument("--mixed_precision", default=None, choices=[None, "no", "fp16", "bf16", "fp8"])
    parser.add_argument("-m", "--module", action="store_true", help="Treat script as a python module")
    parser.add_argument("training_script", help="Script (or module) to run under the profiler window")
    parser.add_argument("training_script_args", nargs=_remainder())
    parser.set_defaults(func=run)
    return parser


def _remainder():
    import argparse

    return argparse.REMAINDER


def build_env(args) -> dict[str, str]:
    env = dict(os.environ)
    env["ACCELERATE_PROFILE_DIR"] = os.path.abspath(args.output_dir)
    env["ACCELERATE_PROFILE_START_STEP"] = str(args.start_step)
    env["ACCELERATE_PROFILE_STEPS"] = str(args.num_steps)
    if args.port is not None:
        env["ACCELERATE_PROFILE_PORT"] = str(args.port)
    env["ACCELERATE_TELEMETRY"] = "1"  # the window rides the telemetry hub

    def put(key: str, value) -> None:
        if value is not None:
            env[key] = str(value)

    put("ACCELERATE_NUM_PROCESSES", args.num_processes)
    put("ACCELERATE_PROCESS_ID", args.process_id)
    put("ACCELERATE_COORDINATOR_ADDRESS", args.coordinator_address)
    put("ACCELERATE_MIXED_PRECISION", args.mixed_precision)
    return env


def run(args) -> int:
    env = build_env(args)
    cmd = [sys.executable]
    if args.module:
        cmd += ["-m", args.training_script]
    else:
        cmd += [args.training_script]
    cmd += args.training_script_args
    completed = subprocess.run(cmd, env=env)
    if completed.returncode == 0:
        print(f"Profiler traces (one dir per host) under: {env['ACCELERATE_PROFILE_DIR']}")
    return completed.returncode
