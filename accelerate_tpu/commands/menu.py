"""Interactive bullet menu for the config questionnaire.

Parity: reference commands/menu/ (cursor.py + input.py + keymap.py +
selection_menu.py, ~450 LoC of raw-terminal machinery) collapsed into one
module: arrow/j/k navigation with an ANSI redraw on a TTY, and a numbered
prompt fallback anywhere stdin is not a terminal (CI, pipes, notebooks) —
the reference menu simply breaks there.
"""

from __future__ import annotations

import sys

_HIDE, _SHOW = "\x1b[?25l", "\x1b[?25h"
_UP_ONE = "\x1b[1A"
_CLEAR_LINE = "\x1b[2K\r"


def _read_key(stdin) -> str:
    """One keypress, decoding CSI (``ESC [ A``) and SS3 (``ESC O A``) arrow
    sequences (SS3 = application cursor-key mode, common after full-screen
    apps). An empty read is EOF — the pty hung up; raising stops the menu
    from busy-looping on "" with the terminal still in cbreak."""
    ch = stdin.read(1)
    if ch in ("", "\x04"):  # true EOF, or Ctrl-D as a literal byte (cbreak
        raise EOFError("stdin closed while the menu was open")  # disables VEOF)
    if ch == "\x1b":
        follow = stdin.read(1)
        if follow == "":
            raise EOFError("stdin closed while the menu was open")
        if follow in ("[", "O"):
            code = stdin.read(1)
            if code == "":
                raise EOFError("stdin closed while the menu was open")
            return {"A": "up", "B": "down"}.get(code, "")
        # bare Esc followed by a normal key: don't swallow the key
        return follow
    if ch in ("\r", "\n"):
        return "enter"
    if ch == "\x03":  # Ctrl-C
        raise KeyboardInterrupt
    return ch


class BulletMenu:
    """``run()`` returns the selected index.

    TTY: ● bullet, ↑/↓ or j/k to move, digits jump, Enter confirms.
    Non-TTY: numbered list + plain ``input()`` (Enter keeps the default).
    """

    def __init__(self, prompt: str, choices: list[str], default: int = 0):
        self.prompt = prompt
        self.choices = list(choices)
        self.default = default

    # -- plain fallback ------------------------------------------------------

    def _run_plain(self) -> int:
        print(self.prompt)
        for i, choice in enumerate(self.choices):
            marker = "*" if i == self.default else " "
            print(f"  {marker} {i}) {choice}")
        raw = input(f"Choice [{self.default}]: ").strip()
        if not raw:
            return self.default
        try:
            index = int(raw)
        except ValueError:
            matches = [i for i, c in enumerate(self.choices) if c == raw]
            if matches:
                return matches[0]
            raise ValueError(f"{raw!r} is not an option of {self.choices}")
        if not 0 <= index < len(self.choices):
            raise ValueError(f"choice {index} out of range 0..{len(self.choices) - 1}")
        return index

    # -- raw-terminal path ---------------------------------------------------

    def _draw(self, current: int, first: bool) -> None:
        out = sys.stdout
        if not first:
            out.write((_UP_ONE + _CLEAR_LINE) * len(self.choices))
        for i, choice in enumerate(self.choices):
            bullet = "\x1b[36m●\x1b[0m" if i == current else " "
            out.write(f" {bullet} {choice}\n")
        out.flush()

    def _run_tty(self) -> int:
        import termios
        import tty

        print(f"{self.prompt} (↑/↓ + Enter)")
        current = self.default
        fd = sys.stdin.fileno()
        saved = termios.tcgetattr(fd)
        sys.stdout.write(_HIDE)
        try:
            tty.setcbreak(fd)  # cbreak only gates INPUT; drawing is unaffected
            self._draw(current, first=True)
            while True:
                key = _read_key(sys.stdin)
                if key == "enter":
                    return current
                if key in ("up", "k"):
                    current = (current - 1) % len(self.choices)
                elif key in ("down", "j"):
                    current = (current + 1) % len(self.choices)
                elif key.isdigit() and int(key) < len(self.choices):
                    current = int(key)
                else:
                    continue
                self._draw(current, first=False)
        finally:
            termios.tcsetattr(fd, termios.TCSADRAIN, saved)
            sys.stdout.write(_SHOW)
            sys.stdout.flush()

    def run(self) -> int:
        if sys.stdin.isatty() and sys.stdout.isatty():
            return self._run_tty()
        return self._run_plain()


def select(prompt: str, choices: list[str], default: str) -> str:
    """Menu over string choices returning the chosen string."""
    menu = BulletMenu(prompt, choices, default=choices.index(default))
    return choices[menu.run()]
