"""`accelerate-tpu config` — write the default config YAML.

Parity: reference commands/config/ (interactive questionnaire cluster.py +
write_basic_config default.py:133). The questionnaire asks mesh axis sizes,
precision, and checkpointing policy; `--default` writes a sane config without
prompting (single host, pure data parallel, bf16).
"""

from __future__ import annotations

import os
from pathlib import Path

import yaml

DEFAULT_CONFIG_DIR = os.path.join(
    os.environ.get("XDG_CACHE_HOME", os.path.join(os.path.expanduser("~"), ".cache")), "accelerate_tpu"
)
DEFAULT_CONFIG_FILE = os.path.join(DEFAULT_CONFIG_DIR, "default_config.yaml")


def register_subcommand(subparsers):
    parser = subparsers.add_parser("config", help="Create the launch config file")
    parser.add_argument("--config_file", default=None, help="Path to write the config YAML")
    parser.add_argument("--default", action="store_true", help="Write the default config without prompting")
    parser.set_defaults(func=run)
    return parser


def _ask(prompt: str, default, cast=str):
    raw = input(f"{prompt} [{default}]: ").strip()
    if not raw:
        return default
    if cast is bool:
        return raw.lower() in ("y", "yes", "true", "1")
    return cast(raw)


def default_config() -> dict:
    return {
        "compute_environment": "LOCAL_MACHINE",
        "mixed_precision": "bf16",
        "num_processes": 1,
        "coordinator_address": None,
        "parallelism": {"data": None, "fsdp": 1, "pipeline": 1, "expert": 1, "sequence": 1, "tensor": 1},
        "gradient_accumulation_steps": 1,
        "seed": None,
    }


def build_config_interactive() -> dict:
    config = default_config()
    config["num_processes"] = _ask("How many hosts (processes) will you launch on", 1, int)
    if config["num_processes"] > 1:
        config["coordinator_address"] = _ask("Coordinator address (host:port) for rendezvous", "localhost:8476")
    from .menu import select

    config["mixed_precision"] = select("Mixed precision?", ["no", "fp16", "bf16"], "bf16")
    par = config["parallelism"]
    par["fsdp"] = _ask("FSDP (parameter-sharding) axis size", 1, int)
    par["tensor"] = _ask("Tensor-parallel axis size", 1, int)
    par["sequence"] = _ask("Sequence-parallel axis size", 1, int)
    par["pipeline"] = _ask("Pipeline-parallel axis size", 1, int)
    config["gradient_accumulation_steps"] = _ask("Gradient accumulation steps", 1, int)
    return config


def load_config_from_file(config_file: str | None = None) -> dict:
    path = config_file or os.environ.get("ACCELERATE_CONFIG_FILE", DEFAULT_CONFIG_FILE)
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return yaml.safe_load(f) or {}


def run(args) -> int:
    config = default_config() if args.default else build_config_interactive()
    path = Path(args.config_file or DEFAULT_CONFIG_FILE)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(config, f, sort_keys=False)
    print(f"Configuration saved to {path}")
    return 0
