"""`accelerate-tpu env` — environment report (parity: reference commands/env.py)."""

from __future__ import annotations

import os
import platform


def register_subcommand(subparsers):
    parser = subparsers.add_parser("env", help="Print environment information for bug reports")
    parser.set_defaults(func=run)
    return parser


def run(args) -> int:
    import jax

    import accelerate_tpu

    info = {
        "accelerate_tpu version": accelerate_tpu.__version__,
        "platform": platform.platform(),
        "python": platform.python_version(),
        "jax version": jax.__version__,
        "jax backend": jax.default_backend(),
        "device count": jax.device_count(),
        "process count": jax.process_count(),
        "devices": ", ".join(str(d) for d in jax.devices()[:8]) + ("..." if jax.device_count() > 8 else ""),
    }
    accelerate_env = {k: v for k, v in sorted(os.environ.items()) if k.startswith("ACCELERATE_")}
    print("\nCopy-and-paste the text below in your GitHub issue\n")
    for key, value in info.items():
        print(f"- {key}: {value}")
    print(f"- ACCELERATE_* env: {accelerate_env or '{}'}")
    return 0
