"""`accelerate-tpu trace` — export request traces to Perfetto.

Reads the ``{"kind": "trace"}`` records a traced serving run appended to
``telemetry.jsonl`` (``ServingEngine(tracer=...)`` / ``ServingRouter
(tracer=...)`` / ``serve-bench --trace``) and emits Chrome trace-event JSON
that ``https://ui.perfetto.dev`` (or ``chrome://tracing``) loads directly:
one swimlane group per replica, one lane per request, spans for
queued / prefill[i] / parked / handoff_attempt[j] / decode and the terminal
``retired(reason)`` — so "where did this request's latency go" is a
picture, not a grep. A handed-off request's spans visibly cross the
prefill- and decode-pool lanes under one trace id.

::

    accelerate-tpu trace telemetry.jsonl --out trace.json
    accelerate-tpu trace telemetry.jsonl --trace-id tr-1a2b-000003 --summary

``--summary`` prints the slowest requests' top spans (the serve-bench drill
line's format) instead of / in addition to writing the JSON.
"""

from __future__ import annotations

import json
import os


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "trace", help="Export request traces from telemetry.jsonl to Perfetto JSON"
    )
    parser.add_argument(
        "path",
        help="telemetry.jsonl (or a directory containing one) from a traced run",
    )
    parser.add_argument(
        "--out", default="trace.json",
        help="Output Chrome/Perfetto trace-event JSON path (default: trace.json)",
    )
    parser.add_argument(
        "--trace-id", default=None, help="Export only this trace id"
    )
    parser.add_argument(
        "--request-id", type=int, default=None, help="Export only this request id"
    )
    parser.add_argument(
        "--summary", action="store_true",
        help="Also print the slowest requests' top spans by duration "
        "(the Perfetto JSON is still written to --out)",
    )
    parser.add_argument(
        "--top", type=int, default=10,
        help="How many slowest requests --summary prints (default: 10)",
    )
    parser.set_defaults(func=run)
    return parser


def load_trace_records(path: str) -> list[dict]:
    """Every ``{"kind": "trace"}`` record in a telemetry.jsonl (a directory
    resolves to the ``telemetry.jsonl`` inside it). Unparseable lines are
    skipped — a crashed run's torn last line must not block the export."""
    if os.path.isdir(path):
        path = os.path.join(path, "telemetry.jsonl")
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if record.get("kind") == "trace":
                records.append(record)
    return records


def run(args) -> int:
    from ..telemetry.tracing import to_perfetto, trace_summary

    try:
        records = load_trace_records(args.path)
    except OSError as error:
        print(f"cannot read {args.path}: {error}")
        return 1
    if args.trace_id is not None:
        records = [r for r in records if r.get("trace_id") == args.trace_id]
    if args.request_id is not None:
        records = [r for r in records if r.get("request_id") == args.request_id]
    if not records:
        print(
            "no {\"kind\": \"trace\"} records matched — was the run traced "
            "(serve-bench --trace, or ServingEngine(tracer=...))?"
        )
        return 1

    if args.summary:
        slowest = sorted(records, key=lambda r: -(r.get("latency_s") or 0.0))
        print(f"{len(records)} trace(s); slowest {min(args.top, len(slowest))}:")
        for record in slowest[: args.top]:
            print(f"  {trace_summary(record)}")

    payload = to_perfetto(records)
    with open(args.out, "w") as f:
        json.dump(payload, f)
    replicas = sum(1 for e in payload["traceEvents"] if e.get("name") == "process_name")
    print(
        f"wrote {args.out}: {len(records)} trace(s), "
        f"{len(payload['traceEvents'])} events across {replicas} replica lane(s) "
        "— open in https://ui.perfetto.dev"
    )
    return 0
