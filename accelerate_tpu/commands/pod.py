"""`accelerate-tpu pod-launch` — run a training script on every worker of a
TPU pod slice.

Parity: reference tpu_pod_launcher (commands/launch.py:812-868), rebuilt for
the JAX process model: one process per host, `jax.distributed.initialize()`
self-discovers the coordinator from the TPU metadata, so "pod launch" is
simply *the same `accelerate-tpu launch` command executed on every worker* —
no xla_dist server, no rendezvous flags. The fan-out transport is
`gcloud compute tpus tpu-vm ssh --worker=all` (what `tpu-config` also uses,
reference commands/tpu.py:90-157).
"""

from __future__ import annotations

import re
import shlex
import subprocess


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "pod-launch", help="Launch a training script on every worker of a TPU pod"
    )
    parser.add_argument("--tpu_name", required=True, help="Name of the TPU VM / pod slice")
    parser.add_argument("--tpu_zone", required=True, help="GCE zone of the pod")
    parser.add_argument("--use_alpha", action="store_true", help="Use `gcloud alpha`")
    parser.add_argument("--use_sudo", action="store_true", help="Run the remote command under sudo")
    parser.add_argument("--worker", default="all", help="Worker selector (default: all)")
    parser.add_argument(
        "--env",
        action="append",
        default=[],
        metavar="KEY=VALUE",
        help="Environment variables exported on every worker (repeatable)",
    )
    parser.add_argument("--workdir", default=None, help="Remote directory to cd into first")
    parser.add_argument(
        "--debug", action="store_true", help="Print the gcloud command instead of running it"
    )
    parser.add_argument("--mixed_precision", default=None)
    parser.add_argument("--num_processes", type=int, default=None, help="Total host count (optional; auto-detected on pods)")
    from .launch import argparse_remainder

    parser.add_argument("training_script")
    parser.add_argument("training_script_args", nargs=argparse_remainder())
    parser.set_defaults(func=run)
    return parser


def assemble_worker_command(args) -> str:
    """The shell command each pod worker runs: env exports + the ordinary
    per-host launch. Every worker runs the SAME command — process identity
    comes from the TPU runtime, not from per-worker flags."""
    parts: list[str] = []
    if args.workdir:
        parts.append(f"cd {shlex.quote(args.workdir)}")
    exports = list(args.env)
    exports.append("ACCELERATE_IN_TPU_POD=1")
    for item in exports:
        if "=" not in item:
            raise ValueError(f"--env expects KEY=VALUE, got {item!r}")
        key, _, value = item.partition("=")
        if not re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", key):
            raise ValueError(f"--env key {key!r} is not a valid environment variable name")
        parts.append(f"export {key}={shlex.quote(value)}")

    launch = []
    if args.use_sudo:
        launch.append("sudo")
    launch += ["accelerate-tpu", "launch"]
    if args.mixed_precision:
        launch += ["--mixed_precision", args.mixed_precision]
    if args.num_processes is not None:
        launch += ["--num_processes", str(args.num_processes)]
    launch.append(args.training_script)
    launch += list(args.training_script_args)
    parts.append(" ".join(shlex.quote(p) for p in launch))
    return "; ".join(parts)


def build_gcloud_ssh_cmd(tpu_name: str, tpu_zone: str, command: str, worker: str = "all", use_alpha: bool = False) -> list[str]:
    cmd = ["gcloud"]
    if use_alpha:
        cmd.append("alpha")
    cmd += [
        "compute", "tpus", "tpu-vm", "ssh", tpu_name,
        "--zone", tpu_zone,
        "--command", command,
        "--worker", worker,
    ]
    return cmd


def run(args) -> int:
    command = assemble_worker_command(args)
    cmd = build_gcloud_ssh_cmd(args.tpu_name, args.tpu_zone, command, worker=args.worker, use_alpha=args.use_alpha)
    if args.debug:
        print(" ".join(shlex.quote(c) for c in cmd))
        return 0
    return subprocess.run(cmd).returncode
