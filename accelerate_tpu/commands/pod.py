"""`accelerate-tpu pod-launch` — run a training script on every worker of a
TPU pod slice.

Parity: reference tpu_pod_launcher (commands/launch.py:812-868), rebuilt for
the JAX process model: one process per host, `jax.distributed.initialize()`
self-discovers the coordinator from the TPU metadata, so "pod launch" is
simply *the same `accelerate-tpu launch` command executed on every worker* —
no xla_dist server, no rendezvous flags. The fan-out transport is
`gcloud compute tpus tpu-vm ssh --worker=all` (what `tpu-config` also uses,
reference commands/tpu.py:90-157).

Supervision (torchrun-elastic analogue, reference commands/launch.py:693-726
--monitor_interval/--max_restarts): with ``--num_workers`` the launcher runs
one ssh per worker and MONITORS them — a worker exiting nonzero (or silent
past ``--heartbeat_timeout``) kills the rest of the job loudly instead of
leaving the surviving hosts hung in the jax.distributed rendezvous, and
``--restart_on_failure N`` relaunches the whole job up to N times. Without
``--num_workers`` the single ``--worker=all`` fan-out is kept (no
supervision — gcloud multiplexes every host through one process).
"""

from __future__ import annotations

import re
import shlex
import signal as signal_mod
import subprocess
import sys
import threading
import time
from typing import Optional

from ..resilience.retry import RetryPolicy

# Backoff between fleet relaunches: an immediate restart after an infra
# failure (TPU runtime crash, zone-wide ssh blip) usually hits the same
# failure again within seconds, burning the restart budget on nothing. The
# jitter also decorrelates supervisors restarting against a shared outage.
RESTART_POLICY = RetryPolicy(base_delay=1.0, max_delay=30.0, jitter=0.25)


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "pod-launch", help="Launch a training script on every worker of a TPU pod"
    )
    parser.add_argument("--tpu_name", required=True, help="Name of the TPU VM / pod slice")
    parser.add_argument("--tpu_zone", required=True, help="GCE zone of the pod")
    parser.add_argument("--use_alpha", action="store_true", help="Use `gcloud alpha`")
    parser.add_argument("--use_sudo", action="store_true", help="Run the remote command under sudo")
    parser.add_argument("--worker", default="all", help="Worker selector (default: all)")
    parser.add_argument(
        "--env",
        action="append",
        default=[],
        metavar="KEY=VALUE",
        help="Environment variables exported on every worker (repeatable)",
    )
    parser.add_argument("--workdir", default=None, help="Remote directory to cd into first")
    parser.add_argument(
        "--debug", action="store_true", help="Print the gcloud command instead of running it"
    )
    parser.add_argument("--mixed_precision", default=None)
    parser.add_argument("--num_processes", type=int, default=None, help="Total host count (optional; auto-detected on pods)")
    parser.add_argument(
        "--num_workers", type=int, default=None,
        help="Worker (host) count: enables per-worker supervision — one ssh "
        "per worker, exit-code propagation, dead-host detection",
    )
    parser.add_argument(
        "--restart_on_failure", type=int, default=0, metavar="N",
        help="Relaunch the whole job up to N times when a worker fails "
        "(needs --num_workers)",
    )
    parser.add_argument(
        "--heartbeat_timeout", type=float, default=0.0, metavar="SECONDS",
        help="Declare a worker dead when it prints nothing for this long "
        "(0 = disabled; needs --num_workers). Training loops that log "
        "per-step keep this armed cheaply.",
    )
    parser.add_argument(
        "--elastic", action="store_true",
        help="Partial-failure mode: when ONE worker dies or goes silent, "
        "signal the survivors (SIGUSR1) to run an elastic mesh shrink "
        "(resilience/elastic.py) and keep supervising the smaller fleet, "
        "instead of killing and relaunching everything. Needs --num_workers.",
    )
    parser.add_argument(
        "--membership_dir", default=None, metavar="PATH",
        help="Rendezvous-store directory for the membership service "
        "(resilience/membership.py) — typically a GCS-fuse mount every "
        "worker sees. The supervisor publishes a dead worker's index there "
        "(it always knew who died; now the survivors do too), and the path "
        "is exported to workers as ACCELERATE_MEMBERSHIP_DIR so an "
        "unmodified training script's ElasticCoordinator resolves the "
        "SIGUSR1 to a NAMED host. Needs --elastic.",
    )
    parser.add_argument(
        "--auto_resume", action="store_true",
        help="On a supervised relaunch, append `--resume auto` to the training "
        "script args so every worker continues from the newest VALID checkpoint "
        "(fault_tolerance.CheckpointManager.resume); the first attempt runs the "
        "script unchanged. Needs --num_workers with --restart_on_failure.",
    )
    from .launch import argparse_remainder

    parser.add_argument("training_script")
    parser.add_argument("training_script_args", nargs=argparse_remainder())
    parser.set_defaults(func=run)
    return parser


def assemble_worker_command(args, resume: bool = False) -> str:
    """The shell command each pod worker runs: env exports + the ordinary
    per-host launch. Every worker runs the SAME command — process identity
    comes from the TPU runtime, not from per-worker flags. ``resume=True``
    (supervised relaunch after a failure) appends ``--resume auto`` so the
    training script restarts from the newest valid checkpoint."""
    parts: list[str] = []
    if args.workdir:
        parts.append(f"cd {shlex.quote(args.workdir)}")
    exports = list(args.env)
    exports.append("ACCELERATE_IN_TPU_POD=1")
    membership_dir = getattr(args, "membership_dir", None)
    if membership_dir:
        # the membership transport: every worker's ElasticCoordinator picks
        # the store up from this var (MembershipService.from_env)
        exports.append(f"ACCELERATE_MEMBERSHIP_DIR={membership_dir}")
    for item in exports:
        if "=" not in item:
            raise ValueError(f"--env expects KEY=VALUE, got {item!r}")
        key, _, value = item.partition("=")
        if not re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", key):
            raise ValueError(f"--env key {key!r} is not a valid environment variable name")
        parts.append(f"export {key}={shlex.quote(value)}")

    launch = []
    if args.use_sudo:
        launch.append("sudo")
    launch += ["accelerate-tpu", "launch"]
    if args.mixed_precision:
        launch += ["--mixed_precision", args.mixed_precision]
    if args.num_processes is not None:
        launch += ["--num_processes", str(args.num_processes)]
    launch.append(args.training_script)
    launch += list(args.training_script_args)
    if resume:
        launch += ["--resume", "auto"]
    parts.append(" ".join(shlex.quote(p) for p in launch))
    return "; ".join(parts)


def build_gcloud_ssh_cmd(tpu_name: str, tpu_zone: str, command: str, worker: str = "all", use_alpha: bool = False) -> list[str]:
    cmd = ["gcloud"]
    if use_alpha:
        cmd.append("alpha")
    cmd += [
        "compute", "tpus", "tpu-vm", "ssh", tpu_name,
        "--zone", tpu_zone,
        "--command", command,
        "--worker", worker,
    ]
    return cmd


class _Worker:
    """One supervised worker process: output is pumped to our stdout with a
    ``[worker i]`` prefix, and every line arms the heartbeat."""

    def __init__(self, index: int, proc):
        self.index = index
        self.proc = proc
        self.last_activity = time.monotonic()
        self._pump = None
        if getattr(proc, "stdout", None) is not None:
            self._pump = threading.Thread(target=self._pump_output, daemon=True)
            self._pump.start()

    def _pump_output(self):
        for line in self.proc.stdout:
            self.last_activity = time.monotonic()
            sys.stdout.write(f"[worker {self.index}] {line}")
        self.proc.stdout.close()

    def poll(self):
        return self.proc.poll()

    def kill(self):
        if self.proc.poll() is None:
            self.proc.kill()

    def notify(self, signum) -> bool:
        """Deliver the elastic partial-failure signal to a live worker
        (ignored if it already exited or the transport can't signal)."""
        try:
            if self.proc.poll() is None and hasattr(self.proc, "send_signal"):
                self.proc.send_signal(signum)
                return True
        except OSError:
            pass
        return False


def supervise(
    spawn,
    num_workers: int,
    restarts: int = 0,
    heartbeat_timeout: float = 0.0,
    poll_interval: float = 1.0,
    restart_policy: Optional[RetryPolicy] = None,
    partial_failure: str = "relaunch",
    elastic_signal=signal_mod.SIGUSR1,
    membership_dir: Optional[str] = None,
) -> int:
    """Run ``spawn(i) -> Popen`` for every worker and monitor the fleet.

    A worker exiting nonzero — or printing nothing for ``heartbeat_timeout``
    seconds — fails the ATTEMPT: the remaining workers are killed (they would
    otherwise hang forever in the collective rendezvous waiting for the dead
    host) and, with ``restarts`` left, the whole fleet relaunches. Per-worker
    exit codes are reported; the job's exit code is the first failing
    worker's (124 for a heartbeat kill).

    ``partial_failure="elastic"`` (``pod-launch --elastic``) changes the
    single-worker-death response: instead of killing the fleet, the failed
    worker is removed (killed if merely heartbeat-silent), the SURVIVORS are
    signalled with ``elastic_signal`` (SIGUSR1 — the training script's
    :class:`~...resilience.elastic.ElasticCoordinator` turns it into a mesh
    shrink at the next step boundary), and supervision continues over the
    shrunken fleet. The job succeeds when every remaining worker exits 0;
    only the LAST worker's failure falls through to the kill-and-relaunch
    ladder. Losing a host then costs a reshard, not a fleet restart.

    With ``membership_dir`` the supervisor also PUBLISHES the dead worker's
    index into the membership rendezvous store before signalling — the
    supervisor always knew who died (exit code / heartbeat silence) and
    used to throw that away, leaving the survivors' ``request_shrink()``
    unresolved. Now SIGUSR1 arrives with an answer attached: the training
    side's :class:`~...resilience.membership.MembershipService` reads the
    ``lost/<i>`` record and the elastic ladder runs against a *named* host.

    ``spawn`` may accept a second ``attempt`` argument (1-based): relaunch
    attempts then get a different command — the auto-resume path appends
    ``--resume auto`` from attempt 2 on, so a restarted fleet continues from
    the newest valid checkpoint instead of step 0.

    Relaunches back off under ``restart_policy`` (default
    :data:`RESTART_POLICY`: jittered exponential, 1 s base, 30 s cap) instead
    of restarting immediately — attempt N sleeps ``delay_for(N-1)`` first.
    """
    import inspect

    if restart_policy is None:
        restart_policy = RESTART_POLICY
    if partial_failure not in ("relaunch", "elastic"):
        raise ValueError(
            f"partial_failure must be 'relaunch' or 'elastic', got {partial_failure!r}"
        )

    try:
        spawn_takes_attempt = len(inspect.signature(spawn).parameters) >= 2
    except (TypeError, ValueError):
        spawn_takes_attempt = False
    attempt = 0
    while True:
        attempt += 1
        workers = [
            _Worker(i, spawn(i, attempt) if spawn_takes_attempt else spawn(i))
            for i in range(num_workers)
        ]
        failed = None  # (index, returncode, reason)
        while failed is None:
            codes = [w.poll() for w in workers]
            for w, code in zip(workers, codes):
                if code is not None and code != 0:
                    failed = (w.index, code, f"exit code {code}")
                    break
            if failed is None and all(code == 0 for code in codes):
                return 0
            if failed is None and heartbeat_timeout > 0:
                now = time.monotonic()
                for w, code in zip(workers, codes):
                    if code is None and now - w.last_activity > heartbeat_timeout:
                        failed = (w.index, 124, f"silent for {heartbeat_timeout:.0f}s")
                        break
            if failed is not None and partial_failure == "elastic" and len(workers) > 1:
                # elastic shrink: drop the dead worker, signal the survivors
                # to reshard, keep supervising the smaller fleet
                dead = next(w for w in workers if w.index == failed[0])
                dead.kill()  # a heartbeat-silent process is operationally dead
                workers = [w for w in workers if w is not dead]
                if membership_dir:
                    # name the lost host BEFORE the signal lands, so the
                    # survivors' next boundary probe finds the answer waiting
                    from ..resilience.membership import publish_supervisor_loss

                    try:
                        publish_supervisor_loss(membership_dir, failed[0], failed[2])
                    except OSError as e:
                        print(
                            f"pod-launch: could not publish lost worker "
                            f"{failed[0]} to membership store: {e}",
                            file=sys.stderr,
                        )
                notified = sum(1 for w in workers if w.notify(elastic_signal))
                # the survivors now pause to reassemble + recompile, printing
                # nothing — restart their heartbeat clocks so the reshard gets
                # one full window instead of being killed as "silent" mid-
                # recovery (which would cascade one host loss into a fleet
                # relaunch). Size --heartbeat_timeout above the expected
                # reshard recompile time.
                now = time.monotonic()
                for w in workers:
                    w.last_activity = now
                print(
                    f"pod-launch: worker {failed[0]} failed ({failed[2]}); "
                    f"elastic mode — signalled {notified}/{len(workers)} "
                    "survivors to shrink instead of relaunching the fleet",
                    file=sys.stderr,
                )
                failed = None
            if failed is None:
                time.sleep(poll_interval)
        for w in workers:
            w.kill()
        states = ", ".join(
            f"worker {w.index}: {'killed' if c is None else c}"
            for w, c in zip(workers, (w.poll() for w in workers))
        )
        print(
            f"pod-launch: worker {failed[0]} failed ({failed[2]}); "
            f"killed the rest of the fleet to free the rendezvous [{states}]",
            file=sys.stderr,
        )
        if attempt > restarts:
            return failed[1]
        delay = restart_policy.delay_for(attempt - 1)
        print(
            f"pod-launch: restarting the whole job in {delay:.1f}s "
            f"(attempt {attempt + 1}/{restarts + 1})",
            file=sys.stderr,
        )
        restart_policy.sleep(delay)


def run(args) -> int:
    auto_resume = getattr(args, "auto_resume", False)
    elastic = getattr(args, "elastic", False)
    membership_dir = getattr(args, "membership_dir", None)
    if membership_dir and not elastic:
        raise ValueError(
            "--membership_dir only matters in partial-failure mode — the "
            "supervisor publishes the dead worker's index for the SURVIVORS' "
            "elastic shrink; pass --elastic too"
        )
    command = assemble_worker_command(args)
    if args.num_workers is None:
        if args.restart_on_failure or args.heartbeat_timeout or auto_resume or elastic:
            raise ValueError(
                "--restart_on_failure/--heartbeat_timeout/--auto_resume/--elastic "
                "need --num_workers (supervision runs one ssh per worker)"
            )
        cmd = build_gcloud_ssh_cmd(
            args.tpu_name, args.tpu_zone, command, worker=args.worker, use_alpha=args.use_alpha
        )
        if args.debug:
            print(" ".join(shlex.quote(c) for c in cmd))
            return 0
        return subprocess.run(cmd).returncode

    if args.worker != "all":
        raise ValueError(
            "--worker targets a single host and conflicts with --num_workers "
            "supervision (which spawns one ssh per worker 0..N-1); drop one"
        )
    if auto_resume and not args.restart_on_failure:
        raise ValueError(
            "--auto_resume only acts on supervised RELAUNCHES — pass "
            "--restart_on_failure N too, or the job dies on the first failure "
            "without ever resuming"
        )

    def spawn(i: int, attempt: int = 1):
        # relaunch attempts resume from the newest valid checkpoint: the
        # first attempt's command is untouched, every later one carries
        # `--resume auto` for the training script's CheckpointManager
        worker_command = (
            assemble_worker_command(args, resume=True)
            if auto_resume and attempt > 1
            else command
        )
        cmd = build_gcloud_ssh_cmd(
            args.tpu_name, args.tpu_zone, worker_command, worker=str(i), use_alpha=args.use_alpha
        )
        return subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True
        )

    if args.debug:
        for i in range(args.num_workers):
            cmd = build_gcloud_ssh_cmd(
                args.tpu_name, args.tpu_zone, command, worker=str(i), use_alpha=args.use_alpha
            )
            print(" ".join(shlex.quote(c) for c in cmd))
        return 0
    return supervise(
        spawn, args.num_workers,
        restarts=args.restart_on_failure,
        heartbeat_timeout=args.heartbeat_timeout,
        partial_failure="elastic" if elastic else "relaunch",
        membership_dir=membership_dir,
    )
