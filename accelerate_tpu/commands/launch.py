"""`accelerate-tpu launch` — run a training script with the configured topology.

Parity: reference commands/launch.py (arg parser 135-678, _validate_launch_command
891, launchers 681-888). Structural difference: JAX runs ONE process per host
that drives every local chip, so there is no torchrun/xmp.spawn process tree —
launch = set ACCELERATE_* env + exec the script. Multi-host pods run this same
command on every host (process_id differs), exactly how `jax.distributed`
expects to be bootstrapped.
"""

from __future__ import annotations

import os
import subprocess
import sys

from .config import load_config_from_file


def register_subcommand(subparsers):
    parser = subparsers.add_parser("launch", help="Launch a training script on this host's devices")
    parser.add_argument("--config_file", default=None)
    parser.add_argument("--mixed_precision", default=None, choices=[None, "no", "fp16", "bf16", "fp8"])
    parser.add_argument("--num_processes", type=int, default=None, help="Total number of hosts in the job")
    parser.add_argument("--process_id", type=int, default=None, help="This host's index (multi-host)")
    parser.add_argument("--coordinator_address", default=None, help="host:port of process 0 (multi-host)")
    parser.add_argument("--data_parallel_size", type=int, default=None)
    parser.add_argument("--fsdp_size", type=int, default=None)
    parser.add_argument("--tensor_size", type=int, default=None)
    parser.add_argument("--sequence_size", type=int, default=None)
    parser.add_argument("--pipeline_size", type=int, default=None)
    parser.add_argument("--expert_size", type=int, default=None)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=None)
    parser.add_argument("--debug", action="store_true", help="Enable debug-mode collective verification")
    parser.add_argument("-m", "--module", action="store_true", help="Treat script as a python module")
    parser.add_argument("training_script", help="Script (or module) to launch")
    parser.add_argument("training_script_args", nargs=argparse_remainder(), help="Arguments for the script")
    parser.set_defaults(func=run)
    return parser


def argparse_remainder():
    import argparse

    return argparse.REMAINDER


def build_env(args) -> dict[str, str]:
    """Resolution order: CLI flag > existing env > YAML config > default."""
    config = load_config_from_file(args.config_file)
    par = config.get("parallelism", {}) or {}
    env = dict(os.environ)

    def put(key: str, cli_value, config_value=None):
        if cli_value is not None:
            env[key] = str(cli_value)
        elif key not in env and config_value is not None:
            env[key] = str(config_value)

    put("ACCELERATE_MIXED_PRECISION", args.mixed_precision, config.get("mixed_precision"))
    put("ACCELERATE_NUM_PROCESSES", args.num_processes, config.get("num_processes"))
    put("ACCELERATE_PROCESS_ID", args.process_id)
    put("ACCELERATE_COORDINATOR_ADDRESS", args.coordinator_address, config.get("coordinator_address"))
    put("ACCELERATE_DATA_PARALLEL_SIZE", args.data_parallel_size, par.get("data"))
    put("ACCELERATE_FSDP_SIZE", args.fsdp_size, par.get("fsdp"))
    put("ACCELERATE_TENSOR_SIZE", args.tensor_size, par.get("tensor"))
    put("ACCELERATE_SEQUENCE_SIZE", args.sequence_size, par.get("sequence"))
    put("ACCELERATE_PIPELINE_SIZE", args.pipeline_size, par.get("pipeline"))
    put("ACCELERATE_EXPERT_SIZE", args.expert_size, par.get("expert"))
    put(
        "ACCELERATE_GRADIENT_ACCUMULATION_STEPS",
        args.gradient_accumulation_steps,
        config.get("gradient_accumulation_steps"),
    )
    put("ACCELERATE_SEED", None, config.get("seed"))
    if args.debug:
        env["ACCELERATE_DEBUG_MODE"] = "1"
    return env


def run(args) -> int:
    env = build_env(args)
    cmd = [sys.executable]
    if args.module:
        cmd += ["-m", args.training_script]
    else:
        cmd += [args.training_script]
    cmd += args.training_script_args
    completed = subprocess.run(cmd, env=env)
    return completed.returncode
