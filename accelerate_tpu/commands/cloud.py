"""`accelerate-tpu cloud-launch` — provision a managed cloud TPU and run a
training script on it, end to end.

Parity: the reference's SageMaker launcher (commands/launch.py:871-888 +
utils/launch.py prepare_sagemager_args_inputs) submits training into AWS's
managed fleet. The TPU-native analogue targets GCP's managed TPU fleet: the
command provisions capacity (`gcloud compute tpus tpu-vm create`, or a
queued-resource for stockout-prone types — the SageMaker-style "submit and
wait" path), pushes the script to every worker, runs it under
``accelerate-tpu launch`` on each host, and optionally tears the slice down.

Like the reference, the heavy lifting is delegated to the vendor CLI
(sagemaker SDK there, ``gcloud`` here); everything this module does is
assemble those invocations — which keeps it unit-testable without cloud
credentials (``--debug`` prints the exact commands instead of running them).
"""

from __future__ import annotations

import re
import shlex
import shutil
import subprocess


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "cloud-launch",
        help="Provision a cloud TPU (gcloud), run a training script on it, optionally delete it",
    )
    parser.add_argument("--tpu_name", required=True, help="Name for the TPU VM / slice")
    parser.add_argument("--zone", required=True, help="GCE zone (e.g. us-central2-b)")
    parser.add_argument("--accelerator_type", default="v5litepod-8", help="TPU type (e.g. v5litepod-8, v4-32)")
    parser.add_argument("--runtime_version", default="tpu-ubuntu2204-base", help="TPU VM runtime image")
    parser.add_argument("--project", default=None, help="GCP project (default: gcloud config)")
    parser.add_argument(
        "--queued", action="store_true",
        help="Provision through a queued resource (capacity-wait submission, "
        "the closest analogue of a SageMaker training-job queue)",
    )
    parser.add_argument("--spot", action="store_true", help="Preemptible/spot capacity")
    parser.add_argument(
        "--setup_cmd", default=None,
        help="Shell command run once on every worker before training (pip installs etc.)",
    )
    parser.add_argument(
        "--env", action="append", default=[], metavar="KEY=VALUE",
        help="Environment variables exported on every worker (repeatable)",
    )
    parser.add_argument(
        "--delete_after", action="store_true",
        help="Delete the TPU when the training command finishes (job semantics)",
    )
    parser.add_argument(
        "--debug", action="store_true", help="Print the gcloud commands instead of running them"
    )
    parser.add_argument(
        "--provision_timeout", type=int, default=3600,
        help="Seconds to wait for queued capacity before giving up",
    )
    parser.add_argument("--mixed_precision", default=None)
    from .launch import argparse_remainder

    parser.add_argument("training_script")
    parser.add_argument("training_script_args", nargs=argparse_remainder())
    parser.set_defaults(func=run)
    return parser


def _gcloud_base(args) -> list[str]:
    cmd = ["gcloud"]
    if args.queued:
        cmd.append("alpha")
    cmd += ["compute", "tpus"]
    return cmd


def _with_project(args, cmd: list[str]) -> list[str]:
    """Every gcloud invocation targets the SAME project — a provision in
    --project with later steps against the gcloud default would strand a
    billed TPU that the scp/train/delete steps can't find."""
    if args.project:
        cmd.append(f"--project={args.project}")
    return cmd


def provision_command(args) -> list[str]:
    """The capacity request (reference: the HuggingFace estimator's instance
    config — instance type/count → accelerator_type here)."""
    if args.queued:
        cmd = _gcloud_base(args) + [
            "queued-resources", "create", args.tpu_name,
            f"--node-id={args.tpu_name}",
            f"--zone={args.zone}",
            f"--accelerator-type={args.accelerator_type}",
            f"--runtime-version={args.runtime_version}",
        ]
        if args.spot:
            cmd.append("--spot")
    else:
        cmd = _gcloud_base(args) + [
            "tpu-vm", "create", args.tpu_name,
            f"--zone={args.zone}",
            f"--accelerator-type={args.accelerator_type}",
            f"--version={args.runtime_version}",
        ]
        if args.spot:
            cmd.append("--preemptible")
    return _with_project(args, cmd)


def wait_command(args) -> list[str]:
    """Block until queued capacity materializes (SageMaker .fit() waits the
    same way on instance provisioning)."""
    return _with_project(args, _gcloud_base(args) + [
        "queued-resources", "describe", args.tpu_name,
        f"--zone={args.zone}", "--format=value(state.state)",
    ])


def scp_command(args) -> list[str]:
    return _with_project(args, [
        "gcloud", "compute", "tpus", "tpu-vm", "scp",
        args.training_script, f"{args.tpu_name}:~/",
        f"--zone={args.zone}", "--worker=all",
    ])


def train_command(args) -> list[str]:
    """Run the pushed script under the per-host launcher on every worker —
    the same fan-out transport as ``pod-launch`` (commands/pod.py)."""
    import os

    remote = f"~/{os.path.basename(args.training_script)}"
    parts = []
    for item in args.env:
        if "=" not in item:
            raise ValueError(f"--env expects KEY=VALUE, got {item!r}")
        key, _, value = item.partition("=")
        # the key is interpolated unquoted into the remote shell line — only
        # identifier-shaped keys are valid env names anyway
        if not re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", key):
            raise ValueError(f"--env key must be an identifier, got {key!r}")
        parts.append(f"export {key}={shlex.quote(value)}")
    if args.setup_cmd:
        parts.append(args.setup_cmd)
    launch = "accelerate-tpu launch"
    if args.mixed_precision:
        launch += f" --mixed_precision {args.mixed_precision}"
    script_args = " ".join(shlex.quote(a) for a in args.training_script_args)
    parts.append(f"{launch} {remote} {script_args}".rstrip())
    # '&&': a failed setup step must abort (and surface through ssh's exit
    # code) instead of training against a broken environment
    return _with_project(args, [
        "gcloud", "compute", "tpus", "tpu-vm", "ssh", args.tpu_name,
        f"--zone={args.zone}", "--worker=all",
        f"--command={' && '.join(parts)}",
    ])


def delete_command(args) -> list[str]:
    if args.queued:
        return _with_project(args, _gcloud_base(args) + [
            "queued-resources", "delete", args.tpu_name, f"--zone={args.zone}", "--force", "--quiet",
        ])
    return _with_project(args, [
        "gcloud", "compute", "tpus", "tpu-vm", "delete", args.tpu_name, f"--zone={args.zone}", "--quiet",
    ])


def plan(args) -> list[list[str]]:
    """The full job as an ordered command list (printed verbatim by --debug)."""
    steps = [provision_command(args)]
    if args.queued:
        steps.append(wait_command(args))
    steps += [scp_command(args), train_command(args)]
    if args.delete_after:
        steps.append(delete_command(args))
    return steps


def run(args) -> int:
    if not args.training_script.endswith(".py"):
        raise ValueError("cloud-launch needs a python training script file (like the reference's SageMaker path)")
    steps = plan(args)
    if args.debug:
        for cmd in steps:
            print(" ".join(shlex.quote(c) for c in cmd))
        return 0
    if shutil.which("gcloud") is None:
        raise EnvironmentError(
            "cloud-launch shells out to gcloud, which is not installed. Install the "
            "Google Cloud SDK (the analogue of `pip install accelerate[sagemaker]`)."
        )
    import time

    def _execute(cmd: list[str]) -> None:
        if args.queued and "describe" in cmd:
            # poll the queued resource until ACTIVE (capacity granted);
            # bounded by --provision_timeout, and a persistently failing
            # describe (bad zone, expired credentials) surfaces its stderr
            # instead of looping forever
            deadline = time.monotonic() + args.provision_timeout
            errors = 0
            while True:
                result = subprocess.run(cmd, capture_output=True, text=True)
                if result.returncode != 0:
                    errors += 1
                    if errors >= 3:
                        raise RuntimeError(
                            f"queued-resource describe keeps failing:\n{result.stderr.strip()}"
                        )
                else:
                    errors = 0
                    state = result.stdout.strip()
                    print(f"queued-resource state: {state or 'PENDING'}")
                    if state == "ACTIVE":
                        return
                    if state in ("FAILED", "SUSPENDED"):
                        raise RuntimeError(f"queued resource entered {state}")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"queued resource not ACTIVE after {args.provision_timeout}s — "
                        "raise --provision_timeout or delete the request"
                    )
                time.sleep(30)
        print("+", " ".join(shlex.quote(c) for c in cmd))
        result = subprocess.run(cmd)
        if result.returncode != 0:
            raise RuntimeError(f"command failed with {result.returncode}: {cmd[0]} {cmd[1] if len(cmd) > 1 else ''}")

    # teardown is job semantics: once provisioning was ATTEMPTED, a failure
    # anywhere later must not strand a billed slice — run the delete step in
    # a finally when --delete_after is set. A teardown failure must not
    # SHADOW the original error (e.g. a quota failure followed by deleting a
    # slice that was never created) — the first failure stays the reported one.
    teardown = steps.pop() if args.delete_after else None
    job_ok = False
    try:
        for cmd in steps:
            _execute(cmd)
        job_ok = True
    finally:
        if teardown is not None:
            try:
                _execute(teardown)
            except Exception as e:
                if job_ok:
                    raise
                print(f"teardown also failed (original error follows): {e}")
    return 0
