"""`accelerate-tpu` console entry point — subcommand router.

Parity: reference commands/accelerate_cli.py:26-46. Subcommands are registered
lazily so `--help` stays fast and optional deps stay optional.
"""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        "accelerate-tpu", description="TPU-native training orchestration CLI", usage="accelerate-tpu <command> [<args>]"
    )
    subparsers = parser.add_subparsers(dest="command")

    from . import analyze, cloud, config, env, estimate, launch, pod, profile, serve_bench, test, tpu, trace, verify

    for module in (analyze, cloud, config, env, estimate, launch, pod, profile, serve_bench, test, tpu, trace, verify):
        module.register_subcommand(subparsers)

    args = parser.parse_args(argv)
    if not hasattr(args, "func"):
        parser.print_help()
        return 1
    return args.func(args) or 0


if __name__ == "__main__":
    sys.exit(main())
