"""`accelerate-tpu serve-bench` — drive the continuous-batching engine (or a
routed fleet of engine replicas) under offered load and report serving
metrics.

The serving analogue of `bench.py`'s training sections: a deterministic
mixed-length prompt trace replays against :class:`serving.ServingEngine` —
or, with ``--replicas N``, a :class:`serving.ServingRouter` over N replicas
— at one or more offered rates (requests/sec; the final sweep point is
always saturation — everything at once), and each point reports throughput,
TTFT/per-token percentiles, slot occupancy, and compile attribution. Works
on any backend (the CPU mesh included), so serve sizing can be rehearsed
before touching a TPU.

``--chaos replica-kill`` arms the replica-death drill: one of the replicas
is SIGKILLed (router-side, deterministic step) mid-stream at the saturation
point, and the report adds the failover accounting — every offered request
must still terminate, goodput retained is printed against the healthy run.

``--prefill-replicas N --decode-replicas M`` splits the fleet into
disaggregated pools: prompts prefill on the N-pool, the live KV hands off
page-by-page to the M-pool (docs/serving.md), and the report adds the
handoff economy — handoffs adopted/fallbacks, pages and bytes moved,
handoff p50/p99. The disaggregation drills
(``--chaos handoff-stall|handoff-loss|prefill-kill``) stall or lose a
transfer mid-flight, or SIGKILL a prefill replica with KV parked:
terminated-exactly-once, fallback count, and goodput retained are the
drill line.

``--trace-load burst|diurnal`` replaces uniform arrivals with a Poisson
arrival trace (a 4× flash crowd, or a sinusoidal rate swing), and
``--autoscale`` pairs it with the pool-autoscaling drill: the same trace
replays against a fixed-shape fleet and one with a
:class:`serving.RoleRebalancer` attached, and the report compares sheds and
TTFT p99 plus the flip/thrash/compile invariants (docs/serving.md,
"Autoscaling").
"""

from __future__ import annotations

import json


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "serve-bench", help="Benchmark the continuous-batching serving engine"
    )
    parser.add_argument("--model", default="llama-125m", help="Registry model name")
    parser.add_argument("--num-slots", type=int, default=8, help="Concurrent decode slots")
    parser.add_argument("--max-len", type=int, default=512, help="Per-slot KV capacity (tokens)")
    parser.add_argument("--requests", type=int, default=32, help="Requests per sweep point")
    parser.add_argument("--max-new-tokens", type=int, default=64)
    parser.add_argument("--prompt-len-min", type=int, default=16)
    parser.add_argument("--prompt-len-max", type=int, default=192)
    parser.add_argument(
        "--offered-load",
        type=float,
        nargs="*",
        default=[],
        help="Offered rates (req/s) to sweep before the saturation point",
    )
    parser.add_argument(
        "--replicas", type=int, default=1,
        help="Engine replicas behind a health-aware router (1 = bare engine)",
    )
    parser.add_argument(
        "--prefill-replicas", type=int, default=0,
        help="Disaggregated serving: replicas in the PREFILL pool (use with "
             "--decode-replicas; overrides --replicas)",
    )
    parser.add_argument(
        "--decode-replicas", type=int, default=0,
        help="Disaggregated serving: replicas in the DECODE pool (prompts "
             "prefill on the prefill pool, live KV hands off here)",
    )
    parser.add_argument(
        "--chaos",
        choices=["replica-kill", "replica-stall", "heartbeat-loss",
                 "handoff-stall", "handoff-loss", "prefill-kill"],
        default=None,
        help="Fleet fault to inject mid-stream at the saturation point "
             "(replica faults need --replicas >= 2; handoff-*/prefill-kill "
             "need --prefill-replicas/--decode-replicas)",
    )
    parser.add_argument(
        "--chaos-step", type=int, default=None,
        help="Fleet step the fault fires at (default: max-new-tokens // 2); "
             "for handoff-stall/handoff-loss this is the handoff ATTEMPT "
             "index (default: 0)",
    )
    parser.add_argument(
        "--autoscale", action="store_true",
        help="Attach the RoleRebalancer (docs/serving.md, 'Autoscaling') and "
             "run a paired fixed-vs-rebalanced drill under the --trace-load "
             "arrival trace: the rebalanced fleet flips idle replicas into "
             "the starved pool mid-burst and the report compares sheds and "
             "TTFT p99. Needs disaggregated pools and --trace-load",
    )
    parser.add_argument(
        "--trace-load", choices=("burst", "diurnal"), default=None,
        help="Replace the saturation point's all-at-once arrivals with a "
             "Poisson arrival trace: 'burst' is a 4x flash crowd mid-trace, "
             "'diurnal' a sinusoidal rate swing (serving/loadgen.py)",
    )
    parser.add_argument(
        "--trace-load-rps", type=float, default=8.0,
        help="Base request rate (req/s) for --trace-load arrivals",
    )
    parser.add_argument(
        "--mixed", action="store_true",
        help="ROADMAP gating trace: mostly-short prompts with a long tail "
        "(--long-fraction at --long-multiplier× the median length) — the "
        "scenario chunked prefill exists for",
    )
    parser.add_argument(
        "--long-fraction", type=float, default=0.1,
        help="Fraction of prompts in the long tail (with --mixed)",
    )
    parser.add_argument(
        "--long-multiplier", type=int, default=8,
        help="Long prompts span long-multiplier..2×long-multiplier × the "
        "median short length (with --mixed)",
    )
    parser.add_argument(
        "--shared-prefix", type=int, default=0,
        help="Prepend a common N-token system prompt to every request — a "
        "paged engine prefills it once and COW-shares its pages",
    )
    parser.add_argument(
        "--page-size", type=int, default=16, help="Tokens per KV page (paged layout)"
    )
    parser.add_argument(
        "--prefill-chunk", type=int, default=None,
        help="Split long prompts into page-aligned chunks of this many tokens, "
        "interleaved into the decode cadence (must be a multiple of --page-size)",
    )
    parser.add_argument(
        "--no-paged", action="store_true",
        help="Serve from the dense per-slot slab instead of the paged pool "
        "(the comparison baseline)",
    )
    parser.add_argument(
        "--no-kernels", action="store_true",
        help="Disable the Pallas kernel layer (paged decode attention + "
        "fused dequant-matmul; docs/performance.md) — the gather/dequant "
        "reference programs, mirroring --no-paged as the A/B baseline. "
        "Default: kernels ON (interpret mode off-TPU)",
    )
    parser.add_argument(
        "--trace", action="store_true",
        help="Request-scoped tracing: spans (queued/prefill/parked/handoff/"
        "decode) for every request land in telemetry.jsonl and export to "
        "Perfetto trace-event JSON; chaos drills additionally print the "
        "slowest request's span breakdown",
    )
    parser.add_argument(
        "--trace-dir", default=".",
        help="Directory for telemetry.jsonl and the exported trace.json "
        "(with --trace; default: current directory)",
    )
    parser.add_argument(
        "--slo-ttft-s", type=float, default=60.0,
        help="TTFT objective for the SLO burn-rate monitor (with --trace): "
        "99%% of requests must see a first token within this many seconds",
    )
    parser.add_argument(
        "--slo-window-s", type=float, default=3600.0,
        help="SLO rolling-window width in seconds (with --trace). The "
        "default covers a whole bench run, so the end-of-run burn-rate "
        "line reflects every trace; narrow it to drill alert-style windows",
    )
    parser.add_argument(
        "--speculative", action="store_true",
        help="Draft-model speculative decoding (docs/serving.md): the draft "
        "proposes --spec-k tokens per step against its own paged pool and "
        "the target verifies the whole window in one decode step. "
        "Temperature-0 + paged only; tokens stay bit-identical",
    )
    parser.add_argument(
        "--draft-model", default=None,
        help="Registry name of the draft model (must share the target's "
        "vocabulary). Default: the target's own architecture at half depth",
    )
    parser.add_argument(
        "--spec-k", type=int, default=4,
        help="Draft tokens proposed per speculative step",
    )
    parser.add_argument(
        "--spec-mode", choices=("linear", "tree"), default="linear",
        help="linear: one draft chain; tree: fork --spec-branches candidate "
        "chains over COW-shared prefix pages and keep the best",
    )
    parser.add_argument(
        "--spec-branches", type=int, default=2,
        help="Tree-mode branch count (top-B seeds from the draft)",
    )
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--eos-token-id", type=int, default=None)
    parser.add_argument("--int8", action="store_true", help="int8 weight-only load path")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--json", action="store_true", help="One JSON object instead of a table")
    parser.set_defaults(func=run)
    return parser


def run(args) -> int:
    import math

    import jax
    import jax.numpy as jnp

    from ..models import build_model
    from ..serving import (
        ServingEngine,
        ServingRouter,
        make_mixed_prompts,
        make_prompts,
        run_offered_load,
    )

    disagg = args.prefill_replicas > 0 or args.decode_replicas > 0
    if disagg and (args.prefill_replicas < 1 or args.decode_replicas < 1):
        print("disaggregation needs BOTH --prefill-replicas >= 1 and --decode-replicas >= 1")
        return 1
    roles = (
        ["prefill"] * args.prefill_replicas + ["decode"] * args.decode_replicas
        if disagg
        else None
    )
    n_replicas = len(roles) if disagg else args.replicas
    if args.chaos in ("handoff-stall", "handoff-loss", "prefill-kill") and not disagg:
        print(f"--chaos {args.chaos} drills the prefill/decode split — set "
              "--prefill-replicas and --decode-replicas")
        return 1
    if args.chaos is not None and n_replicas < 2:
        print(f"--chaos {args.chaos} needs >= 2 replicas (a 1-replica fleet has no failover)")
        return 1
    if disagg and args.no_paged:
        print("disaggregated serving relays page-granular KV — drop --no-paged")
        return 1
    if args.autoscale and not disagg:
        print("--autoscale rebalances between pools — set --prefill-replicas "
              "and --decode-replicas")
        return 1
    if args.autoscale and args.trace_load is None:
        print("--autoscale drills against an arrival trace — add "
              "--trace-load burst|diurnal")
        return 1

    model = build_model(args.model)
    params = model.init(jax.random.key(args.seed))
    if jax.default_backend() != "cpu":
        params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x, params
        )

    spec_cfg = None
    if args.speculative:
        if args.no_paged:
            print("--speculative verifies against the paged pool — drop --no-paged")
            return 1
        if args.temperature != 0.0:
            print("--speculative is temperature-0 only (greedy verify)")
            return 1
        from ..serving import SpeculativeConfig

        if args.draft_model:
            draft = build_model(args.draft_model)
            if draft.config.vocab_size != model.config.vocab_size:
                print(
                    f"--draft-model {args.draft_model} has vocab "
                    f"{draft.config.vocab_size}, target has "
                    f"{model.config.vocab_size} — drafts must share the "
                    "target's vocabulary"
                )
                return 1
        else:
            # default draft: the target's own architecture at half depth —
            # vocabulary and head geometry stay valid by construction
            draft = type(model)(
                model.config.replace(num_layers=max(1, model.config.num_layers // 2))
            )
        draft_params = draft.init(jax.random.key(args.seed + 1))
        if jax.default_backend() != "cpu":
            draft_params = jax.tree.map(
                lambda x: x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x,
                draft_params,
            )
        spec_cfg = SpeculativeConfig(
            draft_model=draft, draft_params=draft_params, k=args.spec_k,
            mode=args.spec_mode, num_branches=args.spec_branches,
        )
    use_kernels = not args.no_kernels
    if args.int8:
        from ..big_modeling import dispatch_model, make_layered_device_map
        from ..serving import params_from_streamed
        from ..utils.quantization import QuantizationConfig

        streamed = dispatch_model(
            model, params, make_layered_device_map(model, "cpu"),
            dtype=params["embed_tokens"].dtype, quantization=QuantizationConfig(load_in_8bit=True),
        )
        packed = None
        if use_kernels:
            # kernel layer: matrix weights stay PACKED on device and the
            # fused dequant-matmul reads them 1 byte/element — no bf16
            # shadow. One install policy, shared with from_streamed.
            from ..serving import quantized_resident_params

            packed = quantized_resident_params(streamed)
        params = packed if packed is not None else params_from_streamed(streamed)

    if args.mixed or args.shared_prefix:
        prompts = make_mixed_prompts(
            args.requests, model.config.vocab_size, args.prompt_len_min,
            args.prompt_len_max,
            long_fraction=args.long_fraction if args.mixed else 0.0,
            long_multiplier=args.long_multiplier,
            shared_prefix=args.shared_prefix,
            seed=args.seed,
        )
    else:
        prompts = make_prompts(
            args.requests, model.config.vocab_size, args.prompt_len_min,
            args.prompt_len_max, seed=args.seed,
        )
    longest = max(p.size for p in prompts)
    max_len = max(args.max_len, longest + args.max_new_tokens)
    if max_len > args.max_len:
        print(
            f"note: --max-len raised {args.max_len} -> {max_len} to fit the "
            f"longest prompt ({longest} tokens) + max_new_tokens"
        )

    # request-scoped tracing: one tracer + hub shared by every sweep point's
    # engines/fleet, so telemetry.jsonl accumulates the whole run's traces
    # and the Perfetto export covers every point (drill included)
    hub = tracer = slo = None
    if args.trace:
        from ..telemetry import (
            RequestTracer,
            SLOMonitor,
            Telemetry,
            TelemetryConfig,
            default_objectives,
        )

        hub = Telemetry(config=TelemetryConfig(dir=args.trace_dir))
        slo = SLOMonitor(
            default_objectives(ttft_s=args.slo_ttft_s, window_s=args.slo_window_s),
            telemetry=hub,
        )
        tracer = RequestTracer(telemetry=hub, slo=slo)

    def fresh_engine():
        # one model instance across engines: the jit cache lives on it, so
        # only the FIRST engine compiles — later sweep points (and every
        # extra replica) measure clean
        engine = ServingEngine(
            model, params, num_slots=args.num_slots, max_len=max_len,
            eos_token_id=args.eos_token_id, temperature=args.temperature,
            paged=not args.no_paged, page_size=args.page_size,
            prefill_chunk=args.prefill_chunk, tracer=tracer,
            use_kernels=use_kernels, speculative=spec_cfg,
        )
        # the hub attaches AFTER construction (exactly like the router wires
        # replicas): a hub passed to the constructor would also hand the
        # engine the hub's process-lifetime CompileTracker, and the sweep's
        # per-point steady-state compile accounting needs each engine's own
        engine.telemetry = hub
        return engine

    def fresh_target(fault_plan=None, autoscale=None):
        if n_replicas == 1 and not disagg:
            return fresh_engine()
        kwargs = {}
        if args.chaos == "handoff-stall" and fault_plan is not None:
            # the stall drill only drills something if the stalled transfer
            # overshoots the timeout — otherwise the attempt just runs 50ms
            # late and adopts first try, reporting the ladder as exercised
            # when nothing was tested
            kwargs["handoff_timeout_s"] = fault_plan.stall_seconds / 2.0
        return ServingRouter(
            engine_factory=fresh_engine, num_replicas=n_replicas,
            roles=roles, fault_plan=fault_plan, tracer=tracer,
            telemetry=hub, autoscale=autoscale, **kwargs,
        )

    def fleet_fault_plan():
        from ..resilience import FaultPlan

        step = args.chaos_step if args.chaos_step is not None else args.max_new_tokens // 2
        attempt = args.chaos_step if args.chaos_step is not None else 0
        kwargs = {
            "replica-kill": {"replica_kill_step": step, "replica_kill_index": n_replicas - 1},
            "replica-stall": {"replica_stall_step": step, "replica_stall_index": n_replicas - 1},
            "heartbeat-loss": {"heartbeat_loss_step": step, "heartbeat_loss_index": n_replicas - 1},
            # replica 0 is always a prefill-pool member (roles list the
            # prefill pool first), so the kill lands where KV parks
            "prefill-kill": {"replica_kill_step": step, "replica_kill_index": 0},
            "handoff-stall": {"handoff_stall_at": (attempt,)},
            "handoff-loss": {"handoff_loss_at": (attempt,)},
        }[args.chaos]
        return FaultPlan(seed=args.seed, **kwargs)

    # warmup: one synthetic request per prefill bucket + the decode step —
    # deterministic full coverage, so no sweep point ever straddles a compile
    warm_engine = fresh_target()
    warm_engine.warmup()
    warm = warm_engine.metrics()
    points = [
        run_offered_load(fresh_target(), prompts, args.max_new_tokens, offered_rps=rate)
        for rate in args.offered_load
    ]
    points.append(run_offered_load(fresh_target(), prompts, args.max_new_tokens, math.inf))

    # -- arrival-trace window (+ the paired autoscale drill) -----------------
    autoscale_drill = None
    trace_point = None
    if args.trace_load is not None:
        from ..serving import make_burst_trace, make_diurnal_trace

        maker = make_burst_trace if args.trace_load == "burst" else make_diurnal_trace
        arrivals = maker(args.requests, args.trace_load_rps, seed=args.seed)
        trace_point = run_offered_load(
            fresh_target(), prompts, args.max_new_tokens, arrival_times=arrivals
        )
        if args.autoscale:
            from ..serving import AutoscalePolicy, RoleRebalancer

            # drill-tuned hysteresis: the trace is seconds long, so the
            # dwell/cooldown windows shrink to fleet-step scale — the
            # production defaults would out-wait the whole trace. Cooldown
            # outlasts the 2x-dwell thrash window, so thrash stays 0 by
            # construction even if the trace's tail argues for a reversal
            rebalancer = RoleRebalancer(
                policy=AutoscalePolicy(
                    cadence_steps=2, min_dwell_steps=8, cooldown_steps=20
                )
            )
            rebalanced = run_offered_load(
                fresh_target(autoscale=rebalancer), prompts, args.max_new_tokens,
                arrival_times=arrivals,
            )
            autoscale_drill = {
                "trace": args.trace_load,
                "base_rps": args.trace_load_rps,
                "fixed_sheds": trace_point["loadgen_sheds"],
                "rebalanced_sheds": rebalanced["loadgen_sheds"],
                "fixed_ttft_p99_ms": trace_point["loadgen_ttft_p99_ms"],
                "rebalanced_ttft_p99_ms": rebalanced["loadgen_ttft_p99_ms"],
                "fixed_completed": trace_point["requests_completed"],
                "rebalanced_completed": rebalanced["requests_completed"],
                "flip_count": rebalanced["autoscale_flip_count"],
                "thrash_count": rebalanced["autoscale_thrash_count"],
                "aborted_flips": rebalanced["autoscale_aborted_flips"],
                "fail_static_count": rebalanced["autoscale_fail_static_count"],
                "steady_state_compile_count": rebalanced["compile_count"],
            }

    drill = None
    # traces_completed is MONOTONIC (the deque it feeds is bounded): the
    # drill's traces are the last (completed_after - completed_before)
    # entries whatever the ring evicted, where a raw len() index would
    # shift under eviction and mis-slice
    drill_trace_mark = tracer.traces_completed if tracer is not None else 0
    if args.chaos is not None:
        target = fresh_target(fault_plan=fleet_fault_plan())
        drill = run_offered_load(target, prompts, args.max_new_tokens, math.inf)
        healthy = points[-1]
        drill.update(
            {
                "chaos": args.chaos,
                "replica_deaths": target.replica_deaths,
                "failovers": target.failovers,
                "kv_handoffs": getattr(target, "kv_handoffs", 0),
                # every offered request must reach a terminal state — the
                # loadgen's completed count IS the accounting check
                "accounted": drill["requests_completed"],
                "goodput_retained": (
                    round(
                        drill["throughput_tokens_per_sec"]
                        / healthy["throughput_tokens_per_sec"],
                        4,
                    )
                    if healthy["throughput_tokens_per_sec"]
                    else None
                ),
            }
        )

    # -- trace export + SLO burn rates (with --trace) ------------------------
    trace_path = None
    slo_records = []
    slowest_drill_trace = None
    if tracer is not None:
        import os as _os

        from ..telemetry.tracing import to_perfetto

        # evaluate AT the last retirement, not at export time: export/IO
        # delay must not age the whole run's traces out of the window
        records = list(tracer.completed)
        last_stamp = max((r["t1"] for r in records), default=None)
        slo_records = slo.evaluate(stamp=last_stamp)  # lands {"kind": "slo"} records
        trace_path = _os.path.join(args.trace_dir, "trace.json")
        with open(trace_path, "w") as f:
            json.dump(to_perfetto(records), f)
        if drill is not None:
            # clamp to what the bounded ring still holds: a drill that
            # completed more traces than the ring keeps must NOT reach back
            # into surviving pre-drill sweep traces
            n_drill = min(tracer.traces_completed - drill_trace_mark, len(records))
            drill_traces = records[-n_drill:] if n_drill > 0 else []
            if drill_traces:
                slowest_drill_trace = max(
                    drill_traces, key=lambda r: r.get("latency_s") or 0.0
                )

    payload = {
        "model": args.model,
        "num_slots": args.num_slots,
        "max_len": max_len,
        "requests": args.requests,
        "max_new_tokens": args.max_new_tokens,
        "replicas": n_replicas,
        "prefill_replicas": args.prefill_replicas if disagg else None,
        "decode_replicas": args.decode_replicas if disagg else None,
        "int8": bool(args.int8),
        "kernels": (
            warm_engine.kernel_summary()
            if hasattr(warm_engine, "kernel_summary")
            else warm_engine.replicas[0].engine.kernel_summary()
        ),
        "paged": not args.no_paged,
        "page_size": args.page_size if not args.no_paged else None,
        "prefill_chunk": args.prefill_chunk,
        "speculative": (
            {
                "k": args.spec_k,
                "mode": args.spec_mode,
                "draft_model": args.draft_model or "auto-half-depth",
            }
            if spec_cfg is not None
            else None
        ),
        "mixed": bool(args.mixed),
        "shared_prefix": args.shared_prefix,
        # each sweep point's engine carries its own CompileTracker, scoped to
        # its lifetime: the saturation point's count IS the steady-state count
        # (for a fleet: any replica's tracker sees the process-wide stream, so
        # one count covers every replica — and it must still be 0)
        "warmup_compile_count": warm["compile_count"],
        "steady_state_compile_count": points[-1]["compile_count"],
        "sweep": points,
    }
    if trace_point is not None:
        payload["load_trace"] = {
            "kind": args.trace_load,
            "base_rps": args.trace_load_rps,
            "point": trace_point,
        }
    if autoscale_drill is not None:
        payload["autoscale_drill"] = autoscale_drill
    if tracer is not None:
        payload["trace"] = {
            "traces_completed": tracer.traces_completed,
            "traces_open": tracer.open_count,  # must be 0 after drain
            "perfetto_path": trace_path,
            "slo": slo_records,
        }
    if drill is not None:
        payload["chaos_drill"] = drill
        if slowest_drill_trace is not None:
            from ..telemetry.tracing import trace_summary

            payload["chaos_drill"]["slowest_trace"] = trace_summary(
                slowest_drill_trace
            )
    if args.json:
        print(json.dumps(payload))
        return 0
    if disagg:
        fleet = f", {args.prefill_replicas} prefill + {args.decode_replicas} decode replicas"
    elif n_replicas > 1:
        fleet = f", {n_replicas} replicas"
    else:
        fleet = ""
    layout = (
        f"paged(page_size={args.page_size}"
        + (f", chunk={args.prefill_chunk}" if args.prefill_chunk else "")
        + ")"
        if not args.no_paged
        else "dense slots"
    )
    ks = payload["kernels"]
    layout += (
        f", kernels(decode={ks['decode_attention']}"
        + (f", quant={ks['quant_matmul']}" if ks["quant_matmul"] else "")
        + ")"
        if use_kernels
        else ", no kernels"
    )
    scenario = (
        (", mixed long/short" if args.mixed else "")
        + (f", shared prefix {args.shared_prefix}" if args.shared_prefix else "")
    )
    print(
        f"serve-bench {args.model}: {args.num_slots} slots × {max_len} tokens "
        f"[{layout}]{fleet}, {args.requests} requests, "
        f"max_new={args.max_new_tokens}{scenario}"
        + (", int8 weights" if args.int8 else "")
    )
    print(
        f"compiles: {payload['warmup_compile_count']} at warmup, "
        f"{payload['steady_state_compile_count']} after (steady state must be 0"
        + (" — per pool" if disagg else (" — per replica" if n_replicas > 1 else ""))
        + ")"
    )
    if spec_cfg is not None:
        sat = points[-1]
        proposed = sat.get("spec_proposed_tokens", 0)
        accepted = sat.get("spec_accepted_tokens", 0)
        acc_rate = accepted / proposed if proposed else 0.0
        print(
            f"speculative: mode={args.spec_mode} k={args.spec_k} "
            f"draft={payload['speculative']['draft_model']} — "
            f"{accepted}/{proposed} draft tokens accepted ({acc_rate:.0%}), "
            f"accepted-len p50 {sat.get('spec_accepted_len_p50', 0.0)} / "
            f"p99 {sat.get('spec_accepted_len_p99', 0.0)}, "
            f"{sat.get('spec_fallbacks', 0)} fallbacks"
        )
    header = (
        f"{'offered req/s':>14} | {'tok/s':>9} | {'ttft p50':>9} | {'ttft p99':>9} | "
        f"{'tok p50':>8} | {'tok p99':>8} | {'occupancy':>9}"
    )
    print(header)
    print("-" * len(header))
    for point in points:
        rate = "saturate" if point["offered_rps"] is None else f"{point['offered_rps']:g}"
        print(
            f"{rate:>14} | {point['throughput_tokens_per_sec']:>9.1f} | "
            f"{point.get('ttft_p50_ms', 0):>7.1f}ms | {point.get('ttft_p99_ms', 0):>7.1f}ms | "
            f"{point.get('per_token_p50_ms', 0):>6.1f}ms | {point.get('per_token_p99_ms', 0):>6.1f}ms | "
            f"{point['slot_occupancy']:>9.2f}"
        )
    sat = points[-1]
    if not args.no_paged and "page_occupancy" in sat:
        print(
            f"page economy (saturation): occupancy {sat['page_occupancy']:.2f}, "
            f"peak {sat['peak_pages_in_use']}/{sat['num_pages'] - 1} pages, "
            f"prefix hit rate {sat.get('prefix_hit_rate', 0.0):.2f} "
            f"({sat.get('prefix_tokens_reused', 0)} tokens reused), "
            f"{sat.get('prefill_chunks', 0)} prefill chunks, "
            f"{sat.get('cow_page_copies', 0)} COW copies"
        )
    if disagg:
        print(
            f"handoff economy (saturation): {sat.get('handoffs_adopted', 0)} adopted / "
            f"{sat.get('handoffs_retried', 0)} retried / "
            f"{sat.get('handoff_fallbacks', 0)} fell back to re-prefill, "
            f"{sat.get('handoff_pages_moved', 0)} pages "
            f"({sat.get('handoff_bytes_moved', 0) / 1e6:.1f} MB) moved, "
            f"handoff p50 {sat.get('handoff_p50_ms', 0):.1f}ms / "
            f"p99 {sat.get('handoff_p99_ms', 0):.1f}ms"
        )
    if trace_point is not None:
        print(
            f"load trace ({args.trace_load} @ {args.trace_load_rps:g} req/s base): "
            f"{trace_point['loadgen_sheds']} sheds, "
            f"ttft p50 {trace_point['loadgen_ttft_p50_ms'] or 0:.1f}ms / "
            f"p99 {trace_point['loadgen_ttft_p99_ms'] or 0:.1f}ms, "
            f"{trace_point['requests_completed']}/{trace_point['offered_requests']} completed"
        )
    if autoscale_drill is not None:
        a = autoscale_drill
        print(
            f"autoscale drill: sheds {a['fixed_sheds']} fixed -> "
            f"{a['rebalanced_sheds']} rebalanced, "
            f"ttft p99 {a['fixed_ttft_p99_ms'] or 0:.1f}ms -> "
            f"{a['rebalanced_ttft_p99_ms'] or 0:.1f}ms, "
            f"{a['flip_count']} flip(s), {a['thrash_count']} thrash (must be 0), "
            f"{a['aborted_flips']} aborted, "
            f"{a['steady_state_compile_count']} steady-state compiles (must be 0)"
        )
    if drill is not None:
        retained = drill["goodput_retained"]
        print(
            f"chaos drill ({drill['chaos']}): {drill['requests_completed']}/"
            f"{drill['offered_requests']} requests terminated exactly once, "
            f"{drill['replica_deaths']} replica death(s), {drill['failovers']} failover(s), "
            + (
                f"{drill.get('handoffs_adopted', 0)} handoff(s) adopted, "
                f"{drill.get('handoff_fallbacks', 0)} fell back to re-prefill, "
                if disagg
                else ""
            )
            + "goodput retained "
            + (f"{retained:.2f}x vs healthy" if retained is not None else "n/a")
        )
        if slowest_drill_trace is not None:
            # WHERE the failed-over request spent its budget — top spans by
            # duration, replica-tagged, so a drill reads as a story
            print(f"slowest drill trace: {drill['slowest_trace']}")
    if tracer is not None:
        for record in slo_records:
            burn = record["burn_rate"]
            print(
                f"slo {record['objective']}: burn rate "
                + (f"{burn:.2f}" if burn is not None else "n/a (no data)")
                + f" of budget {record['budget']:.3f}"
                + (" — BREACHED" if record["breached"] else "")
                + f" ({record['window_bad']}/{record['window_observed']} bad in window)"
            )
        print(
            f"traces: {tracer.traces_completed} completed, "
            f"{tracer.open_count} open (must be 0) — Perfetto JSON at "
            f"{trace_path} (open in https://ui.perfetto.dev)"
        )
    return 0
