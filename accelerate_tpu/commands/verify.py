"""`accelerate-tpu verify-checkpoint <dir>` — offline checkpoint validation
and repair.

Validates a checkpoint directory against its ``manifest.json`` (completeness,
per-file sizes, CRC32 checksums) without touching an accelerator: the CI/ops
counterpart of the commit protocol in ``fault_tolerance.py``. ``<dir>`` may be
one checkpoint (it contains a manifest) or a checkpoints base directory (every
``checkpoint_<n>`` child is verified). Exit code 0 means everything verified
is complete and resumable; 1 lists every problem found.

``--repair`` turns report-only into cleanup: torn ``*.tmp`` staging dirs are
garbage-collected and checkpoints whose manifest fails verification are
pruned (auto-resume already skips them — pruning reclaims the space and keeps
`latest_valid` scans fast), printing exactly what was removed. The newest
valid checkpoint is never touched.
"""

from __future__ import annotations

import os
import shutil
import sys


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "verify-checkpoint",
        help="Validate (and optionally repair) checkpoint dirs offline (sizes + checksums)",
    )
    parser.add_argument(
        "checkpoint_dir",
        help="One checkpoint directory (contains manifest.json) or a base dir of checkpoint_<n> dirs",
    )
    parser.add_argument(
        "--no-checksums",
        action="store_true",
        help="Skip CRC32 verification (sizes/completeness only — fast on huge checkpoints)",
    )
    parser.add_argument(
        "--repair",
        action="store_true",
        help="GC torn *.tmp staging dirs and prune checkpoints that fail verification "
        "(prints what was removed)",
    )
    parser.set_defaults(func=run)
    return parser


def _verify_one(directory: str, check_checksums: bool, problems=None) -> int:
    from ..fault_tolerance import read_manifest, verify_checkpoint

    if problems is None:
        problems = verify_checkpoint(directory, check_checksums=check_checksums)
    if problems:
        for problem in problems:
            print(f"FAIL {directory}: {problem}", file=sys.stderr)
        return 1
    manifest = read_manifest(directory) or {}
    files = manifest.get("files", {})
    total = sum(meta.get("size", 0) for meta in files.values())
    step = manifest.get("step")
    detail = f"{len(files)} files, {total / 2**20:.1f} MiB"
    if step is not None:
        detail += f", step {step}"
    print(f"OK {directory}: {detail}")
    return 0


def run(args) -> int:
    from ..fault_tolerance import (
        garbage_collect_torn,
        list_checkpoints,
        verify_checkpoint,
    )
    from ..utils.constants import CHECKPOINT_MANIFEST_NAME

    base = args.checkpoint_dir
    check = not args.no_checksums
    is_single = os.path.exists(os.path.join(base, CHECKPOINT_MANIFEST_NAME))
    targets = [base] if is_single else list_checkpoints(base)

    verified_clean = False
    if args.repair:
        removed = []
        # torn staging debris first (never shadows valid checkpoints, but
        # wastes space and confuses `ls`-level ops). abspath: a relative
        # single-checkpoint arg must not dirname down to "" and skip the GC
        gc_base = (
            os.path.dirname(os.path.abspath(base.rstrip(os.sep))) if is_single else base
        )
        removed += garbage_collect_torn(gc_base)
        pruned = []
        for path in targets:
            problems = verify_checkpoint(path, check_checksums=check)
            if problems:
                shutil.rmtree(path, ignore_errors=True)
                pruned.append((path, problems))
        for path in removed:
            print(f"REMOVED torn staging dir {path}")
        for path, problems in pruned:
            print(f"PRUNED invalid checkpoint {path}: {problems[0]}"
                  + (f" (+{len(problems) - 1} more)" if len(problems) > 1 else ""))
        if not removed and not pruned:
            print(f"REPAIR {base}: nothing to remove")
        doomed = {path for path, _ in pruned}
        targets = [path for path in targets if path not in doomed]
        verified_clean = True  # every survivor passed the repair pass's verify

    if not targets:
        if args.repair:
            return 0
        print(f"FAIL {base}: no checkpoints found", file=sys.stderr)
        return 1
    worst = 0
    for path in targets:
        # after a repair pass, survivors verified clean moments ago — report
        # without re-reading (CRC'ing multi-GB checkpoints twice is real I/O)
        worst = max(worst, _verify_one(path, check, problems=[] if verified_clean else None))
    return worst
