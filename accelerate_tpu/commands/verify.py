"""`accelerate-tpu verify-checkpoint <dir>` — offline checkpoint validation.

Validates a checkpoint directory against its ``manifest.json`` (completeness,
per-file sizes, CRC32 checksums) without touching an accelerator: the CI/ops
counterpart of the commit protocol in ``fault_tolerance.py``. Exit code 0
means the checkpoint is complete and resumable; 1 lists every problem found.
"""

from __future__ import annotations

import sys


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "verify-checkpoint",
        help="Validate a checkpoint directory's manifest offline (sizes + checksums)",
    )
    parser.add_argument("checkpoint_dir", help="Checkpoint directory (contains manifest.json)")
    parser.add_argument(
        "--no-checksums",
        action="store_true",
        help="Skip CRC32 verification (sizes/completeness only — fast on huge checkpoints)",
    )
    parser.set_defaults(func=run)
    return parser


def run(args) -> int:
    from ..fault_tolerance import read_manifest, verify_checkpoint

    problems = verify_checkpoint(args.checkpoint_dir, check_checksums=not args.no_checksums)
    if problems:
        for problem in problems:
            print(f"FAIL {args.checkpoint_dir}: {problem}", file=sys.stderr)
        return 1
    manifest = read_manifest(args.checkpoint_dir) or {}
    files = manifest.get("files", {})
    total = sum(meta.get("size", 0) for meta in files.values())
    step = manifest.get("step")
    detail = f"{len(files)} files, {total / 2**20:.1f} MiB"
    if step is not None:
        detail += f", step {step}"
    print(f"OK {args.checkpoint_dir}: {detail}")
    return 0
