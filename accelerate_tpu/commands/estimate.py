"""`accelerate-tpu estimate-memory` — static memory estimate for a model.

Parity: reference commands/estimate.py:215-299 (meta-device model → per-dtype
table, loadable from any Hub checkpoint). Three input forms:

- a registry name (``llama-7b``): exact count via ``models.param_count``;
- ``params=N``: raw parameter count;
- a checkpoint path (file or directory): shapes/dtypes are read from the
  safetensors headers (8-byte length + JSON — zero tensor bytes touched) or
  the ``.npz`` member headers, covering anything ``save_model_weights``
  produced, sharded or not;
- a HF ``config.json`` (file, or a directory holding one but no weights):
  the config maps to a zoo TransformerConfig and the count is exact with NO
  weights present — the offline analogue of the reference's
  "estimate any Hub model from its config" (estimate.py:215-299).
"""

from __future__ import annotations

import json
import os
import struct


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "estimate-memory", help="Estimate device memory for training/inference of a model"
    )
    parser.add_argument(
        "model_name",
        help="Built-in model name (e.g. llama-7b, bert-base), params=N, or a "
        "checkpoint path (.safetensors/.npz file or directory)",
    )
    parser.add_argument("--dtypes", nargs="+", default=["float32", "bfloat16", "int8"])
    parser.add_argument(
        "--max-seq-len", type=int, default=None,
        help="KV-cache sequence capacity for the inference column "
        "(default: the model config's max_seq_len)",
    )
    parser.add_argument(
        "--batch", type=int, default=1,
        help="Concurrent sequences (serving slots) for the KV-cache estimate",
    )
    parser.add_argument(
        "--page-size", type=int, default=16,
        help="Tokens per KV page for the paged-pool estimate (the serving "
        "engine's default layout); the dense slab is printed for comparison",
    )
    parser.add_argument(
        "--replicas", type=int, default=8,
        help="Data-parallel replicas for the ZeRO column: optimizer state + "
        "gradient bytes PER CHIP when the update is sharded (the default "
        "training path on a multi-chip mesh)",
    )
    parser.add_argument(
        "--elastic-redundancy", type=int, default=0, choices=(0, 1), metavar="N",
        help="Buddy copies per ZeRO shard for elastic training "
        "(resilience/elastic.py): adds a per-chip column pricing the mirror "
        "(params + optimizer state, 1/replicas each) that lets a host loss "
        "recover in-memory instead of from checkpoint. 0 or 1 — the runtime "
        "supports a single buddy roll (ElasticConfig rejects more)",
    )
    parser.set_defaults(func=run)
    return parser


# safetensors dtype tags and numpy dtype names → bytes per element
_STORED_DTYPE_BYTES = {
    "F64": 8, "F32": 4, "F16": 2, "BF16": 2, "I64": 8, "I32": 4, "I16": 2,
    "I8": 1, "U8": 1, "BOOL": 1,
    "float64": 8, "float32": 4, "float16": 2, "bfloat16": 2, "int64": 8,
    "int32": 4, "int16": 2, "int8": 1, "uint8": 1, "bool": 1,
}


def _safetensors_entries(path: str) -> dict[str, tuple[tuple, str]]:
    """{tensor name: (shape, dtype tag)} from the header only."""
    with open(path, "rb") as f:
        (n,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(n))
    return {
        k: (tuple(v["shape"]), v["dtype"]) for k, v in header.items() if k != "__metadata__"
    }


def _npz_entries(path: str) -> dict[str, tuple[tuple, str]]:
    """{name: (shape, dtype)} from each zip member's .npy header."""
    import zipfile

    from numpy.lib import format as npf

    out = {}
    with zipfile.ZipFile(path) as z:
        for name in z.namelist():
            with z.open(name) as f:
                version = npf.read_magic(f)
                if version == (1, 0):
                    shape, _, dtype = npf.read_array_header_1_0(f)
                else:
                    shape, _, dtype = npf.read_array_header_2_0(f)
            key = name[:-4] if name.endswith(".npy") else name
            out[key] = (shape, dtype.name)
    return out


def checkpoint_entries(path: str) -> dict[str, tuple[tuple, str]]:
    """Tensor shapes/dtypes for a checkpoint file or directory, header-only."""
    if os.path.isfile(path):
        files = [path]
    else:
        names = sorted(os.listdir(path))
        # prefer index-listed shards (canonical), else every weights file
        indexed: set[str] = set()
        for name in names:
            if name.endswith(".index.json"):
                with open(os.path.join(path, name)) as f:
                    indexed.update(json.load(f).get("weight_map", {}).values())
        chosen = sorted(indexed) if indexed else [
            n for n in names if n.endswith((".safetensors", ".npz"))
        ]
        files = [os.path.join(path, n) for n in chosen]
    if not files:
        raise FileNotFoundError(f"No .safetensors/.npz weights under {path!r}")
    entries: dict[str, tuple[tuple, str]] = {}
    for f in files:
        entries.update(_npz_entries(f) if f.endswith(".npz") else _safetensors_entries(f))
    return entries


_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "int8": 1, "int4": 0.5, "fp8": 1}


def _convert_bytes(size: float) -> str:
    for unit in ["B", "KB", "MB", "GB", "TB"]:
        if size < 1024:
            return f"{size:.2f} {unit}"
        size /= 1024
    return f"{size:.2f} PB"


def count_params(model_name: str) -> int:
    if model_name.startswith("params="):
        return int(float(model_name.split("=", 1)[1]))
    from ..models import get_config, param_count

    return param_count(get_config(model_name))


def _config_json_path(path: str) -> str | None:
    """The config.json to estimate from, when the path is config-only."""
    if os.path.isfile(path):
        return path if path.endswith(".json") else None
    candidate = os.path.join(path, "config.json")
    has_weights = any(
        name.endswith((".safetensors", ".npz")) for name in os.listdir(path)
    )
    # weights present → the header route is exact for the actual checkpoint
    return candidate if os.path.exists(candidate) and not has_weights else None


def run(args) -> int:
    config = None  # set when the input names a known geometry → KV estimate
    config_json = _config_json_path(args.model_name) if os.path.exists(args.model_name) else None
    if config_json is not None:
        from ..models.config import config_from_hf_json, param_count

        config = config_from_hf_json(config_json)
        n = param_count(config)
        print(
            f"Config: {config_json} — arch {config.arch}, "
            f"{config.num_layers} layers, hidden {config.hidden_size}, "
            f"{n:,} parameters ({n / 1e9:.2f}B)"
        )
    elif os.path.exists(args.model_name):
        entries = checkpoint_entries(args.model_name)
        import numpy as np

        n = sum(int(np.prod(shape)) for shape, _ in entries.values())
        stored = sum(
            int(np.prod(shape)) * _STORED_DTYPE_BYTES.get(dtype, 4)
            for shape, dtype in entries.values()
        )
        largest_key, (largest_shape, largest_dtype) = max(
            entries.items(), key=lambda kv: int(np.prod(kv[1][0]))
        )
        print(
            f"Checkpoint: {args.model_name} — {len(entries)} tensors, "
            f"{n:,} parameters, {_convert_bytes(stored)} stored"
        )
        print(f"Largest tensor: {largest_key} {list(largest_shape)} {largest_dtype}")
    else:
        n = count_params(args.model_name)
        if not args.model_name.startswith("params="):
            from ..models import get_config

            config = get_config(args.model_name)
        print(f"Model: {args.model_name} — {n / 1e9:.2f}B parameters")

    # KV cache for serving: without it, serve sizing is silently off by
    # 2·L·KV·D·S·B bytes per replica — often the difference between a model
    # "fitting" and OOMing the moment slots fill. The decoder-only formula
    # covers the archs the serving engine decodes (llama/gpt2); bert has no
    # decode cache and t5's per-stack layers + cross-attention cache need a
    # different formula, so both are skipped LOUDLY rather than printed
    # wrong. The cache dtype follows the compute dtype (weight-only int8/int4
    # still decode with a bf16 cache).
    kv_batch = getattr(args, "batch", None) or 1
    kv_seq = getattr(args, "max_seq_len", None)
    kv_page = getattr(args, "page_size", None) or 16
    kv_fn = None
    if config is not None and config.arch in ("llama", "gpt2"):
        from ..serving.kv_cache import kv_cache_bytes, paged_kv_cache_bytes

        kv_seq = kv_seq or config.max_seq_len
        dense_fn = lambda dtype_bytes: kv_cache_bytes(config, kv_batch, kv_seq, dtype_bytes)  # noqa: E731
        # the serving engine pages by default, so the +kv column prices the
        # paged pool (+ its int32 page tables); the dense slab stays printed
        # for comparison — at capacity parity the pool costs one extra (null)
        # page, and the savings come from provisioning below parity for the
        # observed working set (bench: serving_paged_hbm_bytes_per_req)
        kv_fn = lambda dtype_bytes: sum(  # noqa: E731
            paged_kv_cache_bytes(
                config, kv_batch, kv_seq, page_size=kv_page, dtype_bytes=dtype_bytes
            )
        )
        pool, table = paged_kv_cache_bytes(config, kv_batch, kv_seq, page_size=kv_page)
        print(
            f"KV cache (batch={kv_batch}, seq={kv_seq}): "
            f"{_convert_bytes(dense_fn(2))} bf16 / {_convert_bytes(dense_fn(4))} fp32 "
            f"dense slab"
        )
        print(
            f"Paged KV (page_size={kv_page}, capacity parity): pool "
            f"{_convert_bytes(pool)} + page tables {_convert_bytes(table)} bf16 — "
            f"a request only holds pages for tokens it produced"
        )
    elif kv_seq is not None:
        reason = (
            "needs a model config (registry name or config.json)"
            if config is None
            else f"decoder-only formula does not cover arch {config.arch!r}"
        )
        print(f"KV cache: {reason}, skipping")

    # ZeRO column: the sharded update (parallel/zero.py — the default training
    # path on a multi-chip mesh) holds 1/N of the optimizer state and reduced
    # gradient per chip, so the train budget that used to be 4 bytes/param of
    # state per chip becomes 12/N + params — visible here BEFORE anyone runs a
    # step, same as the KV column prices serving.
    from ..parallel.zero import elastic_redundancy_bytes, zero_update_state_bytes

    replicas = max(int(getattr(args, "replicas", 1) or 1), 1)
    redundancy = max(int(getattr(args, "elastic_redundancy", 0) or 0), 0)
    show_elastic = replicas > 1 and redundancy > 0
    zero_col = f" | {f'+adam/chip @{replicas} (ZeRO)':>22}" if replicas > 1 else ""
    # the buddy-mirror column sits NEXT TO the ZeRO column it duplicates:
    # elastic redundancy is priced as extra bytes on top of the sharded state
    elastic_col = f" | {f'+buddy/chip x{redundancy}':>16}" if show_elastic else ""
    kv_col = f" | {'+kv (serve)':>12}" if kv_fn is not None else ""
    header = f"{'dtype':>10} | {'params':>10} | {'+grads':>10} | {'+adam (train)':>14}{zero_col}{elastic_col}{kv_col}"
    print(header)
    print("-" * len(header))
    for dtype in args.dtypes:
        b = _DTYPE_BYTES[dtype]
        params = n * b
        # grads stored in the same dtype; Adam keeps two fp32 moments + fp32 master params
        train = params + n * b + n * 4 * 3
        row = f"{dtype:>10} | {_convert_bytes(params):>10} | {_convert_bytes(params * 2):>10} | {_convert_bytes(train):>14}"
        if replicas > 1:
            opt_chip, grad_chip = zero_update_state_bytes(n, b, replicas)
            # params are stored sharded too under ZeRO, but the forward
            # gathers them, so the per-chip working set still prices them full
            row += f" | {_convert_bytes(params + grad_chip + opt_chip):>22}"
        if show_elastic:
            row += f" | {_convert_bytes(elastic_redundancy_bytes(n, b, replicas, redundancy)):>16}"
        if kv_fn is not None:
            serve = params + kv_fn(4 if dtype == "float32" else 2)
            row += f" | {_convert_bytes(serve):>12}"
        print(row)
    if replicas > 1:
        print(
            f"ZeRO column: optimizer state (12 B/param fp32) and gradients "
            f"sharded 1/{replicas} per chip; reduce-scatter -> sharded adamw "
            f"-> all-gather (docs/performance.md)"
        )
    if show_elastic:
        print(
            f"Buddy column: {redundancy} mirror(s) of each chip's 1/{replicas} "
            f"param + optimizer shard on a different host — a host loss "
            f"recovers in-memory via the elastic ladder (docs/resilience.md)"
        )
    elif redundancy > 0:
        # asked-for but unpriceable: say so instead of dropping the column
        print(
            "Elastic redundancy: needs --replicas N > 1 (the buddy mirrors "
            "1/N ZeRO shards; with one replica there is nothing sharded to "
            "mirror) — column skipped"
        )
    return 0
