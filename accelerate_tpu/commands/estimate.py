"""`accelerate-tpu estimate-memory` — static memory estimate for a model.

Parity: reference commands/estimate.py:215-299 (meta-device model → per-dtype
table). Here the abstract init is `jax.eval_shape`, which is exact and free:
no weights are materialized.
"""

from __future__ import annotations


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "estimate-memory", help="Estimate device memory for training/inference of a model"
    )
    parser.add_argument("model_name", help="Built-in model name (e.g. llama-7b, bert-base) or params=N")
    parser.add_argument("--dtypes", nargs="+", default=["float32", "bfloat16", "int8"])
    parser.set_defaults(func=run)
    return parser


_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "int8": 1, "int4": 0.5, "fp8": 1}


def _convert_bytes(size: float) -> str:
    for unit in ["B", "KB", "MB", "GB", "TB"]:
        if size < 1024:
            return f"{size:.2f} {unit}"
        size /= 1024
    return f"{size:.2f} PB"


def count_params(model_name: str) -> int:
    if model_name.startswith("params="):
        return int(float(model_name.split("=", 1)[1]))
    from ..models import get_config, param_count

    return param_count(get_config(model_name))


def run(args) -> int:
    n = count_params(args.model_name)
    print(f"Model: {args.model_name} — {n / 1e9:.2f}B parameters")
    header = f"{'dtype':>10} | {'params':>10} | {'+grads':>10} | {'+adam (train)':>14}"
    print(header)
    print("-" * len(header))
    for dtype in args.dtypes:
        b = _DTYPE_BYTES[dtype]
        params = n * b
        # grads stored in the same dtype; Adam keeps two fp32 moments + fp32 master params
        train = params + n * b + n * 4 * 3
        print(f"{dtype:>10} | {_convert_bytes(params):>10} | {_convert_bytes(params * 2):>10} | {_convert_bytes(train):>14}")
    return 0
