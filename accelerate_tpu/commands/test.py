"""`accelerate-tpu test` — run the bundled sanity script through the launcher.

Parity: reference commands/test.py:65.
"""

from __future__ import annotations

import os
import subprocess
import sys


def register_subcommand(subparsers):
    parser = subparsers.add_parser("test", help="Run a sanity check of the install/topology")
    parser.add_argument("--config_file", default=None)
    parser.set_defaults(func=run)
    return parser


def run(args) -> int:
    from .. import test_utils

    script = os.path.join(os.path.dirname(test_utils.__file__), "scripts", "test_script.py")
    cmd = [sys.executable, "-m", "accelerate_tpu.commands.cli", "launch"]
    if args.config_file:
        cmd += ["--config_file", args.config_file]
    cmd += [script]
    result = subprocess.run(cmd)
    if result.returncode == 0:
        print("Test is a success! You are ready for your distributed training!")
    return result.returncode
