"""Runtime concurrency sanitizer: lock-order and blocking-hold detection.

The host orchestration layer around the compiled programs — membership
heartbeats, the collective-hang watchdog, the telemetry hub's jsonl sink,
the serving fleet's step watchdogs, the redistribute sequencer — is real
multithreaded code, and its failure modes (lock-order inversions, blocking
I/O under a lock every other thread needs) are invisible to both the
program audit and the AST lint. This module makes them *named findings*:

- :func:`named_lock` wraps ``threading.Lock`` with a registry name. Every
  lock in this codebase is constructed through it, so the registry's
  inventory IS the codebase's lock surface — a new lock shows up in the
  ``concurrency`` contract diff (and a new *raw* ``threading.Lock()`` is a
  ``LOCK_UNREGISTERED`` lint finding), never silently.
- The process-global :class:`LockRegistry` keeps a per-thread held-lock
  stack (always on — one list append per acquire) and, while a
  :func:`record` window is open, folds every nested acquisition into an
  acquisition-order graph. A cycle in that graph (``A → B`` in one thread,
  ``B → A`` in another) is a potential deadlock: ``CONCURRENCY_CYCLE``.
- :func:`record` additionally interposes the blocking boundaries —
  ``time.sleep``, ``os.fsync``, ``jax.block_until_ready``,
  ``jax.device_get``, and the chaos layer's store-I/O probe — and any of
  them reached while this thread holds a named lock is a
  ``LOCK_BLOCKING_HOLD`` finding naming the lock and the boundary (the
  PR 14 bug class, mechanized).
- :class:`ConcurrencyContract` pins the clean state (zero cycles, zero
  blocking holds, the exact lock-name inventory) as
  ``tests/contracts/concurrency.json``; ``analyze --self-check`` runs the
  2-replica traced fleet + an elastic coordinator under the recorder and
  gates that contract the same way program contracts gate collective drift.

The recorder's cost is one flag check per acquire when off, and a small
dict update under the registry's bookkeeping mutex when on — cheap enough
to ride along the existing chaos drills. The report serializes as a
``{"kind": "concurrency"}`` telemetry record via
``telemetry.write_record("concurrency", report.to_dict())``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Optional

from .findings import AnalysisReport, Finding

CONTRACT_FILENAME = "concurrency.json"


def _call_site() -> str:
    """First stack frame outside this module (and jax/stdlib wrappers) —
    where the blocking call was *requested*."""
    here = __file__
    for frame, lineno in traceback.walk_stack(None):
        filename = frame.f_code.co_filename
        if filename == here or "/jax/" in filename or "/jaxlib/" in filename:
            continue
        return f"{filename}:{lineno} ({frame.f_code.co_name})"
    return "<unknown>"


def _find_cycles(edges: set) -> list[list[str]]:
    """Enumerate the simple cycles of a (tiny) directed lock-order graph.
    Deduped up to rotation by anchoring each cycle at its lexicographically
    smallest node."""
    adjacency: dict[str, set[str]] = {}
    for a, b in edges:
        adjacency.setdefault(a, set()).add(b)
    nodes = sorted(set(adjacency) | {b for targets in adjacency.values() for b in targets})
    order = {name: i for i, name in enumerate(nodes)}
    cycles: list[list[str]] = []

    def dfs(start: str, node: str, path: list[str], visited: set[str]) -> None:
        for nxt in sorted(adjacency.get(node, ())):
            if order[nxt] < order[start]:
                continue  # that cycle is (or will be) found anchored at nxt
            if nxt == start:
                cycles.append(list(path))
            elif nxt not in visited:
                visited.add(nxt)
                path.append(nxt)
                dfs(start, nxt, path, visited)
                path.pop()
                visited.remove(nxt)

    for start in nodes:
        dfs(start, start, [start], {start})
    return cycles


class LockRegistry:
    """Process-global bookkeeping for every :func:`named_lock`.

    Always on: per-thread held stacks (a list append/pop per acquire —
    nothing shared, nothing contended). Recording on: held-before edges and
    blocking-hold attribution, guarded by a plain bookkeeping mutex that is
    never held across any user code."""

    def __init__(self):
        # the registry's own bookkeeping mutex must be a RAW lock: wrapping
        # it in named_lock would recurse into this registry on every acquire
        self._meta = threading.Lock()  # accel-lint: disable=LOCK_UNREGISTERED
        self._tls = threading.local()
        self._names: dict[str, int] = {}  # name -> instances constructed
        self._edges: dict[tuple[str, str], int] = {}  # (held, acquired) -> count
        # (lock name, boundary kind) -> {"count", "site"}
        self._blocking: dict[tuple[str, str], dict] = {}
        self._max_hold: dict[str, float] = {}
        self._acquisitions = 0
        self._recording = False

    # -- registration / held-stack maintenance (always on) -----------------

    def register(self, name: str) -> None:
        with self._meta:
            self._names[name] = self._names.get(name, 0) + 1

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def on_acquired(self, name: str) -> None:
        stack = self._stack()
        if self._recording:
            with self._meta:
                self._acquisitions += 1
                for held_name, _ in stack:
                    if held_name != name:  # same-name nesting is two instances
                        key = (held_name, name)
                        self._edges[key] = self._edges.get(key, 0) + 1
        stack.append((name, time.perf_counter()))

    def on_released(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == name:
                _, acquired_at = stack.pop(i)
                break
        else:
            return
        if self._recording:
            held_for = time.perf_counter() - acquired_at
            with self._meta:
                if held_for > self._max_hold.get(name, 0.0):
                    self._max_hold[name] = held_for

    def note_blocking(self, kind: str, site: Optional[str] = None) -> None:
        """A blocking boundary was reached on this thread. Attributed to
        every lock the thread currently holds (an outer lock held across a
        blocking inner call is just as stalled)."""
        if not self._recording:
            return
        stack = getattr(self._tls, "stack", None)
        if not stack:
            return
        if site is None:
            site = _call_site()
        with self._meta:
            for held_name, _ in stack:
                key = (held_name, kind)
                entry = self._blocking.get(key)
                if entry is None:
                    self._blocking[key] = {"count": 1, "site": site}
                else:
                    entry["count"] += 1

    # -- recording window ---------------------------------------------------

    def start(self) -> None:
        self._recording = True

    def stop(self) -> None:
        self._recording = False

    def reset_observations(self) -> None:
        """Clear edges/blocking/hold observations (NOT the name inventory —
        locks registered at construction stay registered for the process)."""
        with self._meta:
            self._edges.clear()
            self._blocking.clear()
            self._max_hold.clear()
            self._acquisitions = 0

    def forget(self, *names: str) -> None:
        """Drop names AND their observations from the inventory. For test
        fixtures: a seeded ``test.A``/``test.B`` inversion must not leak
        into the exact-lock-inventory contract a later drill in the same
        process records against."""
        gone = set(names)
        with self._meta:
            for name in gone:
                self._names.pop(name, None)
                self._max_hold.pop(name, None)
            self._edges = {
                key: count for key, count in self._edges.items()
                if key[0] not in gone and key[1] not in gone
            }
            self._blocking = {
                key: entry for key, entry in self._blocking.items()
                if key[0] not in gone
            }

    # -- readout -------------------------------------------------------------

    def lock_names(self) -> list[str]:
        with self._meta:
            return sorted(self._names)

    def edges(self) -> list[tuple[str, str]]:
        with self._meta:
            return sorted(self._edges)

    def cycles(self) -> list[list[str]]:
        with self._meta:
            edge_set = set(self._edges)
        return _find_cycles(edge_set)

    def blocking_holds(self) -> list[dict]:
        with self._meta:
            return [
                {"lock": lock, "kind": kind, **entry}
                for (lock, kind), entry in sorted(self._blocking.items())
            ]

    def report(self) -> AnalysisReport:
        """The observations as findings + diffable inventory. ``meta.kind``
        marks it for the ``{"kind": "concurrency"}`` telemetry record."""
        with self._meta:
            names = dict(self._names)
            edges = dict(self._edges)
            max_hold = dict(self._max_hold)
            acquisitions = self._acquisitions
        blocking = self.blocking_holds()
        cycles = _find_cycles(set(edges))
        report = AnalysisReport(meta={"label": "concurrency", "kind": "concurrency"})
        for cycle in cycles:
            loop = " -> ".join([*cycle, cycle[0]])
            report.add(
                Finding(
                    "CONCURRENCY_CYCLE",
                    f"lock acquisition-order cycle {loop}: these locks were "
                    "taken in opposite orders on different code paths — two "
                    "threads interleaving there deadlock",
                    path=f"locks:{loop}",
                    data={"cycle": cycle},
                )
            )
        for entry in blocking:
            report.add(
                Finding(
                    "LOCK_BLOCKING_HOLD",
                    f"lock '{entry['lock']}' held across blocking boundary "
                    f"`{entry['kind']}` ({entry['count']}x)",
                    path=entry.get("site"),
                    data={k: v for k, v in entry.items() if k != "site"},
                )
            )
        report.inventory = {
            "locks": sorted(names),
            "lock_instances": names,
            "acquisitions": acquisitions,
            "edges": [[a, b, count] for (a, b), count in sorted(edges.items())],
            "cycles": cycles,
            "blocking_holds": blocking,
            "max_hold_seconds": {
                name: round(seconds, 6) for name, seconds in sorted(max_hold.items())
            },
        }
        return report


_REGISTRY = LockRegistry()


def registry() -> LockRegistry:
    return _REGISTRY


class _NamedLock:
    """A ``threading.Lock`` with a registry identity. Same surface
    (``acquire``/``release``/``locked``/context manager); every transition
    feeds the registry's held-stack so lock-order edges and blocking-hold
    attribution see it. Several instances may share one name (e.g. every
    ``CompileTracker``'s event lock is ``compile_tracker.events``) — the
    *name* is the unit of the order graph, which is exactly the granularity
    a reviewer reasons at."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, inner: Optional[Any] = None):
        self.name = name
        if inner is None:
            inner = threading.Lock()  # accel-lint: disable=LOCK_UNREGISTERED
        self._inner = inner
        _REGISTRY.register(name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        acquired = self._inner.acquire(blocking, timeout)
        if acquired:
            _REGISTRY.on_acquired(self.name)
        return acquired

    def release(self) -> None:
        _REGISTRY.on_released(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "_NamedLock":
        self.acquire()  # accel-lint: disable=LOCK_BARE_ACQUIRE
        return self

    def __exit__(self, *exc_info) -> None:
        self.release()

    def __repr__(self) -> str:
        state = "locked" if self.locked() else "unlocked"
        return f"<named_lock {self.name!r} {state}>"


def named_lock(name: str, inner: Optional[Any] = None) -> _NamedLock:
    """Construct (or wrap) a lock under a registry name. Adopted at every
    lock construction site in this codebase; the name becomes part of the
    ``concurrency`` contract's exact inventory."""
    return _NamedLock(name, inner)


def note_blocking(kind: str, site: Optional[str] = None) -> None:
    """Module-level hook for blocking boundaries the recorder cannot patch
    (the chaos layer's ``probe_io`` calls this for store I/O)."""
    _REGISTRY.note_blocking(kind, site)


def reset_observations() -> None:
    _REGISTRY.reset_observations()


@contextmanager
def record():
    """Arm the recorder: acquisition-order edges accumulate, and the
    blocking boundaries — ``time.sleep``, ``os.fsync``,
    ``jax.block_until_ready``, ``jax.device_get`` — are interposed so a
    lock held across any of them becomes a ``LOCK_BLOCKING_HOLD``. Not
    reentrant (one recording window at a time); patches restore LIFO on
    exit. Yields the registry; read ``registry().report()`` after."""
    _REGISTRY.start()
    patched: list[tuple[Any, str, Any]] = []

    def interpose(owner: Any, attr: str, kind: str) -> None:
        original = getattr(owner, attr, None)
        if original is None:
            return

        def wrapper(*args, **kwargs):
            _REGISTRY.note_blocking(kind)
            return original(*args, **kwargs)

        wrapper.__name__ = getattr(original, "__name__", attr)
        try:
            setattr(owner, attr, wrapper)
        except (TypeError, AttributeError):
            return
        patched.append((owner, attr, original))

    interpose(time, "sleep", "time.sleep")
    interpose(os, "fsync", "os.fsync")
    try:
        import jax
    except ImportError:  # static-analysis-only environments
        jax = None
    if jax is not None:
        interpose(jax, "block_until_ready", "block_until_ready")
        interpose(jax, "device_get", "device_get")
    try:
        yield _REGISTRY
    finally:
        for owner, attr, original in reversed(patched):
            setattr(owner, attr, original)
        _REGISTRY.stop()


# -- the concurrency contract -------------------------------------------------


@dataclass
class ConcurrencyContract:
    """Checked-in expectations for the recorded drill: zero cycles, zero
    blocking holds, and the EXACT lock-name inventory — a lock added (or
    renamed, or removed) anywhere in the codebase moves this file in a
    reviewed diff. Counts are exact; there is nothing to tolerance here."""

    locks: list[str] = field(default_factory=list)
    cycles: int = 0
    blocking_holds: int = 0
    version: int = 1

    @classmethod
    def from_report(cls, report: AnalysisReport) -> "ConcurrencyContract":
        inventory = report.inventory
        return cls(
            locks=sorted(inventory.get("locks", [])),
            cycles=len(inventory.get("cycles", [])),
            blocking_holds=len(inventory.get("blocking_holds", [])),
        )

    @classmethod
    def load(cls, path: str) -> "ConcurrencyContract":
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
        expectations = payload.get("expectations", {})
        return cls(
            locks=[str(name) for name in expectations.get("locks", [])],
            cycles=int(expectations.get("cycles", 0)),
            blocking_holds=int(expectations.get("blocking_holds", 0)),
            version=int(payload.get("version", 1)),
        )

    def to_json(self) -> str:
        payload = {
            "program": "concurrency",
            "version": self.version,
            "expectations": {
                "cycles": self.cycles,
                "blocking_holds": self.blocking_holds,
                "locks": sorted(self.locks),
            },
        }
        return json.dumps(payload, indent=2, sort_keys=True) + "\n"

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.to_json())

    def check(self, report: AnalysisReport) -> list[Finding]:
        findings: list[Finding] = []
        inventory = report.inventory

        def drift(fieldname: str, expected, actual, detail: str = "") -> None:
            findings.append(
                Finding(
                    "CONTRACT_DRIFT",
                    f"concurrency: {fieldname} drifted from its contract: "
                    f"expected {expected}, got {actual}"
                    + (f" ({detail})" if detail else ""),
                    path=f"concurrency:{fieldname}",
                    data={
                        "program": "concurrency",
                        "field": fieldname,
                        "expected": expected,
                        "actual": actual,
                    },
                )
            )

        cycles = inventory.get("cycles", [])
        if len(cycles) != self.cycles:
            drift(
                "cycles", self.cycles, len(cycles),
                "; ".join(" -> ".join(c) for c in cycles[:3]),
            )
        blocking = inventory.get("blocking_holds", [])
        if len(blocking) != self.blocking_holds:
            drift(
                "blocking_holds", self.blocking_holds, len(blocking),
                "; ".join(f"{b['lock']}@{b['kind']}" for b in blocking[:3]),
            )
        actual_locks = sorted(inventory.get("locks", []))
        expected_locks = sorted(self.locks)
        if actual_locks != expected_locks:
            added = sorted(set(actual_locks) - set(expected_locks))
            removed = sorted(set(expected_locks) - set(actual_locks))
            parts = []
            if added:
                parts.append(f"new locks {added}")
            if removed:
                parts.append(f"missing locks {removed}")
            drift("locks", expected_locks, actual_locks, "; ".join(parts))
        return findings


def gate_concurrency(
    report: AnalysisReport, contracts_dir: str, *, update: bool = False
) -> list[Finding]:
    """Check (or refresh) the recorded drill report against
    ``<contracts_dir>/concurrency.json``. Mirrors the program-contract gate:
    churn-free updates, ``CONTRACT_DRIFT`` errors on any mismatch, a
    ``CONTRACT_MISSING`` warning when the file was never committed."""
    path = os.path.join(contracts_dir, CONTRACT_FILENAME)
    if update:
        if os.path.exists(path) and not ConcurrencyContract.load(path).check(report):
            return []  # still passing: byte-identical file, no churn
        ConcurrencyContract.from_report(report).save(path)
        return [
            Finding(
                "CONTRACT_UPDATED",
                f"concurrency: contract written to {path}",
                path=path,
            )
        ]
    if not os.path.exists(path):
        return [
            Finding(
                "CONTRACT_MISSING",
                f"concurrency: no contract at {path} — run with "
                "--update-contracts and commit the JSON",
                path="concurrency",
            )
        ]
    return ConcurrencyContract.load(path).check(report)
