"""Collective-overlap schedule pass: is comm hidden behind compute, or not?

The collective *inventory* (program.py) says what a program communicates;
it cannot say what that communication costs in wall-clock, because the cost
depends on the schedule: an all-gather whose consumer immediately follows it
serializes the interconnect into the critical path, while the same transfer
with independent compute scheduled beside it is (up to bandwidth) free. This
pass reads the post-SPMD HLO and classifies every collective:

- **async pairs** — ``all-gather-start``/``all-gather-done``,
  ``all-reduce-start``/``-done``, ``collective-permute-start``/``-done``:
  matched by the done op consuming the start's value. The pair is
  **overlapped** when at least one real compute op that does *not* depend on
  the start sits between them in instruction order, else **serialized** (the
  consumer is right behind the start — the async form bought nothing).
- **sync ops** — plain ``all-reduce(...)`` etc. In a *scheduled* module
  (``is_scheduled=true``) the walk measures the op's **ready-window**: the
  instructions between its last dependency (when its inputs exist — the
  earliest the transfer can be in flight) and its first dependent consumer
  (when the program must have the result). The op is **overlapped** when at
  least one compute op inside that window is neither an ancestor nor a
  descendant of it — work that can genuinely execute while the transfer
  runs. This is how overlap manifests for sync HLO forms: XLA:CPU's thunk
  executor runs the thunk DAG concurrently (a collective launches when its
  inputs are ready, regardless of its position in the list schedule — the
  list scheduler sinks every collective to just before its consumer, so
  naive post-issue distance would read 0 for everything), and XLA:TPU/GPU
  realize the same window by hoisting the start in their latency-hiding
  schedulers. A sync collective whose window holds no independent compute —
  produced late, consumed immediately, nothing concurrent-eligible between —
  serializes on every runtime. In an UNSCHEDULED module sync ops stay
  serialized-by-definition: instruction order proves nothing there.

The observable is ``serialized_comm_bytes`` — result bytes of every
serialized collective, i.e. the payload sitting on the critical path. This
is the number the ZeRO-style weight-update sharding (parallel/zero.py;
arXiv:2004.13336, SimpleFSDP arXiv:2411.00284) exists to move, and the
contract gate (contracts.py) pins so it cannot regress silently afterwards.
``overlapped_count`` (also pinned) counts both async pairs and scheduled
sync ops that the walk proved overlapped; ``sync_overlapped_count`` breaks
out the sync share so a contract diff shows which mechanism moved.
"""

from __future__ import annotations

import re

from .findings import Finding
from .program import start_result_bytes, sync_result_bytes

# async opcode -> canonical collective kind
_ASYNC_START = {
    "all-gather-start": "all_gather",
    "all-reduce-start": "all_reduce",
    "reduce-scatter-start": "reduce_scatter",
    "collective-permute-start": "collective_permute",
    "all-to-all-start": "all_to_all",
}
_SYNC_OPS = {
    "all-gather": "all_gather",
    "all-reduce": "all_reduce",
    "reduce-scatter": "reduce_scatter",
    "collective-permute": "collective_permute",
    "all-to-all": "all_to_all",
}
_DONE_FOR = {start: start[: -len("start")] + "done" for start in _ASYNC_START}

# ops that move/rename data rather than compute — sitting between a start and
# its done, they hide no communication latency
_NON_COMPUTE = {
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "bitcast-convert", "copy", "copy-start", "copy-done", "reshape",
    "broadcast", "iota", "convert", "transpose", "slice", "concatenate",
    "after-all", "partition-id", "replica-id", "domain", "opt-barrier",
    "send", "send-done", "recv", "recv-done", "infeed", "outfeed",
    "add-dependency",
}

_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w.-]+)\s*=\s*")
_OPERAND_RE = re.compile(r"%([\w.-]+)")


def _opcode_of(line: str) -> str:
    """Opcode of one HLO instruction line ('' when the line is not one).
    The result type may be a tuple with nested parens/spaces, so the type is
    skipped structurally, not by regex."""
    m = _DEF_RE.match(line)
    if not m:
        return ""
    rest = line[m.end():].lstrip()
    if rest.startswith("("):  # tuple result type: skip balanced parens
        depth = 0
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    rest = rest[i + 1:].lstrip()
                    break
        else:
            return ""
    else:  # scalar/array type: one whitespace-free token
        parts = rest.split(None, 1)
        if len(parts) < 2:
            return ""
        rest = parts[1]
    op = re.match(r"([\w-]+)\s*\(", rest)
    return op.group(1) if op else ""


def _operands_of(line: str) -> list[str]:
    """%names consumed by the instruction (everything after the opcode's
    opening paren — includes control deps, which is fine for tainting)."""
    m = _DEF_RE.match(line)
    if not m:
        return []
    paren = line.find("(", m.end())
    return _OPERAND_RE.findall(line[paren + 1:]) if paren != -1 else []


def _computations(text: str):
    """Yield lists of instruction lines, one per HLO computation."""
    current: list[str] = []
    for line in text.splitlines():
        stripped = line.strip()
        if stripped.endswith("{"):
            current = []
        elif stripped.startswith("}"):
            if current:
                yield current
            current = []
        elif " = " in stripped:
            current.append(stripped)
    if current:
        yield current


def collective_schedule(text: str) -> dict:
    """Classify every collective in a post-SPMD HLO text. Returns the
    schedule summary (see module docstring); ``collectives`` lists each op
    with its classification for the report's jsonl sink."""
    scheduled = "is_scheduled=true" in text
    ops: list[dict] = []
    for lines in _computations(text):
        # parse each line exactly once — the overlap walk below revisits
        # later instructions per collective, and a real overlap-heavy FSDP
        # module has hundreds of them over very long HLO texts
        defs = []
        for l in lines:
            m = _DEF_RE.match(l)
            if m is None:
                defs.append((None, l, "", ()))
            else:
                defs.append((m.group(1), l, _opcode_of(l), _operands_of(l)))
        index_of = {d[0]: i for i, d in enumerate(defs) if d[0] is not None}
        # "input-like" values exist (or are pure layout shuffles of values
        # that exist) before any compute runs: parameters, constants, and
        # data-movement chains over them. A collective depending only on
        # these is ready at t=0 wherever the list scheduler placed the defs.
        input_like: set[str] = set()
        for d_name, _d_line, d_op, d_oprs in defs:
            if d_name is None:
                continue
            if d_op in ("parameter", "constant", "iota"):
                input_like.add(d_name)
            elif d_op in _NON_COMPUTE and d_oprs and all(o in input_like for o in d_oprs):
                input_like.add(d_name)
        for idx, (name, line, opcode, my_operands) in enumerate(defs):
            if name is None:
                continue
            kind = _SYNC_OPS.get(opcode)
            if kind is not None:
                # ready-window walk (module docstring): ops between the
                # collective's last dependency and its first consumer that
                # are neither its ancestors nor its descendants can execute
                # while the transfer is in flight.
                overlap_ops = 0
                consumer_found = False
                if scheduled:
                    last_dep = max(
                        (
                            index_of[o]
                            for o in my_operands
                            if o in index_of and o not in input_like
                        ),
                        default=-1,
                    )
                    # ancestors: reverse transitive-dependency walk, so
                    # upstream producers inside the window are not credited
                    needed = set(my_operands)
                    ancestors: set[int] = set()
                    for j in range(idx - 1, last_dep, -1):
                        j_name = defs[j][0]
                        if j_name is not None and j_name in needed:
                            ancestors.add(j)
                            needed.update(defs[j][3])
                    # the consumer that ends the window is the first REAL
                    # dependent op: pure data movement (layout copies, the
                    # tuple feeding a while loop) extends the transfer chain
                    # and taints onward instead of closing the window
                    tainted = {name}
                    tainted_idx: set[int] = set()
                    consumer_idx = None
                    for j in range(idx + 1, len(defs)):
                        later_name, _l, later_opcode, operands = defs[j]
                        if later_name is None:
                            continue
                        if any(o in tainted for o in operands):
                            if later_opcode in _NON_COMPUTE:
                                tainted.add(later_name)
                                tainted_idx.add(j)
                                continue
                            consumer_idx = j
                            consumer_found = True
                            break
                    if consumer_found:
                        for j in range(last_dep + 1, consumer_idx):
                            if j == idx or j in ancestors or j in tainted_idx:
                                continue
                            j_opcode = defs[j][2]
                            if (
                                j_opcode
                                and j_opcode not in _NON_COMPUTE
                                and j_opcode not in _SYNC_OPS
                                and j_opcode not in _ASYNC_START
                                and not j_opcode.endswith("-done")
                            ):
                                overlap_ops += 1
                ops.append(
                    {
                        "kind": kind,
                        "name": name,
                        "bytes": sync_result_bytes(line),
                        "async": False,
                        # a never-consumed result feeds the output tuple: the
                        # NEXT program's first use is immediately behind it,
                        # so no overlap is credited for trailing collectives
                        "overlapped": consumer_found and overlap_ops > 0,
                        "overlap_compute_ops": overlap_ops if consumer_found else 0,
                    }
                )
                continue
            if opcode not in _ASYNC_START:
                continue
            done_op = _DONE_FOR[opcode]
            tainted = {name}
            overlap_ops = 0
            done_line = None
            for later_name, later_line, later_opcode, operands in defs[idx + 1:]:
                if later_name is None:
                    continue
                depends = any(o in tainted for o in operands)
                if later_opcode == done_op and name in operands:
                    done_line = later_line
                    break
                if depends:
                    tainted.add(later_name)
                elif (
                    later_opcode
                    and later_opcode not in _NON_COMPUTE
                    and later_opcode not in _SYNC_OPS
                    and later_opcode not in _ASYNC_START
                    and not later_opcode.endswith("-done")
                ):
                    overlap_ops += 1
            # a done's result is the received payload; combined dones are
            # tuple-typed, so sum like any sync result
            nbytes = sync_result_bytes(done_line) if done_line else 0
            if not nbytes:  # unmatched done (cross-computation): size the start
                nbytes = start_result_bytes(line)
            ops.append(
                {
                    "kind": _ASYNC_START[opcode],
                    "name": name,
                    "bytes": nbytes,
                    "async": True,
                    # an unmatched done (async-wrapped in another computation)
                    # means the walk saw the rest of the computation, not the
                    # start→done window — classify conservatively as
                    # serialized rather than crediting overlap never proven
                    "overlapped": done_line is not None and overlap_ops > 0,
                    "overlap_compute_ops": overlap_ops if done_line is not None else 0,
                }
            )

    per_kind: dict[str, dict] = {}
    serialized_bytes = 0
    overlapped_bytes = 0
    for op in ops:
        entry = per_kind.setdefault(
            op["kind"],
            {"count": 0, "bytes": 0, "overlapped_count": 0, "serialized_bytes": 0},
        )
        entry["count"] += 1
        entry["bytes"] += op["bytes"]
        if op["overlapped"]:
            entry["overlapped_count"] += 1
            overlapped_bytes += op["bytes"]
        else:
            entry["serialized_bytes"] += op["bytes"]
            serialized_bytes += op["bytes"]
    return {
        "scheduled": scheduled,
        "total_count": len(ops),
        "async_count": sum(1 for op in ops if op["async"]),
        "overlapped_count": sum(1 for op in ops if op["overlapped"]),
        "sync_overlapped_count": sum(
            1 for op in ops if op["overlapped"] and not op["async"]
        ),
        "serialized_count": sum(1 for op in ops if not op["overlapped"]),
        "overlapped_comm_bytes": overlapped_bytes,
        "serialized_comm_bytes": serialized_bytes,
        "per_kind": per_kind,
        # cap the per-op listing: a 60-collective program stays readable in
        # jsonl; the aggregates above are the diffed surface anyway
        "collectives": ops[:128],
    }


def schedule_audit(text: str, label: str = "program") -> tuple[list[Finding], dict]:
    """Run the schedule pass over one compiled program's HLO text."""
    summary = collective_schedule(text)
    findings: list[Finding] = []
    if summary["serialized_count"]:
        findings.append(
            Finding(
                "SERIALIZED_COLLECTIVE",
                f"{label}: {summary['serialized_count']} of "
                f"{summary['total_count']} collectives run serialized "
                f"({summary['serialized_comm_bytes'] / (1 << 20):.2f} MiB of "
                "comm on the critical path)",
                path=label,
                data={
                    "serialized_count": summary["serialized_count"],
                    "serialized_comm_bytes": summary["serialized_comm_bytes"],
                    "overlapped_count": summary["overlapped_count"],
                },
            )
        )
    return findings, summary
