"""Compiled-program audit: what the Python source cannot show.

Every performance promise the framework makes lives inside lowered and
compiled XLA programs that no amount of Python review can see: whether
``donate_argnums`` actually aliased (donation drops *silently* on shape or
sharding mismatch), whether a stray numpy scalar upcast the whole program to
f64, whether a closure baked a 100 MiB table into the executable, and —
after GSPMD propagation — which collectives the program really runs and
which parameters quietly resolved to full replication. This module reads
the ``jax.stages.Lowered``/``Compiled`` artifacts and turns those properties
into :class:`~.findings.Finding` records plus a diffable inventory.

Entry point: :func:`audit_lowered`. ``Accelerator.analyze`` and
``ServingEngine.analyze`` feed it their real step/decode programs.
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass
from typing import Any, Optional

from .findings import ERROR, INFO, WARNING, AnalysisReport, Finding

# -- type parsing (shared by StableHLO `tensor<4x4xf32>` and HLO `f32[4,4]`) --
#
# Sizes are BITS so the sub-byte quantized types size correctly (s4/i4 pack
# two elements per byte). The int8 serving path (`from_streamed` + on-device
# dequant) lowers to `tensor<...xi8>`/`tensor<...xui8>` in StableHLO and
# `s8[...]`/`u8[...]` in post-SPMD HLO — both spellings of both signednesses
# must parse, or int8 collectives and baked int8 tables vanish from the
# inventory (and from the contracts built on it).

_DTYPE_BITS = {
    "pred": 8, "i1": 8,  # XLA stores predicates one per byte
    "s2": 2, "u2": 2, "i2": 2, "ui2": 2,
    "s4": 4, "u4": 4, "i4": 4, "ui4": 4, "f4e2m1fn": 4,
    "s8": 8, "u8": 8, "i8": 8, "ui8": 8,
    "f8e4m3fn": 8, "f8e5m2": 8, "f8e4m3": 8, "f8e4m3b11fnuz": 8, "f8e5m2fnuz": 8,
    "f8e8m0fnu": 8,
    "s16": 16, "u16": 16, "i16": 16, "ui16": 16, "f16": 16, "bf16": 16,
    "s32": 32, "u32": 32, "i32": 32, "ui32": 32, "f32": 32,
    "s64": 64, "u64": 64, "i64": 64, "ui64": 64, "f64": 64, "c64": 64,
    "c128": 128,
}

_STABLEHLO_TYPE = re.compile(r"tensor<((?:[0-9]+x)*)([a-z][a-z0-9]*)>")
_HLO_TYPE = re.compile(r"\b([a-z][a-z0-9]{1,12})\[([0-9,]*)\]")


def _numel(dims: str, sep: str) -> int:
    n = 1
    for d in dims.split(sep):
        if d:
            n *= int(d)
    return n


def type_bytes(match: "re.Match", stablehlo: bool) -> Optional[int]:
    """Byte size of one parsed tensor type; None for unknown dtypes (tokens,
    tuples) so callers can skip rather than miscount. Sub-byte types round
    up to whole bytes per tensor (the packed buffer's footprint)."""
    dims, dtype = (match.group(1), match.group(2)) if stablehlo else (match.group(2), match.group(1))
    bits = _DTYPE_BITS.get(dtype)
    if bits is None:
        return None
    return -(-(_numel(dims, "x" if stablehlo else ",") * bits) // 8)


def _last_type_bytes(line: str) -> Optional[int]:
    """Byte size of the last tensor type on a line — for ops, that is the
    result type in both StableHLO (`... -> tensor<...>` / `: tensor<...>`)
    and HLO (`%x = f32[...] op(...)` puts the type first, so HLO callers
    should use :func:`_first_type_bytes` instead)."""
    matches = list(_STABLEHLO_TYPE.finditer(line))
    if matches:
        return type_bytes(matches[-1], stablehlo=True)
    matches = list(_HLO_TYPE.finditer(line))
    if matches:
        return type_bytes(matches[-1], stablehlo=False)
    return None


def _first_type_bytes(line: str) -> Optional[int]:
    m = _STABLEHLO_TYPE.search(line)
    if m:
        return type_bytes(m, stablehlo=True)
    m = _HLO_TYPE.search(line)
    if m:
        return type_bytes(m, stablehlo=False)
    return None


# -- argument metadata --------------------------------------------------------


@dataclass
class ArgLeaf:
    path: str
    shape: tuple
    dtype: str
    donated: bool

    @property
    def nbytes(self) -> int:
        n = 1
        for d in self.shape:
            n *= int(d)
        try:
            import numpy as np

            return n * np.dtype(self.dtype).itemsize
        except Exception:
            return n


def _keystr(path) -> str:
    import jax

    try:
        s = jax.tree_util.keystr(path)
    except Exception:
        s = "".join(str(p) for p in path)
    # "['params']['w']" -> "params/w", ".attr[0]" -> "attr/0"
    s = re.sub(r"\[['\"]?([^'\"\]]*)['\"]?\]", r"/\1", s).replace(".", "/")
    return s.strip("/") or "<arg>"


def flatten_args_info(lowered) -> list[ArgLeaf]:
    """Flatten ``Lowered.args_info`` (the (args, kwargs) pytree of ArgInfo)
    into path-labelled leaves — the analyzer's view of the program's inputs."""
    import jax

    leaves = []
    flat, _ = jax.tree_util.tree_flatten_with_path(
        lowered.args_info, is_leaf=lambda x: hasattr(x, "donated")
    )
    for path, info in flat:
        leaves.append(
            ArgLeaf(
                path=_keystr(path),
                shape=tuple(getattr(info, "shape", ())),
                dtype=str(getattr(info, "dtype", "")),
                donated=bool(getattr(info, "donated", False)),
            )
        )
    return leaves


# -- donation audit -----------------------------------------------------------


def _signature_alias_spans(text: str) -> Optional[list[bool]]:
    """Per-parameter "did the donation survive lowering" flags from the
    StableHLO main signature. jax emits one of two markers: ``tf.aliasing_
    output`` (aliasing resolved statically — single-device programs) or
    ``jax.buffer_donor`` (donation alive, pairing deferred to XLA — the mesh
    path). A donated parameter with *neither* was dropped at lowering (shape/
    dtype matched no output). Returns None when the signature cannot be
    delimited."""
    starts = []
    i = 0
    while True:
        pos = text.find(f"%arg{i}:")
        if pos == -1:
            break
        starts.append(pos)
        i += 1
    if not starts:
        return []
    end = text.find("->", starts[-1])
    if end == -1:
        return None
    flags = []
    for j, start in enumerate(starts):
        stop = starts[j + 1] if j + 1 < len(starts) else end
        span = text[start:stop]
        flags.append("tf.aliasing_output" in span or "jax.buffer_donor" in span)
    return flags


def _executable_alias_entries(compiled_text: str) -> Optional[int]:
    """Number of parameter→output aliases the backend actually kept, from the
    executable's ``input_output_alias={ {0}: (0, {}, may-alias), ... }``
    header (balanced-brace scan — entries contain nested braces)."""
    start = compiled_text.find("input_output_alias={")
    if start == -1:
        return None
    i = start + len("input_output_alias=")
    depth = 0
    end = i
    for end in range(i, min(len(compiled_text), i + 1_000_000)):
        ch = compiled_text[end]
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                break
    body = compiled_text[i:end + 1]
    return body.count("alias")  # may-alias | must-alias, one per entry


def donation_audit(
    lowered,
    compiled=None,
    label: str = "program",
    expect_donation: bool = True,
) -> tuple[list[Finding], dict]:
    """Verify declared ``donate_argnums`` actually alias outputs.

    Donation drops *silently*: a donated input whose shape/dtype/sharding
    matches no output keeps both buffers live (the exact HBM the caller
    thought they saved), and jax's only signal is a warning easily lost in
    startup noise. The lowered text is ground truth — jax annotates each
    donated parameter that survived aliasing with ``tf.aliasing_output`` —
    and the compiled executable's ``input_output_alias`` + memory analysis
    confirm what the backend kept.
    """
    leaves = flatten_args_info(lowered)
    donated = [l for l in leaves if l.donated]
    text = lowered.as_text()
    flags = _signature_alias_spans(text)
    lowered_alive = (
        sum(flags) if flags else text.count("tf.aliasing_output") + text.count("jax.buffer_donor")
    )
    summary: dict[str, Any] = {
        "declared": len(donated),
        "aliased": min(lowered_alive, len(donated)),
        "total_args": len(leaves),
        "declared_bytes": sum(l.nbytes for l in donated),
    }
    findings: list[Finding] = []
    if not donated:
        if expect_donation:
            findings.append(
                Finding(
                    "DONATION_NONE",
                    f"{label}: no buffers are donated — steady-state HBM holds "
                    "two copies of every state tensor",
                    path=label,
                )
            )
        return findings, summary

    if flags is not None and len(flags) == len(leaves):
        # 1:1 leaf↔parameter mapping (nothing was dropped as unused): name
        # exactly which donated leaf failed to alias
        for leaf, aliased in zip(leaves, flags):
            if leaf.donated and not aliased:
                findings.append(
                    Finding(
                        "DONATION_DROPPED",
                        f"{label}: donated buffer {leaf.path} "
                        f"({leaf.shape}, {leaf.dtype}, {leaf.nbytes / (1 << 20):.2f} MiB) "
                        "is not aliased to any output",
                        path=leaf.path,
                        data={"shape": list(leaf.shape), "dtype": leaf.dtype, "bytes": leaf.nbytes},
                    )
                )
    elif lowered_alive < len(donated):
        findings.append(
            Finding(
                "DONATION_DROPPED",
                f"{label}: only {lowered_alive} of {len(donated)} donated buffers "
                "survived lowering (argument mapping unavailable — some inputs "
                "were dropped as unused, itself a donation smell)",
                path=label,
                data={"declared": len(donated), "aliased": lowered_alive},
            )
        )

    if compiled is not None:
        # the executable is ground truth: `jax.buffer_donor` only means the
        # donation reached XLA — input_output_alias says what it actually kept
        comp_text = compiled.as_text() or ""
        exec_entries = _executable_alias_entries(comp_text)
        if exec_entries is not None:
            summary["executable_alias_entries"] = exec_entries
            summary["aliased"] = min(exec_entries, len(donated))
            if exec_entries < min(lowered_alive, len(donated)) and not findings:
                findings.append(
                    Finding(
                        "DONATION_DROPPED",
                        f"{label}: the executable aliased only {exec_entries} of "
                        f"{len(donated)} donated buffers (donation survived "
                        "lowering but XLA dropped it — typically an input/output "
                        "sharding or layout mismatch)",
                        path=label,
                        data={"declared": len(donated), "executable_aliases": exec_entries},
                    )
                )
        try:
            mem = compiled.memory_analysis()
            summary["alias_bytes"] = int(getattr(mem, "alias_size_in_bytes", 0))
            summary["argument_bytes"] = int(getattr(mem, "argument_size_in_bytes", 0))
            summary["output_bytes"] = int(getattr(mem, "output_size_in_bytes", 0))
            summary["temp_bytes"] = int(getattr(mem, "temp_size_in_bytes", 0))
        except Exception:
            pass
    return findings, summary


def donation_drop_warning(declared: int, aliased: int, backend: str) -> Optional[dict]:
    """The engine-side verdict on a first-compile donation consult: None when
    donation held (or none was declared), else a payload describing the drop.
    Pure so the silent-drop branch is unit-testable on any backend."""
    if declared == 0 or aliased >= declared:
        return None
    return {
        "event": "donation_dropped",
        "declared": declared,
        "aliased": aliased,
        "backend": backend,
        "message": (
            f"buffer donation silently dropped: {aliased}/{declared} donated "
            f"buffers aliased on {backend} — steady-state HBM holds both copies"
        ),
    }


# -- dtype / constant audits --------------------------------------------------

_WIDE_TYPES = ("f64", "c128")


def dtype_audit(text: str, label: str = "program", allow_fp64: bool = False) -> list[Finding]:
    """Flag f64/c128 leaks: one stray numpy scalar (np defaults to float64)
    upcasts whole subgraphs, and TPUs emulate f64 at ~1/10 throughput."""
    findings = []
    for wide in _WIDE_TYPES:
        count = len(re.findall(rf"(?:tensor<[0-9x]*{wide}>|\b{wide}\[)", text))
        if count:
            findings.append(
                Finding(
                    "FP64_LEAK",
                    f"{label}: {count} {wide} tensors in the lowered program",
                    severity=INFO if allow_fp64 else ERROR,
                    path=label,
                    data={"dtype": wide, "count": count},
                )
            )
    return findings


def constant_audit(
    text: str, label: str = "program", threshold_bytes: int = 1 << 20
) -> list[Finding]:
    """Flag large constants baked into the program (a closure-captured array
    becomes part of the executable: re-uploaded per recompile, never donated,
    duplicated per program that closes over it)."""
    findings = []
    total = 0
    largest = 0
    count = 0
    for line in text.splitlines():
        if "stablehlo.constant" in line or re.search(r"\bconstant\(", line):
            nbytes = _first_type_bytes(line) if "stablehlo" not in line else _last_type_bytes(line)
            if nbytes is None:
                continue
            total += nbytes
            largest = max(largest, nbytes)
            if nbytes >= threshold_bytes:
                count += 1
    if count:
        findings.append(
            Finding(
                "LARGE_CONSTANT",
                f"{label}: {count} constants >= {threshold_bytes / (1 << 20):.0f} MiB "
                f"baked into the program (largest {largest / (1 << 20):.1f} MiB, "
                f"total constant bytes {total / (1 << 20):.1f} MiB)",
                path=label,
                data={"count": count, "largest_bytes": largest, "total_bytes": total},
            )
        )
    return findings


# -- collective inventory -----------------------------------------------------

# canonical kind -> (stablehlo op substrings, HLO op substrings)
_COLLECTIVES = {
    "all_reduce": (("stablehlo.all_reduce",), ("all-reduce(", "all-reduce-start(")),
    "all_gather": (("stablehlo.all_gather",), ("all-gather(", "all-gather-start(")),
    "reduce_scatter": (
        ("stablehlo.reduce_scatter",),
        ("reduce-scatter(", "reduce-scatter-start("),
    ),
    "collective_permute": (
        ("stablehlo.collective_permute",),
        ("collective-permute(", "collective-permute-start("),
    ),
    "all_to_all": (("stablehlo.all_to_all",), ("all-to-all(", "all-to-all-start(")),
}

_INSTR_PREFIX_RE = re.compile(r"^\s*(?:ROOT\s+)?%[\w.-]+\s*=\s*")


def _result_type_sizes(line: str) -> list[int]:
    """Byte sizes of the types in an HLO instruction's RESULT region — the
    single token after ``=`` for plain results, the balanced-paren prefix for
    tuple results (async starts, combined sync collectives)."""
    m = _INSTR_PREFIX_RE.match(line)
    region = line[m.end():] if m else line
    paren = region.find("(")
    space = region.find(" ")
    if paren != -1 and (space == -1 or paren < space):
        depth, end = 0, -1
        for i, ch in enumerate(region):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        if end != -1:
            region = region[: end + 1]
    else:
        region = region.split(None, 1)[0]
    sizes = [type_bytes(t, True) for t in _STABLEHLO_TYPE.finditer(region)]
    sizes += [type_bytes(t, False) for t in _HLO_TYPE.finditer(region)]
    return [s for s in sizes if s is not None]


def start_result_bytes(line: str) -> int:
    """Byte size of an async START op's result — the payload in flight. Real
    XLA starts are tuple-typed ``(operand_type, result_type, ...)``, so the
    FIRST type on the line is the (smaller, for all-gather) input shape; take
    the largest type in the result region instead. Falls back to the first
    parseable type for non-tuple spellings."""
    sizes = _result_type_sizes(line)
    if sizes:
        return max(sizes)
    return _first_type_bytes(line) or 0


def sync_result_bytes(line: str) -> int:
    """Byte size of a SYNC collective's result. XLA's combiner passes emit
    tuple-typed combined ops (``(f32[1000], f32[2000]) all-reduce(%a, %b)``)
    whose total payload is the SUM of the elements — first-type sizing would
    undercount every combined collective."""
    sizes = _result_type_sizes(line)
    if sizes:
        return sum(sizes)
    return _first_type_bytes(line) or 0


def collective_inventory(text: str) -> dict[str, dict]:
    """Count + size every cross-device collective in a program text (HLO or
    StableHLO). Bytes are the op result size — the payload that rides the
    interconnect — so a sharding regression (e.g. a new all-gather of a full
    parameter) shows up as a diffable number, not a vibe. Async start ops
    count once (the done is a different opcode) and size from the start's
    tuple RESULT, not its operand."""
    out: dict[str, dict] = {}
    for line in text.splitlines():
        for kind, (shlo_pats, hlo_pats) in _COLLECTIVES.items():
            if any(p in line for p in shlo_pats):
                nbytes = _last_type_bytes(line) or 0
            elif any(p in line for p in hlo_pats):
                nbytes = (
                    start_result_bytes(line)
                    if "-start(" in line
                    else sync_result_bytes(line)
                ) or 0
            else:
                continue
            entry = out.setdefault(kind, {"count": 0, "bytes": 0})
            entry["count"] += 1
            entry["bytes"] += nbytes
            break
    return out


# -- sharding / replication audit --------------------------------------------


def replication_audit(
    lowered,
    compiled,
    label: str = "program",
    threshold_bytes: int = 1 << 20,
    sharded_intent: bool = False,
) -> tuple[list[Finding], dict]:
    """Flag inputs above ``threshold_bytes`` whose sharding resolved to full
    replication on a multi-device mesh. GSPMD propagates shardings
    non-locally: one missing annotation replicates a tensor on every device
    with no error anywhere (arXiv:2105.04663 §3.3) — the expensive failure
    mode the Python source cannot show. With ``sharded_intent`` (the caller
    configured model sharding, or the default ZeRO update sharding is
    active) these are ERRORs — for a train step the inputs include the
    optimizer state, so "the moments quietly went replicated again" is an
    asserted failure, not an inventory line (tests/test_zero.py seeds that
    regression). Without declared intent they are inventory (INFO) so the
    report still diffs when a config regresses."""
    import jax

    leaves = flatten_args_info(lowered)
    findings: list[Finding] = []
    summary = {"replicated_large_params": 0, "replicated_bytes": 0}
    try:
        in_shardings = compiled.input_shardings
    except Exception:
        return findings, summary
    sharding_leaves = jax.tree_util.tree_leaves(
        in_shardings, is_leaf=lambda x: isinstance(x, jax.sharding.Sharding)
    )
    if len(sharding_leaves) != len(leaves):
        return findings, summary  # unused-arg dropping broke the 1:1 map
    for leaf, sharding in zip(leaves, sharding_leaves):
        if leaf.nbytes < threshold_bytes:
            continue
        try:
            multi_device = len(sharding.device_set) > 1
            replicated = sharding.is_fully_replicated
        except Exception:
            continue
        if multi_device and replicated:
            summary["replicated_large_params"] += 1
            summary["replicated_bytes"] += leaf.nbytes
            findings.append(
                Finding(
                    "REPLICATED_PARAM" if sharded_intent else "REPLICATED_PARAM_INFO",
                    f"{label}: {leaf.path} ({leaf.nbytes / (1 << 20):.1f} MiB) resolved "
                    f"to full replication over {len(sharding.device_set)} devices",
                    path=leaf.path,
                    data={"bytes": leaf.nbytes, "devices": len(sharding.device_set)},
                )
            )
    return findings, summary


# -- the orchestrator ---------------------------------------------------------


def audit_lowered(
    lowered,
    *,
    compiled=None,
    compile: bool = True,
    label: str = "program",
    sharded_intent: bool = False,
    allow_fp64: bool = False,
    expect_donation: bool = True,
    constant_threshold_bytes: int = 1 << 20,
    replication_threshold_bytes: int = 1 << 20,
    hbm_budget_bytes: Optional[int] = None,
    temp_blowup_factor: Optional[float] = None,
) -> AnalysisReport:
    """Run every program pass over one ``jax.stages.Lowered``.

    With ``compile=True`` (or a pre-built ``compiled``), the post-SPMD
    executable feeds the collective inventory, the executable-level alias
    table, the replication audit, the HBM memory audit (memory.py), and the
    collective-overlap schedule pass (schedule.py) — the properties GSPMD
    only decides at compile time. ``compile=False`` keeps the audit
    trace-only (donation declaration, dtype, constants) for callers who
    cannot afford a second XLA compile. ``hbm_budget_bytes`` arms the
    ``HBM_OVER_BUDGET`` gate on the peak-HBM estimate.
    """
    import jax

    report = AnalysisReport()
    t0 = time.perf_counter()
    text = lowered.as_text()
    report.extend(dtype_audit(text, label=label, allow_fp64=allow_fp64))
    report.extend(constant_audit(text, label=label, threshold_bytes=constant_threshold_bytes))
    inventory: dict[str, Any] = {}

    compile_s = None
    if compiled is None and compile:
        t_c = time.perf_counter()
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t_c
    # ONE donation audit, with the executable when available: it carries both
    # the lowering-level findings and the executable-level drop (XLA keeping
    # fewer aliases than survived lowering) — both must reach the report
    findings, donation_summary = donation_audit(
        lowered, compiled=compiled, label=label, expect_donation=expect_donation
    )
    report.extend(findings)
    inventory["donation"] = donation_summary
    if compiled is not None:
        comp_text = compiled.as_text() or ""
        inventory["collectives"] = collective_inventory(comp_text)
        repl_findings, repl_summary = replication_audit(
            lowered,
            compiled,
            label=label,
            threshold_bytes=replication_threshold_bytes,
            sharded_intent=sharded_intent,
        )
        report.extend(repl_findings)
        inventory["replication"] = repl_summary
        # the executable-only passes (lazy imports: schedule.py imports this
        # module's type parsers, so the dependency must point one way)
        from .memory import DEFAULT_TEMP_BLOWUP_FACTOR, memory_audit
        from .schedule import schedule_audit

        mem_findings, mem_summary = memory_audit(
            compiled,
            label=label,
            hbm_budget_bytes=hbm_budget_bytes,
            temp_blowup_factor=(
                DEFAULT_TEMP_BLOWUP_FACTOR
                if temp_blowup_factor is None
                else temp_blowup_factor
            ),
        )
        report.extend(mem_findings)
        if mem_summary:
            inventory["memory"] = mem_summary
        sched_findings, sched_summary = schedule_audit(comp_text, label=label)
        report.extend(sched_findings)
        inventory["schedule"] = sched_summary
    else:
        # pre-partitioning StableHLO only names collectives the user wrote
        # explicitly (shard_map); GSPMD's inserted ones need the executable
        inventory["collectives"] = collective_inventory(text)

    report.inventory = inventory
    report.meta = {
        "label": label,
        "backend": jax.default_backend(),
        "num_devices": jax.device_count(),
        "compiled": compiled is not None,
        "analysis_seconds": round(time.perf_counter() - t0, 4),
    }
    if compile_s is not None:
        report.meta["compile_seconds"] = round(compile_s, 4)
    return report
