"""Source lint: Python-side hazards inside traced (jit) code.

The program audit sees what XLA compiled; this pass sees what XLA will
*never* see — the Python that runs once at trace time and silently bakes a
wrong constant into every subsequent step. ``time.time()`` freezes to the
trace timestamp, ``np.random`` draws once, ``.item()`` raises (or syncs),
``results.append(...)`` fires exactly once, and ``if traced_value:`` either
raises or specializes one branch forever.

Scope: functions the AST can see entering a traced context —

- decorated with ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)``;
- passed by name (or as an inline lambda) to a traced-context wrapper:
  ``jax.jit``, ``value_and_grad``/``grad``, ``vmap``/``pmap``,
  ``checkpoint``/``remat``, ``lax.scan``/``cond``/``while_loop``/``fori_loop``,
  and this repo's ``accelerator.compiled_step``/``accelerator.backward``;
- any function/lambda nested inside one of the above (nested defs trace too).

Waivers: a trailing ``# accel-lint: disable=CODE[,CODE]`` comment waives that
line; on a ``def`` line it waives the whole function. ``disable=all`` waives
every code. Waivers are the commit-reviewed escape hatch — the CI gate
(tests/test_analysis.py) runs this lint over ``accelerate_tpu/`` and
``examples/`` and fails on any *unwaived* finding.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Optional

from .findings import AnalysisReport, Finding

PRAGMA_RE = re.compile(r"#\s*accel-lint:\s*disable=([A-Za-z0-9_,\s]+)")

# names that put their function-valued arguments into a traced context
TRACE_WRAPPERS = {
    "jit", "value_and_grad", "grad", "vmap", "pmap", "checkpoint", "remat",
    "scan", "cond", "while_loop", "fori_loop", "switch",
    "compiled_step", "backward",
}
_TIME_CALLS = {"time", "time_ns", "perf_counter", "perf_counter_ns", "monotonic",
               "monotonic_ns", "process_time", "clock"}
_DATETIME_CALLS = {"now", "utcnow", "today"}
_SYNC_METHODS = {"item", "tolist"}
_SYNC_NP_CALLS = {"asarray", "array", "copy"}
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear", "update",
             "add", "discard", "setdefault", "popitem"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}


def _callable_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _attr_chain(node: ast.AST) -> list[str]:
    """``np.random.uniform`` -> ["np", "random", "uniform"]."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return list(reversed(parts))


def _is_jit_like(node: ast.AST) -> bool:
    """jax.jit / jit / partial(jax.jit, ...) / jax.jit(...) factory form."""
    if _callable_name(node) == "jit":
        return True
    if isinstance(node, ast.Call):
        fname = _callable_name(node.func)
        if fname == "partial" and node.args and _is_jit_like(node.args[0]):
            return True
        if fname == "jit":
            return True
    return False


_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)


class _Linter:
    def __init__(self, tree: ast.Module, source: str, filename: str):
        self.tree = tree
        self.filename = filename
        self.lines = source.splitlines()
        self.waivers = self._collect_waivers()
        # name -> defs with that name anywhere in the file (over-approximate:
        # per-file scoping is enough for lint, and a false mark only means a
        # non-traced function gets held to traced standards — waivable)
        self.defs_by_name: dict[str, list] = {}
        # names bound to jax.random in this file (`from jax import random`,
        # `import jax.random as jrandom`): the canonical keyed-RNG idiom,
        # which the host-RNG check must never flag
        self.jax_random_aliases: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, _FuncNode):
                self.defs_by_name.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.ImportFrom) and node.module == "jax":
                for alias in node.names:
                    if alias.name == "random":
                        self.jax_random_aliases.add(alias.asname or "random")
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "jax.random" and alias.asname:
                        self.jax_random_aliases.add(alias.asname)
        self.traced_roots: list = []
        self.findings: list[Finding] = []
        self._seen: set[tuple] = set()

    # -- waivers -----------------------------------------------------------

    def _collect_waivers(self) -> dict[int, set]:
        waivers: dict[int, set] = {}
        for i, line in enumerate(self.lines, start=1):
            m = PRAGMA_RE.search(line)
            if m:
                codes = {c.strip().upper() for c in m.group(1).split(",") if c.strip()}
                waivers[i] = codes
        return waivers

    def _waived(self, code: str, lineno: int, root) -> bool:
        for line in (lineno, getattr(root, "lineno", None)):
            if line is None:
                continue
            codes = self.waivers.get(line)
            if codes and (code in codes or "ALL" in codes):
                return True
        return False

    # -- traced-root discovery ---------------------------------------------

    def _mark(self, node: ast.AST) -> None:
        """Mark a function-valued expression (Name / Attribute / Lambda /
        IfExp of those) as entering a traced context."""
        if isinstance(node, ast.Lambda):
            self.traced_roots.append(node)
        elif isinstance(node, ast.IfExp):
            self._mark(node.body)
            self._mark(node.orelse)
        else:
            name = _callable_name(node)
            if name:
                self.traced_roots.extend(self.defs_by_name.get(name, ()))

    def discover(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, _FuncNode):
                for decorator in node.decorator_list:
                    if _is_jit_like(decorator):
                        self.traced_roots.append(node)
            if isinstance(node, ast.Call):
                fname = _callable_name(node.func)
                if isinstance(node.func, ast.Call) and _is_jit_like(node.func):
                    # `jax.jit(fn, ...)(data...)`: the inner factory call
                    # already received the function — the OUTER args are data.
                    # `jax.jit(static_argnums=...)(fn)` / `partial(jax.jit)(fn)`
                    # pass no positional fn to the factory, so the outer arg
                    # IS the function.
                    inner = node.func
                    positional = [
                        a for a in inner.args
                        if not (_callable_name(inner.func) == "partial" and a is inner.args[0])
                    ]
                    if positional:
                        continue
                if _is_jit_like(node.func) or fname in TRACE_WRAPPERS:
                    for arg in node.args:
                        self._mark(arg)
                    for kw in node.keywords:
                        if kw.arg in ("body_fun", "cond_fun", "f", "fun", "loss_fn"):
                            self._mark(kw.value)
        # dedupe while preserving order
        seen: set[int] = set()
        unique = []
        for root in self.traced_roots:
            if id(root) not in seen:
                seen.add(id(root))
                unique.append(root)
        self.traced_roots = unique

    # -- hazard checks ------------------------------------------------------

    def _add(self, code: str, lineno: int, message: str, root, severity: str = "") -> None:
        key = (self.filename, lineno, code)
        if key in self._seen or self._waived(code, lineno, root):
            return
        self._seen.add(key)
        self.findings.append(
            Finding(code, message, severity=severity, path=f"{self.filename}:{lineno}")
        )

    @staticmethod
    def _subtree_params(root) -> set:
        """Parameter names of the root and every nested function — all of
        them hold traced values when the root runs under jit."""
        params: set[str] = set()
        for node in ast.walk(root):
            if isinstance(node, (*_FuncNode, ast.Lambda)):
                a = node.args
                for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs):
                    params.add(arg.arg)
                for arg in (a.vararg, a.kwarg):
                    if arg is not None:
                        params.add(arg.arg)
        return params

    @staticmethod
    def _bound_names(root) -> set:
        """Names bound (assigned / defined / comprehension targets) anywhere
        in the subtree — mutating THESE is function-local, not captured."""
        bound = _Linter._subtree_params(root)
        for node in ast.walk(root):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for target in targets:
                    for sub in ast.walk(target):
                        if isinstance(sub, ast.Name):
                            bound.add(sub.id)
            elif isinstance(node, (ast.For, ast.comprehension)):
                for sub in ast.walk(node.target):
                    if isinstance(sub, ast.Name):
                        bound.add(sub.id)
            elif isinstance(node, _FuncNode):
                bound.add(node.name)
            elif isinstance(node, ast.withitem) and node.optional_vars is not None:
                for sub in ast.walk(node.optional_vars):
                    if isinstance(sub, ast.Name):
                        bound.add(sub.id)
        return bound

    def _branch_names(self, test: ast.AST) -> set:
        """Names in a branch test that would make it data-dependent —
        excluding statically-safe forms: ``x is (not) None``, ``isinstance/
        hasattr/callable/len(...)``, and ``.shape``/``.ndim``/``.dtype``
        accesses (all trace-time constants)."""
        skip: set[int] = set()
        for node in ast.walk(test):
            if isinstance(node, ast.Compare):
                operands = [node.left, *node.comparators]
                if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) and any(
                    isinstance(c, ast.Constant) and c.value is None for c in operands
                ):
                    for sub in operands:
                        for s in ast.walk(sub):
                            skip.add(id(s))
            elif isinstance(node, ast.Call):
                if _callable_name(node.func) in {"isinstance", "hasattr", "callable", "len", "getattr"}:
                    for sub in ast.walk(node):
                        skip.add(id(sub))
            elif isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
                for sub in ast.walk(node):
                    skip.add(id(sub))
        names = set()
        for node in ast.walk(test):
            if isinstance(node, ast.Name) and id(node) not in skip:
                names.add(node.id)
        return names

    def check_root(self, root) -> None:
        params = self._subtree_params(root)
        bound = self._bound_names(root)
        # a mutator call whose result is consumed (`updates, st = tx.update(...)`)
        # is functional API use, not mutation — only bare statements count
        statement_calls = {
            id(stmt.value)
            for stmt in ast.walk(root)
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)
        }
        for node in ast.walk(root):
            lineno = getattr(node, "lineno", getattr(root, "lineno", 1))
            if isinstance(node, (ast.If, ast.While)):
                traced = self._branch_names(node.test) & params
                if traced:
                    kind = "while" if isinstance(node, ast.While) else "if"
                    self._add(
                        "TRACED_BRANCH", node.lineno,
                        f"python `{kind}` on possibly-traced value(s) "
                        f"{sorted(traced)} inside jit-traced code",
                        root,
                    )
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                self._add(
                    "CAPTURED_MUTATION", lineno,
                    f"{'global' if isinstance(node, ast.Global) else 'nonlocal'} "
                    f"{', '.join(node.names)} inside jit-traced code mutates "
                    "host state at trace time only",
                    root,
                )
            elif isinstance(node, ast.Call):
                self._check_call(node, root, bound, statement_calls)

    def _check_call(self, node: ast.Call, root, bound: set, statement_calls: set) -> None:
        chain = _attr_chain(node.func)
        lineno = node.lineno
        name = _callable_name(node.func)
        if not chain:
            chain = [name] if name else []
        base = chain[0] if chain else None
        # wall clock
        if (base == "time" and chain[-1] in _TIME_CALLS) or (
            base in ("datetime", "dt") and chain[-1] in _DATETIME_CALLS
        ):
            self._add(
                "HOST_TIME", lineno,
                f"{'.'.join(chain)}() inside jit-traced code is a trace-time "
                "constant, not a per-step clock",
                root,
            )
        # host RNG (names bound to jax.random are the fix, not the hazard)
        elif (
            base == "random" and len(chain) > 1 and base not in self.jax_random_aliases
        ) or (
            base in ("np", "numpy", "onp") and len(chain) > 2 and chain[1] == "random"
        ):
            self._add(
                "HOST_RANDOM", lineno,
                f"{'.'.join(chain)}() inside jit-traced code draws once at "
                "trace time — thread a jax.random key instead",
                root,
            )
        # host materialization
        elif name in _SYNC_METHODS and isinstance(node.func, ast.Attribute):
            self._add(
                "LINT_HOST_SYNC", lineno,
                f".{name}() inside jit-traced code raises on a tracer (and "
                "host-syncs when leaked outside)",
                root,
            )
        elif base in ("np", "numpy", "onp") and len(chain) == 2 and chain[1] in _SYNC_NP_CALLS:
            self._add(
                "LINT_HOST_SYNC", lineno,
                f"{'.'.join(chain)}() inside jit-traced code materializes on "
                "host — use jnp",
                root,
            )
        elif chain[-2:] == ["jax", "device_get"] or (name == "device_get" and base == "jax"):
            self._add(
                "LINT_HOST_SYNC", lineno,
                "jax.device_get() inside jit-traced code",
                root,
            )
        elif name in ("float", "int", "bool") and isinstance(node.func, ast.Name) and node.args:
            if isinstance(node.args[0], (ast.Name, ast.Attribute, ast.Call)):
                self._add(
                    "HOST_CAST", lineno,
                    f"{name}(...) inside jit-traced code raises on a traced "
                    "array (waive if the value is a static Python scalar)",
                    root,
                )
        elif name == "print" and isinstance(node.func, ast.Name):
            self._add(
                "TRACE_PRINT", lineno,
                "print() inside jit-traced code runs at trace time only — "
                "use jax.debug.print for per-step values",
                root,
            )
        # mutating method on a captured (non-locally-bound) object — only as
        # a bare statement: a consumed result (optax's `tx.update(...)`) is
        # functional API use
        elif (
            name in _MUTATORS
            and id(node) in statement_calls
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id not in bound
        ):
            self._add(
                "CAPTURED_MUTATION_CALL", lineno,
                f"{node.func.value.id}.{name}(...) mutates captured state at "
                "trace time only",
                root,
            )

    def run(self) -> list[Finding]:
        self.discover()
        for root in self.traced_roots:
            self.check_root(root)
        self.findings.sort(key=lambda f: f.path or "")
        return self.findings


# -- public API ---------------------------------------------------------------


def lint_source(source: str, filename: str = "<string>") -> list[Finding]:
    """Lint one source string; returns unwaived findings."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [
            Finding(
                "PARSE_ERROR", f"could not parse {filename}: {e}",
                path=f"{filename}:{e.lineno or 1}",
            )
        ]
    return _Linter(tree, source, filename).run()


def lint_file(path: str) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), filename=path)


_EXCLUDE_DIRS = {"__pycache__", ".git", ".pytest_cache", "build", "dist"}


def iter_python_files(paths: Iterable[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
        elif os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames if d not in _EXCLUDE_DIRS]
                for filename in sorted(filenames):
                    if filename.endswith(".py"):
                        yield os.path.join(dirpath, filename)


def lint_paths(paths: Iterable[str]) -> AnalysisReport:
    """Lint every ``.py`` under the given files/directories. The report's
    inventory counts files scanned and traced functions found."""
    report = AnalysisReport(meta={"label": "lint"})
    files = 0
    for path in iter_python_files(paths):
        files += 1
        report.extend(lint_file(path))
    report.inventory = {"files_scanned": files, "findings": len(report.findings)}
    return report
