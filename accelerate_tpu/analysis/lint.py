"""Source lint: Python-side hazards inside traced (jit) code.

The program audit sees what XLA compiled; this pass sees what XLA will
*never* see — the Python that runs once at trace time and silently bakes a
wrong constant into every subsequent step. ``time.time()`` freezes to the
trace timestamp, ``np.random`` draws once, ``.item()`` raises (or syncs),
``results.append(...)`` fires exactly once, and ``if traced_value:`` either
raises or specializes one branch forever.

Scope: functions the AST can see entering a traced context —

- decorated with ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)``;
- passed by name (or as an inline lambda) to a traced-context wrapper:
  ``jax.jit``, ``value_and_grad``/``grad``, ``vmap``/``pmap``,
  ``checkpoint``/``remat``, ``lax.scan``/``cond``/``while_loop``/``fori_loop``,
  and this repo's ``accelerator.compiled_step``/``accelerator.backward``;
- any function/lambda nested inside one of the above (nested defs trace too).

A second, module-wide family of rules covers host-side *concurrency*
hazards (no traced context required): bare ``lock.acquire()`` without
try/finally, blocking calls lexically inside a ``with <lock>:`` body,
``threading.Thread`` targets mutating attributes also written unguarded
elsewhere in the class, mutable buffer views passed to async jit dispatch,
and raw ``threading.Lock()`` constructions that bypass the
``analysis.concurrency.named_lock`` registry.

Waivers: a trailing ``# accel-lint: disable=<CODE>[,<CODE>]`` comment waives
that line; on a ``def`` line it waives the whole function. ``disable=all``
waives every code. Waivers are the commit-reviewed escape hatch — the CI
gate (tests/test_analysis.py) runs this lint over ``accelerate_tpu/`` and
``examples/`` and fails on any *unwaived* finding — and they are audited:
a pragma that suppresses nothing reports ``LINT_WAIVER_UNUSED`` so a stale
waiver can't silently mask the next regression at that line.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Optional

from .findings import AnalysisReport, Finding

PRAGMA_RE = re.compile(r"#\s*accel-lint:\s*disable=([A-Za-z0-9_,\s]+)")

# names that put their function-valued arguments into a traced context
TRACE_WRAPPERS = {
    "jit", "value_and_grad", "grad", "vmap", "pmap", "checkpoint", "remat",
    "scan", "cond", "while_loop", "fori_loop", "switch",
    "compiled_step", "backward",
}
_TIME_CALLS = {"time", "time_ns", "perf_counter", "perf_counter_ns", "monotonic",
               "monotonic_ns", "process_time", "clock"}
_DATETIME_CALLS = {"now", "utcnow", "today"}
_SYNC_METHODS = {"item", "tolist"}
_SYNC_NP_CALLS = {"asarray", "array", "copy"}
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear", "update",
             "add", "discard", "setdefault", "popitem"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}

# the concurrency rule family (`accelerate-tpu analyze --races`): module-wide
# host-threading hazards, not scoped to traced roots
CONCURRENCY_LINT_CODES = {
    "LOCK_BARE_ACQUIRE",
    "LOCK_BLOCKING_CALL",
    "THREAD_SHARED_MUTATION",
    "ASYNC_NP_VIEW",
    "LOCK_UNREGISTERED",
}
# a with-item whose terminal name matches this is treated as a lock guard
_LOCKISH_RE = re.compile(r"lock|mutex", re.IGNORECASE)


def _callable_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _attr_chain(node: ast.AST) -> list[str]:
    """``np.random.uniform`` -> ["np", "random", "uniform"]."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return list(reversed(parts))


def _is_jit_like(node: ast.AST) -> bool:
    """jax.jit / jit / partial(jax.jit, ...) / jax.jit(...) factory form."""
    if _callable_name(node) == "jit":
        return True
    if isinstance(node, ast.Call):
        fname = _callable_name(node.func)
        if fname == "partial" and node.args and _is_jit_like(node.args[0]):
            return True
        if fname == "jit":
            return True
    return False


_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)


class _Linter:
    def __init__(self, tree: ast.Module, source: str, filename: str):
        self.tree = tree
        self.filename = filename
        self.lines = source.splitlines()
        self.waivers = self._collect_waivers()
        # name -> defs with that name anywhere in the file (over-approximate:
        # per-file scoping is enough for lint, and a false mark only means a
        # non-traced function gets held to traced standards — waivable)
        self.defs_by_name: dict[str, list] = {}
        # names bound to jax.random in this file (`from jax import random`,
        # `import jax.random as jrandom`): the canonical keyed-RNG idiom,
        # which the host-RNG check must never flag
        self.jax_random_aliases: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, _FuncNode):
                self.defs_by_name.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.ImportFrom) and node.module == "jax":
                for alias in node.names:
                    if alias.name == "random":
                        self.jax_random_aliases.add(alias.asname or "random")
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "jax.random" and alias.asname:
                        self.jax_random_aliases.add(alias.asname)
        self.traced_roots: list = []
        self.findings: list[Finding] = []
        self._seen: set[tuple] = set()
        # pragma lines that actually suppressed a finding — the rest are
        # stale and report LINT_WAIVER_UNUSED at the end of the run
        self.used_waiver_lines: set[int] = set()
        # names assigned from named_lock(...) count as lockish even when the
        # variable name itself doesn't say so
        self._named_lock_names: set[str] = set()
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _callable_name(node.value.func) == "named_lock"
            ):
                for target in node.targets:
                    term = self._terminal_name(target)
                    if term:
                        self._named_lock_names.add(term)

    # -- waivers -----------------------------------------------------------

    def _collect_waivers(self) -> dict[int, set]:
        waivers: dict[int, set] = {}
        for i, line in enumerate(self.lines, start=1):
            m = PRAGMA_RE.search(line)
            if m:
                codes = {c.strip().upper() for c in m.group(1).split(",") if c.strip()}
                waivers[i] = codes
        return waivers

    def _waived(self, code: str, lineno: int, root) -> bool:
        for line in (lineno, getattr(root, "lineno", None)):
            if line is None:
                continue
            codes = self.waivers.get(line)
            if codes and (code in codes or "ALL" in codes):
                self.used_waiver_lines.add(line)
                return True
        return False

    # -- traced-root discovery ---------------------------------------------

    def _mark(self, node: ast.AST) -> None:
        """Mark a function-valued expression (Name / Attribute / Lambda /
        IfExp of those) as entering a traced context."""
        if isinstance(node, ast.Lambda):
            self.traced_roots.append(node)
        elif isinstance(node, ast.IfExp):
            self._mark(node.body)
            self._mark(node.orelse)
        else:
            name = _callable_name(node)
            if name:
                self.traced_roots.extend(self.defs_by_name.get(name, ()))

    def discover(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, _FuncNode):
                for decorator in node.decorator_list:
                    if _is_jit_like(decorator):
                        self.traced_roots.append(node)
            if isinstance(node, ast.Call):
                fname = _callable_name(node.func)
                if isinstance(node.func, ast.Call) and _is_jit_like(node.func):
                    # `jax.jit(fn, ...)(data...)`: the inner factory call
                    # already received the function — the OUTER args are data.
                    # `jax.jit(static_argnums=...)(fn)` / `partial(jax.jit)(fn)`
                    # pass no positional fn to the factory, so the outer arg
                    # IS the function.
                    inner = node.func
                    positional = [
                        a for a in inner.args
                        if not (_callable_name(inner.func) == "partial" and a is inner.args[0])
                    ]
                    if positional:
                        continue
                if _is_jit_like(node.func) or fname in TRACE_WRAPPERS:
                    for arg in node.args:
                        self._mark(arg)
                    for kw in node.keywords:
                        if kw.arg in ("body_fun", "cond_fun", "f", "fun", "loss_fn"):
                            self._mark(kw.value)
        # dedupe while preserving order
        seen: set[int] = set()
        unique = []
        for root in self.traced_roots:
            if id(root) not in seen:
                seen.add(id(root))
                unique.append(root)
        self.traced_roots = unique

    # -- hazard checks ------------------------------------------------------

    def _add(self, code: str, lineno: int, message: str, root, severity: str = "") -> None:
        key = (self.filename, lineno, code)
        if key in self._seen or self._waived(code, lineno, root):
            return
        self._seen.add(key)
        self.findings.append(
            Finding(code, message, severity=severity, path=f"{self.filename}:{lineno}")
        )

    @staticmethod
    def _subtree_params(root) -> set:
        """Parameter names of the root and every nested function — all of
        them hold traced values when the root runs under jit."""
        params: set[str] = set()
        for node in ast.walk(root):
            if isinstance(node, (*_FuncNode, ast.Lambda)):
                a = node.args
                for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs):
                    params.add(arg.arg)
                for arg in (a.vararg, a.kwarg):
                    if arg is not None:
                        params.add(arg.arg)
        return params

    @staticmethod
    def _bound_names(root) -> set:
        """Names bound (assigned / defined / comprehension targets) anywhere
        in the subtree — mutating THESE is function-local, not captured."""
        bound = _Linter._subtree_params(root)
        for node in ast.walk(root):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for target in targets:
                    for sub in ast.walk(target):
                        if isinstance(sub, ast.Name):
                            bound.add(sub.id)
            elif isinstance(node, (ast.For, ast.comprehension)):
                for sub in ast.walk(node.target):
                    if isinstance(sub, ast.Name):
                        bound.add(sub.id)
            elif isinstance(node, _FuncNode):
                bound.add(node.name)
            elif isinstance(node, ast.withitem) and node.optional_vars is not None:
                for sub in ast.walk(node.optional_vars):
                    if isinstance(sub, ast.Name):
                        bound.add(sub.id)
        return bound

    def _branch_names(self, test: ast.AST) -> set:
        """Names in a branch test that would make it data-dependent —
        excluding statically-safe forms: ``x is (not) None``, ``isinstance/
        hasattr/callable/len(...)``, and ``.shape``/``.ndim``/``.dtype``
        accesses (all trace-time constants)."""
        skip: set[int] = set()
        for node in ast.walk(test):
            if isinstance(node, ast.Compare):
                operands = [node.left, *node.comparators]
                if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) and any(
                    isinstance(c, ast.Constant) and c.value is None for c in operands
                ):
                    for sub in operands:
                        for s in ast.walk(sub):
                            skip.add(id(s))
            elif isinstance(node, ast.Call):
                if _callable_name(node.func) in {"isinstance", "hasattr", "callable", "len", "getattr"}:
                    for sub in ast.walk(node):
                        skip.add(id(sub))
            elif isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
                for sub in ast.walk(node):
                    skip.add(id(sub))
        names = set()
        for node in ast.walk(test):
            if isinstance(node, ast.Name) and id(node) not in skip:
                names.add(node.id)
        return names

    def check_root(self, root) -> None:
        params = self._subtree_params(root)
        bound = self._bound_names(root)
        # a mutator call whose result is consumed (`updates, st = tx.update(...)`)
        # is functional API use, not mutation — only bare statements count
        statement_calls = {
            id(stmt.value)
            for stmt in ast.walk(root)
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)
        }
        for node in ast.walk(root):
            lineno = getattr(node, "lineno", getattr(root, "lineno", 1))
            if isinstance(node, (ast.If, ast.While)):
                traced = self._branch_names(node.test) & params
                if traced:
                    kind = "while" if isinstance(node, ast.While) else "if"
                    self._add(
                        "TRACED_BRANCH", node.lineno,
                        f"python `{kind}` on possibly-traced value(s) "
                        f"{sorted(traced)} inside jit-traced code",
                        root,
                    )
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                self._add(
                    "CAPTURED_MUTATION", lineno,
                    f"{'global' if isinstance(node, ast.Global) else 'nonlocal'} "
                    f"{', '.join(node.names)} inside jit-traced code mutates "
                    "host state at trace time only",
                    root,
                )
            elif isinstance(node, ast.Call):
                self._check_call(node, root, bound, statement_calls)

    def _check_call(self, node: ast.Call, root, bound: set, statement_calls: set) -> None:
        chain = _attr_chain(node.func)
        lineno = node.lineno
        name = _callable_name(node.func)
        if not chain:
            chain = [name] if name else []
        base = chain[0] if chain else None
        # wall clock
        if (base == "time" and chain[-1] in _TIME_CALLS) or (
            base in ("datetime", "dt") and chain[-1] in _DATETIME_CALLS
        ):
            self._add(
                "HOST_TIME", lineno,
                f"{'.'.join(chain)}() inside jit-traced code is a trace-time "
                "constant, not a per-step clock",
                root,
            )
        # host RNG (names bound to jax.random are the fix, not the hazard)
        elif (
            base == "random" and len(chain) > 1 and base not in self.jax_random_aliases
        ) or (
            base in ("np", "numpy", "onp") and len(chain) > 2 and chain[1] == "random"
        ):
            self._add(
                "HOST_RANDOM", lineno,
                f"{'.'.join(chain)}() inside jit-traced code draws once at "
                "trace time — thread a jax.random key instead",
                root,
            )
        # host materialization
        elif name in _SYNC_METHODS and isinstance(node.func, ast.Attribute):
            self._add(
                "LINT_HOST_SYNC", lineno,
                f".{name}() inside jit-traced code raises on a tracer (and "
                "host-syncs when leaked outside)",
                root,
            )
        elif base in ("np", "numpy", "onp") and len(chain) == 2 and chain[1] in _SYNC_NP_CALLS:
            self._add(
                "LINT_HOST_SYNC", lineno,
                f"{'.'.join(chain)}() inside jit-traced code materializes on "
                "host — use jnp",
                root,
            )
        elif chain[-2:] == ["jax", "device_get"] or (name == "device_get" and base == "jax"):
            self._add(
                "LINT_HOST_SYNC", lineno,
                "jax.device_get() inside jit-traced code",
                root,
            )
        elif name in ("float", "int", "bool") and isinstance(node.func, ast.Name) and node.args:
            if isinstance(node.args[0], (ast.Name, ast.Attribute, ast.Call)):
                self._add(
                    "HOST_CAST", lineno,
                    f"{name}(...) inside jit-traced code raises on a traced "
                    "array (waive if the value is a static Python scalar)",
                    root,
                )
        elif name == "print" and isinstance(node.func, ast.Name):
            self._add(
                "TRACE_PRINT", lineno,
                "print() inside jit-traced code runs at trace time only — "
                "use jax.debug.print for per-step values",
                root,
            )
        # mutating method on a captured (non-locally-bound) object — only as
        # a bare statement: a consumed result (optax's `tx.update(...)`) is
        # functional API use
        elif (
            name in _MUTATORS
            and id(node) in statement_calls
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id not in bound
        ):
            self._add(
                "CAPTURED_MUTATION_CALL", lineno,
                f"{node.func.value.id}.{name}(...) mutates captured state at "
                "trace time only",
                root,
            )

    # -- concurrency rules (module-wide, not traced-root-scoped) -------------

    @staticmethod
    def _terminal_name(node: ast.AST) -> Optional[str]:
        """`self.cache.tables` -> "tables"; `x` -> "x"."""
        if isinstance(node, ast.Attribute):
            return node.attr
        if isinstance(node, ast.Name):
            return node.id
        return None

    def _is_lockish(self, node: ast.AST) -> bool:
        term = self._terminal_name(node)
        return bool(term) and (
            bool(_LOCKISH_RE.search(term)) or term in self._named_lock_names
        )

    @staticmethod
    def _walk_skip_funcs(stmts):
        """Walk statements WITHOUT descending into nested function/lambda
        bodies — code in those runs later, not under the enclosing lock."""
        stack = list(stmts)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (*_FuncNode, ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _blocking_kind(call: ast.Call) -> Optional[str]:
        chain = _attr_chain(call.func)
        if not chain:
            return None
        last = chain[0] if len(chain) == 1 else chain[-1]
        if last == "sleep" and (len(chain) == 1 or chain[0] in ("time",)):
            return "time.sleep"
        if last == "fsync":
            return "os.fsync"
        if last == "block_until_ready":
            return "block_until_ready"
        if last == "device_get" and (len(chain) == 1 or chain[0] == "jax"):
            return "jax.device_get"
        if last == "probe_io":
            return "store I/O probe"
        if (
            last == "join"
            and isinstance(call.func, ast.Attribute)
            and not call.args
            and not call.keywords
        ):
            # zero-arg .join() is a thread/queue join (str.join takes an arg)
            return ".join()"
        return None

    def _statement_lists(self):
        for node in ast.walk(self.tree):
            for fieldname in ("body", "orelse", "finalbody"):
                stmts = getattr(node, fieldname, None)
                if isinstance(stmts, list) and stmts:
                    yield stmts

    @staticmethod
    def _lock_method_chain(call: ast.Call, method: str) -> Optional[str]:
        """`self._lock.acquire()` -> "self._lock" when method matches."""
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr == method:
            chain = _attr_chain(func.value)
            if chain:
                return ".".join(chain)
        return None

    def _check_bare_acquires(self) -> None:
        acquires: list[tuple[ast.Call, str]] = []  # bare-statement acquires
        for stmts in self._statement_lists():
            for stmt in stmts:
                if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
                    chain = self._lock_method_chain(stmt.value, "acquire")
                    if chain:
                        acquires.append((stmt.value, chain))
        if not acquires:
            return
        protected: set[int] = set()
        releases_of = {}  # Try node id -> set of released chains in finalbody

        def finalbody_releases(try_node: ast.Try) -> set:
            released = set()
            for node in self._walk_skip_funcs(try_node.finalbody):
                if isinstance(node, ast.Call):
                    chain = self._lock_method_chain(node, "release")
                    if chain:
                        released.add(chain)
            return released

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Try):
                releases_of[id(node)] = finalbody_releases(node)
                for sub in self._walk_skip_funcs(node.body):
                    if isinstance(sub, ast.Call):
                        chain = self._lock_method_chain(sub, "acquire")
                        if chain and chain in releases_of[id(node)]:
                            protected.add(id(sub))
        # `lock.acquire()` immediately followed by a try releasing it in
        # finally is the other canonical safe shape
        for stmts in self._statement_lists():
            for i, stmt in enumerate(stmts[:-1]):
                nxt = stmts[i + 1]
                if (
                    isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Call)
                    and isinstance(nxt, ast.Try)
                ):
                    chain = self._lock_method_chain(stmt.value, "acquire")
                    if chain and chain in releases_of.get(id(nxt), set()):
                        protected.add(id(stmt.value))
        for call, chain in acquires:
            if id(call) not in protected:
                self._add(
                    "LOCK_BARE_ACQUIRE", call.lineno,
                    f"bare {chain}.acquire() with no try/finally release — "
                    "an exception before release() wedges every waiter; use "
                    f"`with {chain}:`",
                    None,
                )

    def _check_blocking_under_lock(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lock_items = [
                item for item in node.items if self._is_lockish(item.context_expr)
            ]
            if not lock_items:
                continue
            lock_desc = ".".join(_attr_chain(lock_items[0].context_expr)) or "lock"
            for sub in self._walk_skip_funcs(node.body):
                if isinstance(sub, ast.Call):
                    kind = self._blocking_kind(sub)
                    if kind:
                        self._add(
                            "LOCK_BLOCKING_CALL", sub.lineno,
                            f"`{kind}` called while holding `{lock_desc}` — "
                            "every thread waiting on the lock stalls for the "
                            "full blocking call",
                            None,
                        )

    def _unguarded_self_writes(self, method) -> set:
        """Attribute names stored to ``self`` in this method OUTSIDE any
        ``with <lockish>:`` block (lexically)."""
        writes: set[str] = set()

        def visit(node, guarded: bool) -> None:
            if isinstance(node, (*_FuncNode, ast.Lambda)) and node is not method:
                return
            if isinstance(node, ast.With) and any(
                self._is_lockish(item.context_expr) for item in node.items
            ):
                guarded = True
            if not guarded and isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for target in targets:
                    if (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                    ):
                        writes.add(target.attr)
            for child in ast.iter_child_nodes(node):
                visit(child, guarded)

        visit(method, False)
        return writes

    def _check_thread_shared_mutation(self) -> None:
        for cls in ast.walk(self.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = {m.name: m for m in cls.body if isinstance(m, _FuncNode)}
            for node in ast.walk(cls):
                if not (
                    isinstance(node, ast.Call)
                    and _callable_name(node.func) == "Thread"
                ):
                    continue
                target_name = None
                for kw in node.keywords:
                    if (
                        kw.arg == "target"
                        and isinstance(kw.value, ast.Attribute)
                        and isinstance(kw.value.value, ast.Name)
                        and kw.value.value.id == "self"
                    ):
                        target_name = kw.value.attr
                method = methods.get(target_name) if target_name else None
                if method is None:
                    continue
                thread_writes = self._unguarded_self_writes(method)
                other_writes: set[str] = set()
                for name, other in methods.items():
                    if name not in (target_name, "__init__"):
                        other_writes |= self._unguarded_self_writes(other)
                shared = sorted(thread_writes & other_writes)
                if shared:
                    self._add(
                        "THREAD_SHARED_MUTATION", node.lineno,
                        f"thread target {cls.name}.{target_name} writes "
                        f"{shared} which other methods also write outside "
                        "any lock — unsynchronized cross-thread mutation",
                        None,
                    )

    def _check_async_np_views(self) -> None:
        jitted: set[str] = set()
        mutated_bases: set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign):
                if isinstance(node.value, ast.Call) and _is_jit_like(node.value):
                    for target in node.targets:
                        term = self._terminal_name(target)
                        if term:
                            jitted.add(term)
                for target in node.targets:
                    if isinstance(target, ast.Subscript):
                        term = self._terminal_name(target.value)
                        if term:
                            mutated_bases.add(term)
            elif isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Subscript):
                term = self._terminal_name(node.target.value)
                if term:
                    mutated_bases.add(term)
        if not jitted:
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = self._terminal_name(node.func)
            if fname not in jitted:
                continue
            for arg in [*node.args, *(kw.value for kw in node.keywords)]:
                if isinstance(arg, ast.Subscript):
                    base = self._terminal_name(arg.value)
                    if base in mutated_bases:
                        self._add(
                            "ASYNC_NP_VIEW", arg.lineno,
                            f"view `{base}[...]` passed to jitted `{fname}` "
                            "while the same buffer is mutated in place in "
                            "this file — the async dispatch may read the "
                            "mutated bytes; pass a .copy()",
                            None,
                        )

    def _check_unregistered_locks(self) -> None:
        imported_lock_names: set[str] = set()
        safe_ctor_ids: set[int] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "threading":
                for alias in node.names:
                    if alias.name in ("Lock", "RLock"):
                        imported_lock_names.add(alias.asname or alias.name)
            elif isinstance(node, ast.Call) and _callable_name(node.func) == "named_lock":
                for arg in [*node.args, *(kw.value for kw in node.keywords)]:
                    for sub in ast.walk(arg):
                        safe_ctor_ids.add(id(sub))
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call) or id(node) in safe_ctor_ids:
                continue
            chain = _attr_chain(node.func)
            is_ctor = chain[-2:] in (["threading", "Lock"], ["threading", "RLock"]) or (
                len(chain) == 1 and chain[0] in imported_lock_names
            )
            if is_ctor:
                self._add(
                    "LOCK_UNREGISTERED", node.lineno,
                    f"raw {'.'.join(chain)}() bypasses the named-lock "
                    "registry — construct it via analysis.concurrency."
                    'named_lock("subsystem.purpose")',
                    None,
                )

    def check_concurrency(self) -> None:
        self._check_bare_acquires()
        self._check_blocking_under_lock()
        self._check_thread_shared_mutation()
        self._check_async_np_views()
        self._check_unregistered_locks()

    # -- the waiver audit ----------------------------------------------------

    def _audit_waivers(self) -> None:
        """Runs LAST: any pragma line that suppressed nothing is stale. A
        pragma that waives LINT_WAIVER_UNUSED itself is exempt (the reviewed
        way to keep a deliberate placeholder)."""
        for line, codes in sorted(self.waivers.items()):
            if line in self.used_waiver_lines or "LINT_WAIVER_UNUSED" in codes:
                continue
            self._add(
                "LINT_WAIVER_UNUSED", line,
                f"waiver pragma (disable={','.join(sorted(codes))}) "
                "suppresses no finding at this line — delete it before it "
                "masks a real one",
                None,
            )

    def run(self) -> list[Finding]:
        self.discover()
        for root in self.traced_roots:
            self.check_root(root)
        self.check_concurrency()
        self._audit_waivers()
        self.findings.sort(key=lambda f: f.path or "")
        return self.findings


# -- public API ---------------------------------------------------------------


def lint_source(source: str, filename: str = "<string>") -> list[Finding]:
    """Lint one source string; returns unwaived findings."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [
            Finding(
                "PARSE_ERROR", f"could not parse {filename}: {e}",
                path=f"{filename}:{e.lineno or 1}",
            )
        ]
    return _Linter(tree, source, filename).run()


def lint_file(path: str) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), filename=path)


_EXCLUDE_DIRS = {"__pycache__", ".git", ".pytest_cache", "build", "dist"}


def iter_python_files(paths: Iterable[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
        elif os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames if d not in _EXCLUDE_DIRS]
                for filename in sorted(filenames):
                    if filename.endswith(".py"):
                        yield os.path.join(dirpath, filename)


def lint_paths(paths: Iterable[str], only: Optional[set] = None) -> AnalysisReport:
    """Lint every ``.py`` under the given files/directories. The report's
    inventory counts files scanned and traced functions found. ``only``
    restricts the report to a set of finding codes (e.g.
    ``CONCURRENCY_LINT_CODES`` for ``analyze --races``)."""
    report = AnalysisReport(meta={"label": "lint"})
    files = 0
    for path in iter_python_files(paths):
        files += 1
        report.extend(lint_file(path))
    if only is not None:
        report.findings = [f for f in report.findings if f.code in only]
    report.inventory = {"files_scanned": files, "findings": len(report.findings)}
    return report
