"""Runtime hazard sanitizer: catch what static analysis cannot.

The three silent warm-loop killers — implicit device→host syncs, steady-state
recompiles, and jit-cache misses — leave no trace in Python source and no
error anywhere; they just stretch step time. :class:`HazardSanitizer` is a
context manager that watches a *warm window* of a live loop:

    step(batch)                      # warmup: compiles happen here, fine
    with HazardSanitizer(telemetry=accelerator.telemetry) as san:
        for batch in loader:         # warm window: nothing may compile/sync
            step(batch)
    report = san.report              # findings with call sites

Three fused feeds:

1. **Host syncs** — the jax array type's host-materialization hooks
   (``__float__``/``__int__``/``__bool__``/``__index__``/``item``/``tolist``
   plus ``jax.device_get``) are interposed for the window's duration, so a
   ``loss.item()`` buried three calls deep is caught *with its call site* on
   every backend — including CPU, where ``jax.transfer_guard`` sees nothing
   because D2H is zero-copy. (``np.asarray`` reaches the buffer protocol
   below Python and is only caught when it routes through ``__array__`` or
   ``device_get``; the lint covers it statically.)
2. **Recompiles / cache misses** — a private
   :class:`~..telemetry.compile_tracker.CompileTracker` rides the existing
   ``jax.monitoring`` + ``utils/jit_cache.cache_event_hook`` dispatcher; any
   compile or program-cache miss inside the window is a finding.
3. **H2D re-uploads** (optional) — ``transfer_guard="disallow"`` arms jax's
   transfer guard for implicit host→device transfers (a numpy array
   re-uploaded every step); it *raises* at the offending line, so it is off
   by default.

:func:`explain_recompile` answers the follow-up question a recompile finding
always raises — *which argument retraced?* — by diffing two abstract
signatures (shape/dtype/weak-type per pytree leaf, repr for static leaves)
and naming exactly the leaves that changed. ``HazardSanitizer.watch(step)`` wraps a step
callable to capture those signatures per call and attach the diff to the
finding (and, via the telemetry hub, to the ``{"kind": "compile"}`` record in
``telemetry.jsonl``).
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, Optional

from .concurrency import named_lock
from .findings import AnalysisReport, Finding

_HOOK_NAMES = ("__array__", "__float__", "__int__", "__bool__", "__index__", "item", "tolist")

_patch_lock = named_lock("sanitizer.patch")
_patch_depth = 0
_patch_originals: dict[str, Any] = {}
_active_sanitizers: list["HazardSanitizer"] = []


# -- abstract signatures ------------------------------------------------------


def signature_of(tree: Any) -> dict[str, str]:
    """Abstract signature of a pytree of call arguments: ``path ->
    "shape/dtype"`` for array leaves (with a ``/weak`` suffix for weak-typed
    arrays — a Python-scalar-born ``jnp.asarray(0.0)`` and an explicit
    ``jnp.float32(0.0)`` share shape and dtype but are DIFFERENT trace keys,
    and without the suffix that retrace would diff as "identical
    signatures"), ``repr`` for static leaves (whose value IS part of the
    trace key). Cheap — no device access, no hashing of data."""
    import jax

    from .program import _keystr

    out: dict[str, str] = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        key = _keystr(path)
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            weak = getattr(leaf, "weak_type", None)
            if weak is None:
                weak = getattr(getattr(leaf, "aval", None), "weak_type", False)
            out[key] = f"{tuple(leaf.shape)}/{leaf.dtype}" + ("/weak" if weak else "")
        else:
            out[key] = f"static:{leaf!r}"[:120]
    return out


def explain_recompile(before: Optional[dict], after: Optional[dict]) -> dict:
    """Diff two abstract signatures and name exactly which leaf forced the
    retrace. Returns ``{changed, added, removed, summary}`` — ``changed``
    maps pytree paths to ``(old, new)``."""
    before = before or {}
    after = after or {}
    changed = {
        k: (before[k], after[k]) for k in before if k in after and before[k] != after[k]
    }
    added = {k: after[k] for k in after if k not in before}
    removed = {k: before[k] for k in before if k not in after}
    if not (changed or added or removed):
        summary = (
            "identical abstract signatures — the retrace came from a new "
            "callable object (fresh lambda/closure per step), not the arguments"
        )
    else:
        parts = []
        for k, (a, b) in list(changed.items())[:4]:
            parts.append(f"{k}: {a} -> {b}")
        for k in list(added)[:2]:
            parts.append(f"+{k}: {added[k]}")
        for k in list(removed)[:2]:
            parts.append(f"-{k}: {removed[k]}")
        summary = "; ".join(parts)
    return {"changed": changed, "added": added, "removed": removed, "summary": summary}


# -- the host-sync interposer -------------------------------------------------


def _user_call_site() -> str:
    """First stack frame outside jax/numpy/this module — where the sync was
    *requested*, which is what the user needs to go fix."""
    here = __file__
    for frame, lineno in traceback.walk_stack(None):
        filename = frame.f_code.co_filename
        if (
            filename == here
            or "/jax/" in filename
            or "/jaxlib/" in filename
            or "/numpy/" in filename
        ):
            continue
        return f"{filename}:{lineno} ({frame.f_code.co_name})"
    return "<unknown>"


def _site_from_traceback(tb) -> str:
    """Deepest user frame of an in-flight exception (the transfer-guard trip
    raises inside jax — walk the traceback down, keep the last non-jax frame)."""
    site = "<unknown>"
    while tb is not None:
        frame = tb.tb_frame
        filename = frame.f_code.co_filename
        if not ("/jax/" in filename or "/jaxlib/" in filename or filename == __file__):
            site = f"{filename}:{tb.tb_lineno} ({frame.f_code.co_name})"
        tb = tb.tb_next
    return site


def _record_sync(kind: str) -> None:
    site = _user_call_site()
    for sanitizer in list(_active_sanitizers):
        sanitizer._on_host_sync(kind, site)


def _install_patches() -> None:
    global _patch_depth
    import jax

    with _patch_lock:
        _patch_depth += 1
        if _patch_depth > 1:
            return
        try:
            # resolve the concrete array type WITHOUT creating an array: the
            # caller may already hold jax's transfer guard open, and a probe
            # jnp.zeros(()) would itself be a disallowed host->device transfer
            from jax._src.array import ArrayImpl as array_type
        except ImportError:  # jax moved it: fall back to a probe array
            array_type = type(jax.numpy.zeros(()))
        for name in _HOOK_NAMES:
            original = getattr(array_type, name, None)
            if original is None:
                continue

            def make_wrapper(hook_name: str, orig: Any):
                def wrapper(self, *args, **kwargs):
                    _record_sync(hook_name)
                    return orig(self, *args, **kwargs)

                return wrapper

            try:
                setattr(array_type, name, make_wrapper(name, original))
            except (TypeError, AttributeError):
                continue  # backend array type refuses patching: partial coverage
            _patch_originals[(name)] = (array_type, original)
        original_get = jax.device_get

        def device_get(x):
            _record_sync("device_get")
            return original_get(x)

        jax.device_get = device_get
        _patch_originals["device_get"] = (jax, original_get)


def _remove_patches() -> None:
    global _patch_depth
    with _patch_lock:
        _patch_depth -= 1
        if _patch_depth > 0:
            return
        for name, (owner, original) in _patch_originals.items():
            try:
                if name == "device_get":
                    owner.device_get = original
                else:
                    setattr(owner, name, original)
            except (TypeError, AttributeError):
                pass
        _patch_originals.clear()


# -- the sanitizer ------------------------------------------------------------


class HazardSanitizer:
    """Warm-window watcher fusing host-sync interposition, compile/cache
    tracking, and (optionally) jax's transfer guard. See module docstring.

    ``allow`` suppresses finding codes (e.g. ``allow={"CACHE_MISS"}`` for a
    window that legitimately builds one late program). ``transfer_guard``
    ("disallow"/"log") additionally arms jax's implicit-H2D guard — note
    "disallow" raises at the offending transfer rather than recording.
    """

    def __init__(
        self,
        telemetry: Any = None,
        label: str = "warm-loop",
        allow: Optional[set] = None,
        transfer_guard: Optional[str] = None,
    ):
        from ..telemetry.compile_tracker import CompileTracker

        self.telemetry = telemetry
        self.label = label
        self.allow = set(allow or ())
        self.transfer_guard = transfer_guard
        self.compiles = CompileTracker()
        self.syncs: dict[tuple[str, str], int] = {}  # (kind, site) -> count
        self.h2d_trips: list[str] = []  # transfer-guard trip sites
        self.recompile_explanations: list[dict] = []
        self._active = False
        self._guard_ctx = None
        self._last_signature: Optional[dict] = None
        self._prev_signature: Optional[dict] = None

    # -- window lifecycle --------------------------------------------------

    def __enter__(self) -> "HazardSanitizer":
        # the (fallible) guard context enters FIRST: a bad level string must
        # raise before the process-global array patches go in, or a failed
        # __enter__ (whose __exit__ never runs) would leak them forever
        if self.transfer_guard:
            import jax

            self._guard_ctx = jax.transfer_guard_host_to_device(self.transfer_guard)
            self._guard_ctx.__enter__()
        try:
            self.compiles.start()
            _install_patches()
            _active_sanitizers.append(self)
        except BaseException:
            if self._guard_ctx is not None:
                self._guard_ctx.__exit__(None, None, None)
                self._guard_ctx = None
            raise
        self._active = True
        return self

    def __exit__(self, *exc_info) -> None:
        self._active = False
        exc = exc_info[1] if len(exc_info) > 1 else None
        if exc is not None and "host-to-device" in str(exc):
            # the transfer guard tripped inside the window: the exception
            # still propagates (disallow mode aborts the loop by design), but
            # the report documents the transfer with its site
            self.h2d_trips.append(_site_from_traceback(exc_info[2]))
        if self._guard_ctx is not None:
            self._guard_ctx.__exit__(*exc_info)
            self._guard_ctx = None
        if self in _active_sanitizers:
            _active_sanitizers.remove(self)
        _remove_patches()
        self.compiles.stop()
        if self.telemetry is not None:
            self.telemetry.write_record(
                "analysis", {"sanitizer": self.report.to_dict(), "label": self.label}
            )

    # -- feeds -------------------------------------------------------------

    def _on_host_sync(self, kind: str, site: str) -> None:
        if not self._active:
            return
        key = (kind, site)
        self.syncs[key] = self.syncs.get(key, 0) + 1

    def watch(self, fn: Callable, label: Optional[str] = None) -> Callable:
        """Wrap a step callable: capture the abstract signature of every call
        and, when a call compiled after the first one, attach the signature
        diff naming the leaf that retraced."""
        name = label or getattr(fn, "__name__", "step")

        def wrapped(*args, **kwargs):
            signature = signature_of((args, kwargs))
            if signature != self._last_signature:
                self._prev_signature = self._last_signature
                self._last_signature = signature
            before = self.compiles.compile_count + self.compiles.cache_misses
            result = fn(*args, **kwargs)
            after = self.compiles.compile_count + self.compiles.cache_misses
            if self._active and after > before and self._prev_signature is not None:
                explanation = explain_recompile(self._prev_signature, self._last_signature)
                explanation["callable"] = name
                self.recompile_explanations.append(explanation)
            return result

        wrapped.__name__ = f"sanitized_{name}"
        return wrapped

    # -- readout -----------------------------------------------------------

    @property
    def report(self) -> AnalysisReport:
        report = AnalysisReport(meta={"label": self.label})
        for (kind, site), count in sorted(self.syncs.items()):
            report.add(
                Finding(
                    "HOST_SYNC",
                    f"{count}x device->host sync via {kind} inside the "
                    f"{self.label} window",
                    path=site,
                    data={"kind": kind, "count": count},
                )
            )
        snapshot = self.compiles.snapshot()
        if snapshot["compile_count"]:
            data = dict(snapshot)
            if self.recompile_explanations:
                data["explanations"] = self.recompile_explanations
                detail = "; ".join(
                    e["summary"] for e in self.recompile_explanations[:2]
                )
            else:
                detail = "wrap the step with .watch() to capture the signature diff"
            report.add(
                Finding(
                    "WARM_RECOMPILE",
                    f"{snapshot['compile_count']} compiles "
                    f"({snapshot['compile_seconds']:.2f}s) inside the "
                    f"{self.label} window — {detail}",
                    data=data,
                )
            )
        for site in self.h2d_trips:
            report.add(
                Finding(
                    "H2D_TRANSFER",
                    f"implicit host->device transfer tripped the guard inside "
                    f"the {self.label} window",
                    path=site,
                )
            )
        if snapshot["jit_cache_misses"]:
            report.add(
                Finding(
                    "CACHE_MISS",
                    f"{snapshot['jit_cache_misses']} program-cache misses inside "
                    f"the {self.label} window",
                    data={
                        "misses": snapshot["jit_cache_misses"],
                        "hits": snapshot["jit_cache_hits"],
                        "recent_miss_keys": snapshot.get("recent_miss_keys", []),
                    },
                )
            )
        report.findings = [f for f in report.findings if f.code not in self.allow]
        report.inventory = {"compiles": snapshot, "host_syncs": sum(self.syncs.values())}
        return report
