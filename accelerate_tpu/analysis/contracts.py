"""Program contracts: checked-in expectations that make the analyzer a
*differential* gate.

PR 5's audit measures a compiled program once; nothing stopped the next
change from silently regressing what it measured — one extra all-gather, a
dropped donation alias, a temp-buffer blowup all pass a point-in-time audit
that only asks "zero errors?". A :class:`ProgramContract` pins the measured
properties of one program as a JSON file under ``tests/contracts/``:

.. code-block:: json

    {
      "program": "bert_tiny_step",
      "version": 1,
      "tolerance_pct": 25.0,
      "env": {"backend": "cpu", "num_devices": 8},
      "expectations": {
        "max_errors": 0,
        "collectives": {"all_reduce": {"count": 26, "bytes": 1394700}},
        "donation": {"declared": 76, "aliased": 76},
        "memory": {"peak_hbm_bytes": 14313861, "temp_bytes": 7577960},
        "schedule": {"serialized_comm_bytes": 1394700, "overlapped_count": 0},
        "compile_seconds_budget": 24.0
      }
    }

``check(report)`` compares a live :class:`~.findings.AnalysisReport` against
the contract and emits one ``CONTRACT_DRIFT`` (error) per moved expectation,
naming the field, both values, and the delta. **Counts are exact** (a new
collective is a new collective); **byte fields carry a tolerance**
(``tolerance_pct``, scaled up by callers on backends whose lowering differs
from the recording environment); ``compile_seconds_budget`` is a ceiling
only. Drift is symmetric for counts and byte expectations — an *improvement*
also fails the gate until the contract is updated, which is the point: the
expectation moves in a reviewed diff (``--update-contracts``), never
silently.

Contracts pin the environment they were recorded on (backend + device
count): collective counts are functions of both, so a mismatched environment
skips with ``CONTRACT_ENV_SKIPPED`` instead of fabricating drift.

``update_contract`` is churn-free: when the existing contract still passes
against the live report, the file is left byte-identical (tolerances and
budgets are not re-derived every run), so ``--update-contracts`` twice in a
row is a no-op — the round-trip the tests pin.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Optional

from .findings import ERROR, WARNING, AnalysisReport, Finding

CONTRACT_VERSION = 1
DEFAULT_TOLERANCE_PCT = 25.0
# below this, percentage tolerances on byte fields collapse to nothing and
# tiny shape jitters (a 512-byte gather) would read as drift
_BYTE_SLACK_FLOOR = 1024
# compile budgets leave generous headroom over the recorded wall time: the
# gate is for order-of-magnitude compile regressions, not machine weather
_COMPILE_BUDGET_FACTOR = 8.0
_COMPILE_BUDGET_FLOOR_S = 10.0


def default_contracts_dir() -> str:
    """``tests/contracts`` of the repo this package lives in, falling back to
    the working directory's ``tests/contracts`` for installed copies."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    candidate = os.path.join(repo, "tests", "contracts")
    if os.path.isdir(candidate):
        return candidate
    return os.path.join(os.getcwd(), "tests", "contracts")


def contract_path(contracts_dir: str, program: str) -> str:
    return os.path.join(contracts_dir, f"{program}.json")


def _is_program_report(report: AnalysisReport) -> bool:
    """Only compiled/lowered program audits are contractable — lint reports
    and fleet-merge shells (whose inventory is just sub-program prefixes)
    have no donation/collective surface of their own."""
    return bool(report.meta.get("label")) and (
        "donation" in report.inventory or "collectives" in report.inventory
    )


@dataclass
class ProgramContract:
    program: str
    expectations: dict
    env: dict = field(default_factory=dict)
    tolerance_pct: float = DEFAULT_TOLERANCE_PCT
    version: int = CONTRACT_VERSION
    # whether the recording audit compiled the program: post-GSPMD sections
    # (executable collectives, memory, schedule) only exist then, and a
    # lowered-only report must not read as "all collectives vanished"
    compiled: bool = True

    # -- construction / persistence ---------------------------------------

    @classmethod
    def from_report(
        cls, report: AnalysisReport, tolerance_pct: float = DEFAULT_TOLERANCE_PCT
    ) -> "ProgramContract":
        """Pin a live report's measured properties. Only sections the report
        actually carries are recorded, so a lowered-only audit (prefill
        spans) yields a contract checkable against lowered-only reports."""
        inv = report.inventory
        exp: dict[str, Any] = {"max_errors": 0}
        if "collectives" in inv:
            exp["collectives"] = {
                kind: {"count": int(stats["count"]), "bytes": int(stats["bytes"])}
                for kind, stats in sorted(inv["collectives"].items())
            }
        donation = inv.get("donation")
        if donation:
            exp["donation"] = {
                "declared": int(donation.get("declared", 0)),
                "aliased": int(donation.get("aliased", 0)),
            }
        memory = inv.get("memory")
        if memory:
            exp["memory"] = {
                "peak_hbm_bytes": int(memory.get("peak_hbm_bytes", 0)),
                "temp_bytes": int(memory.get("temp_bytes", 0)),
            }
        schedule = inv.get("schedule")
        if schedule:
            exp["schedule"] = {
                "serialized_comm_bytes": int(schedule.get("serialized_comm_bytes", 0)),
                "overlapped_count": int(schedule.get("overlapped_count", 0)),
            }
        compile_s = report.meta.get("compile_seconds")
        if compile_s is not None:
            exp["compile_seconds_budget"] = round(
                max(_COMPILE_BUDGET_FLOOR_S, float(compile_s) * _COMPILE_BUDGET_FACTOR), 1
            )
        env = {
            "backend": report.meta.get("backend", "unknown"),
            "num_devices": int(report.meta.get("num_devices", 0)),
        }
        return cls(
            program=report.meta.get("label", "program"),
            expectations=exp,
            env=env,
            tolerance_pct=tolerance_pct,
            compiled=bool(report.meta.get("compiled", False)),
        )

    @classmethod
    def load(cls, path: str) -> "ProgramContract":
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
        return cls(
            program=payload["program"],
            expectations=payload["expectations"],
            env=payload.get("env", {}),
            tolerance_pct=float(payload.get("tolerance_pct", DEFAULT_TOLERANCE_PCT)),
            version=int(payload.get("version", CONTRACT_VERSION)),
            compiled=bool(payload.get("compiled", True)),
        )

    def to_json(self) -> str:
        """Deterministic serialization (sorted keys, stable formatting) so an
        unchanged contract is byte-identical across updates."""
        payload = {
            "program": self.program,
            "version": self.version,
            "compiled": self.compiled,
            "tolerance_pct": self.tolerance_pct,
            "env": self.env,
            "expectations": self.expectations,
        }
        return json.dumps(payload, indent=2, sort_keys=True) + "\n"

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.to_json())

    # -- the check ---------------------------------------------------------

    def _drift(
        self, findings: list, fieldname: str, expected, actual, unit: str = ""
    ) -> None:
        try:
            delta = actual - expected
            delta_s = f"{delta:+g}"
        except TypeError:
            delta, delta_s = None, "changed"
        findings.append(
            Finding(
                "CONTRACT_DRIFT",
                f"{self.program}: {fieldname} drifted from its contract: "
                f"expected {expected}{unit}, got {actual}{unit} ({delta_s}{unit})",
                path=f"{self.program}:{fieldname}",
                data={
                    "program": self.program,
                    "field": fieldname,
                    "expected": expected,
                    "actual": actual,
                    **({"delta": delta} if delta is not None else {}),
                },
            )
        )

    def check(
        self, report: AnalysisReport, tolerance_scale: float = 1.0
    ) -> list[Finding]:
        """Compare a live report against this contract. Returns the drift
        findings (empty = the program still matches its expectations)."""
        report_env = {
            "backend": report.meta.get("backend", "unknown"),
            "num_devices": int(report.meta.get("num_devices", 0)),
        }
        if self.env and report_env != self.env:
            return [
                Finding(
                    "CONTRACT_ENV_SKIPPED",
                    f"{self.program}: contract recorded on {self.env}, this "
                    f"report ran on {report_env} — collective counts are "
                    "environment functions, skipping",
                    path=self.program,
                    data={"contract_env": self.env, "report_env": report_env},
                )
            ]
        findings: list[Finding] = []
        exp = self.expectations
        tol_pct = self.tolerance_pct * max(tolerance_scale, 0.0)
        # compiled and lowered-only audits measure DIFFERENT collective
        # inventories (post-GSPMD executable vs pre-partitioning StableHLO,
        # which only names user-written collectives), and memory/schedule
        # exist only compiled — so any compiled-flag mismatch, in EITHER
        # direction, skips those sections instead of fabricating mass drift.
        # Donation and errors are lowering-level and still gate.
        report_compiled = bool(report.meta.get("compiled", False))
        degraded = self.compiled != report_compiled
        if degraded:
            side = (
                "this report is lowered-only — rerun without --no-compile"
                if self.compiled
                else "this report is compiled — regenerate the contract "
                "with --update-contracts from a compiled run"
            )
            findings.append(
                Finding(
                    "CONTRACT_DRIFT",
                    f"{self.program}: contract recorded "
                    f"{'compiled' if self.compiled else 'lowered-only'} but "
                    f"{side}; collectives/memory/schedule/compile budget "
                    "unchecked",
                    severity=WARNING,
                    path=f"{self.program}:compiled",
                    data={"program": self.program, "field": "compiled"},
                )
            )

        def bytes_drift(fieldname: str, expected: int, actual: int) -> None:
            slack = max(expected * tol_pct / 100.0, _BYTE_SLACK_FLOOR)
            if abs(actual - expected) > slack:
                self._drift(findings, fieldname, expected, actual, unit=" bytes")

        # zero-ERROR requirement (contract findings are appended after this
        # check, so only genuine program findings count here). A merged root
        # carries its sub-programs' findings too (engine prefill spans, fleet
        # replicas) — those gate via their OWN contracts; counting them here
        # would misattribute a prefill regression as decode drift as well.
        sub_findings = {
            id(f) for sub in report.sub_reports.values() for f in sub.findings
        }
        program_errors = [
            f
            for f in report.errors
            if not f.code.startswith("CONTRACT_") and id(f) not in sub_findings
        ]
        if len(program_errors) > exp.get("max_errors", 0):
            self._drift(
                findings, "errors", exp.get("max_errors", 0), len(program_errors)
            )

        exp_coll = exp.get("collectives")
        if exp_coll is not None and not degraded:
            actual_coll = report.inventory.get("collectives", {})
            for kind in sorted(set(exp_coll) | set(actual_coll)):
                e = exp_coll.get(kind, {"count": 0, "bytes": 0})
                a = actual_coll.get(kind, {"count": 0, "bytes": 0})
                if int(a.get("count", 0)) != int(e.get("count", 0)):
                    self._drift(
                        findings,
                        f"collectives.{kind}.count",
                        int(e.get("count", 0)),
                        int(a.get("count", 0)),
                    )
                else:
                    bytes_drift(
                        f"collectives.{kind}.bytes",
                        int(e.get("bytes", 0)),
                        int(a.get("bytes", 0)),
                    )

        exp_don = exp.get("donation")
        if exp_don is not None:
            actual_don = report.inventory.get("donation", {})
            for key in ("declared", "aliased"):
                if int(actual_don.get(key, 0)) != int(exp_don.get(key, 0)):
                    self._drift(
                        findings,
                        f"donation.{key}",
                        int(exp_don.get(key, 0)),
                        int(actual_don.get(key, 0)),
                    )

        for section, fields in (
            ("memory", ("peak_hbm_bytes", "temp_bytes")),
            ("schedule", ("serialized_comm_bytes",)),
        ):
            exp_sec = exp.get(section)
            if exp_sec is None or degraded:
                continue
            actual_sec = report.inventory.get(section)
            if not actual_sec:
                findings.append(
                    Finding(
                        "CONTRACT_DRIFT",
                        f"{self.program}: contract pins {section} but the "
                        "report carries none — audit with compile=True to "
                        "check it",
                        severity=WARNING,
                        path=f"{self.program}:{section}",
                        data={"program": self.program, "field": section},
                    )
                )
                continue
            for key in fields:
                if key in exp_sec:
                    bytes_drift(
                        f"{section}.{key}", int(exp_sec[key]), int(actual_sec.get(key, 0))
                    )
        exp_sched = exp.get("schedule")
        if exp_sched is not None and not degraded and "overlapped_count" in exp_sched:
            actual_sched = report.inventory.get("schedule")
            if actual_sched and int(actual_sched.get("overlapped_count", 0)) != int(
                exp_sched["overlapped_count"]
            ):
                self._drift(
                    findings,
                    "schedule.overlapped_count",
                    int(exp_sched["overlapped_count"]),
                    int(actual_sched.get("overlapped_count", 0)),
                )

        budget = exp.get("compile_seconds_budget")
        compile_s = report.meta.get("compile_seconds")
        if budget is not None and compile_s is not None and not degraded:
            ceiling = float(budget) * max(tolerance_scale, 1.0)
            if float(compile_s) > ceiling:
                # expected = the contract's recorded budget (the number the
                # author can find in the JSON), not the scaled ceiling
                self._drift(
                    findings,
                    "compile_seconds_budget",
                    round(float(budget), 1),
                    round(float(compile_s), 2),
                    unit=" s",
                )
        return findings


# -- the repo-wide gate --------------------------------------------------------


def _expand(reports) -> list[tuple[AnalysisReport, AnalysisReport]]:
    """Flatten merged reports one level as ``(root, report)`` pairs: the
    engine's prefill spans and the fleet's per-replica audits are programs
    with contracts of their own, but their drift must surface on the ROOT
    report too — that's what renders, serializes, and drives exit codes."""
    out: list[tuple[AnalysisReport, AnalysisReport]] = []
    for report in reports:
        out.append((report, report))
        for sub in report.sub_reports.values():
            out.append((report, sub))
    return out


def update_contract(
    path: str,
    report: AnalysisReport,
    tolerance_pct: float = DEFAULT_TOLERANCE_PCT,
    tolerance_scale: float = 1.0,
) -> bool:
    """Write/refresh one contract from a live report. Churn-free: when the
    existing file still passes against the report (same environment, no
    drift), it is left byte-identical. Refuses (returns False, file
    untouched) when the existing contract was recorded on a DIFFERENT
    environment, or pins compiled sections this report cannot re-derive
    (lowered-only) — an update must never silently clobber expectations it
    cannot reproduce. Returns True when the file changed."""
    if os.path.exists(path):
        existing = ProgramContract.load(path)
        report_compiled = bool(report.meta.get("compiled", False))
        if existing.compiled and not report_compiled:
            return False
        if existing.compiled or not report_compiled:
            # like-for-like: rewrite only on a gate-failing (ERROR) drift —
            # env skips and report-carries-no-section warnings must not
            # regenerate the file (from_report would silently drop the very
            # sections this report cannot reproduce). The remaining case
            # (lowered-only contract, compiled report) always upgrades: the
            # compiled audit strictly supersedes what lowering recorded.
            findings = existing.check(report, tolerance_scale=tolerance_scale)
            if not any(f.severity == ERROR for f in findings):
                return False
    ProgramContract.from_report(report, tolerance_pct=tolerance_pct).save(path)
    return True


def gate_reports(
    reports,
    contracts_dir: Optional[str] = None,
    *,
    update: bool = False,
    tolerance_scale: float = 1.0,
    require_contract: bool = True,
) -> list[Finding]:
    """Check (or, with ``update=True``, refresh) every contractable program
    report against ``contracts_dir``. Drift findings are appended to the
    report they belong to — so renders and jsonl records carry them — and
    returned flat for the caller's exit code. With ``update``, the returned
    findings are informational ``CONTRACT_*`` notes of what was written."""
    contracts_dir = contracts_dir or default_contracts_dir()
    all_findings: list[Finding] = []
    for root, report in _expand(reports):
        if not _is_program_report(report):
            continue
        label = report.meta["label"]
        path = contract_path(contracts_dir, label)
        if update:
            changed = update_contract(path, report, tolerance_scale=tolerance_scale)
            if changed:
                all_findings.append(
                    Finding(
                        "CONTRACT_UPDATED",
                        f"{label}: contract written to {path}",
                        path=path,
                    )
                )
            continue
        if not os.path.exists(path):
            if require_contract:
                finding = Finding(
                    "CONTRACT_MISSING",
                    f"{label}: no contract at {path} — run with "
                    "--update-contracts and commit the JSON",
                    path=label,
                )
                report.add(finding)
                if root is not report:
                    root.add(finding)
                all_findings.append(finding)
            continue
        contract = ProgramContract.load(path)
        findings = contract.check(report, tolerance_scale=tolerance_scale)
        report.extend(findings)
        # a sub-program's drift must gate the whole audit: merge() copied the
        # sub's findings into the root BEFORE this check ran, so the root's
        # errors (the CLI exit code, the rendered report, the telemetry
        # record) would otherwise never see it
        if root is not report:
            root.extend(findings)
        all_findings.extend(findings)
    return all_findings


def drift_count(findings) -> int:
    """ERROR-level contract drifts in a findings list — the bench metric."""
    return sum(
        1 for f in findings if f.code == "CONTRACT_DRIFT" and f.severity == ERROR
    )
