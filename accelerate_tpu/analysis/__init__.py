"""Static analysis for compiled step and decode programs.

Three passes over three layers of the stack, one report shape:

- :mod:`.program` — jaxpr/HLO audit of a ``jax.stages.Lowered``/``Compiled``
  program: donation aliasing, fp64 leaks, baked-in constants, the collective
  inventory, and sharding-resolved-to-replication. Reached via
  ``Accelerator.analyze()`` / ``ServingEngine.analyze()``.
- :mod:`.sanitizer` — runtime hazard watcher for warm-loop windows: implicit
  device→host syncs, steady-state recompiles (with ``explain_recompile``
  signature diffs), jit-cache misses.
- :mod:`.lint` — AST lint of user step functions (and this repo's own code)
  for trace-time hazards: branching on traced values, wall clocks, host RNG,
  host materialization, captured-state mutation.

CLI: ``accelerate-tpu analyze`` (commands/analyze.py). Findings catalog:
docs/analysis.md.
"""

from .findings import CATALOG, ERROR, INFO, WARNING, AnalysisReport, Finding
from .lint import lint_file, lint_paths, lint_source
from .program import (
    audit_lowered,
    collective_inventory,
    constant_audit,
    donation_audit,
    donation_drop_warning,
    dtype_audit,
    flatten_args_info,
    replication_audit,
)
from .sanitizer import HazardSanitizer, explain_recompile, signature_of

__all__ = [
    "CATALOG",
    "ERROR",
    "INFO",
    "WARNING",
    "AnalysisReport",
    "Finding",
    "HazardSanitizer",
    "audit_lowered",
    "collective_inventory",
    "constant_audit",
    "donation_audit",
    "donation_drop_warning",
    "dtype_audit",
    "explain_recompile",
    "flatten_args_info",
    "lint_file",
    "lint_paths",
    "lint_source",
    "replication_audit",
    "signature_of",
]
