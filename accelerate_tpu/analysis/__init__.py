"""Static analysis for compiled step and decode programs.

Seven passes over the layers of the stack, one report shape:

- :mod:`.program` — jaxpr/HLO audit of a ``jax.stages.Lowered``/``Compiled``
  program: donation aliasing, fp64 leaks, baked-in constants, the collective
  inventory, and sharding-resolved-to-replication. Reached via
  ``Accelerator.analyze()`` / ``ServingEngine.analyze()``.
- :mod:`.memory` — HBM audit over the executable's buffer assignment:
  argument/output/temp/alias bytes, a peak-HBM estimate, bytes saved by
  donation, ``TEMP_BLOWUP``/``HBM_OVER_BUDGET`` findings.
- :mod:`.schedule` — collective-overlap pass over post-SPMD HLO: pairs
  async start/done collectives, classifies each as overlapped-with-compute
  vs serialized, and prices the serialized-comm bytes on the critical path.
- :mod:`.contracts` — per-program checked-in expectations
  (``tests/contracts/*.json``) turning the audits into a differential
  regression gate: ``CONTRACT_DRIFT`` names exactly which expectation moved
  and by how much.
- :mod:`.sanitizer` — runtime hazard watcher for warm-loop windows: implicit
  device→host syncs, steady-state recompiles (with ``explain_recompile``
  signature diffs), jit-cache misses.
- :mod:`.lint` — AST lint of user step functions (and this repo's own code)
  for trace-time hazards: branching on traced values, wall clocks, host RNG,
  host materialization, captured-state mutation — plus the module-wide
  concurrency rule family (bare acquires, blocking-under-lock, unguarded
  thread-shared state, numpy views into async dispatch, raw locks).
- :mod:`.concurrency` — runtime lock-order race detector: every subsystem
  lock is a :func:`named_lock`, the :class:`LockRegistry` records per-thread
  held-before edges, and :func:`record` patches the blocking boundaries
  (``time.sleep``, ``os.fsync``, ``block_until_ready``, store I/O) so a lock
  held across one becomes a ``LOCK_BLOCKING_HOLD`` finding and an
  acquisition-order cycle becomes ``CONCURRENCY_CYCLE``. Gated by
  ``tests/contracts/concurrency.json``.

CLI: ``accelerate-tpu analyze`` (commands/analyze.py). Findings catalog:
docs/analysis.md.
"""

from .concurrency import (
    ConcurrencyContract,
    LockRegistry,
    gate_concurrency,
    named_lock,
    note_blocking,
    record,
    registry,
)
from .contracts import (
    ProgramContract,
    default_contracts_dir,
    drift_count,
    gate_reports,
    update_contract,
)
from .findings import CATALOG, ERROR, INFO, WARNING, AnalysisReport, Finding
from .lint import lint_file, lint_paths, lint_source
from .memory import memory_audit, memory_summary
from .program import (
    audit_lowered,
    collective_inventory,
    constant_audit,
    donation_audit,
    donation_drop_warning,
    dtype_audit,
    flatten_args_info,
    replication_audit,
)
from .sanitizer import HazardSanitizer, explain_recompile, signature_of
from .schedule import collective_schedule, schedule_audit

__all__ = [
    "CATALOG",
    "ERROR",
    "INFO",
    "WARNING",
    "AnalysisReport",
    "ConcurrencyContract",
    "Finding",
    "HazardSanitizer",
    "LockRegistry",
    "ProgramContract",
    "audit_lowered",
    "collective_inventory",
    "collective_schedule",
    "constant_audit",
    "default_contracts_dir",
    "donation_audit",
    "donation_drop_warning",
    "drift_count",
    "dtype_audit",
    "explain_recompile",
    "flatten_args_info",
    "gate_concurrency",
    "gate_reports",
    "lint_file",
    "lint_paths",
    "lint_source",
    "memory_audit",
    "memory_summary",
    "named_lock",
    "note_blocking",
    "record",
    "registry",
    "replication_audit",
    "schedule_audit",
    "signature_of",
    "update_contract",
]
