"""HBM memory audit: price what the executable will actually hold.

``compiled.memory_analysis()`` is XLA's own buffer-assignment summary —
argument, output, temp, and alias bytes for the exact program that will run.
Those four numbers answer the capacity questions every deploy asks and no
Python review can: *what does a step really cost in HBM* (peak estimate),
*what did donation actually save* (alias bytes — the buffers that exist once
instead of twice), and *did the compiler materialize a temp working set far
larger than the live state* (a missing remat policy, a fusion-defeating
transpose, an accidental upcast).

Two findings:

- ``TEMP_BLOWUP`` (warning) — temp bytes exceed ``temp_blowup_factor`` ×
  argument bytes AND an absolute floor (tiny programs with proportionally
  large scratch are not a capacity problem).
- ``HBM_OVER_BUDGET`` (error) — the peak-HBM estimate exceeds a caller-
  supplied budget. Off by default; contracts (contracts.py) pin the measured
  peak per program instead, which is the repo's own budget line.

The summary lands in ``report.inventory["memory"]`` and is the diffable
observable: the paged-KV PR's "−46.5% HBM/request" and the coming ZeRO PR's
sharded-optimizer-state savings are exactly moves of these numbers.
"""

from __future__ import annotations

from typing import Optional

from .findings import Finding

# temp/argument ratio above which TEMP_BLOWUP fires — 4× means the compiler's
# scratch dwarfs the live state the caller sized the chip for
DEFAULT_TEMP_BLOWUP_FACTOR = 4.0
# ...but only when the temps are big enough to matter on real HBM
TEMP_BLOWUP_FLOOR_BYTES = 64 << 20


def memory_summary(compiled) -> Optional[dict]:
    """Raw byte accounting from the executable's buffer assignment, or None
    when the backend exposes no ``memory_analysis()`` (older plugins)."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return None
    if mem is None:
        return None

    def _field(name: str) -> int:
        try:
            return int(getattr(mem, name, 0) or 0)
        except Exception:
            return 0

    argument = _field("argument_size_in_bytes")
    output = _field("output_size_in_bytes")
    temp = _field("temp_size_in_bytes")
    alias = _field("alias_size_in_bytes")
    code = _field("generated_code_size_in_bytes")
    summary = {
        "argument_bytes": argument,
        "output_bytes": output,
        "temp_bytes": temp,
        "alias_bytes": alias,
        "generated_code_bytes": code,
        # live peak: inputs + outputs coexist with the temp working set,
        # minus the aliased bytes that are one buffer, not two — donation's
        # saving priced in the same line that shows the budget
        "peak_hbm_bytes": max(0, argument + output - alias) + temp + code,
        "donation_saved_bytes": alias,
    }
    host = {
        f"host_{k}_bytes": _field(f"host_{k}_size_in_bytes")
        for k in ("argument", "output", "temp")
    }
    if any(host.values()):  # offload paths only; zero noise otherwise
        summary.update(host)
    return summary


def memory_audit(
    compiled,
    label: str = "program",
    *,
    hbm_budget_bytes: Optional[int] = None,
    temp_blowup_factor: float = DEFAULT_TEMP_BLOWUP_FACTOR,
    temp_blowup_floor_bytes: int = TEMP_BLOWUP_FLOOR_BYTES,
) -> tuple[list[Finding], dict]:
    """Audit one executable's HBM footprint. Returns ``(findings, summary)``;
    the summary is ``{}`` when the backend cannot report buffer sizes, so
    callers can still diff the key's presence."""
    summary = memory_summary(compiled)
    if summary is None:
        return [], {}
    findings: list[Finding] = []
    argument = summary["argument_bytes"]
    temp = summary["temp_bytes"]
    if temp >= temp_blowup_floor_bytes and temp > temp_blowup_factor * max(argument, 1):
        findings.append(
            Finding(
                "TEMP_BLOWUP",
                f"{label}: {temp / (1 << 20):.1f} MiB of temp buffers vs "
                f"{argument / (1 << 20):.1f} MiB of arguments "
                f"({temp / max(argument, 1):.1f}x, threshold {temp_blowup_factor:g}x)",
                path=label,
                data={
                    "temp_bytes": temp,
                    "argument_bytes": argument,
                    "factor": round(temp / max(argument, 1), 2),
                },
            )
        )
    peak = summary["peak_hbm_bytes"]
    if hbm_budget_bytes is not None and peak > hbm_budget_bytes:
        findings.append(
            Finding(
                "HBM_OVER_BUDGET",
                f"{label}: peak-HBM estimate {peak / (1 << 20):.1f} MiB exceeds "
                f"the {hbm_budget_bytes / (1 << 20):.1f} MiB budget by "
                f"{(peak - hbm_budget_bytes) / (1 << 20):.1f} MiB",
                path=label,
                data={"peak_hbm_bytes": peak, "budget_bytes": int(hbm_budget_bytes)},
            )
        )
    return findings, summary
