"""Findings: the analyzer's unit of output.

Every pass — the compiled-program audit (program.py), the runtime hazard
sanitizer (sanitizer.py), and the source lint (lint.py) — emits the same
:class:`Finding` shape, so one :class:`AnalysisReport` can gate CI, diff
across commits, land in ``telemetry.jsonl``, and render for humans.

The catalog below is the single source of truth for finding IDs: severity
defaults, one-line descriptions, and fix hints all live here (docs/analysis.md
renders from the same entries, tests assert the two never drift).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

# severity ladder; ERROR findings gate CI (see tests/test_analysis.py self-gate)
INFO = "info"
WARNING = "warning"
ERROR = "error"
_SEVERITY_ORDER = {INFO: 0, WARNING: 1, ERROR: 2}


@dataclass
class CatalogEntry:
    code: str
    severity: str
    title: str
    fix_hint: str
    example: str = ""


# -- the findings catalog (docs/analysis.md renders this) ---------------------

CATALOG: dict[str, CatalogEntry] = {
    entry.code: entry
    for entry in [
        # program audit (program.py)
        CatalogEntry(
            "DONATION_DROPPED", ERROR,
            "A donated buffer was not aliased to any output",
            "Make the donated input's shape/dtype/sharding match an output, or "
            "drop it from donate_argnums — XLA silently keeps both copies live.",
            "donate_argnums=(0,) but the compiled program aliases 0 of 1 donated buffers",
        ),
        CatalogEntry(
            "DONATION_DISABLED", INFO,
            "Donation was declared but is disabled for this backend",
            "Expected on backends without buffer donation; verify on TPU/GPU "
            "where the HBM saving is real.",
            "ServingEngine built with donation off on the cpu backend",
        ),
        CatalogEntry(
            "DONATION_NONE", INFO,
            "No buffers are donated by this program",
            "Donate the params/opt_state (or KV cache) arguments that the "
            "program overwrites — halves steady-state HBM traffic for them.",
            "a step program rebuilt without donate_argnums",
        ),
        CatalogEntry(
            "FP64_LEAK", ERROR,
            "The program computes in float64/complex128",
            "Find the leaf or literal that upcast (np scalars default to f64) "
            "and cast to f32/bf16; TPUs emulate f64 at ~1/10 throughput.",
            "tensor<4x4xf64> in the lowered StableHLO",
        ),
        CatalogEntry(
            "LARGE_CONSTANT", WARNING,
            "A large constant is baked into the program",
            "Pass the array as an argument instead of closing over it: baked "
            "constants bloat the executable, re-upload on every recompile, and "
            "defeat donation.",
            "a 64 MiB embedding table captured by the jitted step",
        ),
        CatalogEntry(
            "REPLICATED_PARAM", ERROR,
            "A large parameter's sharding resolved to full replication",
            "Add a partition rule (or with_sharding_constraint) for this leaf — "
            "one missing annotation makes GSPMD replicate it on every device.",
            "params['layers']['mlp']['w'] (512 MiB) fully replicated on an 8-way mesh",
        ),
        CatalogEntry(
            "REPLICATED_PARAM_INFO", INFO,
            "A large parameter is fully replicated (no sharding intent declared)",
            "Expected under pure data parallelism; listed so the report diffs "
            "when a sharding config regresses to replication.",
            "bert params replicated under the default data-parallel mesh",
        ),
        # HBM memory audit (memory.py)
        CatalogEntry(
            "TEMP_BLOWUP", WARNING,
            "Temp-buffer bytes dwarf the program's argument bytes",
            "XLA materialized intermediates far larger than the live state — "
            "look for a missing remat policy, an accidental full-precision "
            "upcast, or a transpose that defeated fusion.",
            "a step program with 80 MiB of arguments and 900 MiB of temps",
        ),
        CatalogEntry(
            "HBM_OVER_BUDGET", ERROR,
            "The program's peak-HBM estimate exceeds the caller's budget",
            "Shrink the program (remat, sharding, smaller buckets) or raise "
            "the budget deliberately — this gate exists so HBM growth is a "
            "reviewed decision, not a surprise OOM at deploy.",
            "a decode program estimated at 17.2 GiB against a 16 GiB budget",
        ),
        # collective-schedule pass (schedule.py)
        CatalogEntry(
            "SERIALIZED_COLLECTIVE", INFO,
            "Collectives run serialized with no compute overlapping them",
            "Inventory for the comm/compute-overlap work: serialized-comm "
            "bytes sit on the critical path. Decompose (reduce-scatter + "
            "all-gather) and overlap the gathers with forward compute.",
            "26 all-reduces (1.3 MiB) with their consumers immediately behind them",
        ),
        # program contracts (contracts.py)
        CatalogEntry(
            "CONTRACT_DRIFT", ERROR,
            "A measured program property drifted from its checked-in contract",
            "Either the change is a regression (fix it) or the new value is "
            "intended — rerun with --update-contracts and commit the diff so "
            "the expectation moves in review, not silently.",
            "collectives.all_gather.count: expected 0, got 1 (+1)",
        ),
        CatalogEntry(
            "CONTRACT_MISSING", WARNING,
            "An audited program has no checked-in contract",
            "Run `accelerate-tpu analyze --self-check --contracts "
            "--update-contracts` and commit the generated JSON so the next "
            "change to this program is diffable.",
            "a new prefill span bucket with no tests/contracts entry",
        ),
        CatalogEntry(
            "CONTRACT_UPDATED", INFO,
            "A contract file was written/refreshed by --update-contracts",
            "Commit the JSON diff — the moved expectation is the change's "
            "measured effect, stated in collected numbers.",
            "bert_tiny_step: contract written to tests/contracts/bert_tiny_step.json",
        ),
        CatalogEntry(
            "CONTRACT_ENV_SKIPPED", INFO,
            "A contract was skipped because it was recorded on a different environment",
            "Contracts pin backend + device count (collective counts depend on "
            "both). Regenerate on this environment to gate here too.",
            "an 8-device CPU-mesh contract checked on a 1-device laptop run",
        ),
        # runtime sanitizer (sanitizer.py)
        CatalogEntry(
            "HOST_SYNC", ERROR,
            "A device→host sync happened inside a warm-loop window",
            "Remove the .item()/float()/np.asarray() from the hot loop (batch "
            "reads onto the sampling cadence, or keep values on device).",
            "float(loss) every step stalls the async dispatch pipeline",
        ),
        CatalogEntry(
            "WARM_RECOMPILE", ERROR,
            "A compile happened after the warm-loop window started",
            "The signature diff names the leaf that retraced — stabilize its "
            "shape/dtype (pad to buckets) or mark it static.",
            "a new batch shape forced a retrace at step 50",
        ),
        CatalogEntry(
            "CACHE_MISS", WARNING,
            "A jit-cache miss happened inside a warm-loop window",
            "A program key changed mid-loop (new temperature, toggled dot_fn); "
            "warm every variant up front.",
            "serving decode missed its program cache after warmup",
        ),
        CatalogEntry(
            "H2D_TRANSFER", WARNING,
            "An implicit host→device transfer happened inside a warm-loop window",
            "Move the host array to device once outside the loop (device_put) "
            "instead of re-uploading it every step.",
            "a numpy mask re-uploaded on every decode step",
        ),
        # source lint (lint.py)
        CatalogEntry(
            "TRACED_BRANCH", WARNING,
            "Python branch on a traced value",
            "if/while on a traced value fails (or silently bakes one path at "
            "trace time) — use jax.lax.cond/select, or mark the argument static.",
            "if loss > 0: inside a jitted step function",
        ),
        CatalogEntry(
            "HOST_TIME", ERROR,
            "Wall-clock call inside traced code",
            "time.time() freezes to a trace-time constant — time outside the "
            "jitted function (telemetry.step() already fences correctly).",
            "time.perf_counter() inside a jitted loss",
        ),
        CatalogEntry(
            "HOST_RANDOM", ERROR,
            "Python/numpy RNG call inside traced code",
            "random()/np.random freeze to one trace-time draw — thread a "
            "jax.random key through the function instead.",
            "np.random.uniform() inside a jitted augmentation",
        ),
        CatalogEntry(
            "LINT_HOST_SYNC", ERROR,
            "Host materialization inside traced code",
            ".item()/.tolist()/np.asarray() on a traced value raises under jit "
            "(or silently syncs when leaked) — keep the computation in jnp.",
            "loss.item() inside a jitted step",
        ),
        CatalogEntry(
            "HOST_CAST", WARNING,
            "float()/int()/bool() cast of a possibly-traced value",
            "Casting a traced array to a Python scalar raises under jit; if the "
            "value is a static Python number, waive with a pragma.",
            "float(scale) inside a jitted update",
        ),
        CatalogEntry(
            "CAPTURED_MUTATION", ERROR,
            "Mutation of captured state inside traced code",
            "Writes to globals/nonlocals happen once at trace time, not per "
            "step — return the new value from the function instead.",
            "global step_count; step_count += 1 inside a jitted fn",
        ),
        CatalogEntry(
            "CAPTURED_MUTATION_CALL", WARNING,
            "Mutating method call on a captured object inside traced code",
            ".append()/.update() on captured containers runs at trace time "
            "only — accumulate through the carry/return value instead.",
            "results.append(x) inside a jitted scan body",
        ),
        CatalogEntry(
            "TRACE_PRINT", INFO,
            "print() inside traced code runs at trace time only",
            "Use jax.debug.print() to see per-step values, or drop the print.",
            "print(loss) inside a jitted step prints once, at trace",
        ),
        CatalogEntry(
            "PARSE_ERROR", WARNING,
            "A file handed to the lint could not be parsed",
            "Fix the syntax error (or check the interpreter version) — the "
            "file was not analyzed at all.",
            "a file using syntax newer than the running Python",
        ),
        # concurrency sanitizer (concurrency.py) — runtime detector
        CatalogEntry(
            "CONCURRENCY_CYCLE", ERROR,
            "Lock acquisition-order cycle observed (potential deadlock)",
            "Two code paths take the named locks in opposite orders — pick "
            "one canonical order (or narrow one critical section so the "
            "nested acquire disappears) and keep it.",
            "state.singleton -> hub.write in one thread, hub.write -> "
            "state.singleton in another",
        ),
        CatalogEntry(
            "LOCK_BLOCKING_HOLD", ERROR,
            "A named lock was held across a blocking boundary",
            "Move the sleep/fsync/device-sync/store-I/O outside the critical "
            "section: snapshot (or detach) the guarded state under the lock, "
            "then block without it — every other thread needing the lock "
            "stalls for the full blocking call otherwise.",
            "hub.write held across os.fsync while a tracer retire waits on it",
        ),
        # concurrency lint rules (lint.py) — static AST pass
        CatalogEntry(
            "LOCK_BARE_ACQUIRE", WARNING,
            "Bare lock.acquire() without try/finally or `with`",
            "Use `with lock:` (or acquire immediately before a try whose "
            "finally releases) — any exception between acquire and release "
            "leaves the lock held forever.",
            "self._lock.acquire() followed by fallible code with no finally",
        ),
        CatalogEntry(
            "LOCK_BLOCKING_CALL", WARNING,
            "Blocking call lexically inside a `with <lock>:` body",
            "sleep/fsync/block_until_ready/store-I/O under a lock serializes "
            "every waiter behind the blocking call — do the blocking work "
            "outside the critical section on a local snapshot.",
            "time.sleep(0.1) inside `with self._lock:`",
        ),
        CatalogEntry(
            "THREAD_SHARED_MUTATION", WARNING,
            "A thread target mutates attributes also written unguarded elsewhere",
            "Guard the shared attribute with one lock on both sides, or make "
            "the cross-thread signal a threading.Event — unsynchronized "
            "read-modify-write from two threads is a data race (waive when "
            "the attribute is a monotonic flag with benign races).",
            "threading.Thread(target=self._run) where _run and step() both "
            "write self.state without a lock",
        ),
        CatalogEntry(
            "ASYNC_NP_VIEW", WARNING,
            "A mutable buffer view passed to async jit dispatch",
            "Pass a copy (`table[slot].copy()`): jit dispatch returns before "
            "the device read finishes, so a host-side write to the same "
            "buffer races the in-flight transfer (the PR 9 page-table race).",
            "jitted_step(self.tables[slot]) while another path assigns "
            "self.tables[slot][...] in place",
        ),
        CatalogEntry(
            "LOCK_UNREGISTERED", WARNING,
            "A raw threading.Lock() bypasses the named-lock registry",
            "Construct it via analysis.concurrency.named_lock(\"subsystem."
            "purpose\") so the lock-order detector and the concurrency "
            "contract's inventory can see it.",
            "self._lock = threading.Lock() instead of named_lock(...)",
        ),
        CatalogEntry(
            "LINT_WAIVER_UNUSED", WARNING,
            "A lint waiver pragma suppresses nothing",
            "Delete the stale pragma — left in place it would silently mask "
            "the next real finding on that line.",
            "a stale disable=HOST_CAST pragma on a line with no finding",
        ),
    ]
}


@dataclass
class Finding:
    """One analyzer observation.

    ``path`` locates it: a pytree path for program findings (``params/
    layers/mlp/w``), a ``file:line`` for lint findings, a call-site for
    runtime hazards. ``data`` carries machine-readable detail (byte counts,
    signature diffs) for the jsonl sink.
    """

    code: str
    message: str
    severity: str = ""
    path: Optional[str] = None
    fix_hint: Optional[str] = None
    data: dict = field(default_factory=dict)

    def __post_init__(self):
        entry = CATALOG.get(self.code)
        if not self.severity:
            self.severity = entry.severity if entry else WARNING
        if self.fix_hint is None and entry is not None:
            self.fix_hint = entry.fix_hint

    def to_dict(self) -> dict:
        out = {"code": self.code, "severity": self.severity, "message": self.message}
        if self.path:
            out["path"] = self.path
        if self.fix_hint:
            out["fix_hint"] = self.fix_hint
        if self.data:
            out["data"] = self.data
        return out

    def __str__(self) -> str:
        loc = f" [{self.path}]" if self.path else ""
        return f"{self.severity.upper():7s} {self.code}{loc}: {self.message}"


@dataclass
class AnalysisReport:
    """The analyzer's output: findings + the diffable program inventory.

    ``inventory`` holds what is worth diffing across commits even when no
    finding fires: the collective inventory (counts + bytes per kind), the
    donation summary, and parameter-size/sharding stats. ``meta`` names the
    program and the analysis cost.
    """

    findings: list[Finding] = field(default_factory=list)
    inventory: dict = field(default_factory=dict)
    meta: dict = field(default_factory=dict)
    # merged sub-program reports by prefix (engine prefill spans, fleet
    # replicas) — kept object-level for the contract gate to walk; the
    # serialized form stays flat (their inventories land under the prefix)
    sub_reports: dict = field(default_factory=dict, repr=False)

    def add(self, finding: Finding) -> None:
        self.findings.append(finding)

    def extend(self, findings) -> None:
        self.findings.extend(findings)

    def merge(self, other: "AnalysisReport", prefix: Optional[str] = None) -> None:
        self.findings.extend(other.findings)
        if prefix:
            self.inventory[prefix] = other.inventory
            self.sub_reports[prefix] = other
        else:
            self.inventory.update(other.inventory)

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == WARNING]

    @property
    def has_errors(self) -> bool:
        return bool(self.errors)

    def counts(self) -> dict:
        out = {INFO: 0, WARNING: 0, ERROR: 0}
        for f in self.findings:
            out[f.severity] = out.get(f.severity, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "findings": [f.to_dict() for f in self.sorted_findings()],
            "counts": self.counts(),
            "inventory": self.inventory,
            "meta": self.meta,
        }

    def sorted_findings(self) -> list[Finding]:
        return sorted(
            self.findings,
            key=lambda f: (-_SEVERITY_ORDER.get(f.severity, 0), f.code, f.path or ""),
        )

    def render(self) -> str:
        """Human-readable report (what the CLI prints)."""
        lines = []
        label = self.meta.get("label")
        if label:
            lines.append(f"== analysis: {label} ==")
        counts = self.counts()
        lines.append(
            f"{len(self.findings)} findings "
            f"({counts[ERROR]} error, {counts[WARNING]} warning, {counts[INFO]} info)"
        )
        for f in self.sorted_findings():
            lines.append(f"  {f}")
            if f.fix_hint and f.severity != INFO:
                lines.append(f"          fix: {f.fix_hint}")
        collectives = self.inventory.get("collectives")
        if collectives:
            lines.append("  collectives:")
            for kind, stats in sorted(collectives.items()):
                mib = stats.get("bytes", 0) / (1 << 20)
                lines.append(f"    {kind:20s} count={stats['count']:<4d} bytes={mib:.2f} MiB")
        donation = self.inventory.get("donation")
        if donation:
            lines.append(
                f"  donation: {donation.get('aliased', 0)}/{donation.get('declared', 0)} "
                f"declared buffers aliased"
            )
        memory = self.inventory.get("memory")
        if memory:
            lines.append(
                "  memory: peak-HBM est {:.1f} MiB (args {:.1f} + out {:.1f} "
                "+ temp {:.1f} − alias {:.1f})".format(
                    memory.get("peak_hbm_bytes", 0) / (1 << 20),
                    memory.get("argument_bytes", 0) / (1 << 20),
                    memory.get("output_bytes", 0) / (1 << 20),
                    memory.get("temp_bytes", 0) / (1 << 20),
                    memory.get("donation_saved_bytes", 0) / (1 << 20),
                )
            )
        schedule = self.inventory.get("schedule")
        if schedule and schedule.get("total_count"):
            lines.append(
                "  schedule: {}/{} collectives overlapped; serialized comm "
                "{:.2f} MiB on the critical path".format(
                    schedule.get("overlapped_count", 0),
                    schedule.get("total_count", 0),
                    schedule.get("serialized_comm_bytes", 0) / (1 << 20),
                )
            )
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.render()
