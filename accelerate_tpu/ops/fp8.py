"""fp8 (e4m3) matmul compute with per-tensor scaling.

Parity: the reference converts nn.Linear to TransformerEngine layers under
``fp8_autocast`` with a scaling recipe (utils/transformer_engine.py:24-72,
accelerator.py:1360-1374). XLA has native float8_e4m3fn, so the TPU shape of
the same capability is a scaled-quantize → fp8 ``dot_general`` (fp32
accumulation) → dequantize, swapped into the model zoo's projections via the
``dot_fn`` hook (set by ``Accelerator.prepare_model`` when
``mixed_precision="fp8"``).

Scaling is *current-tensor* (TE "current scaling"): each operand is scaled by
its own abs-max to the e4m3 dynamic range at every call. Gradients flow
straight through the casts (XLA's convert_element_type transpose), so this
trains — the backward matmuls themselves stay in the compute dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

E4M3_MAX = 448.0  # largest finite float8_e4m3fn value


def quantize_e4m3(x: jax.Array, margin: int = 0) -> tuple[jax.Array, jax.Array]:
    """Per-tensor scale to the e4m3 range (minus ``margin`` headroom bits);
    returns (quantized, scale)."""
    amax = jnp.max(jnp.abs(x))
    scale = jnp.maximum(amax, 1e-12) * (2.0**margin) / E4M3_MAX
    return (x / scale).astype(jnp.float8_e4m3fn), scale


def make_fp8_dot(margin: int = 0):
    """Build the fp8 projection matmul with ``margin`` headroom bits in the
    scale (FP8RecipeKwargs.margin — TE recipe parity)."""

    def dot(x: jax.Array, w: jax.Array) -> jax.Array:
        orig_dtype = x.dtype
        qx, sx = quantize_e4m3(x.astype(jnp.float32), margin)
        qw, sw = quantize_e4m3(w.astype(jnp.float32), margin)
        contract = (((x.ndim - 1,), (0,)), ((), ()))
        out = jax.lax.dot_general(qx, qw, contract, preferred_element_type=jnp.float32)
        return (out * (sx * sw)).astype(orig_dtype)

    return dot


# the default recipe: no margin. ``x``: [..., K], ``w``: [K, N]; output in
# ``x``'s dtype — drop-in for the model zoo's projection matmuls.
fp8_dot = make_fp8_dot()
