"""Shared Pallas runtime policy for every kernel in ``ops/``.

All four kernels (flash attention, paged decode attention, fused
dequant-matmul, fused adamw) need the same decision: lower through Mosaic
(real TPU) or run the interpreter (CPU/GPU test meshes, where tier-1
exercises the kernel semantics for real). Before this module each kernel
would have grown its own backend sniff; this is the one definition, plus an
env override for the two debugging directions:

- ``ACCELERATE_PALLAS_INTERPRET=1`` forces interpret mode ON a TPU — step
  through kernel logic with python-level semantics when chasing a Mosaic
  miscompile or a numerics drift;
- ``ACCELERATE_PALLAS_INTERPRET=0`` forces Mosaic lowering everywhere —
  the assert-compiled mode a TPU bench round runs under, so a kernel that
  silently fell back to the interpreter (and its ~100x slowdown) fails
  loudly instead of polluting the recorded numbers.

Unset, the policy is the historical one from ``ops/flash_attention.py``:
interpret everywhere except a real TPU backend.
"""

from __future__ import annotations

import os

import jax

ENV_INTERPRET = "ACCELERATE_PALLAS_INTERPRET"


def interpret_mode() -> bool:
    """Whether Pallas kernels should run in interpret mode right now.

    Consulted at trace time (every ``pallas_call`` site), so flipping the
    env var between program builds takes effect without a restart — but a
    cached jit program keeps the mode it was traced with.
    """
    override = os.environ.get(ENV_INTERPRET)
    if override is not None:
        if override.strip() in ("0", "1"):
            return override.strip() == "1"
        # fail loud, not silent: a typo'd override ("true", "yes") dropped
        # quietly would leave the operator in the OPPOSITE mode they asked
        # for — the exact confusion the env var exists to remove
        from ..logging import get_logger

        get_logger(__name__).warning_once(
            f"{ENV_INTERPRET}={override!r} is not '0' or '1' — ignoring the "
            "override and using the backend default "
            f"(interpret={jax.default_backend() != 'tpu'})."
        )
    return jax.default_backend() != "tpu"


def fit_block(block: int, size: int, floor: int = 1) -> int:
    """Adapt a block size DOWNWARD (halving, to ``floor``) until it divides
    ``size`` — the one tile-fitting rule for every ``ops/`` kernel (the
    flash kernels use it with floor 128, the lane width)."""
    block = min(block, size)
    while block > floor and size % block:
        block //= 2
    return block


def sds(shape, dtype, like) -> jax.ShapeDtypeStruct:
    """Out-shape struct inheriting ``like``'s varying-manual-axes type, so a
    kernel also runs inside shard_map manual regions (the ZeRO step, the
    pipeline schedule). Shared by every ``ops/`` kernel."""
    vma = getattr(getattr(like, "aval", None), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def kernels_default() -> bool:
    """Default for ``use_kernels``-style knobs when the caller passes None:
    on for real TPU backends (the kernels are the fast path there), off for
    CPU/GPU meshes (the reference paths are byte-identical to what every
    pre-kernel program ran, and interpret-mode kernels are slower than the
    XLA reference on a host CPU). Tests and benches opt in explicitly."""
    return jax.default_backend() == "tpu"
