"""Paged decode attention as a Pallas TPU kernel.

The serving engine's reference decode gathers every slot's FULL contiguous
KV view per step (``ServingEngine._gathered_view``: ``jnp.take`` over the
page pool, ``[L, view_len, KV, D]`` per slot per layer) before the model's
einsum attention reads it. The paged layout (PR 7) made HBM *residency*
proportional to tokens actually held, but the gather still moves — and
temporarily materializes — ``view_len`` worth of K/V per slot per token,
regardless of how few positions are valid.

This kernel attends the page pool DIRECTLY: each program owns one
(slot, kv-head) pair — the slot axis rides in as a vmap-batched grid
dimension, so one slot-batched launch serves every lane of the decode step —
walks that slot's int32 page-table row up to its dynamic ``length`` bound,
DMAs one ``[page_size, D]`` page block at a time from HBM into VMEM, and
folds it into an online softmax. The gathered view is never materialized,
invalid pages are never read (a fresh request touches one page, not
``view_len``), and the current token's K/V — not yet scattered into the
pool — joins the softmax as a final key, so the engine's write-back stays
a separate scatter exactly as in the reference program.

Numerics: scores accumulate in fp32 (``preferred_element_type``), the
running max starts at the flash kernel's ``M_INIT`` so padded tail
positions of a partial page underflow ``exp`` to exactly 0. The new-token
score is always valid, so a decode row can never be fully masked. At
temperature 0 the engine's kernel path emits the same tokens as the
gather-reference path (pinned by tests/test_paged_attention.py over mixed
lengths for both decode protocols); the blocked accumulation order means
logits agree to roundoff, not bit-for-bit.

Off-TPU the kernel runs in interpret mode (tier-1 exercises the page walk
for real); shapes Mosaic cannot tile (lane-unaligned head dim) fall back to
a gather reference with identical masking semantics.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import M_INIT, NEG_INF
from .runtime import interpret_mode


def paged_kernel_fallback_reason(
    page_shape: tuple, num_heads: int, kv_heads: int
) -> Optional[str]:
    """Why the paged decode kernel cannot serve this pool geometry (None =
    it can). Interpret mode runs any shape; Mosaic needs the head dim to
    fill lanes. The engine records the reason in its ``{"kind":"kernels"}``
    telemetry so a fleet's kernel coverage is a query away."""
    ps, d = int(page_shape[-3]), int(page_shape[-1])
    if num_heads % kv_heads:
        return f"num_heads {num_heads} not a multiple of kv_heads {kv_heads}"
    if interpret_mode():
        return None
    if d % 128:
        return f"head dim {d} is not a multiple of 128 (Mosaic lane tiling)"
    if ps % 8:
        return f"page_size {ps} is not a multiple of 8 (fp32 sublane tiling)"
    return None


def _decode_kernel(
    table_ref,  # SMEM [1, pps] int32: this slot's page-table row
    length_ref,  # SMEM [1, 1] int32: valid positions already in the pool
    q_ref,  # VMEM [1, group, D]: the q heads sharing this kv head (pre-scaled)
    kn_ref,  # VMEM [1, D]: current token's key for this kv head
    vn_ref,  # VMEM [1, D]: current token's value
    pool_k_ref,  # ANY (HBM) [P, ps, KV, D]
    pool_v_ref,  # ANY (HBM) [P, ps, KV, D]
    o_ref,  # VMEM [1, group, D] out
    k_scratch,  # VMEM [ps, D] pool dtype
    v_scratch,  # VMEM [ps, D]
    sems,  # DMA semaphores (2,)
    *,
    page_size: int,
):
    g = pl.program_id(0)  # kv head (slot axis joins via vmap batching)
    length = length_ref[0, 0]
    q = q_ref[0]  # [group, D]
    group, d = q.shape

    m = jnp.full((group, 1), M_INIT, jnp.float32)
    l = jnp.zeros((group, 1), jnp.float32)
    acc = jnp.zeros((group, d), jnp.float32)

    # pages holding positions 0..length-1 (zero-trip for a fresh/idle lane)
    npages = jax.lax.div(length + jnp.int32(page_size - 1), jnp.int32(page_size))
    pos_in_page = jax.lax.broadcasted_iota(jnp.int32, (group, page_size), 1)

    def body(j, carry):
        m, l, acc = carry
        page = table_ref[0, j]
        k_dma = pltpu.make_async_copy(pool_k_ref.at[page, :, g, :], k_scratch, sems.at[0])
        v_dma = pltpu.make_async_copy(pool_v_ref.at[page, :, g, :], v_scratch, sems.at[1])
        k_dma.start()
        v_dma.start()
        k_dma.wait()
        v_dma.wait()
        s = jax.lax.dot_general(
            q, k_scratch[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [group, ps]
        # mask the partial last page: positions >= length hold stale pool
        # data (or the unwritten tail) and must underflow exp to exactly 0
        s = jnp.where(j * page_size + pos_in_page < length, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + jax.lax.dot_general(
            p.astype(v_scratch.dtype), v_scratch[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, npages, body, (m, l, acc))

    # the current token (position == length) is not in the pool yet — it is
    # the engine's post-step scatter — so it joins as one final key here
    kn = kn_ref[:]  # [1, D]
    vn = vn_ref[:]
    s_new = jax.lax.dot_general(
        q, kn, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [group, 1]
    m_new = jnp.maximum(m, s_new)
    correction = jnp.exp(m - m_new)
    p_new = jnp.exp(s_new - m_new)
    l = l * correction + p_new
    acc = acc * correction + jax.lax.dot_general(
        p_new.astype(vn.dtype), vn, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _reference(q, k_new, v_new, pool_k, pool_v, table, length, scale):
    """Gather-based fallback with the kernel's exact masking semantics —
    attends the table-gathered view plus the new token. Only reached for
    Mosaic-untileable geometries; the engine's ``use_kernels=False`` path is
    a different (byte-identical-to-PR-7) program and never lands here."""
    from ..models.attention import dot_product_attention

    taken_k = jnp.take(pool_k, table, axis=0).reshape(-1, *pool_k.shape[2:])
    taken_v = jnp.take(pool_v, table, axis=0).reshape(-1, *pool_v.shape[2:])
    keys = jnp.concatenate([taken_k, k_new[0]], axis=0)[None]  # [1, T+1, KV, D]
    values = jnp.concatenate([taken_v, v_new[0]], axis=0)[None]
    t = taken_k.shape[0]
    valid = jnp.concatenate(
        [jnp.arange(t) < length, jnp.ones((1,), bool)]
    )[None, None, None, :]
    return dot_product_attention(q, keys, values, mask=valid, scale=scale)


def paged_decode_attention(
    q: jax.Array,  # [1, 1, NH, D]: one slot's single decode query
    k_new: jax.Array,  # [1, 1, KV, D]: current token's key (pre-scatter)
    v_new: jax.Array,  # [1, 1, KV, D]
    pool_k: jax.Array,  # [P, page_size, KV, D]: one layer of the page pool
    pool_v: jax.Array,  # [P, page_size, KV, D]
    table: jax.Array,  # [pps] int32 page-table row
    length: jax.Array,  # scalar int32: positions already in the pool
    scale: Optional[float] = None,
) -> jax.Array:
    """One decode token's attention over its paged KV — the ``attend`` hook
    the serving engine threads through the models' decode-cache protocol
    (``decoder_layer`` / ``GPT2._block``) when ``use_kernels`` is on. The
    engine's vmap over slots batches the launch, so the compiled program is
    ONE slot-batched ``pallas_call`` per layer per decode step."""
    _, _, nh, d = q.shape
    kv = k_new.shape[2]
    ps = pool_k.shape[-3]
    if scale is None:
        scale = 1.0 / (d**0.5)
    if paged_kernel_fallback_reason(pool_k.shape, nh, kv) is not None:
        return _reference(q, k_new, v_new, pool_k, pool_v, table, length, scale)
    # the reference einsum path scales q (in q's dtype) before the score
    # matmul — mirror it so kernel and reference agree to roundoff
    qs = (q * jnp.asarray(scale, q.dtype))[0, 0]  # [NH, D]
    group = nh // kv
    out = pl.pallas_call(
        functools.partial(_decode_kernel, page_size=ps),
        grid=(kv,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # table [1, pps]
            pl.BlockSpec(memory_space=pltpu.SMEM),  # length [1, 1]
            pl.BlockSpec((1, group, d), lambda g: (g, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((1, group, d), lambda g: (g, 0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((kv, group, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((ps, d), pool_k.dtype),
            pltpu.VMEM((ps, d), pool_v.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret_mode(),
    )(
        table.reshape(1, -1).astype(jnp.int32),
        jnp.asarray(length, jnp.int32).reshape(1, 1),
        qs.reshape(kv, group, d),
        k_new[0, 0],
        v_new[0, 0],
        pool_k,
        pool_v,
    )
    return out.reshape(1, 1, nh, d)


def _verify_kernel(
    table_ref,  # SMEM [1, pps] int32: this slot's page-table row
    length_ref,  # SMEM [1, 1] int32: committed positions in the pool
    q_ref,  # VMEM [1, W*group, D]: window queries, row = wi*group + gi (pre-scaled)
    kn_ref,  # VMEM [1, W, D]: the window's keys for this kv head (pre-scatter)
    vn_ref,  # VMEM [1, W, D]
    pool_k_ref,  # ANY (HBM) [P, ps, KV, D]
    pool_v_ref,  # ANY (HBM) [P, ps, KV, D]
    o_ref,  # VMEM [1, W*group, D] out
    k_scratch,  # VMEM [ps, D] pool dtype
    v_scratch,  # VMEM [ps, D]
    sems,  # DMA semaphores (2,)
    *,
    page_size: int,
    window: int,
    group: int,
):
    g = pl.program_id(0)  # kv head (slot axis joins via vmap batching)
    length = length_ref[0, 0]
    q = q_ref[0]  # [W*group, D]
    rows, d = q.shape

    m = jnp.full((rows, 1), M_INIT, jnp.float32)
    l = jnp.zeros((rows, 1), jnp.float32)
    acc = jnp.zeros((rows, d), jnp.float32)

    # committed pages (positions 0..length-1): every window row attends all
    # of them — the page walk is the decode kernel's, with W*group query rows
    npages = jax.lax.div(length + jnp.int32(page_size - 1), jnp.int32(page_size))
    pos_in_page = jax.lax.broadcasted_iota(jnp.int32, (rows, page_size), 1)

    def body(j, carry):
        m, l, acc = carry
        page = table_ref[0, j]
        k_dma = pltpu.make_async_copy(pool_k_ref.at[page, :, g, :], k_scratch, sems.at[0])
        v_dma = pltpu.make_async_copy(pool_v_ref.at[page, :, g, :], v_scratch, sems.at[1])
        k_dma.start()
        v_dma.start()
        k_dma.wait()
        v_dma.wait()
        s = jax.lax.dot_general(
            q, k_scratch[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [rows, ps]
        s = jnp.where(j * page_size + pos_in_page < length, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + jax.lax.dot_general(
            p.astype(v_scratch.dtype), v_scratch[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, npages, body, (m, l, acc))

    # the candidate window (positions length..length+W-1) is not in the pool
    # yet — the engine's write-back is a separate masked scatter — so it folds
    # in as one final block with a causal mask INSIDE the window: query row
    # wi*group+gi (window position wi) may attend window keys 0..wi. Row 0
    # attends exactly its own key, reducing to the decode kernel at W=1.
    kn = kn_ref[0]  # [W, D]
    vn = vn_ref[0]
    s_w = jax.lax.dot_general(
        q, kn, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [rows, W]
    row_pos = jax.lax.broadcasted_iota(jnp.int32, (rows, window), 0) // group
    key_pos = jax.lax.broadcasted_iota(jnp.int32, (rows, window), 1)
    s_w = jnp.where(key_pos <= row_pos, s_w, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s_w, axis=-1, keepdims=True))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(s_w - m_new)
    l = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    acc = acc * correction + jax.lax.dot_general(
        p.astype(vn.dtype), vn, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _verify_reference(q, k_new, v_new, pool_k, pool_v, table, length, scale):
    """Gather-based fallback with the verify kernel's exact masking
    semantics: the table-gathered view (positions < length valid) plus the
    candidate window under a lower-triangular in-window mask."""
    from ..models.attention import dot_product_attention

    taken_k = jnp.take(pool_k, table, axis=0).reshape(-1, *pool_k.shape[2:])
    taken_v = jnp.take(pool_v, table, axis=0).reshape(-1, *pool_v.shape[2:])
    keys = jnp.concatenate([taken_k, k_new[0]], axis=0)[None]  # [1, T+W, KV, D]
    values = jnp.concatenate([taken_v, v_new[0]], axis=0)[None]
    t = taken_k.shape[0]
    w = q.shape[1]
    committed = jnp.broadcast_to(jnp.arange(t)[None, :] < length, (w, t))
    in_window = jnp.tril(jnp.ones((w, w), bool))
    valid = jnp.concatenate([committed, in_window], axis=1)[None, None]  # [1,1,W,T+W]
    return dot_product_attention(q, keys, values, mask=valid, scale=scale)


def paged_verify_attention(
    q: jax.Array,  # [1, W, NH, D]: one slot's candidate-window queries
    k_new: jax.Array,  # [1, W, KV, D]: the window's keys (pre-scatter)
    v_new: jax.Array,  # [1, W, KV, D]
    pool_k: jax.Array,  # [P, page_size, KV, D]: one layer of the page pool
    pool_v: jax.Array,  # [P, page_size, KV, D]
    table: jax.Array,  # [pps] int32 page-table row
    length: jax.Array,  # scalar int32: committed positions in the pool
    scale: Optional[float] = None,
) -> jax.Array:
    """Speculative-decoding verify: score a W=k+1 candidate window against a
    slot's paged KV in ONE launch — the decode kernel with a window axis.
    Each (slot, kv-head) program walks the committed pages exactly as
    :func:`paged_decode_attention` does, then folds the window's own keys in
    under a causal in-window mask. The serving engine threads this as the
    ``attend`` hook of the window protocol
    (:func:`~..models.generation.forward_window_with_cache`); its vmap over
    slots batches the launch."""
    _, w, nh, d = q.shape
    kv = k_new.shape[2]
    ps = pool_k.shape[-3]
    if scale is None:
        scale = 1.0 / (d**0.5)
    if paged_kernel_fallback_reason(pool_k.shape, nh, kv) is not None:
        return _verify_reference(q, k_new, v_new, pool_k, pool_v, table, length, scale)
    qs = (q * jnp.asarray(scale, q.dtype))[0]  # [W, NH, D]
    group = nh // kv
    # row layout (kv, W*group): row wi*group+gi is window position wi of the
    # gi-th query head sharing kv head g — head h = g*group+gi, as in decode
    qs = qs.reshape(w, kv, group, d).transpose(1, 0, 2, 3).reshape(kv, w * group, d)
    out = pl.pallas_call(
        functools.partial(_verify_kernel, page_size=ps, window=w, group=group),
        grid=(kv,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # table [1, pps]
            pl.BlockSpec(memory_space=pltpu.SMEM),  # length [1, 1]
            pl.BlockSpec((1, w * group, d), lambda g: (g, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, w, d), lambda g: (g, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, w, d), lambda g: (g, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((1, w * group, d), lambda g: (g, 0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((kv, w * group, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((ps, d), pool_k.dtype),
            pltpu.VMEM((ps, d), pool_v.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret_mode(),
    )(
        table.reshape(1, -1).astype(jnp.int32),
        jnp.asarray(length, jnp.int32).reshape(1, 1),
        qs,
        jnp.moveaxis(k_new[0], 1, 0),  # (KV, W, D)
        jnp.moveaxis(v_new[0], 1, 0),
        pool_k,
        pool_v,
    )
    return out.reshape(kv, w, group, d).transpose(1, 0, 2, 3).reshape(1, w, nh, d)
