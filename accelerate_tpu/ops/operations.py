"""Tree-recursive tensor utilities and host-level collectives.

Parity: reference utils/operations.py (recursively_apply:84, send_to_device:135,
gather:308-441, gather_object:451, broadcast:545, broadcast_object_list:566,
reduce:727, pad_across_processes:634, concatenate:607, slice_tensors:587,
convert_outputs_to_fp32:818, verify_operation:370).

Semantics shift: the reference's collectives move per-rank tensors through
NCCL/xm at every call. Here there are two distinct worlds:

1. **Inside jit** nothing in this file is needed — sharding annotations make
   XLA emit ICI collectives.
2. **Outside jit (this file)** data is either a *global* ``jax.Array`` (already
   the result of an SPMD computation — "gather" just means fetch/replicate) or
   *host-local* numpy (per-host loader output, metrics — "gather" means
   all-gather across hosts via ``multihost_utils``).

Every function is recursive over nested list/tuple/dict/namedtuple trees.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

import numpy as np

import jax
import jax.numpy as jnp

from ..state import PartialState


class DistributedOperationException(Exception):
    """Raised by debug-mode verification when per-host operands disagree."""


# ---------------------------------------------------------------------------
# tree recursion
# ---------------------------------------------------------------------------


def honor_type(obj, generator):
    """Rebuild ``obj``'s container type (incl. namedtuples) from ``generator``."""
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # namedtuple
        return type(obj)(*list(generator))
    return type(obj)(generator)


def is_tensor(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray)) and not isinstance(x, np.generic)


def recursively_apply(
    func: Callable,
    data: Any,
    *args,
    test_type: Callable = is_tensor,
    error_on_other_type: bool = False,
    **kwargs,
):
    """Apply ``func`` to every leaf of a nested container passing ``test_type``."""
    if isinstance(data, (tuple, list)):
        return honor_type(
            data,
            (
                recursively_apply(
                    func, o, *args, test_type=test_type, error_on_other_type=error_on_other_type, **kwargs
                )
                for o in data
            ),
        )
    if isinstance(data, Mapping):
        return type(data)(
            {
                k: recursively_apply(
                    func, v, *args, test_type=test_type, error_on_other_type=error_on_other_type, **kwargs
                )
                for k, v in data.items()
            }
        )
    if test_type(data):
        return func(data, *args, **kwargs)
    if error_on_other_type:
        raise TypeError(
            f"Unsupported type {type(data)} passed to {getattr(func, '__name__', func)}; only nested "
            "list/tuple/dict of arrays are supported."
        )
    return data


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------


def send_to_device(tensor, device=None, non_blocking: bool = False, skip_keys=None):
    """Recursively place arrays on ``device`` (a Device or NamedSharding).

    ``device=None`` targets the batch sharding of the active mesh — the usual
    case for training batches. ``skip_keys`` mirrors the reference's API for
    dict entries that should stay on host.
    """
    if device is None:
        device = PartialState().data_sharding()
    if isinstance(skip_keys, str):
        skip_keys = [skip_keys]

    def _send(t):
        target = device
        state = PartialState()
        if isinstance(target, jax.sharding.NamedSharding):
            # Leaves that can't split evenly over the batch axes (scalars,
            # odd-length metadata) are replicated instead. Multi-process, the
            # input is this HOST's rows, so the global extent is rows × hosts.
            entry = target.spec[0] if len(target.spec) else None
            axes = (entry,) if isinstance(entry, str) else (entry or ())
            split = 1
            for axis in axes:
                split *= target.mesh.shape[axis]
            # every process must hold the same LOCAL row count (the loaders'
            # even-batch padding guarantees it); values may differ per host
            global_rows = (t.shape[0] if t.ndim else 0) * state.num_processes
            if t.ndim == 0 or (split > 1 and global_rows % split != 0):
                if state.num_processes > 1:
                    # replicated fallback (scalars, odd-length metadata): take
                    # rank 0's value so every host installs the SAME global
                    # array. Per-host ROW data that lands here is a bug on the
                    # caller's side — pad it (ops.pad_across_processes) or use
                    # an even-batch loader.
                    from jax.experimental import multihost_utils

                    return jax.device_put(
                        multihost_utils.broadcast_one_to_all(jnp.asarray(t)),
                        jax.sharding.NamedSharding(target.mesh, jax.sharding.PartitionSpec()),
                    )
                target = jax.sharding.NamedSharding(target.mesh, jax.sharding.PartitionSpec())
            elif state.num_processes > 1 and split > 1:
                # per-host VALUES differ: assemble the global array from
                # process-local shards (a replicated device_put would install
                # rank-dependent data)
                return jax.make_array_from_process_local_data(target, np.asarray(t))
        return jax.device_put(t, target)

    if skip_keys:
        # skip_keys applies at every Mapping level of the tree (reference
        # operations.py:178,187), so recurse manually through containers.
        if isinstance(tensor, Mapping):
            return type(tensor)(
                {
                    k: (v if k in skip_keys else send_to_device(v, device, skip_keys=skip_keys))
                    for k, v in tensor.items()
                }
            )
        if isinstance(tensor, (tuple, list)):
            return honor_type(tensor, (send_to_device(v, device, skip_keys=skip_keys) for v in tensor))
    return recursively_apply(_send, tensor)


def to_numpy(tensor):
    """Fetch every leaf to host numpy (fully replicating sharded arrays)."""

    def _get(t):
        if isinstance(t, jax.Array) and not t.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(t, tiled=True))
        return np.asarray(t)

    return recursively_apply(_get, tensor)


# ---------------------------------------------------------------------------
# introspection
# ---------------------------------------------------------------------------


def find_device(data):
    """First device found in the tree (reference operations.py:830)."""
    if isinstance(data, Mapping):
        for v in data.values():
            d = find_device(v)
            if d is not None:
                return d
    elif isinstance(data, (tuple, list)):
        for v in data:
            d = find_device(v)
            if d is not None:
                return d
    elif isinstance(data, jax.Array):
        return next(iter(data.devices()))
    return None


def find_batch_size(data):
    """Leading-dim size of the first array leaf (reference operations.py:254)."""
    if isinstance(data, Mapping):
        for v in data.values():
            b = find_batch_size(v)
            if b is not None:
                return b
    elif isinstance(data, (tuple, list)):
        for v in data:
            b = find_batch_size(v)
            if b is not None:
                return b
    elif is_tensor(data) and data.ndim >= 1:
        return data.shape[0]
    return None


def get_shape(data):
    return recursively_apply(lambda t: list(t.shape), data)


def get_data_structure(data):
    """Shape+dtype skeleton used to rebuild trees across hosts (operations.py:244)."""
    from ..utils.dataclasses import TensorInformation

    return recursively_apply(lambda t: TensorInformation(shape=tuple(t.shape), dtype=t.dtype), data)


def initialize_tensors(data_structure):
    from ..utils.dataclasses import TensorInformation

    return recursively_apply(
        lambda ti: np.empty(ti.shape, dtype=ti.dtype),
        data_structure,
        test_type=lambda x: isinstance(x, TensorInformation),
    )


def listify(data):
    """Arrays → nested python lists (reference operations.py:203)."""
    return recursively_apply(lambda t: np.asarray(t).tolist(), data)


def slice_tensors(data, tensor_slice, process_index=None, num_processes=None):
    return recursively_apply(lambda t: t[tensor_slice], data)


def concatenate(data, dim: int = 0):
    """Concatenate a list of same-structure trees leafwise (operations.py:607)."""
    first = data[0]
    if isinstance(first, (tuple, list)):
        return honor_type(first, (concatenate([d[i] for d in data], dim=dim) for i in range(len(first))))
    if isinstance(first, Mapping):
        return type(first)({k: concatenate([d[k] for d in data], dim=dim) for k in first.keys()})
    if isinstance(first, jax.Array):
        return jnp.concatenate(data, axis=dim)
    return np.concatenate(data, axis=dim)


# ---------------------------------------------------------------------------
# debug-mode operation verification (reference operations.py:370-421, §5.2)
# ---------------------------------------------------------------------------


def _verify_same_shapes(operation: str, tensor) -> None:
    state = PartialState()
    if not state.debug or state.num_processes == 1:
        return
    shapes = gather_object([get_shape(tensor)])
    if any(s != shapes[0] for s in shapes):
        table = "\n".join(f"  - Process {i}: {s}" for i, s in enumerate(shapes))
        raise DistributedOperationException(
            f"Cannot apply the desired operation ({operation}) due to shape mismatches across processes:\n{table}"
        )


# ---------------------------------------------------------------------------
# host-level collectives
# ---------------------------------------------------------------------------


def _is_global_jax_array(t) -> bool:
    return isinstance(t, jax.Array) and len(t.sharding.device_set) > 1


def gather(tensor):
    """All-gather across the data dimension.

    - global sharded ``jax.Array``: replicate + fetch (the array already *is*
      the global batch; XLA's all-gather happens in ``to_numpy``).
    - host-local array in a multi-host job: concat every host's copy along the
      leading axis (reference all_gather semantics).
    """
    _verify_same_shapes("gather", tensor)
    state = PartialState()

    def _gather(t):
        if _is_global_jax_array(t):
            return to_numpy(t)
        if state.num_processes > 1:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(np.asarray(t), tiled=False)).reshape(
                (-1,) + tuple(t.shape[1:])
            )
        return np.asarray(t)

    return recursively_apply(_gather, tensor, error_on_other_type=True)


def gather_object(obj: list):
    """Gather a list of picklable objects from every host (operations.py:451).

    One padded ``process_allgather`` round regardless of host count: each host
    contributes (size, pickled-bytes) padded to the global max.
    """
    import pickle

    state = PartialState()
    if state.num_processes == 1:
        return list(obj)
    from jax.experimental import multihost_utils

    blob = np.frombuffer(pickle.dumps(list(obj)), dtype=np.uint8)
    sizes = multihost_utils.process_allgather(np.array([blob.size], dtype=np.int64))
    max_size = int(np.max(sizes))
    padded = np.zeros(max_size, dtype=np.uint8)
    padded[: blob.size] = blob
    blobs = multihost_utils.process_allgather(padded)
    gathered = []
    for p in range(state.num_processes):
        gathered.extend(pickle.loads(bytes(bytearray(np.asarray(blobs[p][: int(sizes[p][0])])))))
    return gathered


def _broadcast_py(obj, src: int = 0):
    """Broadcast an arbitrary picklable object from host ``src``."""
    import pickle

    state = PartialState()
    if state.num_processes == 1:
        return obj
    from jax.experimental import multihost_utils

    if state.process_index == src:
        blob = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        size = np.array([blob.size], dtype=np.int64)
    else:
        blob = None
        size = np.zeros(1, dtype=np.int64)
    # Two rounds: size, then payload. broadcast_one_to_all only sends from
    # process 0, so for src != 0 we route through an allgather.
    if src == 0:
        size = multihost_utils.broadcast_one_to_all(size)
        buf = blob if blob is not None else np.zeros(int(size[0]), dtype=np.uint8)
        buf = multihost_utils.broadcast_one_to_all(buf)
    else:
        sizes = multihost_utils.process_allgather(size)
        size = sizes[src]
        buf_local = blob if blob is not None else np.zeros(int(size[0]), dtype=np.uint8)
        pad = np.zeros(int(np.max(sizes)), dtype=np.uint8)
        pad[: buf_local.size] = buf_local
        bufs = multihost_utils.process_allgather(pad)
        buf = bufs[src][: int(size[0])]
    return pickle.loads(bytes(bytearray(np.asarray(buf))))


def broadcast(tensor, from_process: int = 0):
    """Broadcast each array leaf from ``from_process`` (operations.py:545)."""
    _verify_same_shapes("broadcast", tensor)
    state = PartialState()
    if state.num_processes == 1:
        return tensor

    def _bcast(t):
        return _broadcast_py(np.asarray(t), src=from_process)

    return recursively_apply(_bcast, tensor, error_on_other_type=True)


def broadcast_object_list(object_list: list, from_process: int = 0):
    """In-place broadcast of a list of objects (operations.py:566)."""
    state = PartialState()
    if state.num_processes == 1:
        return object_list
    received = _broadcast_py(list(object_list), src=from_process)
    object_list[:] = received
    return object_list


def reduce(tensor, reduction: str = "mean", scale: float = 1.0):
    """Sum/mean each leaf across hosts (operations.py:727).

    Semantics per leaf kind:
    - host-local numpy in a multi-host job: true cross-host reduction (the
      reference's per-rank all_reduce).
    - global ``jax.Array`` (sharded or replicated): the leaf already *is* one
      logical global value produced under SPMD — there is nothing left to
      reduce, so it is fetched as-is (``reduction`` does not multiply by the
      host count; that would double-count replication).
    """
    state = PartialState()

    def _reduce(t):
        if _is_global_jax_array(t):
            arr = to_numpy(t)
        elif state.num_processes > 1:
            from jax.experimental import multihost_utils

            stacked = np.asarray(multihost_utils.process_allgather(np.asarray(t), tiled=False))
            arr = stacked.sum(axis=0)
            if reduction == "mean":
                arr = arr / state.num_processes
            return arr * scale
        else:
            arr = np.asarray(t)
        return arr * scale

    return recursively_apply(_reduce, tensor, error_on_other_type=True)


def pad_across_processes(tensor, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
    """Pad each host's array to the max size along ``dim`` (operations.py:634)."""
    state = PartialState()

    def _pad(t):
        t = np.asarray(t)
        if t.ndim == 0 or state.num_processes == 1:
            return t
        sizes = gather_object([t.shape[dim]])
        max_size = max(sizes)
        if t.shape[dim] == max_size:
            return t
        new_shape = list(t.shape)
        new_shape[dim] = max_size
        out = np.full(new_shape, pad_index, dtype=t.dtype)
        idx = [slice(None)] * t.ndim
        if pad_first:
            idx[dim] = slice(max_size - t.shape[dim], max_size)
        else:
            idx[dim] = slice(0, t.shape[dim])
        out[tuple(idx)] = t
        return out

    return recursively_apply(_pad, tensor, error_on_other_type=True)


def pad_input_tensors(tensor, batch_size: int, num_processes: int, dim: int = 0):
    """Pad batch to divisibility by num_processes (operations.py:686)."""

    remainder = batch_size % num_processes
    if remainder == 0:
        return tensor
    pad_count = num_processes - remainder

    def _pad(t):
        t = np.asarray(t)
        if t.shape[dim] != batch_size:
            return t
        reps = [1] * t.ndim
        reps[dim] = pad_count
        tail = np.take(t, [-1], axis=dim)
        return np.concatenate([t, np.tile(tail, reps)], axis=dim)

    return recursively_apply(_pad, tensor, error_on_other_type=True)


# ---------------------------------------------------------------------------
# dtype conversion (reference operations.py:768-827)
# ---------------------------------------------------------------------------


def convert_to_fp32(tensor):
    def _upcast(t):
        if hasattr(t, "dtype") and t.dtype in (jnp.float16, jnp.bfloat16):
            return t.astype(jnp.float32) if isinstance(t, jax.Array) else np.asarray(t, dtype=np.float32)
        return t

    return recursively_apply(_upcast, tensor)


class ConvertOutputsToFp32:
    """Pickleable callable wrapper upcasting a function's outputs to fp32."""

    def __init__(self, model_forward: Callable):
        self.model_forward = model_forward

    def __call__(self, *args, **kwargs):
        return convert_to_fp32(self.model_forward(*args, **kwargs))

    def __getstate__(self):
        return {"model_forward": self.model_forward}

    def __setstate__(self, state):
        self.model_forward = state["model_forward"]


def convert_outputs_to_fp32(model_forward: Callable) -> Callable:
    return ConvertOutputsToFp32(model_forward)
