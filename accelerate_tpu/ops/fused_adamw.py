"""Fused adamw update as a Pallas TPU kernel.

PR 11's ZeRO step closes with ``sharded adamw`` on 1/N state — optax's
``adamw`` there lowers to a chain of ~10 elementwise HLO ops per buffer
(moment EMAs, bias corrections, rsqrt, weight decay, apply), which XLA fuses
only partially: params, both moments, and grads round-trip HBM several
times per step. This kernel runs the WHOLE m/v/param update in one pass —
each buffer is read once and written once, in place
(``input_output_aliases``), so the sharded update stays bandwidth-optimal
in the spirit of the cross-replica weight-update sharding it implements
(arXiv 2004.13336).

:func:`fused_adamw` is the opt-in: a drop-in for ``optax.adamw`` (same
state pytree — ``ScaleByAdamState`` + two ``EmptyState``s — so
checkpointing, sharding layouts, and the coupling probe all treat it as
optax) whose ``update`` IS optax's, plus a ``fused_apply`` the shared
update seam (``optimizer.scaled_optimizer_update``) dispatches to. Both the
eager update path and the ZeRO manual-shard_map step therefore engage the
kernel through one seam, and the opt-out is simply ``optax.adamw``.

Bit-exactness: the kernel replays optax's exact elementwise sequence —
``mu' = (1-b1)·g + b1·mu``; ``nu' = (1-b2)·g² + b2·nu``; bias corrections
``1 - bᵢ^t`` computed OUTSIDE the kernel with optax's own expression (pow
implementations differ between Mosaic and XLA; a scalar per step costs
nothing); ``u = mû/(√(ν̂+eps_root)+eps) + wd·p``; ``p' = p - lr·u`` — so
``tests/test_fused_adamw.py`` pins tolerance-0 equality against
``optax.adamw`` per step, and the ZeRO update-equivalence gate holds with
the kernel engaged. Leaves whose element count cannot tile (and every leaf
on Mosaic-unaligned geometries) take a reference path built from the SAME
formula, keeping the transform exact leaf by leaf.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .runtime import fit_block as _fit
from .runtime import interpret_mode, sds

# lane width 128 is fixed; rows per block bound the VMEM working set
# (4 operands + 3 outputs x 8 sublane-rows x 512 lanes x 4B ~= 7 MB ceiling)
_LANES = 512
_BLOCK_ROWS = 256


class AdamWHyperparams(NamedTuple):
    """Static hyperparameters (hashable: they ride the kernel's closure)."""

    learning_rate: float
    b1: float
    b2: float
    eps: float
    eps_root: float
    weight_decay: float


def _leaf_geometry(n: int) -> Optional[tuple[int, int, int]]:
    """(rows, cols, block_rows) tiling ``n`` elements, or None when the leaf
    cannot tile (kernel falls back to the reference formula for that leaf).
    Mosaic needs 128-multiple lanes; interpret mode takes any 2-D split."""
    cols = _fit(_LANES, n, floor=1)
    if n % cols:
        return None
    rows = n // cols
    if not interpret_mode() and (cols % 128 or rows % 8):
        return None
    return rows, cols, _fit(_BLOCK_ROWS, rows, floor=1)


def _adamw_kernel(bc_ref, p_ref, mu_ref, nu_ref, g_ref, po_ref, muo_ref, nuo_ref, *, hp):
    g = g_ref[:].astype(jnp.float32)
    mu = (1.0 - hp.b1) * g + hp.b1 * mu_ref[:].astype(jnp.float32)
    nu = (1.0 - hp.b2) * (g * g) + hp.b2 * nu_ref[:].astype(jnp.float32)
    mu_hat = mu / bc_ref[0, 0]
    nu_hat = nu / bc_ref[0, 1]
    u = mu_hat / (jnp.sqrt(nu_hat + hp.eps_root) + hp.eps)
    p32 = p_ref[:].astype(jnp.float32)
    u = u + hp.weight_decay * p32
    po_ref[:] = (p32 + (-hp.learning_rate) * u).astype(po_ref.dtype)
    muo_ref[:] = mu.astype(muo_ref.dtype)
    nuo_ref[:] = nu.astype(nuo_ref.dtype)


def _reference_leaf(p, mu, nu, g, bc1, bc2, hp: AdamWHyperparams):
    """Optax's adamw math, leaf-at-a-time — the untileable-leaf fallback and
    the equality oracle the tests compare the kernel against."""
    g32 = g.astype(jnp.float32)
    mu_new = (1.0 - hp.b1) * g32 + hp.b1 * mu.astype(jnp.float32)
    nu_new = (1.0 - hp.b2) * (g32 * g32) + hp.b2 * nu.astype(jnp.float32)
    mu_hat = mu_new / bc1
    nu_hat = nu_new / bc2
    u = mu_hat / (jnp.sqrt(nu_hat + hp.eps_root) + hp.eps)
    u = u + hp.weight_decay * p.astype(jnp.float32)
    p_new = (p.astype(jnp.float32) + (-hp.learning_rate) * u).astype(p.dtype)
    return p_new, mu_new.astype(mu.dtype), nu_new.astype(nu.dtype)


def _fused_leaf(p, mu, nu, g, bc, hp: AdamWHyperparams):
    geom = _leaf_geometry(p.size)
    if geom is None:
        return _reference_leaf(p, mu, nu, g, bc[0, 0], bc[0, 1], hp)
    rows, cols, br = geom
    shape = p.shape

    def flat(x):
        return x.reshape(rows, cols)

    block = lambda i: (i, 0)  # noqa: E731 - four identical index maps
    specs = [pl.BlockSpec((br, cols), block, memory_space=pltpu.VMEM)]
    p_new, mu_new, nu_new = pl.pallas_call(
        functools.partial(_adamw_kernel, hp=hp),
        grid=(rows // br,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + specs * 4,
        out_specs=specs * 3,
        out_shape=[
            sds((rows, cols), p.dtype, p),
            sds((rows, cols), mu.dtype, mu),
            sds((rows, cols), nu.dtype, nu),
        ],
        # one read + one write per buffer, IN PLACE: params and both moments
        # alias their outputs (argument 0 is the SMEM scalar pair)
        input_output_aliases={1: 0, 2: 1, 3: 2},
        interpret=interpret_mode(),
    )(bc, flat(p), flat(mu), flat(nu), flat(g))
    return p_new.reshape(shape), mu_new.reshape(shape), nu_new.reshape(shape)


class FusedAdamW:
    """``optax.adamw``-compatible transform carrying the fused kernel.

    ``init``/``update`` delegate to a real ``optax.adamw`` chain (identical
    state structure and generic-path semantics); ``fused_apply`` is the
    one-shot params+state update the shared seam in
    ``optimizer.scaled_optimizer_update`` prefers when present."""

    def __init__(self, hp: AdamWHyperparams):
        import optax

        self.hyperparams = hp
        self._tx = optax.adamw(
            learning_rate=hp.learning_rate, b1=hp.b1, b2=hp.b2, eps=hp.eps,
            eps_root=hp.eps_root, weight_decay=hp.weight_decay,
        )

    def init(self, params):
        return self._tx.init(params)

    def update(self, updates, state, params=None):
        return self._tx.update(updates, state, params)

    def fused_apply(self, params, opt_state, grads):
        """One fused pass: ``(params, opt_state, grads) -> (params', state')``
        — the moment EMAs, bias-corrected step, weight decay, and apply all
        land in one kernel per leaf (one read, one write per buffer)."""
        from optax._src.numerics import safe_int32_increment
        from optax._src.transform import ScaleByAdamState

        adam_state = opt_state[0]
        count_inc = safe_int32_increment(adam_state.count)
        hp = self.hyperparams
        # optax's own bias-correction expressions, computed once per step
        # outside the kernel (Mosaic's pow need not match XLA's bit-for-bit)
        bc = jnp.stack(
            [1 - hp.b1**count_inc, 1 - hp.b2**count_inc]
        ).astype(jnp.float32).reshape(1, 2)
        p_leaves, treedef = jax.tree_util.tree_flatten(params)
        mu_leaves = jax.tree_util.tree_leaves(adam_state.mu)
        nu_leaves = jax.tree_util.tree_leaves(adam_state.nu)
        g_leaves = jax.tree_util.tree_leaves(grads)
        outs = [
            _fused_leaf(p, mu, nu, g, bc, hp)
            for p, mu, nu, g in zip(p_leaves, mu_leaves, nu_leaves, g_leaves)
        ]
        params_new = jax.tree_util.tree_unflatten(treedef, [o[0] for o in outs])
        mu_new = jax.tree_util.tree_unflatten(treedef, [o[1] for o in outs])
        nu_new = jax.tree_util.tree_unflatten(treedef, [o[2] for o in outs])
        state_new = (
            ScaleByAdamState(count=count_inc, mu=mu_new, nu=nu_new),
        ) + tuple(opt_state[1:])
        return params_new, state_new


def fused_adamw(
    learning_rate: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    eps_root: float = 0.0,
    weight_decay: float = 1e-4,
) -> FusedAdamW:
    """Drop-in for ``optax.adamw`` with the fused-kernel update. Scalar
    hyperparameters only (no schedules, no decay mask) — exactly the shape
    the serving-scale training steps use; anything fancier keeps
    ``optax.adamw`` and the generic path."""
    if callable(learning_rate):
        raise ValueError(
            "fused_adamw takes a scalar learning_rate (schedules keep the "
            "generic optax.adamw path)"
        )
    return FusedAdamW(
        AdamWHyperparams(
            float(learning_rate), float(b1), float(b2), float(eps),
            float(eps_root), float(weight_decay),
        )
    )
