"""Fused int8/int4 dequant-matmul as a Pallas TPU kernel.

The streamed quantized serving path (``ServingEngine.from_streamed`` over a
``QuantizedLayerPacker``) historically dequantized every layer to the
compute dtype on device before any matmul ran: one full bandwidth pass over
the weights to WRITE the bf16 shadow, a resident bf16 copy of every layer in
HBM for the engine's lifetime, and every decode matmul reading 2-byte
weights. This kernel collapses all three: the weight stays packed
(``QuantizedWeight`` leaves in the params tree), int8 blocks load into VMEM,
dequantize on the fly (scale-and-widen to the activation dtype — the exact
rounding the unpack path applied), and the matmul accumulates in fp32. HBM
weight traffic drops to 1 byte/element (0.5 for int4) and the bf16 shadow
never exists — ``tests/test_quant_matmul.py`` pins the resident-bytes delta.

Wired in as the model zoo's ``dot_fn`` hook (``quant_dot``): every layer
projection already routes through ``resolve_dot``, so a params tree whose
matrix leaves are :class:`~.utils.quantization.QuantizedWeight` engages the
kernel with zero model changes, and non-quantized leaves (norms, biases,
fp32-skipped modules) take the plain matmul exactly as before.

Grid: ``(N/bn, K/bk)`` with the K axis innermost — each program owns one
output-column block, accumulating K-block partial products into a VMEM fp32
scratch that flushes to the output on the last K step (revisiting an output
block on consecutive grid steps is legal on TPU: the grid is sequential).
Off-TPU the kernel runs in interpret mode; Mosaic-untileable geometries
(lane/sublane-unaligned K or N) fall back to dequantize-then-matmul — per
call, not per layer, so even the fallback never keeps a resident shadow.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.quantization import QuantizedWeight, unpack_int4
from .runtime import fit_block as _fit
from .runtime import interpret_mode

# K/N tile ceilings: big enough to amortize the per-block dequant, small
# enough that x-block + w-block + fp32 acc fit VMEM at decode batch sizes
BLOCK_K = 512
BLOCK_N = 512


def quant_fallback_reason(k: int, n: int, bits: int) -> Optional[str]:
    """Why the fused kernel cannot serve this weight geometry (None = it
    can). Interpret mode accepts anything the block fitter can tile; Mosaic
    additionally needs lane/sublane-aligned blocks (int8 tiles are 32×128)."""
    floor_k = 2 if bits == 4 else 1
    bk, bn = _fit(BLOCK_K, k, floor_k), _fit(BLOCK_N, n, 1)
    if k % bk or n % bn or (bits == 4 and bk % 2):
        return f"K={k}, N={n} not tileable by power-of-two blocks"
    if interpret_mode():
        return None
    if bk % 32 or bn % 128:
        return (
            f"fitted blocks ({bk}, {bn}) miss Mosaic's int8 tiling "
            "(32 sublanes x 128 lanes)"
        )
    return None


def _matmul_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, bits, k_blocks):
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    wq = w_ref[:]
    if bits == 4:
        wq = unpack_int4(wq)
    # dequant in fp32 then round to the activation dtype — the exact value
    # the unpack path's resident shadow held, so fused and shadowed serving
    # agree to the matmul's own accumulation order
    w = (wq.astype(jnp.float32) * s_ref[:].astype(jnp.float32)).astype(x_ref.dtype)
    acc_ref[:] += jax.lax.dot_general(
        x_ref[:], w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(ki == k_blocks - 1)
    def _():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def quant_matmul(x: jax.Array, w: QuantizedWeight) -> jax.Array:
    """``x @ dequantize(w)`` without ever materializing the dequantized
    weight: ``x`` is ``[..., K]``, ``w`` a packed int8/int4
    :class:`QuantizedWeight` of logical shape ``[K, N]``. Output is
    ``[..., N]`` in ``x``'s dtype."""
    *lead, k = x.shape
    kq, n = w.q.shape[-2], w.q.shape[-1]
    if w.bits == 4:
        kq *= 2
    if kq != k:
        raise ValueError(f"contraction mismatch: x[..., {k}] @ quantized [{kq}, {n}]")
    if quant_fallback_reason(k, n, w.bits) is not None:
        return x @ w.dequantize().astype(x.dtype)
    bk = _fit(BLOCK_K, k, 2 if w.bits == 4 else 1)
    bn = _fit(BLOCK_N, n, 1)
    m = 1
    for dim in lead:
        m *= dim
    x2 = x.reshape(m, k)
    # int4 packs two K rows per stored byte: the stored block is bk // 2 rows
    wk_block = bk // 2 if w.bits == 4 else bk
    out = pl.pallas_call(
        functools.partial(_matmul_kernel, bits=w.bits, k_blocks=k // bk),
        grid=(n // bn, k // bk),
        in_specs=[
            pl.BlockSpec((m, bk), lambda ni, ki: (0, ki), memory_space=pltpu.VMEM),
            pl.BlockSpec((wk_block, bn), lambda ni, ki: (ki, ni), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda ni, ki: (0, ni), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, bn), lambda ni, ki: (0, ni), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((m, bn), jnp.float32)],
        interpret=interpret_mode(),
    )(x2, w.q, w.scale.reshape(1, n))
    return out.reshape(*lead, n)


def quant_dot(a: jax.Array, w) -> jax.Array:
    """The ``dot_fn`` hook for quantized-resident serving: fused kernel for
    :class:`QuantizedWeight` leaves, the plain matmul for everything else.
    A module-level singleton on purpose — the dot-keyed jit cache
    (utils/jit_cache.py) compares hooks by identity, so every engine sharing
    a model reuses one compiled program set."""
    if isinstance(w, QuantizedWeight):
        return quant_matmul(a, w)
    return a @ w
