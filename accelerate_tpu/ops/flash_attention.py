"""Blockwise (flash) causal attention as a Pallas TPU kernel.

The einsum path (models/attention.py) materializes the [B, N, S, S] score
matrix in HBM — at seq 1024, bs 32 that single buffer is ~1.6 GB fp32 per
layer and caps the trainable batch. This kernel streams K/V blocks through
VMEM with an online softmax, so attention memory is O(S·D) per core instead
of O(S²), forward AND backward (the backward recomputes P blockwise from the
saved logsumexp — the standard flash-attention recipe).

Layout notes (MXU/VMEM-first):
- operates on [B, N, S, D] (heads made a leading grid dim; the wrapper
  transposes from the model-zoo [B, S, N, D]);
- the query axis is the grid's innermost dim: each program owns one
  (batch, head, q-block) and loops over k-blocks ≤ its causal limit;
- all matmuls run with fp32 accumulation; running max/denominator in fp32.

v1 scope: causal self-attention, no padding mask (the wrapper falls back to
the einsum path when a mask is present), full K/V of one head resident in
VMEM (fine to ~8k tokens at D=64..128). GQA is handled by a K/V index map
(q head h reads kv head h // group) — no repetition in HBM.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _sds(shape, dtype, like) -> jax.ShapeDtypeStruct:
    """Out-shape struct inheriting ``like``'s varying-manual-axes type, so the
    kernel also runs inside shard_map manual regions (the pipeline schedule)."""
    vma = getattr(getattr(like, "aval", None), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q, block_k, scale, seq_len):
    iq = pl.program_id(2)
    # keep q/k/v in their native dtype: the dots accumulate in fp32 via
    # preferred_element_type, but bf16 OPERANDS run the MXU at full rate —
    # an fp32 upcast before the dot would quarter the matmul throughput.
    # Scaling applies to the fp32 scores, not to bf16 q, for precision.
    q = q_ref[0, 0]  # [BQ, D]
    bq, d = q.shape

    m = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    q_pos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
    num_kb = seq_len // block_k

    def body(j, carry):
        m, l, acc = carry

        def attend(args):
            m, l, acc = args
            k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
            v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
            s = scale * jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )  # [BQ, BK] fp32
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            correction = jnp.exp(m - m_new)
            l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * correction + jax.lax.dot_general(
                p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new

        # causal: k-blocks entirely above the diagonal contribute nothing
        return jax.lax.cond(j * block_k <= iq * block_q + bq - 1, attend, lambda a: a, (m, l, acc))

    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m, l, acc))
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    # lse broadcast over 8 sublanes: [B,N,S,8] satisfies TPU tiling while
    # costing 8x a scalar row (vs the 128-lane layout jax's kernel uses)
    lse_ref[0, 0] = jnp.broadcast_to(m + jnp.log(l), (bq, 8))


def _flash_forward(q, k, v, *, block_q, block_k, scale):
    b, n, s, d = q.shape
    kv_heads = k.shape[1]
    group = n // kv_heads
    grid = (b, n, s // block_q)

    kv_spec = pl.BlockSpec(
        (1, 1, s, d), lambda bi, ni, qi: (bi, ni // group, 0, 0), memory_space=pltpu.VMEM
    )
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_q=block_q, block_k=block_k, scale=scale, seq_len=s
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, ni, qi: (bi, ni, qi, 0), memory_space=pltpu.VMEM),
            kv_spec,
            kv_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, ni, qi: (bi, ni, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, 8), lambda bi, ni, qi: (bi, ni, qi, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _sds((b, n, s, d), q.dtype, q),
            _sds((b, n, s, 8), jnp.float32, q),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, block_q, block_k, scale, seq_len):
    iq = pl.program_id(2)
    # native-dtype operands on every dot (bf16 MXU rate), fp32 accumulation
    q = q_ref[0, 0]  # [BQ, D]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0][:, :1]  # [BQ, 1] (sublane-broadcast storage)
    delta = delta_ref[0, 0][:, :1]
    bq, d = q.shape

    q_pos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
    dq = jnp.zeros((bq, d), jnp.float32)

    def body(j, dq):
        def attend(dq):
            k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
            v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
            s = scale * jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
            p = jnp.exp(s - lse)  # [BQ, BK] fp32
            dp = jax.lax.dot_general(
                do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            ds = (p * (dp - delta) * scale).astype(k_blk.dtype)
            return dq + jax.lax.dot_general(
                ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )

        return jax.lax.cond(j * block_k <= iq * block_q + bq - 1, attend, lambda x: x, dq)

    dq = jax.lax.fori_loop(0, seq_len // block_k, body, dq)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, block_q, block_k, scale, seq_len, group):
    ik = pl.program_id(2)
    # native-dtype operands on every dot (bf16 MXU rate), fp32 accumulation
    k_blk = k_ref[0, 0]  # [BK, D]
    v_blk = v_ref[0, 0]
    bk, d = k_blk.shape

    k_pos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)

    def q_block_loop(args):
        dk, dv, g = args

        def body(jq, carry):
            dk, dv = carry

            def attend(carry):
                dk, dv = carry
                q = q_ref[0, g, pl.ds(jq * block_q, block_q), :]
                do = do_ref[0, g, pl.ds(jq * block_q, block_q), :]
                lse = lse_ref[0, g, pl.ds(jq * block_q, block_q), :][:, :1]
                delta = delta_ref[0, g, pl.ds(jq * block_q, block_q), :][:, :1]
                s = scale * jax.lax.dot_general(
                    q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
                )  # [BQ, BK] fp32
                q_pos = jq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
                s = jnp.where(k_pos <= q_pos, s, NEG_INF)
                p = jnp.exp(s - lse)
                dv_new = dv + jax.lax.dot_general(
                    p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                dp = jax.lax.dot_general(
                    do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
                )
                ds = (p * (dp - delta) * scale).astype(q.dtype)
                dk_new = dk + jax.lax.dot_general(
                    ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
                )
                return dk_new, dv_new

            # causal: q blocks strictly above this k block see none of it
            return jax.lax.cond((jq + 1) * block_q - 1 >= ik * block_k, attend, lambda c: c, (dk, dv))

        return jax.lax.fori_loop(0, seq_len // block_q, body, (dk, dv))

    for g_off in range(group):  # static loop over the q heads sharing this kv head
        dk, dv = q_block_loop((dk, dv, g_off))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_backward(res, g, *, block_q, block_k, scale):
    q, k, v, out, lse = res
    b, n, s, d = q.shape
    kv_heads = k.shape[1]
    group = n // kv_heads
    do = g.astype(jnp.float32)
    delta = jnp.sum(do * out.astype(jnp.float32), axis=-1)  # [B, N, S]
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, 8))

    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda bi, ni, qi: (bi, ni, qi, 0), memory_space=pltpu.VMEM)
    kv_full = pl.BlockSpec((1, 1, s, d), lambda bi, ni, qi: (bi, ni // group, 0, 0), memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, 1, block_q, 8), lambda bi, ni, qi: (bi, ni, qi, 0), memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_q=block_q, block_k=block_k, scale=scale, seq_len=s
        ),
        grid=(b, n, s // block_q),
        in_specs=[q_spec, kv_full, kv_full, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=_sds((b, n, s, d), q.dtype, q),
        interpret=_interpret(),
    )(q, k, v, g, lse, delta)

    # one program per (batch, kv head, k block); its q-head group is looped
    # inside, so dk/dv accumulate without cross-program races
    kv_blk_spec = pl.BlockSpec((1, 1, block_k, d), lambda bi, ki, kbi: (bi, ki, kbi, 0), memory_space=pltpu.VMEM)
    qhead_group = pl.BlockSpec(
        (1, group, s, d), lambda bi, ki, kbi: (bi, ki, 0, 0), memory_space=pltpu.VMEM
    )
    rows_group = pl.BlockSpec((1, group, s, 8), lambda bi, ki, kbi: (bi, ki, 0, 0), memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=block_q, block_k=block_k, scale=scale, seq_len=s, group=group
        ),
        grid=(b, kv_heads, s // block_k),
        in_specs=[qhead_group, kv_blk_spec, kv_blk_spec, qhead_group, rows_group, rows_group],
        out_specs=[kv_blk_spec, kv_blk_spec],
        out_shape=[
            _sds((b, kv_heads, s, d), k.dtype, k),
            _sds((b, kv_heads, s, d), v.dtype, v),
        ],
        interpret=_interpret(),
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_bnsd(q, k, v, block_q, block_k, bwd_block_q, bwd_block_k, scale):
    out, _ = _flash_forward(q, k, v, block_q=block_q, block_k=block_k, scale=scale)
    return out


def _fwd_rule(q, k, v, block_q, block_k, bwd_block_q, bwd_block_k, scale):
    out, lse = _flash_forward(q, k, v, block_q=block_q, block_k=block_k, scale=scale)
    # named for remat policies: under "save_flash" (the activation-checkpointing
    # default) the backward keeps out/lse instead of re-running the forward
    # kernel — q/k/v rebuild from cheap projections, the flash pass does not
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


def _bwd_rule(block_q, block_k, bwd_block_q, bwd_block_k, scale, res, g):
    return _flash_backward(res, g, block_q=bwd_block_q, block_k=bwd_block_k, scale=scale)


_flash_attention_bnsd.defvjp(_fwd_rule, _bwd_rule)


def _fit_block(block: int, s: int) -> int:
    """Adapt a block size DOWNWARD (halving, floor 128) until it divides s."""
    block = min(block, s)
    while block > 128 and s % block:
        block //= 2
    return block


def flash_attention(
    q: jax.Array,  # [B, S, N, D] (model-zoo layout)
    k: jax.Array,  # [B, S, KV, D]
    v: jax.Array,  # [B, S, KV, D]
    kv_mask: Optional[jax.Array] = None,
    block_q: int = 256,
    block_k: int = 512,
    bwd_block_q: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
) -> jax.Array:
    """Causal flash attention with the ``attention_fn`` hook signature.

    Block sizes adapt DOWNWARD (halving, floor 128) until they divide the
    sequence, so any seq that is a multiple of 128 runs the kernel; only a
    padding mask or an untileable length falls back to the einsum path.

    The backward kernels tile independently of the forward (``bwd_block_*``):
    the dq pass owns a q-block and loops k-blocks, the dkv pass owns a
    k-block and loops q-blocks, and their best tile shapes differ from the
    forward's (measured on v5e at seq 4096 — see BWD_BLOCK_Q/BWD_BLOCK_K).
    """
    b, s, n, d = q.shape
    bq, bk = _fit_block(block_q, s), _fit_block(block_k, s)
    bbq = _fit_block(bwd_block_q or BWD_BLOCK_Q, s)
    bbk = _fit_block(bwd_block_k or BWD_BLOCK_K, s)
    # interpret-mode pallas inside a shard_map manual region (CPU pipeline
    # tests) trips a jax hlo_interpreter lowering-cache bug — use the exact
    # einsum path there; real TPUs lower through Mosaic and keep the kernel
    in_manual_region = bool(getattr(getattr(q, "aval", None), "vma", None))
    if (
        kv_mask is not None
        or (in_manual_region and _interpret())
        or any(x % 128 or s % x for x in (bq, bk, bbq, bbk))
    ):
        from ..models.attention import dot_product_attention

        mask = None if kv_mask is None else kv_mask[:, None, None, :].astype(bool)
        return dot_product_attention(q, k, v, mask=mask, causal=True)
    scale = 1.0 / math.sqrt(d)
    out = _flash_attention_bnsd(
        q.swapaxes(1, 2), k.swapaxes(1, 2), v.swapaxes(1, 2), bq, bk, bbq, bbk, scale
    )
    return out.swapaxes(1, 2)


# backward tile defaults from the round-4 v5e sweep at seq 4096 (bs=8, 12
# heads, d=64; fwd fixed at 256/512): (512, 256) 33.9 ms vs the forward's
# (256, 512) at 34.5 ms; small blocks lose badly (128/128: 60 ms)
BWD_BLOCK_Q = 512
BWD_BLOCK_K = 256


def make_auto_attention(min_seq: int = 1024):
    """Per-shape dispatch: with 256/512 blocks the flash kernel beats XLA's
    fused einsum attention from ~1k tokens (measured on v5e: ~2.1x at 4k,
    ~15% at 1k in full training programs) — shorter sequences keep the
    einsum path, whose single fused softmax wins when the whole score tile
    fits on-chip."""

    def attention(q, k, v, kv_mask=None):
        if q.shape[1] >= min_seq:
            return flash_attention(q, k, v, kv_mask)  # self-falls-back on mask
        from ..models.attention import dot_product_attention

        mask = None if kv_mask is None else kv_mask[:, None, None, :].astype(bool)
        return dot_product_attention(q, k, v, mask=mask, causal=True)

    return attention
