"""Blockwise (flash) attention as a Pallas TPU kernel.

The einsum path (models/attention.py) materializes the [B, N, S, S] score
matrix in HBM — at seq 1024, bs 32 that single buffer is ~1.6 GB fp32 per
layer and caps the trainable batch. This kernel streams K/V blocks through
VMEM with an online softmax, so attention memory is O(S·D) per core instead
of O(S²), forward AND backward (the backward recomputes P blockwise from the
saved logsumexp — the standard flash-attention recipe).

Layout notes (MXU/VMEM-first):
- operates on [B, N, S, D] (heads made a leading grid dim; the wrapper
  transposes from the model-zoo [B, S, N, D]);
- the query axis is the grid's innermost dim (except when reducing a
  broadcast bias gradient — see below): each program owns one
  (batch, head, q-block) and loops over k-blocks up to a DYNAMIC bound —
  the causal limit and/or the last valid key of its batch row, so padded
  tails and future blocks are skipped, not masked;
- all matmuls run with fp32 accumulation; running max/denominator in fp32.

v2 scope (VERDICT r4 #4): causal AND non-causal, [B, S] key-validity masks
(fully-padded k-blocks are skipped via a per-batch limit in SMEM), an
optional additive attention bias [1|B, N, Sq, Sk] with exact gradient
(T5 relative position bias — reference integrations get this from torch
SDPA's attn_mask), and distinct q/kv lengths (cross-attention). A broadcast
bias ([1, ...]) gets its batch-summed gradient by reordering the dq grid so
the batch is innermost and accumulating into a revisited output block
(legal on TPU: grid steps are sequential). Full K/V of one head stays
resident in VMEM (fine to ~8k tokens at D=64..128). GQA is handled by a
K/V index map (q head h reads kv head h // group) — no repetition in HBM.

Numerical guards: the running max starts at NEG_INF/2 (not NEG_INF), so a
fully-masked row keeps every exp() at exactly 0.0 and the output at 0 —
no NaN/Inf leaks into residual streams or gradients (the einsum path's
softmax would give a uniform distribution instead; those rows are padding
and their values are never consumed).
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .runtime import fit_block
from .runtime import interpret_mode as _interpret
from .runtime import sds as _sds

# block sizes adapt downward to divide the sequence; floor 128 = lane width
_fit_block = functools.partial(fit_block, floor=128)

NEG_INF = -1e30
# running-max init: far below any real score, far above NEG_INF, so masked
# scores underflow exp() even when a row never sees a valid key
M_INIT = NEG_INF / 2


class _Cfg(NamedTuple):
    """Static kernel configuration (hashable: custom_vjp nondiff arg)."""

    block_q: int
    block_k: int
    bwd_block_q: int
    bwd_block_k: int
    scale: float
    causal: bool
    has_mask: bool
    has_bias: bool
    bias_batched: bool  # bias leading dim == B (no batch reduction of dbias)
    has_offsets: bool = False  # global (q_offset, kv_offset) positions (ring)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------



def _split_refs(refs, has_mask, has_bias, has_offsets=False):
    """(q, k, v, mask?, limit?, offsets?, bias?, rest) — shared preamble."""
    q_ref, k_ref, v_ref = refs[0], refs[1], refs[2]
    i = 3
    mask_ref = limit_ref = offs_ref = bias_ref = None
    if has_mask:
        mask_ref, limit_ref = refs[i], refs[i + 1]
        i += 2
    if has_offsets:
        offs_ref = refs[i]
        i += 1
    if has_bias:
        bias_ref = refs[i]
        i += 1
    return q_ref, k_ref, v_ref, mask_ref, limit_ref, offs_ref, bias_ref, refs[i:]


def _block_scores(q_tile, k_tile, scale, bias_tile, causal_pos, penalty):
    """[BQ, BK] fp32 scores: q.k^T (+scale) (+bias) (+causal) (+mask penalty).

    ONE recipe for the forward and both backward kernels — they must mask
    identically or gradients desynchronize from the saved lse. ``causal_pos``
    is a (k_pos, q_pos) iota pair or None; ``penalty`` a [1, BK] additive row
    from _mask_penalty or None.
    """
    s = jax.lax.dot_general(
        q_tile, k_tile, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if scale != 1.0:
        s = s * scale
    if bias_tile is not None:
        s = s + bias_tile.astype(jnp.float32)
    if causal_pos is not None:
        k_pos, q_pos = causal_pos
        s = jnp.where(k_pos <= q_pos, s, NEG_INF)
    if penalty is not None:
        s = s + penalty
    return s


def _mask_penalty(mask_ref, start, size):
    """Additive mask penalty row [1, BK] from a 2-D sublane-block read:
    2.3x faster than a 1-D load + where broadcast (v5e, seq 4096 — the 1-D
    lane-vector broadcast lowers poorly in Mosaic). Masked scores land at
    ~-1e30 (or ~-2e30 when causal-masked too): exp() underflows to exactly
    0 either way, and M_INIT guards the running max."""
    rows = mask_ref[0, :, pl.ds(start, size)].astype(jnp.float32)
    return (rows[:1] - 1.0) * -NEG_INF



def _nblocks(last_index, block: int):
    """Blocks covering key indices 0..last_index (0 when negative) — uses
    truncating lax.div on NON-NEGATIVE operands: jnp's signed floor-div
    emits sign-fixup ops that Mosaic cannot lower inside manual regions."""
    covered = jnp.maximum(last_index + 1, 0)
    return jax.lax.div(covered + jnp.int32(block - 1), jnp.int32(block))

def _fwd_kernel(*refs, block_q, block_k, scale, kv_len, causal, has_mask, has_bias, has_offsets):
    q_ref, k_ref, v_ref, mask_ref, limit_ref, offs_ref, bias_ref, (o_ref, lse_ref) = _split_refs(
        refs, has_mask, has_bias, has_offsets
    )

    bi = pl.program_id(0)
    iq = pl.program_id(2)
    # global positions (ring blocks live at an offset into the full sequence)
    qoff = offs_ref[0, 0] if has_offsets else 0
    koff = offs_ref[0, 1] if has_offsets else 0
    # keep q/k/v in their native dtype: the dots accumulate in fp32 via
    # preferred_element_type, but bf16 OPERANDS run the MXU at full rate —
    # an fp32 upcast before the dot would quarter the matmul throughput.
    # Scaling applies to the fp32 scores, not to bf16 q, for precision.
    q = q_ref[0, 0]  # [BQ, D]
    bq, d = q.shape

    m = jnp.full((bq, 1), M_INIT, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    q_pos = qoff + iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    # dynamic k-block bound: causal limit and/or last valid key of this row
    upper = kv_len // block_k
    if causal:
        # last attendable LOCAL k index for this q block (can be negative:
        # the whole k block set is in the future — zero iterations)
        last_k = qoff - koff + iq * block_q + bq - 1
        upper = jnp.minimum(_nblocks(last_k, block_k), upper)
    if has_mask:
        upper = jnp.minimum(upper, _nblocks(limit_ref[bi, 0], block_k))  # -1 → 0

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = _block_scores(
            q, k_blk, scale,
            bias_ref[0, 0, :, pl.ds(j * block_k, block_k)] if has_bias else None,
            (koff + j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1), q_pos)
            if causal else None,
            _mask_penalty(mask_ref, j * block_k, block_k) if has_mask else None,
        )  # [BQ, BK] fp32
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, upper, body, (m, l, acc))
    l_safe = jnp.maximum(l, 1e-30)  # fully-masked rows: 0/eps = 0, not NaN
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    # lse broadcast over 8 sublanes: [B,N,S,8] satisfies TPU tiling while
    # costing 8x a scalar row (vs the 128-lane layout jax's kernel uses)
    lse_ref[0, 0] = jnp.broadcast_to(m + jnp.log(l_safe), (bq, 8))



def _common_operand_specs(cfg: _Cfg, mask, limit, offsets, kv_len, gidx=lambda f: f):
    """(in_specs, args) for the optional mask/limit/offsets operands — ONE
    definition for the forward and both backward passes, in _split_refs
    order (a missed branch here fails only at Mosaic lowering). The bias
    operand stays per-site: its block geometry differs between the q-major
    passes and the dkv pass."""
    specs, args = [], []
    if cfg.has_mask:
        specs.append(pl.BlockSpec((1, 8, kv_len), gidx(lambda bi, ni, qi: (bi, 0, 0)), memory_space=pltpu.VMEM))
        specs.append(pl.BlockSpec(limit.shape, gidx(lambda bi, ni, qi: (0, 0)), memory_space=pltpu.SMEM))
        args += [mask, limit]
    if cfg.has_offsets:
        specs.append(pl.BlockSpec(offsets.shape, gidx(lambda bi, ni, qi: (0, 0)), memory_space=pltpu.SMEM))
        args.append(offsets)
    return specs, args


def _flash_forward(q, k, v, mask, limit, offsets, bias, cfg: _Cfg):
    b, n, sq, d = q.shape
    kv_len = k.shape[2]
    kv_heads = k.shape[1]
    group = n // kv_heads
    block_q, block_k = cfg.block_q, cfg.block_k
    grid = (b, n, sq // block_q)

    kv_spec = pl.BlockSpec(
        (1, 1, kv_len, d), lambda bi, ni, qi: (bi, ni // group, 0, 0), memory_space=pltpu.VMEM
    )
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda bi, ni, qi: (bi, ni, qi, 0), memory_space=pltpu.VMEM),
        kv_spec,
        kv_spec,
    ]
    args = [q, k, v]
    opt_specs, opt_args = _common_operand_specs(cfg, mask, limit, offsets, kv_len)
    in_specs += opt_specs
    args += opt_args
    if cfg.has_bias:
        bb = bias.shape[0]
        in_specs.append(
            pl.BlockSpec(
                (1, 1, block_q, kv_len),
                (lambda bi, ni, qi: (bi, ni, qi, 0)) if bb > 1 else (lambda bi, ni, qi: (0, ni, qi, 0)),
                memory_space=pltpu.VMEM,
            )
        )
        args.append(bias)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_q=block_q, block_k=block_k, scale=cfg.scale,
            kv_len=kv_len, causal=cfg.causal, has_mask=cfg.has_mask, has_bias=cfg.has_bias,
            has_offsets=cfg.has_offsets,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, ni, qi: (bi, ni, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, 8), lambda bi, ni, qi: (bi, ni, qi, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _sds((b, n, sq, d), q.dtype, q),
            _sds((b, n, sq, 8), jnp.float32, q),
        ],
        interpret=_interpret(),
    )(*args)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    *refs, block_q, block_k, scale, kv_len, causal, has_mask, has_bias,
    has_offsets, emit_dbias, bias_reduce,
):
    q_ref, k_ref, v_ref, mask_ref, limit_ref, offs_ref, bias_ref, rest = _split_refs(
        refs, has_mask, has_bias, has_offsets
    )
    do_ref, lse_ref, delta_ref, dq_ref = rest[0], rest[1], rest[2], rest[3]
    dbias_ref = rest[4] if emit_dbias else None

    # grid is (B, N, Q) normally, (N, Q, B) when reducing a broadcast dbias
    # over the batch (the revisited output block must be revisited on
    # CONSECUTIVE grid steps, so the batch goes innermost)
    iq = pl.program_id(1 if bias_reduce else 2)
    bi = pl.program_id(2) if bias_reduce else pl.program_id(0)
    qoff = offs_ref[0, 0] if has_offsets else 0
    koff = offs_ref[0, 1] if has_offsets else 0

    # native-dtype operands on every dot (bf16 MXU rate), fp32 accumulation
    q = q_ref[0, 0]  # [BQ, D]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0][:, :1]  # [BQ, 1] (sublane-broadcast storage)
    delta = delta_ref[0, 0][:, :1]
    bq, d = q.shape

    q_pos = qoff + iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
    dq = jnp.zeros((bq, d), jnp.float32)

    if emit_dbias and bias_reduce:
        # zero the revisited block once per (head, q-block) sweep
        @pl.when(bi == 0)
        def _():
            dbias_ref[0, 0] = jnp.zeros_like(dbias_ref[0, 0])
    elif emit_dbias:
        dbias_ref[0, 0] = jnp.zeros_like(dbias_ref[0, 0])

    upper = kv_len // block_k
    if causal:
        last_k = qoff - koff + iq * block_q + bq - 1
        upper = jnp.minimum(_nblocks(last_k, block_k), upper)
    if has_mask:
        upper = jnp.minimum(upper, _nblocks(limit_ref[bi, 0], block_k))

    def body(j, dq):
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = _block_scores(
            q, k_blk, scale,
            bias_ref[0, 0, :, pl.ds(j * block_k, block_k)] if has_bias else None,
            (koff + j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1), q_pos)
            if causal else None,
            _mask_penalty(mask_ref, j * block_k, block_k) if has_mask else None,
        )
        p = jnp.exp(s - lse)  # [BQ, BK] fp32; masked s underflow to exactly 0
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        dsb = p * (dp - delta)  # d(score before scale) == dbias
        if emit_dbias:
            sl = pl.ds(j * block_k, block_k)
            if bias_reduce:
                dbias_ref[0, 0, :, sl] = dbias_ref[0, 0, :, sl] + dsb
            else:
                dbias_ref[0, 0, :, sl] = dsb
        ds = (dsb * scale).astype(k_blk.dtype) if scale != 1.0 else dsb.astype(k_blk.dtype)
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(0, upper, body, dq)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    *refs, block_q, block_k, scale, q_len, causal, has_mask, has_bias,
    has_offsets, group,
):
    q_ref, k_ref, v_ref, mask_ref, limit_ref, offs_ref, bias_ref, rest = _split_refs(
        refs, has_mask, has_bias, has_offsets
    )
    do_ref, lse_ref, delta_ref, dk_ref, dv_ref = rest

    bi = pl.program_id(0)
    ik = pl.program_id(2)
    qoff = offs_ref[0, 0] if has_offsets else 0
    koff = offs_ref[0, 1] if has_offsets else 0
    # native-dtype operands on every dot (bf16 MXU rate), fp32 accumulation
    k_blk = k_ref[0, 0]  # [BK, D]
    v_blk = v_ref[0, 0]
    bk, d = k_blk.shape

    k_pos = koff + ik * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)

    penalty = _mask_penalty(mask_ref, ik * block_k, bk) if has_mask else None

    # q-block loop bounds: causal — q blocks strictly above this k block see
    # none of it; mask — a k block past the last valid key contributes nothing
    if causal:
        first_q = (koff - qoff + ik * block_k) if has_offsets else ik * block_k
        lower = jax.lax.div(jnp.maximum(first_q, 0), jnp.int32(block_q))
    else:
        lower = 0
    upper = q_len // block_q
    if has_mask:
        upper = jnp.where(ik * block_k <= limit_ref[bi, 0], upper, lower)

    def q_block_loop(args):
        dk, dv, g = args

        def body(jq, carry):
            dk, dv = carry
            q = q_ref[0, g, pl.ds(jq * block_q, block_q), :]
            do = do_ref[0, g, pl.ds(jq * block_q, block_q), :]
            lse = lse_ref[0, g, pl.ds(jq * block_q, block_q), :][:, :1]
            delta = delta_ref[0, g, pl.ds(jq * block_q, block_q), :][:, :1]
            s = _block_scores(
                q, k_blk, scale,
                bias_ref[0, g, pl.ds(jq * block_q, block_q), :] if has_bias else None,
                (k_pos, qoff + jq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0))
                if causal else None,
                penalty,
            )  # [BQ, BK] fp32
            p = jnp.exp(s - lse)
            dv_new = dv + jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(
                do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            dsb = p * (dp - delta)
            ds = (dsb * scale).astype(q.dtype) if scale != 1.0 else dsb.astype(q.dtype)
            dk_new = dk + jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            return dk_new, dv_new

        return jax.lax.fori_loop(lower, upper, body, (dk, dv))

    for g_off in range(group):  # static loop over the q heads sharing this kv head
        dk, dv = q_block_loop((dk, dv, g_off))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_backward(res, g, cfg: _Cfg, dlse=None):
    q, k, v, mask, limit, offsets, bias, out, lse3 = res
    # saved residuals hold lse UNPADDED [B, N, S]: the kernels' 8-sublane
    # layout pads its minor dim to 128 lanes on HBM (16x — 2.25 GB at
    # bs32/seq1024/12 layers when saved across the fwd/bwd boundary under
    # the save_flash remat policy). Rebroadcast only for the kernel call.
    lse = jnp.broadcast_to(lse3[..., None], (*lse3.shape, 8))
    b, n, sq, d = q.shape
    kv_len = k.shape[2]
    kv_heads = k.shape[1]
    group = n // kv_heads
    block_q, block_k = cfg.bwd_block_q, cfg.bwd_block_k
    do = g.astype(jnp.float32)
    delta = jnp.sum(do * out.astype(jnp.float32), axis=-1)  # [B, N, S]
    if dlse is not None:
        # lse is a USED output (the ring merge weights blocks by it):
        # dL/ds_ij = p_ij (dp_ij - delta_i + dlse_i) — absorbing dlse into
        # the delta term keeps the kernels untouched
        delta = delta - dlse
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, 8))

    emit_dbias = cfg.has_bias
    bias_reduce = emit_dbias and not cfg.bias_batched

    # --- dq (+ dbias) pass: one program per (batch, head, q block) ---------
    # With a broadcast-bias gradient the batch must be the INNERMOST grid dim
    # so the revisited dbias block accumulates on consecutive steps.
    if bias_reduce:
        def gidx(f):  # (ni, qi, bi) grid → reorder into the (bi, ni, qi) maps
            return lambda ni, qi, bi: f(bi, ni, qi)
        grid_dq = (n, sq // block_q, b)
    else:
        def gidx(f):
            return f
        grid_dq = (b, n, sq // block_q)

    q_spec = pl.BlockSpec((1, 1, block_q, d), gidx(lambda bi, ni, qi: (bi, ni, qi, 0)), memory_space=pltpu.VMEM)
    kv_full = pl.BlockSpec((1, 1, kv_len, d), gidx(lambda bi, ni, qi: (bi, ni // group, 0, 0)), memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, 1, block_q, 8), gidx(lambda bi, ni, qi: (bi, ni, qi, 0)), memory_space=pltpu.VMEM)

    in_specs = [q_spec, kv_full, kv_full]
    args = [q, k, v]
    opt_specs, opt_args = _common_operand_specs(cfg, mask, limit, offsets, kv_len, gidx)
    in_specs += opt_specs
    args += opt_args
    if cfg.has_bias:
        bb = bias.shape[0]
        in_specs.append(
            pl.BlockSpec(
                (1, 1, block_q, kv_len),
                gidx((lambda bi, ni, qi: (bi, ni, qi, 0)) if bb > 1 else (lambda bi, ni, qi: (0, ni, qi, 0))),
                memory_space=pltpu.VMEM,
            )
        )
        args.append(bias)
    in_specs += [q_spec, row_spec, row_spec]
    args += [g, lse, delta]

    out_specs = [q_spec]
    out_shape = [_sds((b, n, sq, d), q.dtype, q)]
    if emit_dbias:
        out_specs.append(
            pl.BlockSpec(
                (1, 1, block_q, kv_len),
                gidx((lambda bi, ni, qi: (bi, ni, qi, 0)) if cfg.bias_batched else (lambda bi, ni, qi: (0, ni, qi, 0))),
                memory_space=pltpu.VMEM,
            )
        )
        out_shape.append(_sds((bias.shape[0], n, sq, kv_len), jnp.float32, q))

    res_dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_q=block_q, block_k=block_k, scale=cfg.scale,
            kv_len=kv_len, causal=cfg.causal, has_mask=cfg.has_mask,
            has_bias=cfg.has_bias, has_offsets=cfg.has_offsets,
            emit_dbias=emit_dbias, bias_reduce=bias_reduce,
        ),
        grid=grid_dq,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(*args)
    if emit_dbias:
        dq, dbias = res_dq
        dbias = dbias.astype(bias.dtype)
    else:
        (dq,), dbias = res_dq, None

    # --- dk/dv pass: one program per (batch, kv head, k block); its q-head
    # group is looped inside, so dk/dv accumulate without cross-program races
    kv_blk_spec = pl.BlockSpec((1, 1, block_k, d), lambda bi, ki, kbi: (bi, ki, kbi, 0), memory_space=pltpu.VMEM)
    qhead_group = pl.BlockSpec((1, group, sq, d), lambda bi, ki, kbi: (bi, ki, 0, 0), memory_space=pltpu.VMEM)
    rows_group = pl.BlockSpec((1, group, sq, 8), lambda bi, ki, kbi: (bi, ki, 0, 0), memory_space=pltpu.VMEM)

    in_specs2 = [qhead_group, kv_blk_spec, kv_blk_spec]
    args2 = [q, k, v]
    opt_specs, opt_args = _common_operand_specs(cfg, mask, limit, offsets, kv_len)
    in_specs2 += opt_specs
    args2 += opt_args
    if cfg.has_bias:
        bb = bias.shape[0]
        in_specs2.append(
            pl.BlockSpec(
                (1, group, sq, block_k),
                (lambda bi, ki, kbi: (bi, ki, 0, kbi)) if bb > 1 else (lambda bi, ki, kbi: (0, ki, 0, kbi)),
                memory_space=pltpu.VMEM,
            )
        )
        args2.append(bias)
    in_specs2 += [qhead_group, rows_group, rows_group]
    args2 += [g, lse, delta]

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=block_q, block_k=block_k, scale=cfg.scale,
            q_len=sq, causal=cfg.causal, has_mask=cfg.has_mask,
            has_bias=cfg.has_bias, has_offsets=cfg.has_offsets, group=group,
        ),
        grid=(b, kv_heads, kv_len // block_k),
        in_specs=in_specs2,
        out_specs=[kv_blk_spec, kv_blk_spec],
        out_shape=[
            _sds((b, kv_heads, kv_len, d), k.dtype, k),
            _sds((b, kv_heads, kv_len, d), v.dtype, v),
        ],
        interpret=_interpret(),
    )(*args2)
    return dq, dk, dv, dbias


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------


def _float0_like(x):
    """Cotangent for integer primals (mask / limit)."""
    return np.zeros(x.shape, jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def _flash_attention_bnsd(q, k, v, mask, limit, offsets, bias, cfg: _Cfg):
    out, _ = _flash_forward(q, k, v, mask, limit, offsets, bias, cfg)
    return out


def _fwd_rule(q, k, v, mask, limit, offsets, bias, cfg: _Cfg):
    out, lse = _flash_forward(q, k, v, mask, limit, offsets, bias, cfg)
    # named for remat policies: under "save_flash" (the activation-checkpointing
    # default) the backward keeps out/lse instead of re-running the forward
    # kernel — q/k/v rebuild from cheap projections, the flash pass does not
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse3 = checkpoint_name(lse[..., 0], "flash_lse")
    return out, (q, k, v, mask, limit, offsets, bias, out, lse3)


def _bwd_rule(cfg: _Cfg, res, g):
    dq, dk, dv, dbias = _flash_backward(res, g, cfg)
    mask, limit, offsets = res[3], res[4], res[5]
    return (
        dq, dk, dv,
        None if mask is None else _float0_like(mask),
        None if limit is None else _float0_like(limit),
        None if offsets is None else _float0_like(offsets),
        dbias,
    )


_flash_attention_bnsd.defvjp(_fwd_rule, _bwd_rule)


# ring-block entry: lse is a REAL output (the ring merge weights blocks by
# it), so this variant's vjp also consumes the lse cotangent
@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def _flash_attention_lse_bnsd(q, k, v, mask, limit, offsets, bias, cfg: _Cfg):
    return _flash_forward(q, k, v, mask, limit, offsets, bias, cfg)


def _lse_fwd_rule(q, k, v, mask, limit, offsets, bias, cfg: _Cfg):
    out, lse = _flash_forward(q, k, v, mask, limit, offsets, bias, cfg)
    return (out, lse), (q, k, v, mask, limit, offsets, bias, out, lse[..., 0])


def _lse_bwd_rule(cfg: _Cfg, res, gs):
    do, dlse8 = gs
    # the wrapper exposes lse as [..., 0] of the 8-sublane storage, so the
    # cotangent rides column 0; summing is exact for any consumer pattern
    dq, dk, dv, dbias = _flash_backward(res, do, cfg, dlse=dlse8.sum(axis=-1))
    mask, limit, offsets = res[3], res[4], res[5]
    return (
        dq, dk, dv,
        None if mask is None else _float0_like(mask),
        None if limit is None else _float0_like(limit),
        None if offsets is None else _float0_like(offsets),
        dbias,
    )


_flash_attention_lse_bnsd.defvjp(_lse_fwd_rule, _lse_bwd_rule)




def _mask_limit(kv_mask: jax.Array):
    """[B, S] validity → (mask int32 [B, 8, S], limit int32 [B, 1]). The mask
    is broadcast over 8 sublanes to satisfy Mosaic's VMEM block tiling (same
    trick as the lse rows); ``limit`` is the index of the last valid key
    (-1 when the row is fully padded) — the kernels' dynamic k-block bound."""
    mask = kv_mask.astype(jnp.int32)
    idx = jax.lax.broadcasted_iota(jnp.int32, mask.shape, 1)
    limit = jnp.max(jnp.where(mask != 0, idx, -1), axis=1, keepdims=True)
    b, s = mask.shape
    return jnp.broadcast_to(mask[:, None, :], (b, 8, s)), limit


def flash_attention(
    q: jax.Array,  # [B, S, N, D] (model-zoo layout)
    k: jax.Array,  # [B, T, KV, D]
    v: jax.Array,  # [B, T, KV, D]
    kv_mask: Optional[jax.Array] = None,  # [B, T] key validity (1 = attend)
    block_q: int = 256,
    block_k: int = 512,
    bwd_block_q: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
    causal: bool = True,
    bias: Optional[jax.Array] = None,  # [1|B, N, S, T] additive (T5 rel bias)
    scale: Optional[float] = None,
) -> jax.Array:
    """Flash attention with the ``attention_fn`` hook signature.

    Block sizes adapt DOWNWARD (halving, floor 128) until they divide the
    sequence, so any seq that is a multiple of 128 runs the kernel; only an
    untileable length falls back to the einsum path. Padding masks and
    non-causal attention run IN the kernel (v2); fully-padded key blocks are
    skipped via a per-batch limit. ``bias`` is an additive score bias with
    exact gradients (pass ``scale=1.0`` for T5, which folds the 1/sqrt(d)
    into its init).

    The backward kernels tile independently of the forward (``bwd_block_*``):
    the dq pass owns a q-block and loops k-blocks, the dkv pass owns a
    k-block and loops q-blocks, and their best tile shapes differ from the
    forward's (measured on v5e at seq 4096 — see BWD_BLOCK_Q/BWD_BLOCK_K).
    """
    b, s, n, d = q.shape
    t = k.shape[1]
    bq, bk = _fit_block(block_q, s), _fit_block(block_k, t)
    bbq = _fit_block(bwd_block_q or BWD_BLOCK_Q, s)
    bbk = _fit_block(bwd_block_k or BWD_BLOCK_K, t)
    # interpret-mode pallas inside a shard_map manual region (CPU pipeline
    # tests) trips a jax hlo_interpreter lowering-cache bug — use the exact
    # einsum path there; real TPUs lower through Mosaic and keep the kernel
    in_manual_region = bool(getattr(getattr(q, "aval", None), "vma", None))
    untileable = any(x % 128 for x in (bq, bk, bbq, bbk)) or s % bq or t % bk or s % bbq or t % bbk
    if (in_manual_region and _interpret()) or untileable or (causal and s != t):
        from ..models.attention import dot_product_attention

        mask = None if kv_mask is None else kv_mask[:, None, None, :].astype(bool)
        return dot_product_attention(q, k, v, mask=mask, causal=causal, scale=scale, bias=bias)
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if bias is not None and bias.shape[0] not in (1, b):
        # the kernel's index maps only know broadcast-or-batched; anything
        # else would silently read bias[0] everywhere and leave dbias rows
        # unwritten (the einsum path would raise a broadcast error)
        raise ValueError(f"bias batch dim must be 1 or {b}, got {bias.shape[0]}")
    mask = limit = None
    if kv_mask is not None:
        mask, limit = _mask_limit(kv_mask)
    cfg = _Cfg(
        block_q=bq, block_k=bk, bwd_block_q=bbq, bwd_block_k=bbk, scale=scale,
        causal=causal, has_mask=mask is not None, has_bias=bias is not None,
        bias_batched=bias is not None and bias.shape[0] == b,
    )
    out = _flash_attention_bnsd(
        q.swapaxes(1, 2), k.swapaxes(1, 2), v.swapaxes(1, 2), mask, limit, None, bias, cfg
    )
    return out.swapaxes(1, 2)


def _einsum_attention_lse(q, k, v, kv_mask, causal, q_offset, kv_offset, scale):
    """Exact fallback with the block entry's (out, lse) contract — same merge
    semantics as the kernel (fully-masked rows: out 0, lse very negative).
    Head grouping rides models.attention.grouped_scores/grouped_output, the
    zoo's single source of truth for the GQA convention."""
    from ..models.attention import grouped_output, grouped_scores

    b, s, n, d = q.shape
    t = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    scores = grouped_scores(q, k).astype(jnp.float32) * scale
    if causal:
        q_pos = (0 if q_offset is None else q_offset) + jnp.arange(s)
        k_pos = (0 if kv_offset is None else kv_offset) + jnp.arange(t)
        scores = jnp.where(k_pos[None, :] <= q_pos[:, None], scores, NEG_INF)
    if kv_mask is not None:
        scores = jnp.where(kv_mask[:, None, None, :] != 0, scores, NEG_INF)
    m = jnp.maximum(jnp.max(scores, axis=-1), M_INIT)  # [B,N,S]
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)
    l_safe = jnp.maximum(l, 1e-30)
    out = grouped_output((p / l_safe[..., None]).astype(q.dtype), v)
    lse = (m + jnp.log(l_safe)).transpose(0, 2, 1)  # [B, S, N]
    return out, lse


def flash_attention_block(
    q: jax.Array,  # [B, S, N, D]
    k: jax.Array,  # [B, T, KV, D]
    v: jax.Array,  # [B, T, KV, D]
    kv_mask: Optional[jax.Array] = None,  # [B, T] key validity
    *,
    causal: bool = False,
    q_offset=None,  # global position of q[.., 0] (traced ok — ring rotation)
    kv_offset=None,  # global position of k[.., 0]
    block_q: int = 256,
    block_k: int = 512,
    bwd_block_q: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
    scale: Optional[float] = None,
):
    """One attention BLOCK with online-softmax stats: ``(out, lse)`` where
    ``out`` [B, S, N, D] is the normalized block attention and ``lse``
    [B, S, N] fp32 its log-sum-exp — exactly what a ring/flash-decoding
    merge needs: a block contributes ``(numerator=out, max=lse, sum=1)``.
    Both outputs are differentiable (the merge weights blocks by lse).

    ``causal`` compares GLOBAL positions ``q_offset + i <= kv_offset + j``
    (dynamic offsets — the ring's rotation index is traced), so one compiled
    kernel serves diagonal, past (fully attended) and future (skipped via a
    zero-trip k-block loop) ring blocks. Falls back to an einsum with
    identical semantics off-TPU or for untileable shapes.
    """
    b, s, n, d = q.shape
    t = k.shape[1]
    bq, bk = _fit_block(block_q, s), _fit_block(block_k, t)
    bbq = _fit_block(bwd_block_q or BWD_BLOCK_Q, s)
    bbk = _fit_block(bwd_block_k or BWD_BLOCK_K, t)
    in_manual_region = bool(getattr(getattr(q, "aval", None), "vma", None))
    untileable = any(x % 128 for x in (bq, bk, bbq, bbk)) or s % bq or t % bk or s % bbq or t % bbk
    if (in_manual_region and _interpret()) or untileable:
        return _einsum_attention_lse(q, k, v, kv_mask, causal, q_offset, kv_offset, scale)
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    mask = limit = None
    if kv_mask is not None:
        mask, limit = _mask_limit(kv_mask)
    offsets = None
    has_offsets = causal and (q_offset is not None or kv_offset is not None)
    if has_offsets:
        offsets = jnp.stack([
            jnp.asarray(0 if q_offset is None else q_offset, jnp.int32),
            jnp.asarray(0 if kv_offset is None else kv_offset, jnp.int32),
        ]).reshape(1, 2)
    cfg = _Cfg(
        block_q=bq, block_k=bk, bwd_block_q=bbq, bwd_block_k=bbk, scale=scale,
        causal=causal, has_mask=mask is not None, has_bias=False,
        bias_batched=False, has_offsets=has_offsets,
    )
    out, lse8 = _flash_attention_lse_bnsd(
        q.swapaxes(1, 2), k.swapaxes(1, 2), v.swapaxes(1, 2), mask, limit, offsets, None, cfg
    )
    return out.swapaxes(1, 2), lse8[..., 0].transpose(0, 2, 1)


# backward tile defaults from the round-4 v5e sweep at seq 4096 (bs=8, 12
# heads, d=64; fwd fixed at 256/512): (512, 256) 33.9 ms vs the forward's
# (256, 512) at 34.5 ms; small blocks lose badly (128/128: 60 ms)
BWD_BLOCK_Q = 512
BWD_BLOCK_K = 256


def make_auto_attention(min_seq: int = 1024, causal: bool = True):
    """Per-shape dispatch: with 256/512 blocks the flash kernel beats XLA's
    fused einsum attention from ~1k tokens (measured on v5e: ~2.1x at 4k,
    ~15% at 1k in full training programs) — shorter sequences keep the
    einsum path, whose single fused softmax wins when the whole score tile
    fits on-chip. Masked and non-causal shapes run the kernel too (v2).

    ``causal`` is the model-level default; per-call override lets mixed
    models (T5: bidirectional encoder + causal decoder) share one hook.
    """

    def attention(q, k, v, kv_mask=None, bias=None, scale=None, causal=None):
        causal_ = causal if causal is not None else make_causal
        if q.shape[1] >= min_seq:
            return flash_attention(
                q, k, v, kv_mask, causal=causal_, bias=bias, scale=scale
            )
        from ..models.attention import dot_product_attention

        mask = None if kv_mask is None else kv_mask[:, None, None, :].astype(bool)
        return dot_product_attention(q, k, v, mask=mask, causal=causal_, scale=scale, bias=bias)

    make_causal = causal
    # marks the hook as accepting bias/scale/causal kwargs — model bodies
    # that need them (T5) only engage hooks carrying this flag (the ring
    # hooks do not support additive bias)
    attention.supports_bias = True
    return attention
