"""Sharded data loading.

Parity: reference data_loader.py — prepare_data_loader (745), BatchSamplerShard
(100), IterableDatasetShard (256), DataLoaderShard (391), DataLoaderDispatcher
(548), SeedableRandomSampler (67), skip_first_batches (1026),
DataLoaderStateMixin (355).

Design shift: the reference hands each rank a *local* per-rank batch; under
SPMD the training step consumes one *global* array whose leading dim is
sharded over the data-like mesh axes. So every loader here:

1. computes this process's index shard with the same arithmetic the reference
   uses (BatchSamplerShard / IterableDatasetShard behavior tables),
2. collates the host-local rows to numpy,
3. assembles a global ``jax.Array`` via
   ``jax.make_array_from_process_local_data`` (multi-host) or a sharded
   ``device_put`` (single host).

The result: user code iterates batches exactly like the reference, but what
comes out is already laid out for the jit-compiled step — no H2D copies inside
the step, no per-rank choreography.
"""

from __future__ import annotations

import math
import time
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

import jax

from .logging import get_logger
from .resilience.retry import DEFAULT_IO_RETRY
from .state import GradientState, PartialState
from .ops.operations import broadcast_object_list, concatenate, find_batch_size, recursively_apply

logger = get_logger(__name__)

# Transient-I/O policy for map-style batch fetches: datasets reading off
# GCS-fuse/NFS drop rows with EIO/ESTALE weather exactly like checkpoint
# writes do, and re-indexing a map-style dataset is idempotent — so the fetch
# retries under the stack-wide policy instead of killing the epoch.
# (Iterable datasets cannot be retried: a generator that raised is spent.)
io_retry_policy = DEFAULT_IO_RETRY


# ---------------------------------------------------------------------------
# samplers
# ---------------------------------------------------------------------------


class SeedableRandomSampler:
    """Shuffling whose permutation depends only on (seed, epoch).

    Parity: reference data_loader.py:67-97 — every process derives the same
    order, so index-sharding stays consistent without broadcasting RNG state.
    """

    def __init__(self, data_source_len: int, seed: int = 42, generator: Optional[np.random.Generator] = None):
        self.data_source_len = data_source_len
        self.initial_seed = seed
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.data_source_len

    def __iter__(self) -> Iterator[int]:
        rng = np.random.default_rng(self.initial_seed + self.epoch)
        yield from rng.permutation(self.data_source_len).tolist()


class SequentialSampler:
    def __init__(self, data_source_len: int):
        self.data_source_len = data_source_len

    def set_epoch(self, epoch: int) -> None:  # noqa: ARG002 - API parity
        pass

    def __len__(self) -> int:
        return self.data_source_len

    def __iter__(self) -> Iterator[int]:
        yield from range(self.data_source_len)


class BatchSampler:
    """Groups sampler indices into batches (torch BatchSampler semantics)."""

    def __init__(self, sampler, batch_size: int, drop_last: bool = False):
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.sampler, "set_epoch"):
            self.sampler.set_epoch(epoch)

    def __len__(self) -> int:
        if self.drop_last:
            return len(self.sampler) // self.batch_size
        return math.ceil(len(self.sampler) / self.batch_size)

    def __iter__(self) -> Iterator[list[int]]:
        batch: list[int] = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch


class BatchSamplerShard:
    """This process's share of a batch sampler (reference data_loader.py:100-253).

    Two modes:
    - ``split_batches=True``: each process takes its slice of *every* batch
      (global batch size == sampler's batch size).
    - ``split_batches=False``: processes take whole batches round-robin
      (global batch size == sampler's batch size * num_processes).

    ``even_batches=True`` pads by cycling indices from the start so every
    process sees the same number of equally-sized batches.
    """

    def __init__(
        self,
        batch_sampler,
        num_processes: int,
        process_index: int,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        if split_batches and getattr(batch_sampler, "batch_size", None) is not None:
            if batch_sampler.batch_size % num_processes != 0:
                raise ValueError(
                    f"split_batches=True requires the batch size ({batch_sampler.batch_size}) "
                    f"to be a round multiple of num_processes ({num_processes})."
                )
        self.batch_sampler = batch_sampler
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        self.drop_last = getattr(batch_sampler, "drop_last", False)

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(epoch)

    def __len__(self) -> int:
        if self.split_batches:
            return len(self.batch_sampler)
        length = len(self.batch_sampler)
        if self.drop_last:
            # the trailing incomplete window is dropped regardless of even_batches
            return length // self.num_processes
        if length % self.num_processes == 0:
            return length // self.num_processes
        return length // self.num_processes + 1

    def __iter__(self) -> Iterator[list[int]]:
        if self.split_batches:
            yield from self._iter_split()
        else:
            yield from self._iter_round_robin()

    def _iter_split(self) -> Iterator[list[int]]:
        full_size = self.batch_size
        for batch in self.batch_sampler:
            if full_size is not None and len(batch) < full_size:
                # final short batch
                if self.drop_last:
                    continue
                if self.even_batches:
                    # pad to full size by cycling the batch (duplicates land at
                    # the tail, so gather_for_metrics' remainder truncation works)
                    batch = (batch * (full_size // len(batch) + 1))[:full_size]
            share = len(batch) // self.num_processes
            if share == 0:
                continue
            yield batch[self.process_index * share : (self.process_index + 1) * share]

    def _iter_round_robin(self) -> Iterator[list[int]]:
        initial_batches: list[list[int]] = []  # for even_batches cycling
        cursor = 0
        pending: list[list[int]] = []
        for batch in self.batch_sampler:
            if len(initial_batches) < self.num_processes:
                initial_batches.append(batch)
            pending.append(batch)
            if len(pending) == self.num_processes:
                if len(pending[self.process_index]) == (self.batch_size or len(pending[self.process_index])):
                    yield pending[self.process_index]
                else:
                    # short final batch landed on us
                    yield self._maybe_pad(pending[self.process_index])
                pending = []
                cursor += 1
        if pending:
            if self.drop_last:
                return
            if self.even_batches:
                # recycle indices from the first batches to fill the window
                all_idx = [i for b in pending for i in b]
                fill = [i for b in initial_batches for i in b]
                target = (self.batch_size or len(initial_batches[0])) * self.num_processes
                while len(all_idx) < target and fill:
                    all_idx.extend(fill[: target - len(all_idx)])
                per = target // self.num_processes
                piece = all_idx[self.process_index * per : (self.process_index + 1) * per]
                if piece:
                    yield piece
            elif self.process_index < len(pending):
                yield pending[self.process_index]

    def _maybe_pad(self, batch: list[int]) -> list[int]:
        if not self.even_batches or self.batch_size is None or len(batch) == self.batch_size:
            return batch
        cycled = (batch * (self.batch_size // len(batch) + 1))[: self.batch_size]
        return cycled


class IterableDatasetShard:
    """Shard an un-indexable iterable across processes (data_loader.py:256-352).

    Buffers ``batch_size * num_processes`` elements and yields this process's
    slice; a final partial buffer is padded from the first buffer when
    ``even_batches``.
    """

    def __init__(
        self,
        dataset: Iterable,
        batch_size: int,
        num_processes: int,
        process_index: int,
        drop_last: bool = False,
        split_batches: bool = False,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.num_processes = num_processes
        self.process_index = process_index
        self.drop_last = drop_last
        self.split_batches = split_batches
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __iter__(self):
        real_batch_size = self.batch_size if self.split_batches else self.batch_size * self.num_processes
        share = real_batch_size // self.num_processes
        process_slice = range(self.process_index * share, (self.process_index + 1) * share)

        first_buffer = None
        buffer = []
        for element in self.dataset:
            buffer.append(element)
            if len(buffer) == real_batch_size:
                if first_buffer is None:
                    first_buffer = buffer.copy()
                for i in process_slice:
                    yield buffer[i]
                buffer = []
        if len(buffer) > 0 and not self.drop_last:
            if first_buffer is None:
                first_buffer = buffer.copy()
            while len(buffer) < real_batch_size:
                buffer += first_buffer[: real_batch_size - len(buffer)]
            for i in process_slice:
                yield buffer[i]


# ---------------------------------------------------------------------------
# collation
# ---------------------------------------------------------------------------


def default_collate(rows: list) -> Any:
    """Stack a list of samples into a batch tree of numpy arrays."""
    first = rows[0]
    if isinstance(first, dict):
        return {k: default_collate([r[k] for r in rows]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([r[i] for r in rows]) for i in range(len(first)))
    arr = np.asarray(rows)
    return arr


# ---------------------------------------------------------------------------
# loaders
# ---------------------------------------------------------------------------


class DataLoaderStateMixin:
    """GradientState begin/end bookkeeping (reference data_loader.py:355-388)."""

    def begin(self):
        self.reset()
        self.gradient_state._add_dataloader(self)

    def end(self):
        self.gradient_state._remove_dataloader(self)

    def reset(self):
        self.end_of_dataloader = False
        self.remainder = -1
        self.batches_yielded = 0


class BaseDataLoader(DataLoaderStateMixin):
    """Common machinery: one-batch lookahead (to flag end-of-epoch *before* the
    last batch is consumed — reference data_loader.py:450-471), global-array
    assembly, and async prefetch.

    ``prefetch > 0`` runs collate + global-array assembly + H2D transfer in a
    background thread, ``prefetch`` batches ahead of the training step — the
    reference's MpDeviceLoader transfer threads (data_loader.py:504-545).
    Batch order and end-of-epoch semantics are identical to the synchronous
    path: the producer only *tags* the final batch; the epoch-state flags
    flip on the consumer side right before that batch is yielded.
    """

    def __init__(self, device_placement: bool = True, non_blocking: bool = False, prefetch: int = 2):
        self.device_placement = device_placement
        self.non_blocking = non_blocking
        self.prefetch = prefetch
        self.gradient_state = GradientState()
        self.state = PartialState()
        self.epoch = 0
        # mid-epoch resume bookkeeping (fault_tolerance.CheckpointManager):
        # position = batches already consumed this epoch, counting the batches
        # a skip_first_batches loader skipped (its _skip_offset)
        self._skip_offset = 0
        self.reset()

    @property
    def position(self) -> int:
        """Batches consumed this epoch (absolute: a resumed loader counts the
        batches it skipped) — what CheckpointManager snapshots so a resumed
        run's next batch is bit-exact the one this run would have consumed."""
        return self._skip_offset + self.batches_yielded

    def _globalize(self, local_batch):
        """Host-local numpy batch → global sharded jax.Array tree."""
        if not self.device_placement:
            return local_batch
        sharding = self.state.data_sharding()

        def _make(arr):
            arr = np.asarray(arr)
            if self.state.num_processes > 1:
                return jax.make_array_from_process_local_data(sharding, arr)
            target = sharding
            split = sharding.mesh.shape["data"] * sharding.mesh.shape.get("fsdp", 1)
            if arr.ndim == 0 or arr.shape[0] % split != 0:
                target = jax.sharding.NamedSharding(sharding.mesh, jax.sharding.PartitionSpec())
            return jax.device_put(arr, target)

        return recursively_apply(_make, local_batch)

    def _remesh_stale(self, host_batch, global_batch):
        """Elastic-training guard (resilience/elastic.py): a batch the
        prefetch thread globalized BEFORE a mesh shrink/regrow is laid out
        for the dead mesh — stepping it would resurrect lost devices.
        Re-globalize from the retained host copy when the batch's mesh is no
        longer the live one; the steady-state cost is one mesh identity
        compare per batch."""
        if not self.device_placement:
            return global_batch
        for leaf in jax.tree_util.tree_leaves(global_batch):
            if isinstance(leaf, jax.Array):
                mesh = getattr(leaf.sharding, "mesh", None)
                if mesh is not None and mesh != self.state.mesh:
                    return self._globalize(host_batch)
                break
        return global_batch

    def _mark_last_batch(self) -> None:
        self.end_of_dataloader = True
        if getattr(self, "_total_samples", None) is not None:
            self.remainder = self._total_samples % self.total_batch_size or -1

    def _iterate_with_lookahead(self, batches: Iterator):
        if self.prefetch and self.prefetch > 0:
            yield from self._iterate_prefetched(batches)
            return
        self.begin()
        try:
            current = None
            have_current = False
            for nxt in batches:
                if have_current:
                    self.batches_yielded += 1
                    yield self._globalize(current)
                current = nxt
                have_current = True
            if have_current:
                self._mark_last_batch()
                self.batches_yielded += 1
                yield self._globalize(current)
        finally:
            self.end()

    def _iterate_prefetched(self, batches: Iterator):
        """Producer thread collates/globalizes up to ``prefetch`` batches ahead
        while the consumer's step runs — H2D rides DMA under the compute."""
        import queue
        import threading

        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                current = None
                have_current = False
                # each item keeps its HOST batch alongside the globalized one:
                # an elastic mesh shrink between produce and consume leaves
                # the device copy on a dead mesh, and the consumer re-shards
                # from the host copy (_remesh_stale). This pins up to
                # `prefetch` host batch copies until consume (previously only
                # the producer's in-flight pair was live) — the host-RAM
                # price of elastic re-sharding; lower `prefetch` if it bites.
                for nxt in batches:
                    if have_current and not _put(
                        ("batch", (current, self._globalize(current)), False)
                    ):
                        return
                    current = nxt
                    have_current = True
                if have_current:
                    if not _put(("batch", (current, self._globalize(current)), True)):
                        return
            except BaseException as exc:  # surface dataset/collate errors in the consumer
                _put(("error", exc, False))
                return
            _put(("done", None, False))

        self.begin()
        thread = threading.Thread(target=produce, name="accelerate-tpu-prefetch", daemon=True)
        thread.start()
        try:
            while True:
                kind, payload, is_last = q.get()
                if kind == "done":
                    break
                if kind == "error":
                    raise payload
                if is_last:
                    self._mark_last_batch()
                self.batches_yielded += 1
                yield self._remesh_stale(*payload)
                if is_last:
                    break
        finally:
            stop.set()
            while True:  # unblock a producer stuck on a full queue
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            thread.join(timeout=5)
            self.end()


class DataLoaderShard(BaseDataLoader):
    """Map-style dataset loader: index shard → collate → global array.

    Parity: reference DataLoaderShard (data_loader.py:391-501).
    """

    def __init__(
        self,
        dataset,
        batch_sampler,
        collate_fn: Optional[Callable] = None,
        device_placement: bool = True,
        split_batches: bool = False,
        prefetch: int = 2,
        **kwargs,
    ):
        super().__init__(device_placement=device_placement, prefetch=prefetch)
        self.dataset = dataset
        self.batch_sampler = batch_sampler
        self.collate_fn = collate_fn or default_collate
        self.split_batches = split_batches
        self.epoch = 0
        try:
            self._total_samples = len(dataset)
        except TypeError:
            self._total_samples = None

    @property
    def total_batch_size(self) -> int:
        """Global batch size across all processes (reference data_loader.py:487).

        Attribute-based (not isinstance) so wrappers like SkipBatchSampler,
        which forward num_processes/split_batches, keep the arithmetic right.
        """
        bs = self.batch_sampler.batch_size or 1
        if not getattr(self.batch_sampler, "split_batches", False):
            return bs * getattr(self.batch_sampler, "num_processes", 1)
        return bs

    @property
    def total_dataset_length(self) -> int:
        return self._total_samples if self._total_samples is not None else -1

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(epoch)
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.batch_sampler)

    def _fetch_batch(self, index_batch):
        return self.collate_fn([self.dataset[i] for i in index_batch])

    def _local_batches(self):
        for index_batch in self.batch_sampler:
            yield io_retry_policy.call(self._fetch_batch, index_batch)

    def __iter__(self):
        yield from self._iterate_with_lookahead(self._local_batches())


class IterableDataLoaderShard(BaseDataLoader):
    """Loader over an IterableDatasetShard (no indices)."""

    def __init__(
        self,
        dataset_shard: IterableDatasetShard,
        collate_fn: Optional[Callable] = None,
        device_placement: bool = True,
        prefetch: int = 2,
    ):
        super().__init__(device_placement=device_placement, prefetch=prefetch)
        self.dataset = dataset_shard
        self.collate_fn = collate_fn or default_collate
        self._total_samples = None

    @property
    def total_batch_size(self) -> int:
        ds = self.dataset
        return ds.batch_size if ds.split_batches else ds.batch_size * ds.num_processes

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        self.dataset.set_epoch(epoch)

    def _local_batches(self):
        share = self.total_batch_size // self.dataset.num_processes
        rows = []
        for row in self.dataset:
            rows.append(row)
            if len(rows) == share:
                yield self.collate_fn(rows)
                rows = []
        if rows:
            yield self.collate_fn(rows)

    def __iter__(self):
        yield from self._iterate_with_lookahead(self._local_batches())


class DataLoaderDispatcher(BaseDataLoader):
    """Process 0 reads the full loader and scatters slices.

    Parity: reference DataLoaderDispatcher (data_loader.py:548-742). Needed
    when the dataset is only readable on one host (e.g. a stream). Host 0
    iterates, broadcasts the batch structure + data; every host slices its
    share and the batch is assembled into a global array.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        collate_fn: Optional[Callable] = None,
        device_placement: bool = True,
        drop_last: bool = False,
    ):
        # prefetch=0: the scatter path issues cross-process broadcasts, which
        # must stay on the main thread in the same order as the training
        # step's collectives — a producer thread could reorder them per host
        super().__init__(device_placement=device_placement, prefetch=0)
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.drop_last = drop_last
        self._total_samples = None

    @property
    def total_batch_size(self) -> int:
        return self.batch_size * self.state.num_processes

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def _local_batches(self):
        state = self.state
        target = self.total_batch_size
        if state.is_main_process:
            rows: list = []
            iterator = iter(self.dataset)
            first_full: list | None = None
            while True:
                try:
                    while len(rows) < target:
                        rows.append(next(iterator))
                except StopIteration:
                    if not rows:
                        broadcast_object_list([None]) if state.num_processes > 1 else None
                        return
                    if self.drop_last:
                        if state.num_processes > 1:
                            broadcast_object_list([None])
                        return
                    if first_full is not None:
                        rows += first_full[: target - len(rows)]
                    else:
                        while len(rows) < target:
                            rows += rows[: target - len(rows)]
                    yield self._scatter(rows)
                    if state.num_processes > 1:
                        broadcast_object_list([None])
                    return
                if first_full is None:
                    first_full = rows.copy()
                yield self._scatter(rows)
                rows = []
        else:
            while True:
                batch = self._scatter(None)
                if batch is None:
                    return
                yield batch

    def _scatter(self, rows):
        state = self.state
        if state.num_processes == 1:
            return self.collate_fn(rows)
        payload = [rows] if state.is_main_process else [None]
        broadcast_object_list(payload)
        rows = payload[0]
        if rows is None:
            return None
        share = len(rows) // state.num_processes
        mine = rows[state.process_index * share : (state.process_index + 1) * share]
        return self.collate_fn(mine)

    def __iter__(self):
        yield from self._iterate_with_lookahead(self._local_batches())


# ---------------------------------------------------------------------------
# factory
# ---------------------------------------------------------------------------


def prepare_data_loader(
    dataloader_or_dataset,
    device_placement: bool = True,
    split_batches: bool = False,
    batch_size: Optional[int] = None,
    shuffle: Optional[bool] = None,
    seed: Optional[int] = None,
    collate_fn: Optional[Callable] = None,
    drop_last: Optional[bool] = None,
    even_batches: bool = True,
    dispatch_batches: Optional[bool] = None,
    use_seedable_sampler: bool = True,
    prefetch: Optional[int] = None,
) -> BaseDataLoader:
    """Decide the sharding strategy and build the loader (data_loader.py:745-978).

    Accepts:
    - a map-style dataset (``__len__`` + ``__getitem__``),
    - an iterable dataset (no ``__len__``),
    - a torch ``DataLoader`` (its dataset/sampler config is re-derived),
    - an existing prepared loader (returned unchanged).
    """
    if isinstance(dataloader_or_dataset, BaseDataLoader):
        return dataloader_or_dataset

    state = PartialState()

    dataset = dataloader_or_dataset
    # torch DataLoader interop: lift its config
    if hasattr(dataset, "dataset") and hasattr(dataset, "batch_size") and not hasattr(dataset, "__getitem__"):
        loader = dataset
        dataset = loader.dataset
        batch_size = batch_size or loader.batch_size
        if drop_last is None:
            drop_last = getattr(loader, "drop_last", False)
        if collate_fn is None:
            lcf = getattr(loader, "collate_fn", None)
            # torch default_collate returns torch tensors; keep ours unless custom
            if lcf is not None and type(lcf).__module__ != "torch.utils.data._utils.collate":
                collate_fn = lcf
        if shuffle is None:
            sampler = getattr(loader, "sampler", None)
            shuffle = type(sampler).__name__ == "RandomSampler"

    batch_size = batch_size or 8
    drop_last = bool(drop_last)
    shuffle = bool(shuffle) if shuffle is not None else False
    seed = 42 if seed is None else seed

    indexable = hasattr(dataset, "__len__") and hasattr(dataset, "__getitem__")

    if dispatch_batches:
        if prefetch:
            logger.warning(
                "prefetch is not supported with dispatch_batches=True (the "
                "scatter path's cross-process broadcasts must stay on the main "
                "thread, in order) — continuing without prefetching."
            )
        return DataLoaderDispatcher(
            dataset,
            batch_size=batch_size if not split_batches else batch_size // state.num_processes,
            collate_fn=collate_fn,
            device_placement=device_placement,
            drop_last=drop_last,
        )
    prefetch = 2 if prefetch is None else prefetch

    if not indexable:
        shard = IterableDatasetShard(
            dataset,
            batch_size=batch_size,
            num_processes=state.num_processes,
            process_index=state.process_index,
            drop_last=drop_last,
            split_batches=split_batches,
        )
        return IterableDataLoaderShard(
            shard, collate_fn=collate_fn, device_placement=device_placement, prefetch=prefetch
        )

    n = len(dataset)
    # Shuffling is always (seed, epoch)-derived: jax has no mutable global
    # generator whose state a non-seedable sampler could consume, so
    # use_seedable_sampler is accepted for API parity but there is only one
    # (reproducible) shuffle implementation.
    sampler = SeedableRandomSampler(n, seed=seed) if shuffle else SequentialSampler(n)
    inner = BatchSampler(sampler, batch_size=batch_size, drop_last=drop_last)
    shard = BatchSamplerShard(
        inner,
        num_processes=state.num_processes,
        process_index=state.process_index,
        split_batches=split_batches,
        even_batches=even_batches,
    )
    return DataLoaderShard(
        dataset,
        batch_sampler=shard,
        collate_fn=collate_fn,
        device_placement=device_placement,
        split_batches=split_batches,
        prefetch=prefetch,
    )


# ---------------------------------------------------------------------------
# mid-epoch resume
# ---------------------------------------------------------------------------


class SkipBatchSampler:
    """Yields the inner batch sampler's batches after the first N (data_loader.py:981)."""

    def __init__(self, batch_sampler, skip_batches: int = 0):
        self.batch_sampler = batch_sampler
        self.skip_batches = skip_batches

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(epoch)

    @property
    def batch_size(self):
        return getattr(self.batch_sampler, "batch_size", None)

    @property
    def num_processes(self):
        return getattr(self.batch_sampler, "num_processes", 1)

    @property
    def split_batches(self):
        return getattr(self.batch_sampler, "split_batches", False)

    def __len__(self) -> int:
        return max(len(self.batch_sampler) - self.skip_batches, 0)

    def __iter__(self):
        for i, batch in enumerate(self.batch_sampler):
            if i >= self.skip_batches:
                yield batch


# Telemetry seam: called as ``hook(seconds, batches_skipped)`` when a
# SkipDataLoader finishes replaying consumed batches — the dataloader-rewind
# cost of a mid-epoch resume. (The DataLoaderShard path skips at the
# batch-SAMPLER level, which costs nothing and reports nothing.) The
# Telemetry hub installs this; it must never raise into the data path.
rewind_seconds_hook: "Optional[Callable[[float, int], None]]" = None


def _fire_rewind(seconds: float, batches: int) -> None:
    hook = rewind_seconds_hook
    if hook is not None:
        try:
            hook(seconds, batches)
        except Exception:
            pass


class SkipDataLoader(BaseDataLoader):
    """Iterable-loader variant of batch skipping (data_loader.py:1026)."""

    def __init__(self, inner_loader: BaseDataLoader, skip_batches: int):
        super().__init__(device_placement=False)  # inner loader already globalizes
        self.inner_loader = inner_loader
        self.skip_batches = skip_batches
        self._skip_offset = skip_batches  # position stays absolute for resume
        self.epoch = getattr(inner_loader, "epoch", 0)

    def __getattr__(self, name):
        return getattr(self.__dict__["inner_loader"], name)

    def __iter__(self):
        self.batches_yielded = 0
        rewind_start = time.perf_counter() if self.skip_batches else None
        for i, batch in enumerate(self.inner_loader):
            if i >= self.skip_batches:
                if rewind_start is not None:
                    # the replayed batches are pure resume overhead — surface
                    # them to the goodput ledger once, at the first real batch
                    _fire_rewind(time.perf_counter() - rewind_start, self.skip_batches)
                    rewind_start = None
                self.batches_yielded += 1
                yield batch


def skip_first_batches(dataloader, num_batches: int = 0):
    """Resume mid-epoch: a loader equivalent to ``dataloader`` minus its first
    ``num_batches`` batches (reference data_loader.py:1026-1093)."""
    if num_batches == 0:
        return dataloader
    if isinstance(dataloader, DataLoaderShard):
        skipped = DataLoaderShard(
            dataloader.dataset,
            batch_sampler=SkipBatchSampler(dataloader.batch_sampler, num_batches),
            collate_fn=dataloader.collate_fn,
            device_placement=dataloader.device_placement,
            split_batches=dataloader.split_batches,
            prefetch=dataloader.prefetch,
        )
        # position stays absolute so a save during the resumed epoch records
        # the true batch index, not the count since the resume
        skipped._skip_offset = num_batches
        skipped.epoch = dataloader.epoch
        return skipped
    return SkipDataLoader(dataloader, num_batches)
