"""Fault-tolerant checkpointing: atomic commits, preemption handling, auto-resume.

Production pods are preemptible: a spot-VM SIGTERM or a crashed host must
never cost more than the work since the last checkpoint, and must NEVER cost
the run itself. The reference treats ``save_state`` as a best-effort in-place
write — a kill mid-save can corrupt the newest checkpoint while the rotation
logic has already deleted the previous good one. This module closes both
holes with three cooperating pieces:

1. **Atomic commit protocol** (used by ``checkpointing.save_accelerator_state``):
   every save stages into ``<dir>.tmp``, a ``manifest.json`` records per-file
   sizes + CRC32 checksums + step/topology metadata, all hosts barrier, and
   only then does process 0 rename the staging dir to its final name. Old
   checkpoints rotate strictly AFTER the new one is committed. A kill at any
   instant therefore leaves at least one complete, verifiable checkpoint; the
   torn ``.tmp`` dir is garbage-collected on the next save.

2. **Preemption handling** (``CheckpointManager``): a SIGTERM/SIGINT handler
   flips a flag — it does NOT save from the handler, because mid-step state is
   inconsistent — and ``should_save()`` turns the flag into exactly one save
   at the next step boundary. Multi-host agreement rides
   ``PartialState.any_process``: the grace-window signal may land on one host
   only, and every host must decide to save at the same boundary or the save
   barrier deadlocks. Saves are wrapped in ``retry_transient_io`` so GCS-fuse
   style flaky writes back off and retry instead of killing the run.

3. **Auto-resume** (``latest_valid`` / ``CheckpointManager.resume``): scan the
   checkpoint dir newest-first, validate manifests (skipping ``.tmp`` and torn
   dirs), ``load_state`` the newest valid one, and rewind the dataloaders via
   ``set_epoch`` + ``skip_first_batches`` so the next batch is bit-exact the
   one the dead run would have consumed. ``resume_from_checkpoint="auto"``
   needs zero operator input — which is what lets ``pod-launch --auto_resume``
   restart a dead worker unattended.

The manifest/commit protocol assumes the checkpoint directory is a shared
filesystem across hosts (GCS-fuse / NFS — the pod norm). On non-shared
filesystems each non-main host commits its local staging dir too (its RNG
file lives there), without a manifest.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import signal
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from .logging import get_logger
from .state import PartialState
from .utils.constants import (
    CHECKPOINT_DIR_PREFIX,
    CHECKPOINT_MANIFEST_NAME,
    CHECKPOINT_TMP_SUFFIX,
)
from .resilience.chaos import probe_io as _chaos_probe_io
from .resilience.retry import DEFAULT_IO_RETRY, RetryPolicy
from .utils.memory import retry_transient_io

logger = get_logger(__name__)

MANIFEST_FORMAT_VERSION = 1

# Test seam: when set, called as ``hook(stage, directory)`` at the named
# points of the commit protocol ("staged" = all state files written,
# "manifest" = manifest written, both before the rename). Crash-injection
# tests raise from here to simulate a kill at that exact instant.
fault_injection_hook: Optional[Callable[[str, str], None]] = None


def _run_fault_hook(stage: str, directory: str) -> None:
    if fault_injection_hook is not None:
        fault_injection_hook(stage, directory)


# ---------------------------------------------------------------------------
# manifest: build / write / verify
# ---------------------------------------------------------------------------


def _file_crc32(path: str, chunk_bytes: int = 1 << 20) -> str:
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk_bytes)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return format(crc & 0xFFFFFFFF, "08x")


def build_manifest(directory: str, step: Optional[int] = None, metadata: Optional[dict] = None) -> dict:
    """Walk ``directory`` and record every file's size + CRC32, plus the
    step/topology metadata a resume needs to sanity-check compatibility."""
    files: dict[str, dict] = {}
    for root, _, names in os.walk(directory):
        for name in sorted(names):
            if name == CHECKPOINT_MANIFEST_NAME:
                continue
            full = os.path.join(root, name)
            rel = os.path.relpath(full, directory)
            files[rel] = {"size": os.path.getsize(full), "crc32": _file_crc32(full)}
    state = PartialState()
    manifest = {
        "format_version": MANIFEST_FORMAT_VERSION,
        "step": step,
        "files": files,
        "topology": {
            "num_processes": state.num_processes,
            "num_devices": state.num_devices,
            "mesh": {axis: int(size) for axis, size in state.mesh.shape.items()},
        },
        "created": time.time(),
    }
    if metadata:
        manifest["metadata"] = metadata
    return manifest


@retry_transient_io
def write_manifest(directory: str, manifest: dict) -> str:
    """Durably write ``manifest.json`` (fsync'd: the rename that follows must
    never promote a dir whose manifest is still in the page cache)."""
    _chaos_probe_io("checkpoint_save")  # chaos harness: injected EIO rides the retry above
    path = os.path.join(directory, CHECKPOINT_MANIFEST_NAME)
    tmp = path + ".part"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_manifest(directory: str) -> Optional[dict]:
    path = os.path.join(directory, CHECKPOINT_MANIFEST_NAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def verify_checkpoint(directory: str, check_checksums: bool = True) -> list[str]:
    """Validate a checkpoint directory against its manifest.

    Returns a list of human-readable problems — empty means the checkpoint is
    complete and verifiable. Used by ``latest_valid`` (skip torn dirs), the
    ``verify-checkpoint`` CLI, and tests.
    """
    if not os.path.isdir(directory):
        return [f"{directory} is not a directory"]
    if directory.rstrip(os.sep).endswith(CHECKPOINT_TMP_SUFFIX):
        return [f"{directory} is an uncommitted staging dir ({CHECKPOINT_TMP_SUFFIX})"]
    path = os.path.join(directory, CHECKPOINT_MANIFEST_NAME)
    if not os.path.exists(path):
        return [f"missing {CHECKPOINT_MANIFEST_NAME}"]
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [f"unreadable manifest: {e}"]
    files = manifest.get("files")
    if not isinstance(files, dict) or not files:
        return ["manifest lists no files"]
    problems = []
    for rel, meta in files.items():
        full = os.path.join(directory, rel)
        if not os.path.exists(full):
            problems.append(f"missing file {rel}")
            continue
        size = os.path.getsize(full)
        if size != meta.get("size"):
            problems.append(f"size mismatch for {rel}: manifest {meta.get('size')}, on disk {size}")
            continue
        if check_checksums and _file_crc32(full) != meta.get("crc32"):
            problems.append(f"checksum mismatch for {rel}")
    return problems


def is_valid_checkpoint(directory: str) -> bool:
    return not verify_checkpoint(directory)


# ---------------------------------------------------------------------------
# atomic commit + torn-dir garbage collection
# ---------------------------------------------------------------------------


def staging_dir_for(final_dir: str) -> str:
    return final_dir.rstrip(os.sep) + CHECKPOINT_TMP_SUFFIX


@retry_transient_io
def commit_checkpoint(staging_dir: str, final_dir: str) -> str:
    """Atomically promote a complete staging dir to its final name.

    The rename is the commit point: before it, readers see only the previous
    checkpoints; after it, the new one is complete (its manifest was fsync'd
    first). Re-saving into an existing ``final_dir`` moves the old tree aside
    before the rename so the swap stays a pair of renames, never a partial
    in-place overwrite. The aside name ends in ``.old`` — deliberately NOT
    the ``.tmp`` suffix ``garbage_collect_torn`` matches — so a kill between
    the two renames (only the complete staging dir and the complete old dir
    on disk, neither under the final name) leaves both copies recoverable
    instead of feeding the old one to the next save's torn-dir GC.
    """
    doomed = final_dir.rstrip(os.sep) + ".old"
    if os.path.exists(final_dir):
        if os.path.exists(doomed):
            shutil.rmtree(doomed, ignore_errors=True)
        os.rename(final_dir, doomed)
        os.rename(staging_dir, final_dir)
    else:
        os.rename(staging_dir, final_dir)
    # with the new checkpoint committed, the old copy (this commit's aside,
    # or one left by a previously interrupted commit) is no longer needed
    shutil.rmtree(doomed, ignore_errors=True)
    return final_dir


def garbage_collect_torn(base: str) -> list[str]:
    """Remove leftover ``*.tmp`` staging dirs under ``base`` — the debris of a
    previous run killed mid-save. Called on the next save, so torn dirs never
    accumulate and never shadow valid checkpoints."""
    removed = []
    if not os.path.isdir(base):
        return removed
    for name in os.listdir(base):
        if name.endswith(CHECKPOINT_TMP_SUFFIX):
            full = os.path.join(base, name)
            if os.path.isdir(full):
                shutil.rmtree(full, ignore_errors=True)
                removed.append(full)
                logger.info(f"Garbage-collected torn checkpoint staging dir {full}")
    return removed


# ---------------------------------------------------------------------------
# checkpoint discovery / auto-resume
# ---------------------------------------------------------------------------


def list_checkpoints(base: str) -> list[str]:
    """Committed ``checkpoint_<n>`` dirs under ``base``, oldest→newest."""
    if not os.path.isdir(base):
        return []
    entries = []
    for name in os.listdir(base):
        match = re.fullmatch(rf"{CHECKPOINT_DIR_PREFIX}_(\d+)", name)
        if match and os.path.isdir(os.path.join(base, name)):
            entries.append((int(match.group(1)), os.path.join(base, name)))
    return [path for _, path in sorted(entries)]


def latest_valid_checkpoint(base: str, check_checksums: bool = True) -> Optional[str]:
    """Newest checkpoint under ``base`` whose manifest validates.

    ``.tmp`` staging dirs never match the ``checkpoint_<n>`` pattern, and a
    committed-but-torn dir (a manifest whose files fail verification —
    possible only through external damage, since the commit protocol renames
    after the manifest validates) is skipped with a warning rather than
    resumed into a corrupt run.
    """
    for path in reversed(list_checkpoints(base)):
        problems = verify_checkpoint(path, check_checksums=check_checksums)
        if not problems:
            return path
        logger.warning(
            f"Skipping invalid checkpoint {path}: {'; '.join(problems[:3])}"
            + (f" (+{len(problems) - 3} more)" if len(problems) > 3 else "")
        )
    return None


def checkpoint_step(directory: str, manifest: Optional[dict] = None) -> int:
    """The training step a checkpoint was saved at, from its manifest (the
    ``metadata.step`` CheckpointManager records, falling back to the manifest
    root's step, then 0). Shared by ``CheckpointManager.resume`` and the
    elastic checkpoint rung (resilience/elastic.py) so both agree on how many
    steps a disk restore loses. Pass an already-read ``manifest`` to skip the
    re-read."""
    if manifest is None:
        manifest = read_manifest(directory) or {}
    meta = manifest.get("metadata", {})
    return int(meta.get("step", manifest.get("step") or 0))


@dataclass
class ResumePoint:
    """What ``CheckpointManager.resume`` restored: the checkpoint path plus
    the positions needed to rewind dataloaders to the exact next batch."""

    path: str
    step: int = 0
    epoch: int = 0
    dataloaders: list = field(default_factory=list)  # [{"epoch": e, "position": n}, ...]
    metadata: dict = field(default_factory=dict)


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------


class CheckpointManager:
    """Owns a run's checkpoint lifecycle: periodic atomic saves, rotation,
    preemption-triggered boundary saves, and auto-resume.

    Canonical loop::

        manager = CheckpointManager(accelerator, "ckpts", save_interval=500)
        resume = manager.resume("auto")           # None on a fresh run
        start_epoch = resume.epoch if resume else 0
        step = resume.step if resume else 0
        for epoch in range(start_epoch, num_epochs):
            loader.set_epoch(epoch)
            epoch_loader = manager.resumed_loader(loader, resume, epoch)
            for batch in epoch_loader:
                loss = train_step(batch)
                step += 1
                if manager.should_save(step):
                    manager.save(step, epoch=epoch)
                if manager.exit_requested:        # preemption save landed
                    return
            resume = None                          # later epochs start at 0
    """

    def __init__(
        self,
        accelerator: Any,
        checkpoint_dir: Optional[str] = None,
        save_interval: Optional[int] = None,
        total_limit: Optional[int] = None,
        sharded: bool = False,
        handle_signals: tuple = (signal.SIGTERM, signal.SIGINT),
        check_checksums: bool = True,
        preemption_sync_every: int = 1,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self.accelerator = accelerator
        project = accelerator.project_configuration
        if project.automatic_checkpoint_naming:
            # the two naming schemes fight: save(step) would write
            # checkpoint_<iteration> while returning/rotating checkpoint_<step>,
            # and iteration resets on restart ("already exists" on the first
            # post-resume save) — exactly what an unattended run cannot have
            raise ValueError(
                "CheckpointManager names checkpoints by training step and "
                "cannot run with ProjectConfiguration(automatic_checkpoint_naming"
                "=True); disable it — the manager handles naming and rotation."
            )
        self.checkpoint_dir = checkpoint_dir or os.path.join(project.project_dir or ".", "checkpoints")
        self.save_interval = save_interval
        self.total_limit = total_limit if total_limit is not None else project.total_limit
        self.sharded = sharded
        self.check_checksums = check_checksums
        # multi-host: how often (in steps) should_save runs the collective
        # preemption agreement. 1 = every step (tightest reaction); larger
        # values amortize the allgather on big pods — keep it well under the
        # grace window in steps. Single-host runs never pay a collective.
        self.preemption_sync_every = max(int(preemption_sync_every), 1)
        # jittered-backoff policy for whole-call save/load retries (the
        # per-operation commit-protocol retries keep their own wrapping)
        self.retry_policy = retry_policy if retry_policy is not None else DEFAULT_IO_RETRY
        self._preempted = False
        self._preempt_signum: Optional[int] = None
        self._saved_on_preemption = False
        self._prev_handlers: dict = {}
        self._swapped_loaders: dict = {}  # id(original) -> wrapper in _dataloaders
        if handle_signals:
            self._install_handlers(handle_signals)

    def _telemetry_pause(self, category: str):
        """Goodput bracket around save/restore: the elapsed time lands in the
        accelerator's telemetry ledger (and the step-timer's in-flight window
        is discarded so the stall never reads as a slow step). No-op when the
        accelerator carries no telemetry hub."""
        telemetry = getattr(self.accelerator, "telemetry", None)
        if telemetry is None:
            from contextlib import nullcontext

            return nullcontext()
        return telemetry.pause(category)

    # -- preemption --------------------------------------------------------

    def _install_handlers(self, signals_to_handle) -> None:
        for sig in signals_to_handle:
            try:
                self._prev_handlers[sig] = signal.signal(sig, self._on_signal)
            except ValueError:
                # not the main thread (notebook executors, test runners):
                # preemption saves then need an explicit request_preemption()
                logger.warning(
                    "CheckpointManager could not install signal handlers outside "
                    "the main thread; call request_preemption() manually."
                )
                break

    def _on_signal(self, signum, frame) -> None:  # noqa: ARG002
        # Flag only — never save from a handler: the signal can land mid-step
        # (half-applied optimizer update, in-flight collective). should_save()
        # converts the flag into exactly one save at the next step boundary,
        # inside the spot-VM grace window.
        self._preempted = True
        self._preempt_signum = signum

    def request_preemption(self) -> None:
        """Programmatic SIGTERM equivalent (tests, external schedulers)."""
        self._preempted = True

    @property
    def preemption_requested(self) -> bool:
        """Whether ANY host caught a preemption signal (collective-agreeing:
        every host sees the same answer, so the save barrier cannot deadlock
        when the grace signal lands on a single worker)."""
        return PartialState().any_process(self._preempted)

    @property
    def exit_requested(self) -> bool:
        """True once the preemption-triggered boundary save has landed — the
        loop should exit cleanly (the supervisor restarts with auto-resume)."""
        return self._saved_on_preemption

    def restore_signal_handlers(self) -> None:
        for sig, handler in self._prev_handlers.items():
            try:
                signal.signal(sig, handler)
            except ValueError:
                pass
        self._prev_handlers.clear()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc_info) -> None:
        self.restore_signal_handlers()

    # -- save --------------------------------------------------------------

    def should_save(self, step: int) -> bool:
        """True at a periodic boundary OR when a preemption is pending (the
        latter exactly once — after the preemption save lands, further steps
        should not happen; see ``exit_requested``).

        The preemption check is collective on multi-host; it runs only on
        steps where ``step % preemption_sync_every == 0`` — a gate every host
        evaluates identically, so the allgather stays aligned across the
        fleet while big pods avoid paying it every step.
        """
        if (
            not self._saved_on_preemption
            and step % self.preemption_sync_every == 0
            and self.preemption_requested
        ):
            return True
        return (
            self.save_interval is not None
            and step > 0
            and step % self.save_interval == 0
        )

    def save_on_preemption(self, step: int, epoch: int = 0, metadata: Optional[dict] = None) -> bool:
        """Convenience for loops that handle preemption separately from
        periodic saves: performs the (single) boundary save if a preemption is
        pending, and returns True when the caller should exit cleanly."""
        if self.preemption_requested and not self._saved_on_preemption:
            self.save(step, epoch=epoch, metadata=metadata)
        return self.exit_requested

    def _dataloader_positions(self) -> list[dict]:
        positions = []
        for loader in getattr(self.accelerator, "_dataloaders", []):
            positions.append(
                {
                    "epoch": int(getattr(loader, "epoch", 0)),
                    "position": int(getattr(loader, "position", 0)),
                }
            )
        return positions

    def save(self, step: int, epoch: int = 0, metadata: Optional[dict] = None) -> str:
        """One atomic checkpoint: garbage-collect torn staging dirs, stage +
        commit ``checkpoint_<step>``, then rotate old checkpoints (strictly
        after the commit — the previous good checkpoint survives any kill
        during this call). Transient I/O errors back off and retry."""
        state = PartialState()
        if state.is_main_process:
            garbage_collect_torn(self.checkpoint_dir)
        target = os.path.join(self.checkpoint_dir, f"{CHECKPOINT_DIR_PREFIX}_{step}")
        meta = {
            "step": int(step),
            "epoch": int(epoch),
            "dataloaders": self._dataloader_positions(),
        }
        if metadata:
            meta.update(metadata)
        # Whole-call retry only when single-process: save_state is a barrier
        # sequence, and re-entering it on ONE host while the others wait at a
        # later barrier would deadlock the fleet. Multi-host runs still get
        # the per-operation retries inside the commit protocol
        # (write_manifest / commit_checkpoint).
        save = self.accelerator.save_state
        if state.num_processes == 1:
            save = self.retry_policy.wrap(save)
        with self._telemetry_pause("checkpoint_save"):
            save(target, sharded=self.sharded, manifest_metadata=meta)
        # collective check, not the host-local flag: the signal landed on one
        # host, but EVERY host must flip exit_requested or the others keep
        # looping into a deadlocked barrier
        if self.preemption_requested:
            self._saved_on_preemption = True
            logger.info(
                f"Preemption save committed at step {step} → {target}; exit when convenient."
            )
        self._rotate(keep=target)
        return target

    def _rotate(self, keep: str) -> None:
        if self.total_limit is None:
            return
        state = PartialState()
        if state.is_main_process:
            existing = list_checkpoints(self.checkpoint_dir)
            doomed = [p for p in existing if p != keep]
            for stale in doomed[: max(len(existing) - self.total_limit, 0)]:
                logger.info(f"Rotating out {stale} (total_limit={self.total_limit})")
                shutil.rmtree(stale, ignore_errors=True)
        state.wait_for_everyone()

    # -- resume ------------------------------------------------------------

    def latest_valid(self) -> Optional[str]:
        """Newest checkpoint whose manifest validates (torn/.tmp dirs skipped)."""
        return latest_valid_checkpoint(self.checkpoint_dir, check_checksums=self.check_checksums)

    def resume(self, resume_from_checkpoint: "str | None" = "auto") -> Optional[ResumePoint]:
        """Restore the run: ``"auto"`` loads the newest valid checkpoint (None
        if there is none — a fresh run), a path loads that checkpoint after
        validating it. Restores model/optimizer/scheduler/RNG via
        ``load_state`` and returns the positions for dataloader rewind."""
        if resume_from_checkpoint in (None, False):
            return None
        state = PartialState()
        if resume_from_checkpoint == "auto":
            # ONE fleet-wide decision: process 0 scans + validates and its
            # answer binds every host. Independent per-host scans could
            # diverge (per-host bit-rot, filesystem propagation lag) and a
            # host resuming while another starts fresh deadlocks load_state's
            # barrier. This makes resume() a collective — call it on every
            # host, like save().
            path = self.latest_valid() if state.is_main_process else None
            if state.num_processes > 1:
                from .ops.operations import broadcast_object_list

                path = broadcast_object_list([path])[0]
            if path is None:
                logger.info(f"No valid checkpoint under {self.checkpoint_dir}; starting fresh.")
                return None
        else:
            path = resume_from_checkpoint
            problems = verify_checkpoint(path, check_checksums=self.check_checksums)
            if problems:
                raise ValueError(
                    f"Refusing to resume from {path}: {'; '.join(problems[:5])}"
                )
        # same single-process-only whole-call retry rationale as save()
        load = self.accelerator.load_state
        if PartialState().num_processes == 1:
            load = self.retry_policy.wrap(load)
        with self._telemetry_pause("checkpoint_restore"):
            load(path)
        telemetry = getattr(self.accelerator, "telemetry", None)
        if telemetry is not None:
            # a restore on THIS process means the run restarted (or was
            # explicitly rewound) — either way the goodput ledger records it
            telemetry.goodput.mark_restart()
        manifest = read_manifest(path) or {}
        meta = manifest.get("metadata", {})
        point = ResumePoint(
            path=path,
            step=checkpoint_step(path, manifest),
            epoch=int(meta.get("epoch", 0)),
            dataloaders=meta.get("dataloaders", []),
            metadata=meta,
        )
        logger.info(f"Resumed from {path} (step {point.step}, epoch {point.epoch})")
        return point

    def resumed_loader(self, loader, resume: Optional[ResumePoint], epoch: int, index: int = 0):
        """The loader to iterate for ``epoch`` after a resume: mid-epoch, the
        first ``position`` batches are skipped (``set_epoch`` + the seedable
        sampler make the underlying permutation identical, so the next batch
        is bit-exact the one the dead run would have consumed); any other
        epoch iterates the loader unchanged. Call it every epoch (as the
        canonical loop does) — that also keeps the manager's position
        tracking pointed at the loader actually being iterated."""
        loaders = getattr(self.accelerator, "_dataloaders", None)
        # Undo a previous epoch's swap: once the resumed epoch is over, saves
        # must record the LIVE loader's epoch/position, not the stale wrapper.
        prev = self._swapped_loaders.pop(id(loader), None)
        if prev is not None and loaders is not None and prev in loaders:
            loaders[loaders.index(prev)] = loader
        if resume is None or index >= len(resume.dataloaders):
            return loader
        info = resume.dataloaders[index]
        if int(info.get("epoch", 0)) != epoch:
            return loader
        position = int(info.get("position", 0))
        if position == 0:
            return loader
        from .data_loader import skip_first_batches

        if hasattr(loader, "set_epoch"):
            loader.set_epoch(epoch)
        skipped = skip_first_batches(loader, position)
        skipped._skip_offset = position  # later saves record the absolute position
        skipped.epoch = epoch
        # keep position tracking live for saves during the resumed epoch
        if loaders is not None and loader in loaders:
            loaders[loaders.index(loader)] = skipped
            self._swapped_loaders[id(loader)] = skipped
        return skipped
