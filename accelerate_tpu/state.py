"""Process/topology/mesh state singletons.

Parity: reference state.py — PartialState (111), AcceleratorState (808),
GradientState (1085). The reference's PartialState must pick among nine
communication backends and bind one device per OS process; here there is
exactly one backend (the JAX runtime) and one process per *host* driving all
of that host's TPU chips. The "distributed environment" is therefore:

    control plane:  jax.distributed (coordination service, one proc/host)
    data plane:     a jax.sharding.Mesh over every device in the job; all
                    collectives are emitted by XLA from sharding annotations

The Borg pattern (shared ``_shared_state`` dict) is kept so every component
sees one consistent topology without plumbing.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from functools import wraps
from typing import Any, Callable, Iterable, Optional

import numpy as np

import jax

from .analysis.concurrency import named_lock
from .logging import get_logger
from .utils.constants import CANONICAL_MESH_AXES, MESH_AXIS_DATA
from .utils.dataclasses import (
    DistributedType,
    GradientAccumulationPlugin,
    MixedPrecisionPolicy,
    ParallelismConfig,
    PrecisionType,
)
from .utils.environment import get_multihost_env, parse_flag_from_env

logger = get_logger(__name__)


def is_initialized() -> bool:
    """Whether AcceleratorState has been constructed (reference state.py:66)."""
    return AcceleratorState._shared_state != {}


def _init_timeout_kwargs() -> dict[str, int]:
    """ACCELERATE_INIT_TIMEOUT → jax.distributed.initialize kwargs (if set)."""
    timeout = os.environ.get("ACCELERATE_INIT_TIMEOUT")
    return {"initialization_timeout": int(timeout)} if timeout else {}


def distributed_is_initialized() -> bool:
    """Whether the jax.distributed rendezvous already ran (version-portable:
    ``jax.distributed.is_initialized`` only exists on newer jax)."""
    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    from jax._src import distributed as _distributed

    return _distributed.global_state.client is not None


class PartialState:
    """Topology bootstrap singleton.

    Responsibilities (mapping reference state.py:111-805):
    - multi-host rendezvous: ``jax.distributed.initialize`` when env coordinates
      are present (replaces init_process_group / xm.set_replication).
    - expose process_index / num_processes / local device list.
    - build the global device Mesh from a ParallelismConfig.
    - process-control helpers: wait_for_everyone, split_between_processes,
      main_process_first, on_main_process/on_last_process/on_process decorators.
    """

    _shared_state: dict[str, Any] = {}
    _mutex = named_lock("state.singleton")

    def __init__(self, parallelism: Optional[ParallelismConfig] = None, **kwargs: Any) -> None:
        with PartialState._mutex:
            self.__dict__ = PartialState._shared_state
            if self.initialized:
                if parallelism is not None and parallelism != self.parallelism:
                    raise ValueError(
                        "PartialState is already initialized with a different ParallelismConfig; "
                        "call PartialState._reset_state() first (tests) or construct it once."
                    )
                return
            self._bootstrap_distributed(**kwargs)
            self.debug = parse_flag_from_env("ACCELERATE_DEBUG_MODE")
            self.parallelism = parallelism or ParallelismConfig.from_env()
            self._build_mesh()

    # -- bootstrap ---------------------------------------------------------

    def _bootstrap_distributed(self, **kwargs: Any) -> None:
        env = get_multihost_env()
        coordinator = kwargs.get("coordinator_address", env["coordinator_address"])
        num_processes = kwargs.get("num_processes", env["num_processes"])
        process_id = kwargs.get("process_id", env["process_id"])
        if coordinator and (num_processes or 0) > 1:
            # PROCESS BOUNDARY: every host blocks here until the whole job
            # has rendezvoused with the coordinator (replaces the reference's
            # MASTER_ADDR/MASTER_PORT TCPStore rendezvous, state.py:213).
            # Probing jax.process_count() first would initialize the local
            # backend and defeat distributed init, so ask the distributed
            # module itself whether it is live.
            if not distributed_is_initialized():
                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=num_processes,
                    process_id=process_id,
                    **_init_timeout_kwargs(),
                )
        elif parse_flag_from_env("ACCELERATE_IN_TPU_POD"):
            # pod-launch path: no explicit coordinator — every worker runs the
            # identical command and jax self-discovers coordinator/process_id/
            # process count from the TPU pod metadata (argless initialize)
            if not distributed_is_initialized():
                jax.distributed.initialize(**_init_timeout_kwargs())
        self.backend = "xla"
        self.device = jax.local_devices()[0]
        self.initialized = True

    def _build_mesh(self) -> None:
        devices = jax.devices()
        axis_sizes = self.parallelism.axis_sizes(len(devices))
        shape = tuple(axis_sizes[a] for a in CANONICAL_MESH_AXES)
        # mesh_utils lays devices out to keep inner axes on the fastest ICI links.
        try:
            from jax.experimental import mesh_utils

            device_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except Exception:  # CPU meshes / odd shapes: plain reshape is fine
            device_array = np.asarray(devices).reshape(shape)
        self.mesh = jax.sharding.Mesh(device_array, CANONICAL_MESH_AXES)

    def rebuild_mesh(
        self,
        devices: Optional[list] = None,
        parallelism: Optional[ParallelismConfig] = None,
    ) -> jax.sharding.Mesh:
        """Rebuild the global mesh over an explicit device set — the elastic
        shrink/regrow seam (resilience/elastic.py). ``devices`` defaults to
        every device (a pure re-layout); a subset builds the survivor mesh
        after a host loss. The new ``parallelism`` must exactly cover the
        device count (``axis_sizes`` validates). Arrays placed on the old
        mesh stay valid — callers reshard state explicitly; this only swaps
        what NEW placements (``data_sharding``, ``infer_shardings``) see.
        """
        if parallelism is not None:
            self.parallelism = parallelism
        if devices is None:
            self._build_mesh()
            return self.mesh
        axis_sizes = self.parallelism.axis_sizes(len(devices))
        shape = tuple(axis_sizes[a] for a in CANONICAL_MESH_AXES)
        self.mesh = jax.sharding.Mesh(
            np.asarray(devices, dtype=object).reshape(shape), CANONICAL_MESH_AXES
        )
        return self.mesh

    def rejoin(
        self,
        devices: Optional[list] = None,
        parallelism: Optional[ParallelismConfig] = None,
    ) -> jax.sharding.Mesh:
        """The elastic re-rendezvous seam (resilience/membership.py): rebuild
        the topology over the CURRENT member set after a membership
        transition — a shrink onto the survivors, or a regrow re-admitting a
        revived host picked up from its join record.

        Under the single controller (every tier-1 drill) the device set is
        still owned by this process, so rejoin is a pure
        :meth:`rebuild_mesh` — the simulation boundary, stated honestly.

        On a real multi-controller pod the surviving *processes* must
        re-rendezvous before any in-process reshard can run: every survivor
        tears down and re-initializes ``jax.distributed`` over the new
        member set at the same step boundary (the membership epoch is the
        agreement on WHO). That call is env-gated behind
        ``ACCELERATE_ELASTIC_REAL_REJOIN=1`` because on 0.4.37-era runtimes
        a shutdown+initialize cycle is only supported on real TPU backends
        — the CPU simulation must never attempt it — and it carries a
        CONTRACT: the launcher/supervisor must refresh the coordinate env
        vars (``get_multihost_env``: coordinator address, num_processes,
        process_id) to the SURVIVOR set before the boundary, because the
        original values still count the dead host and an argless
        re-initialize would barrier-wait on a process that will never
        arrive. Explicit env coordinates are passed through when present;
        validating this path on hardware is the ROADMAP's multi-slice
        remainder. See docs/resilience.md § Failure detection & membership.
        """
        if parse_flag_from_env("ACCELERATE_ELASTIC_REAL_REJOIN"):
            kwargs: dict[str, Any] = dict(_init_timeout_kwargs())
            env = get_multihost_env()
            if env["coordinator_address"] and env["num_processes"]:
                # launcher-refreshed survivor coordinates (see contract
                # above); without them jax re-reads the pod metadata
                kwargs.update(
                    coordinator_address=env["coordinator_address"],
                    num_processes=env["num_processes"],
                    process_id=env["process_id"],
                )
            jax.distributed.shutdown()
            jax.distributed.initialize(**kwargs)
        return self.rebuild_mesh(devices=devices, parallelism=parallelism)

    # -- topology properties ----------------------------------------------

    @property
    def initialized(self) -> bool:
        return self._shared_state.get("_ready", False)

    @initialized.setter
    def initialized(self, value: bool) -> None:
        self._shared_state["_ready"] = value

    @property
    def num_processes(self) -> int:
        return jax.process_count()

    @property
    def process_index(self) -> int:
        return jax.process_index()

    @property
    def local_process_index(self) -> int:
        # One process per host: the local index is always 0. Kept for API parity.
        return 0

    @property
    def num_devices(self) -> int:
        return jax.device_count()

    @property
    def local_devices(self):
        return jax.local_devices()

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0

    @property
    def is_local_main_process(self) -> bool:
        return True  # one process per host

    @property
    def is_last_process(self) -> bool:
        return self.process_index == self.num_processes - 1

    @property
    def use_distributed(self) -> bool:
        return self.num_devices > 1

    @property
    def distributed_type(self) -> DistributedType:
        if self.num_devices == 1:
            return DistributedType.NO
        return self.parallelism.distributed_type

    def data_sharding(self, extra_batch_axes: tuple[str, ...] = ()) -> jax.sharding.NamedSharding:
        """Sharding for a batch: leading dim split over data-like axes."""
        from jax.sharding import NamedSharding, PartitionSpec

        batch_axes = (MESH_AXIS_DATA, "fsdp") + extra_batch_axes
        present = tuple(a for a in batch_axes if a in self.mesh.shape)
        return NamedSharding(self.mesh, PartitionSpec(present))

    # -- process control ---------------------------------------------------

    def wait_for_everyone(self) -> None:
        """Block until all hosts reach this point (reference state.py:348)."""
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("accelerate_tpu.wait_for_everyone")

    def any_process(self, flag: bool) -> bool:
        """Logical OR of a host-local flag across all processes.

        The preemption-agreement primitive (fault_tolerance.py): a spot-VM
        SIGTERM lands on ONE host's grace window, but every host must decide
        to checkpoint at the same step boundary — otherwise the save's
        collective barrier deadlocks. This is a collective: either all hosts
        call it at the same point, or none do.
        """
        if self.num_processes <= 1:
            return bool(flag)
        from jax.experimental import multihost_utils

        votes = multihost_utils.process_allgather(np.asarray([1 if flag else 0], np.int32))
        return bool(np.asarray(votes).sum() > 0)

    def aggregate_metrics(self, metrics: "dict[str, Any]") -> "dict[str, dict[str, float]]":
        """min/max/mean of each numeric metric across hosts.

        The telemetry flush primitive: per-host scalars (step time, HBM
        watermark, goodput) become fleet-wide spreads — a straggler shows up
        as max ≫ mean, a leaking host as an HBM max outlier. COLLECTIVE when
        ``num_processes > 1`` (one ``gather_object`` round): every host must
        call it at the same point. Non-numeric entries are dropped; hosts may
        carry different key sets (union semantics, like missing samples).
        """
        numeric = {
            k: float(v)
            for k, v in metrics.items()
            if isinstance(v, (int, float, np.integer, np.floating)) and not isinstance(v, bool)
        }
        if self.num_processes == 1:
            return {k: {"min": v, "max": v, "mean": v} for k, v in numeric.items()}
        from .ops.operations import gather_object

        rows = gather_object([numeric])
        keys = sorted({k for row in rows for k in row})
        out = {}
        for key in keys:
            values = [row[key] for row in rows if key in row]
            out[key] = {
                "min": min(values),
                "max": max(values),
                "mean": sum(values) / len(values),
            }
        return out

    @contextmanager
    def main_process_first(self):
        """Main host runs the body first, the rest afterwards (state.py:484)."""
        if not self.is_main_process:
            self.wait_for_everyone()
        yield
        if self.is_main_process:
            self.wait_for_everyone()

    @contextmanager
    def split_between_processes(self, inputs, apply_padding: bool = False):
        """Yield this host's slice of ``inputs`` (reference state.py:393-481).

        Supports lists/tuples/dicts-of-lists and numpy/jax arrays. With
        ``apply_padding`` the last host's share is padded (repeating the final
        element) so every host yields equally many items — required when the
        results feed a collective.
        """
        if self.num_processes == 1:
            yield inputs
            return
        length = len(inputs) if not isinstance(inputs, dict) else len(next(iter(inputs.values())))
        base, extra = divmod(length, self.num_processes)
        sizes = [base + (1 if p < extra else 0) for p in range(self.num_processes)]
        start = sum(sizes[: self.process_index])
        end = start + sizes[self.process_index]

        def _slice(seq):
            piece = seq[start:end]
            if apply_padding and len(piece) < max(sizes) and len(seq):
                pad_count = max(sizes) - len(piece)
                if isinstance(piece, (np.ndarray, jax.Array)):
                    xp = jax.numpy if isinstance(piece, jax.Array) else np
                    tail = xp.repeat(seq[-1:], pad_count, axis=0)
                    piece = xp.concatenate([piece, tail])
                elif isinstance(piece, tuple):
                    piece = piece + (seq[-1],) * pad_count
                else:
                    piece = list(piece) + [seq[-1]] * pad_count
            return piece

        if isinstance(inputs, dict):
            yield {k: _slice(v) for k, v in inputs.items()}
        else:
            yield _slice(inputs)

    def on_main_process(self, function: Callable) -> Callable:
        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_main_process:
                return function(*args, **kwargs)

        return wrapper

    def on_last_process(self, function: Callable) -> Callable:
        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_last_process:
                return function(*args, **kwargs)

        return wrapper

    def on_process(self, function: Callable | None = None, process_index: int = 0) -> Callable:
        def decorator(fn):
            @wraps(fn)
            def wrapper(*args, **kwargs):
                if self.process_index == process_index:
                    return fn(*args, **kwargs)

            return wrapper

        return decorator(function) if function is not None else decorator

    def print(self, *args, **kwargs) -> None:
        if self.is_main_process:
            print(*args, **kwargs)

    def __repr__(self) -> str:
        return (
            f"PartialState(num_processes={self.num_processes}, process_index={self.process_index}, "
            f"num_devices={self.num_devices}, mesh={dict(self.mesh.shape)}, "
            f"distributed_type={self.distributed_type})"
        )

    @classmethod
    def _reset_state(cls) -> None:
        """Test hygiene: drop the Borg dict (reference testing.py:419-431)."""
        cls._shared_state.clear()


class AcceleratorState:
    """PartialState + precision/plugin state (reference state.py:808).

    Shares the PartialState dict for topology and layers mixed-precision policy
    and the active plugins on top.
    """

    _shared_state: dict[str, Any] = {}

    def __init__(
        self,
        mixed_precision: str | None = None,
        parallelism: Optional[ParallelismConfig] = None,
        **kwargs: Any,
    ) -> None:
        self.__dict__ = AcceleratorState._shared_state
        self._partial = PartialState(parallelism=parallelism, **kwargs)
        if not getattr(self, "_as_ready", False):
            if mixed_precision is None:
                mixed_precision = os.environ.get("ACCELERATE_MIXED_PRECISION", "no")
            self.precision_policy = MixedPrecisionPolicy(PrecisionType(mixed_precision))
            self._as_ready = True
        elif mixed_precision is not None and mixed_precision != self.mixed_precision:
            raise ValueError(
                f"AcceleratorState is already initialized with mixed_precision="
                f"{self.mixed_precision!r}; got conflicting {mixed_precision!r}. "
                "Call AcceleratorState._reset_state() first (tests) or construct it once."
            )

    # Topology is delegated so there is a single source of truth.
    def __getattr__(self, name: str):
        partial = self.__dict__.get("_partial")
        if partial is not None and hasattr(partial, name):
            return getattr(partial, name)
        raise AttributeError(name)

    @property
    def mixed_precision(self) -> str:
        return self.precision_policy.mixed_precision.value

    def __repr__(self) -> str:
        return f"{self._partial!r} mixed_precision={self.mixed_precision}"

    @classmethod
    def _reset_state(cls, reset_partial_state: bool = True) -> None:
        cls._shared_state.clear()
        if reset_partial_state:
            PartialState._reset_state()


class GradientState:
    """Gradient-accumulation bookkeeping singleton (reference state.py:1085).

    Tracks whether this step's gradients should be applied (``sync_gradients``)
    and which prepared dataloaders are active so the final partial accumulation
    window at end-of-epoch still steps (``sync_with_dataloader``).
    """

    _shared_state: dict[str, Any] = {}

    def __init__(self, gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None):
        self.__dict__ = GradientState._shared_state
        if not self.initialized:
            self.sync_gradients = True
            self.active_dataloader = None
            self.dataloader_references: list = [None]
            self.plugin_kwargs = {}
            self._step = 0
        if gradient_accumulation_plugin is not None:
            self.plugin_kwargs = gradient_accumulation_plugin.to_kwargs()

    @property
    def initialized(self) -> bool:
        return GradientState._shared_state != {}

    @property
    def num_steps(self) -> int:
        return self.plugin_kwargs.get("num_steps", 1)

    @property
    def adjust_scheduler(self) -> bool:
        return self.plugin_kwargs.get("adjust_scheduler", True)

    @property
    def sync_with_dataloader(self) -> bool:
        return self.plugin_kwargs.get("sync_with_dataloader", True)

    @property
    def sync_each_batch(self) -> bool:
        return self.plugin_kwargs.get("sync_each_batch", False)

    @property
    def end_of_dataloader(self) -> bool:
        if not self.in_dataloader:
            return False
        return self.active_dataloader.end_of_dataloader

    @property
    def remainder(self) -> int:
        if not self.in_dataloader:
            return -1
        return self.active_dataloader.remainder

    @property
    def in_dataloader(self) -> bool:
        return self.active_dataloader is not None

    def _add_dataloader(self, dataloader) -> None:
        self.active_dataloader = dataloader
        self.dataloader_references.append(dataloader)

    def _remove_dataloader(self, dataloader) -> None:
        if dataloader in self.dataloader_references:
            self.dataloader_references.remove(dataloader)
        self.active_dataloader = self.dataloader_references[-1]

    def _set_sync_gradients(self, value: bool) -> None:
        self.sync_gradients = value

    def __repr__(self) -> str:
        return (
            f"GradientState(sync_gradients={self.sync_gradients}, num_steps={self.num_steps}, "
            f"end_of_dataloader={self.end_of_dataloader}, remainder={self.remainder})"
        )

    @classmethod
    def _reset_state(cls) -> None:
        cls._shared_state.clear()
