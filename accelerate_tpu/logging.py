"""Multi-process-aware logging.

Parity: reference logging.py (MultiProcessAdapter:38, get_logger:83,
warning_once:71, level from env:117).
"""

from __future__ import annotations

import functools
import logging
import os


class MultiProcessAdapter(logging.LoggerAdapter):
    """Logs only on the main process unless ``main_process_only=False``.

    Every record is stamped with ``process_index``/``local_process_index``
    so multi-host telemetry logs stay attributable once they are interleaved
    in a shared sink (format with ``%(process_index)s`` to surface them).
    ``in_order=True`` emits from each process in process-index order (each host
    waits for the ones before it) — useful for debugging per-host state.
    """

    @staticmethod
    def _should_log(main_process_only: bool) -> bool:
        from .state import PartialState

        return not main_process_only or PartialState().is_main_process

    def process(self, msg, kwargs):
        extra = kwargs.setdefault("extra", {})
        try:
            from .state import PartialState

            state = PartialState()
            extra.setdefault("process_index", state.process_index)
            extra.setdefault("local_process_index", state.local_process_index)
        except Exception:
            # logging must work even before/without topology bootstrap
            extra.setdefault("process_index", 0)
            extra.setdefault("local_process_index", 0)
        return msg, kwargs

    def log(self, level, msg, *args, **kwargs):
        from .state import PartialState

        main_process_only = kwargs.pop("main_process_only", True)
        in_order = kwargs.pop("in_order", False)
        if not self.isEnabledFor(level):
            return
        if in_order:
            # Every process participates in the same barrier sequence
            # (otherwise hosts would deadlock on mismatched collective counts),
            # logging only on its turn. in_order implies all processes log.
            state = PartialState()
            for i in range(state.num_processes):
                if i == state.process_index:
                    pmsg, pkwargs = self.process(msg, kwargs)
                    self.logger.log(level, pmsg, *args, **pkwargs)
                state.wait_for_everyone()
        elif self._should_log(main_process_only):
            msg, kwargs = self.process(msg, kwargs)
            self.logger.log(level, msg, *args, **kwargs)

    @functools.lru_cache(None)
    def warning_once(self, *args, **kwargs):
        self.warning(*args, **kwargs)


def get_logger(name: str, log_level: str | None = None) -> MultiProcessAdapter:
    if log_level is None:
        log_level = os.environ.get("ACCELERATE_LOG_LEVEL", None)
    logger = logging.getLogger(name)
    if log_level is not None:
        logger.setLevel(log_level.upper())
        logger.root.setLevel(log_level.upper())
    return MultiProcessAdapter(logger, {})
