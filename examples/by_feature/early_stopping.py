"""Feature example: coordinated early stopping with set_trigger/check_trigger
(reference examples/by_feature/early_stopping.py, accelerator.py:2037-2094).

Any process may decide to stop (here: loss under a threshold); the decision
is all-reduced as a flag tensor so every process breaks on the same step —
a conditional Python ``break`` alone would desynchronize the collectives.

Run:
    python examples/by_feature/early_stopping.py --threshold 0.5
"""

from __future__ import annotations

import argparse
import os
import sys

import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import PairClassificationDataset

from accelerate_tpu import Accelerator
from accelerate_tpu.models import Bert
from accelerate_tpu.utils import set_seed


def main(argv=None):
    parser = argparse.ArgumentParser(description="Early-stopping example.")
    parser.add_argument("--num_epochs", type=int, default=10)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--threshold", type=float, default=0.5)
    args = parser.parse_args(argv)

    accelerator = Accelerator()
    set_seed(42)
    bert = Bert("bert-tiny")
    dataset = PairClassificationDataset(vocab_size=bert.config.vocab_size, max_len=64)
    model, optimizer, loader = accelerator.prepare(
        bert,
        optax.adamw(args.lr),
        accelerator.prepare_data_loader(dataset, batch_size=args.batch_size, shuffle=True, seed=42),
    )
    loss_fn = Bert.loss_fn(bert)

    stopped = False
    for epoch in range(args.num_epochs):
        loader.set_epoch(epoch)
        for batch in loader:
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
            if float(loss) < args.threshold:
                accelerator.set_trigger()  # this process votes to stop
            if accelerator.check_trigger():  # all-reduced: everyone agrees
                stopped = True
                break
        accelerator.print(f"epoch {epoch}: loss={float(loss):.4f} stopped={stopped}")
        if stopped:
            break
    accelerator.print(f"early stopping {'engaged' if stopped else 'never triggered'}")
    accelerator.end_training()


if __name__ == "__main__":
    main()
