"""Feature example: LocalSGD — independent per-worker updates with periodic
parameter averaging (reference examples/by_feature/local_sgd.py).

Each data-parallel worker trains its own replica; every
``--local_sgd_steps`` steps the replicas are averaged. Communication drops
from one gradient all-reduce per step to one parameter average per window.

Run:
    python examples/by_feature/local_sgd.py --local_sgd_steps 4
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import optax

import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import PairClassificationDataset, accuracy_f1

from accelerate_tpu import Accelerator, LocalSGD
from accelerate_tpu.models import Bert
from accelerate_tpu.utils import set_seed


def main(argv=None):
    parser = argparse.ArgumentParser(description="LocalSGD example.")
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--local_sgd_steps", type=int, default=4)
    args = parser.parse_args(argv)

    accelerator = Accelerator()
    set_seed(42)

    bert = Bert("bert-tiny")
    dataset = PairClassificationDataset(vocab_size=bert.config.vocab_size, max_len=64)
    model = accelerator.prepare_model(bert)
    train_loader = accelerator.prepare_data_loader(dataset, batch_size=args.batch_size, shuffle=True, seed=42)
    loss_fn = Bert.loss_fn(bert)

    with LocalSGD(accelerator, model, optax.adamw(args.lr), local_sgd_steps=args.local_sgd_steps) as lsgd:
        for epoch in range(args.num_epochs):
            train_loader.set_epoch(epoch)
            for batch in train_loader:
                loss = lsgd.step(loss_fn, batch)
            accelerator.print(f"epoch {epoch}: loss={float(loss):.4f}")
    # on context exit the averaged replica is written back to model.params

    predictions, references = [], []
    for batch in train_loader:
        logits = bert.apply(model.params, batch["input_ids"], batch["attention_mask"], batch["token_type_ids"])
        preds, refs = accelerator.gather_for_metrics((jnp.argmax(logits, -1), batch["labels"]))
        predictions.append(np.asarray(preds))
        references.append(np.asarray(refs))
    metric = accuracy_f1(np.concatenate(predictions), np.concatenate(references))
    accelerator.print(f"final: {metric}")
    accelerator.end_training()


if __name__ == "__main__":
    main()
