"""Feature example: Schedule-Free optimization (reference
examples/by_feature/schedule_free.py) — optax's schedule-free AdamW needs no
LR schedule at all; evaluation uses the averaged iterate.

Run:
    python examples/by_feature/schedule_free.py --num_epochs 2
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import optax

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import PairClassificationDataset, accuracy_f1, train_eval_split

from accelerate_tpu import Accelerator
from accelerate_tpu.models import Bert
from accelerate_tpu.utils import set_seed


def main(argv=None):
    parser = argparse.ArgumentParser(description="Schedule-free optimizer example.")
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--warmup_steps", type=int, default=2)
    args = parser.parse_args(argv)

    accelerator = Accelerator()
    set_seed(42)
    bert = Bert("bert-tiny")
    dataset = PairClassificationDataset(vocab_size=bert.config.vocab_size, max_len=64)
    train_set, eval_set = train_eval_split(dataset)

    tx = optax.contrib.schedule_free_adamw(learning_rate=args.lr, warmup_steps=args.warmup_steps)
    model, optimizer, train_loader = accelerator.prepare(
        bert,
        tx,
        accelerator.prepare_data_loader(train_set, batch_size=args.batch_size, shuffle=True, seed=42),
    )
    eval_loader = accelerator.prepare_data_loader(eval_set, batch_size=16)
    loss_fn = Bert.loss_fn(bert)

    for epoch in range(args.num_epochs):
        train_loader.set_epoch(epoch)
        for batch in train_loader:
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()

        # schedule-free evaluates at the AVERAGED iterate, not the raw params
        eval_params = optax.contrib.schedule_free_eval_params(optimizer.opt_state, model.params)
        predictions, references = [], []
        for batch in eval_loader:
            logits = bert.apply(eval_params, batch["input_ids"], batch["attention_mask"], batch["token_type_ids"])
            preds, refs = accelerator.gather_for_metrics((jnp.argmax(logits, -1), batch["labels"]))
            predictions.append(np.asarray(preds))
            references.append(np.asarray(refs))
        metric = accuracy_f1(np.concatenate(predictions), np.concatenate(references))
        accelerator.print(f"epoch {epoch}: loss={float(loss):.4f} {metric}")

    accelerator.end_training()


if __name__ == "__main__":
    main()
