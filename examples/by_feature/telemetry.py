"""Feature example: the telemetry subsystem end to end.

Trains bert-tiny with the Telemetry hub wired in — async-dispatch-correct
step timing (fences only every ``--sample_every`` steps), compile-event
capture, memory watermarks, tokens/sec + MFU, and goodput accounting across
a simulated preemption (SIGTERM-equivalent boundary save, then auto-resume
in a fresh Accelerator, exactly what a relaunched worker does). Produces a
machine-readable ``telemetry.jsonl`` next to the checkpoints.

Run:
    python examples/by_feature/telemetry.py --project_dir /tmp/telemetry_demo

See docs/observability.md for the metrics glossary and jsonl schema.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import optax

import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import PairClassificationDataset

from accelerate_tpu import Accelerator, TelemetryConfig
from accelerate_tpu.models import Bert
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils import set_seed


def build(args):
    accelerator = Accelerator(
        telemetry_config=TelemetryConfig(
            sample_every=args.sample_every, dir=args.project_dir
        )
    )
    set_seed(42)
    model = Bert("bert-tiny")
    dataset = PairClassificationDataset(vocab_size=model.config.vocab_size, max_len=64)
    prepared, optimizer, loader = accelerator.prepare(
        model,
        optax.adamw(1e-3),
        accelerator.prepare_data_loader(
            dataset, batch_size=args.batch_size, shuffle=True, seed=42
        ),
    )
    step = accelerator.compiled_step(Bert.loss_fn(model))
    accelerator.telemetry.configure_throughput(
        model.config,
        batch_size=args.batch_size,
        seq_len=64,
        # CPU has no meaningful hardware peak; a nominal 1 TFLOP/s keeps the
        # MFU field populated for the demo (on TPU, omit this — the real
        # chip peak is looked up automatically)
        peak_flops_per_device=None if accelerator.device.platform == "tpu" else 1e12,
    )
    manager = accelerator.checkpoint_manager(
        os.path.join(args.project_dir, "checkpoints"), handle_signals=()
    )
    return accelerator, loader, step, manager


def train(accelerator, loader, step, manager, steps, start_step, preempt_at=None):
    telemetry = accelerator.telemetry
    n = start_step
    for epoch in range(1000):  # the step budget, not the dataset, ends the run
        loader.set_epoch(epoch)
        for batch in loader:
            loss = step(batch)
            telemetry.step(loss)
            n += 1
            if preempt_at is not None and n == preempt_at:
                manager.request_preemption()  # what the SIGTERM handler does
            if manager.should_save(n):
                manager.save(n)
            if manager.exit_requested or n >= start_step + steps:
                return n
    return n


def main(argv=None):
    parser = argparse.ArgumentParser(description="Telemetry subsystem demo.")
    parser.add_argument("--project_dir", type=str, required=True)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_steps", type=int, default=24)
    parser.add_argument("--sample_every", type=int, default=4)
    args = parser.parse_args(argv)
    os.makedirs(args.project_dir, exist_ok=True)

    # phase 1: train until a simulated spot-VM preemption lands mid-run
    accelerator, loader, step, manager = build(args)
    preempt_at = args.num_steps // 2
    n = train(accelerator, loader, step, manager, args.num_steps, 0, preempt_at=preempt_at)
    assert manager.exit_requested, "preemption save should have landed"
    accelerator.print(f"preempted at step {n}; state saved, 'process' exits")

    # phase 2: the relaunched process — fresh state, auto-resume, finish the run
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    accelerator, loader, step, manager = build(args)
    resume = manager.resume("auto")
    assert resume is not None and resume.step == n, (resume, n)
    n = train(accelerator, loader, step, manager, args.num_steps - n, n)
    accelerator.telemetry.finish()  # final flush → telemetry.jsonl

    sink = os.path.join(args.project_dir, "telemetry.jsonl")
    record = [json.loads(line) for line in open(sink)][-1]
    metrics = record["metrics"]
    accelerator.print(
        "telemetry: "
        f"p50 {metrics.get('step_time_p50_ms', float('nan')):.2f} ms/step, "
        f"{metrics.get('tokens_per_sec', 0):.0f} tokens/sec, "
        f"MFU {metrics.get('mfu', 0):.4f}, "
        f"{metrics['compile_count']} compiles ({metrics['compile_seconds']:.1f}s), "
        f"goodput {metrics['goodput']:.3f} after {record['goodput']['restarts']} restart"
    )
    accelerator.print(f"Telemetry demo complete: {sink}")


if __name__ == "__main__":
    main()
