"""Feature example: the program analyzer end to end.

Audits a bert-tiny fused step program (donation aliasing, collective
inventory, fp64/constant scan), then demonstrates the warm-loop hazard
sanitizer catching the two classic steady-state killers — a hidden
``float(loss)`` host sync and a shape-change recompile, with
``explain_recompile`` naming exactly the batch leaf that retraced.
Everything also lands as ``{"kind": "analysis"}`` / ``{"kind": "compile"}``
records in ``telemetry.jsonl``.

Run:
    python examples/by_feature/analysis.py --project_dir /tmp/analysis_demo

See docs/analysis.md for the findings catalog.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import optax

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu import Accelerator, HazardSanitizer, TelemetryConfig
from accelerate_tpu.models import Bert
from accelerate_tpu.utils import set_seed


def make_batch(model, batch_size, seq_len, sharding, seed=0):
    rng = np.random.default_rng(seed)
    batch = {
        "input_ids": jnp.asarray(
            rng.integers(0, model.config.vocab_size, (batch_size, seq_len)), jnp.int32
        ),
        "attention_mask": jnp.ones((batch_size, seq_len), jnp.int32),
        "labels": jnp.asarray(rng.integers(0, 2, (batch_size,)), jnp.int32),
    }
    return {k: jax.device_put(v, sharding) for k, v in batch.items()}


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--project_dir", default="/tmp/analysis_demo")
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--seq_len", type=int, default=16)
    args = parser.parse_args()

    accelerator = Accelerator(telemetry_config=TelemetryConfig(dir=args.project_dir))
    set_seed(42)
    model = Bert("bert-tiny")
    accelerator.prepare_model(model)
    accelerator.prepare_optimizer(optax.adamw(1e-3))
    sharding = accelerator.state.data_sharding()
    batch = make_batch(model, args.batch_size, args.seq_len, sharding)

    # 1. the compiled-program audit: what XLA actually built
    step = accelerator.compiled_step(Bert.loss_fn(model))
    report = accelerator.analyze(step=step, batch=batch)
    print(report.render())
    assert not report.has_errors, "the repo's own step program must audit clean"

    # 2. the warm-loop sanitizer: warm up, then watch a steady-state window
    for _ in range(2):
        loss = step(batch)
    with HazardSanitizer(telemetry=accelerator.telemetry, label="demo-loop") as sanitizer:
        watched = sanitizer.watch(step, label="train_step")
        loss = watched(batch)
        _ = float(loss)  # the hidden per-step host sync the sanitizer exists for
        # a shape change mid-loop: forces a retrace the sanitizer explains
        watched(make_batch(model, args.batch_size, args.seq_len + 8, sharding))
    hazard_report = sanitizer.report
    print(hazard_report.render())
    codes = {finding.code for finding in hazard_report.findings}
    assert "HOST_SYNC" in codes and "WARM_RECOMPILE" in codes
    print("recompile explained:", sanitizer.recompile_explanations[0]["summary"])

    accelerator.end_training()
    print(f"records in {os.path.join(args.project_dir, 'telemetry.jsonl')}")
    print("analysis demo complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
