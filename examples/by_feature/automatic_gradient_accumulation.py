"""Feature example: automatic gradient accumulation (reference
examples/by_feature/automatic_gradient_accumulation.py) — keep the EFFECTIVE
batch size fixed while find_executable_batch_size shrinks the per-step batch
to whatever fits, raising the accumulation count to compensate.

Run:
    python examples/by_feature/automatic_gradient_accumulation.py \
        --observed_batch_size 64
"""

from __future__ import annotations

import argparse
import os
import sys

import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import PairClassificationDataset, reset_accelerator_state

from accelerate_tpu import Accelerator
from accelerate_tpu.models import Bert
from accelerate_tpu.utils import find_executable_batch_size, set_seed


def main(argv=None):
    parser = argparse.ArgumentParser(description="Automatic gradient accumulation example.")
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument(
        "--observed_batch_size", type=int, default=64,
        help="The effective batch size training should behave as if it used",
    )
    parser.add_argument("--lr", type=float, default=1e-3)
    args = parser.parse_args(argv)

    @find_executable_batch_size(starting_batch_size=args.observed_batch_size)
    def training_function(batch_size):
        reset_accelerator_state()  # a failed attempt must not leak prepared objects
        accumulation = max(args.observed_batch_size // batch_size, 1)
        accelerator = Accelerator(gradient_accumulation_steps=accumulation)
        set_seed(42)
        bert = Bert("bert-tiny")
        dataset = PairClassificationDataset(vocab_size=bert.config.vocab_size, max_len=64)
        model, optimizer, loader = accelerator.prepare(
            bert,
            optax.adamw(args.lr),
            accelerator.prepare_data_loader(dataset, batch_size=batch_size, shuffle=True, seed=42),
        )
        loss_fn = Bert.loss_fn(bert)
        for epoch in range(args.num_epochs):
            loader.set_epoch(epoch)
            for batch in loader:
                with accelerator.accumulate(model):
                    loss = accelerator.backward(loss_fn, batch)
                    optimizer.step()
                    optimizer.zero_grad()
        accelerator.print(
            f"trained at batch_size={batch_size} x accumulation={accumulation} "
            f"(effective {batch_size * accumulation}); loss={float(loss):.4f}"
        )
        return batch_size, accumulation

    used, accum = training_function()
    print(f"final: batch_size={used} accumulation={accum}")


if __name__ == "__main__":
    main()
