"""Feature example: exact metrics across processes with gather_for_metrics
(reference examples/by_feature/multi_process_metrics.py).

With even-batch padding, the final batch contains duplicated samples on some
ranks; ``gather_for_metrics`` drops exactly those duplicates so the metric
sees every dataset row once — a plain ``gather`` would overcount.

Run:
    python examples/by_feature/multi_process_metrics.py
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import optax

import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import PairClassificationDataset, accuracy_f1

from accelerate_tpu import Accelerator
from accelerate_tpu.models import Bert
from accelerate_tpu.utils import set_seed


def main(argv=None):
    parser = argparse.ArgumentParser(description="Multi-process metrics example.")
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=1e-3)
    args = parser.parse_args(argv)

    accelerator = Accelerator()
    set_seed(42)
    bert = Bert("bert-tiny")
    dataset = PairClassificationDataset(vocab_size=bert.config.vocab_size, max_len=64)
    model, optimizer, train_loader = accelerator.prepare(
        bert,
        optax.adamw(args.lr),
        accelerator.prepare_data_loader(dataset, batch_size=args.batch_size, shuffle=True, seed=42),
    )
    loss_fn = Bert.loss_fn(bert)

    for epoch in range(args.num_epochs):
        train_loader.set_epoch(epoch)
        for batch in train_loader:
            accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()

    # eval loader with a deliberately uneven tail: batch 20 does not divide
    # the 48-row dataset, so the final batch engages the remainder
    # bookkeeping (and, multi-process, the duplicate-dropping) in
    # gather_for_metrics
    eval_loader = accelerator.prepare_data_loader(dataset, batch_size=20)
    predictions, references = [], []
    for batch in eval_loader:
        logits = bert.apply(model.params, batch["input_ids"], batch["attention_mask"], batch["token_type_ids"])
        preds, refs = accelerator.gather_for_metrics((jnp.argmax(logits, -1), batch["labels"]))
        predictions.append(np.asarray(preds))
        references.append(np.asarray(refs))
    predictions = np.concatenate(predictions)
    references = np.concatenate(references)
    assert len(predictions) == len(dataset), (len(predictions), len(dataset))
    metric = accuracy_f1(predictions, references)
    accelerator.print(f"exact sample count: {len(predictions)} == {len(dataset)}; {metric}")
    accelerator.end_training()


if __name__ == "__main__":
    main()
