"""Feature example: gradient accumulation.

Parity: reference examples/by_feature/gradient_accumulation.py — pass
``gradient_accumulation_steps=N`` to ``Accelerator`` and wrap the step in
``accumulate()``; the optimizer/scheduler only advance on the Nth micro-step.

On TPU there is additionally a fused fast path: ``accelerator.compiled_step``
folds the whole accumulation window into one jit program (``lax.scan`` over
microbatches) — shown at the bottom.

Run:
    python examples/by_feature/gradient_accumulation.py --gradient_accumulation_steps 4
"""

from __future__ import annotations

import argparse
import os
import sys

import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import PairClassificationDataset

from accelerate_tpu import Accelerator
from accelerate_tpu.models import Bert
from accelerate_tpu.utils import set_seed


def main(argv=None):
    parser = argparse.ArgumentParser(description="Gradient accumulation example.")
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=4)
    parser.add_argument("--lr", type=float, default=1e-3)
    args = parser.parse_args(argv)

    accelerator = Accelerator(gradient_accumulation_steps=args.gradient_accumulation_steps)
    set_seed(42)

    model = Bert("bert-tiny")
    dataset = PairClassificationDataset(vocab_size=model.config.vocab_size, max_len=64)
    model, optimizer, train_loader = accelerator.prepare(
        model,
        optax.adamw(args.lr),
        accelerator.prepare_data_loader(dataset, batch_size=args.batch_size, shuffle=True, seed=42),
    )
    loss_fn = Bert.loss_fn(accelerator.unwrap_model(model))

    for epoch in range(args.num_epochs):
        train_loader.set_epoch(epoch)
        for batch in train_loader:
            # inside accumulate(), optimizer.step()/zero_grad() are no-ops
            # until the window closes — the loop body stays identical
            with accelerator.accumulate(model):
                loss = accelerator.backward(loss_fn, batch)
                optimizer.step()
                optimizer.zero_grad()
        accelerator.print(
            f"epoch {epoch}: loss={float(loss):.4f} optimizer_steps={optimizer.step_count}"
        )

    # --- fused alternative: one compiled program per optimizer step ---------
    # The batch's leading dim is split into gradient_accumulation_steps
    # microbatches inside jit; no Python between micro-steps.
    step = accelerator.compiled_step(loss_fn)
    big_batch = next(iter(train_loader))  # leading dim divisible by the window
    loss = step(big_batch)
    accelerator.print(f"fused accumulation step: loss={float(loss):.4f}")
    accelerator.end_training()


if __name__ == "__main__":
    main()
