"""Feature example: k-fold cross validation (reference
examples/by_feature/cross_validation.py) — train k models on k splits,
evaluate each on its held-out fold with exact distributed metrics, and report
the mean.

Run:
    python examples/by_feature/cross_validation.py --num_folds 3
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import optax

import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import PairClassificationDataset, Subset, accuracy_f1

from accelerate_tpu import Accelerator
from accelerate_tpu.models import Bert
from accelerate_tpu.utils import set_seed


def main(argv=None):
    parser = argparse.ArgumentParser(description="K-fold cross-validation example.")
    parser.add_argument("--num_folds", type=int, default=3)
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=1e-3)
    args = parser.parse_args(argv)

    accelerator = Accelerator()
    set_seed(42)
    bert_cfg = Bert("bert-tiny").config
    dataset = PairClassificationDataset(vocab_size=bert_cfg.vocab_size, max_len=64)
    indices = np.random.default_rng(0).permutation(len(dataset))
    folds = np.array_split(indices, args.num_folds)

    scores = []
    for fold in range(args.num_folds):
        eval_idx = folds[fold]
        train_idx = np.concatenate([f for j, f in enumerate(folds) if j != fold])
        bert = Bert("bert-tiny")  # fresh model per fold
        model = accelerator.prepare_model(bert)
        optimizer = accelerator.prepare_optimizer(optax.adamw(args.lr))
        train_loader = accelerator.prepare_data_loader(
            Subset(dataset, train_idx), batch_size=args.batch_size, shuffle=True, seed=42 + fold
        )
        eval_loader = accelerator.prepare_data_loader(Subset(dataset, eval_idx), batch_size=16)
        loss_fn = Bert.loss_fn(bert)

        for epoch in range(args.num_epochs):
            train_loader.set_epoch(epoch)
            for batch in train_loader:
                accelerator.backward(loss_fn, batch, model=model)
                optimizer.step()
                optimizer.zero_grad()

        predictions, references = [], []
        for batch in eval_loader:
            logits = bert.apply(model.params, batch["input_ids"], batch["attention_mask"], batch["token_type_ids"])
            preds, refs = accelerator.gather_for_metrics((jnp.argmax(logits, -1), batch["labels"]))
            predictions.append(np.asarray(preds))
            references.append(np.asarray(refs))
        metric = accuracy_f1(np.concatenate(predictions), np.concatenate(references))
        scores.append(metric["accuracy"])
        accelerator.print(f"fold {fold}: {metric}")
        # release this fold's params/optimizer state before the next fold
        accelerator.free_memory()

    accelerator.print(f"mean accuracy over {args.num_folds} folds: {float(np.mean(scores)):.4f}")
    accelerator.end_training()


if __name__ == "__main__":
    main()
