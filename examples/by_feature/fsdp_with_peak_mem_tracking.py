"""Feature example: FSDP training with peak-memory tracking (reference
examples/by_feature/fsdp_with_peak_mem_tracking.py).

The reference wraps the model in torch FSDP and reads psutil/cuda peak
counters around each epoch. Here FSDP is a mesh axis: the
FullyShardedDataParallelPlugin shards parameters and optimizer state over
every device, and peak HBM comes from ``device.memory_stats()`` (XLA keeps
``peak_bytes_in_use`` per device; on CPU test meshes the stats are absent and
the example prints host RSS instead).

Run:
    python examples/by_feature/fsdp_with_peak_mem_tracking.py --num_epochs 1
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import optax

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import PairClassificationDataset, accuracy_f1, train_eval_split

from accelerate_tpu import Accelerator, FullyShardedDataParallelPlugin, ParallelismConfig
from accelerate_tpu.models import Bert
from accelerate_tpu.utils import set_seed


def peak_memory_bytes() -> int | None:
    """Max over devices of XLA's peak HBM counter; None when unavailable."""
    peaks = []
    for device in jax.local_devices():
        stats = device.memory_stats() or {}
        if "peak_bytes_in_use" in stats:
            peaks.append(stats["peak_bytes_in_use"])
    return max(peaks) if peaks else None


def host_rss_bytes() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) * 1024
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(description="FSDP + peak-memory example.")
    parser.add_argument("--mixed_precision", type=str, default=None, choices=[None, "no", "fp16", "bf16"])
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--zero_stage", type=int, default=3, choices=[1, 2, 3])
    args = parser.parse_args(argv)

    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        parallelism=ParallelismConfig(fsdp=jax.device_count()),
        fsdp_plugin=FullyShardedDataParallelPlugin(
            stage=args.zero_stage, activation_checkpointing=True
        ),
    )
    set_seed(42)

    bert = Bert("bert-tiny")
    dataset = PairClassificationDataset(vocab_size=bert.config.vocab_size, max_len=64)
    train_set, eval_set = train_eval_split(dataset)
    model, optimizer, train_loader = accelerator.prepare(
        bert,
        optax.adamw(args.lr),
        accelerator.prepare_data_loader(train_set, batch_size=args.batch_size, shuffle=True, seed=42),
    )
    eval_loader = accelerator.prepare_data_loader(eval_set, batch_size=16)
    loss_fn = Bert.loss_fn(bert)

    for epoch in range(args.num_epochs):
        train_loader.set_epoch(epoch)
        for batch in train_loader:
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()

        peak = peak_memory_bytes()
        if peak is not None:
            accelerator.print(f"epoch {epoch}: peak HBM {peak / 2**20:.1f} MiB")
        else:
            accelerator.print(f"epoch {epoch}: host RSS {host_rss_bytes() / 2**20:.1f} MiB (no HBM stats)")

        predictions, references = [], []
        for batch in eval_loader:
            logits = bert.apply(model.params, batch["input_ids"], batch["attention_mask"], batch["token_type_ids"])
            preds, refs = accelerator.gather_for_metrics((jnp.argmax(logits, -1), batch["labels"]))
            predictions.append(np.asarray(preds))
            references.append(np.asarray(refs))
        metric = accuracy_f1(np.concatenate(predictions), np.concatenate(references))
        accelerator.print(f"epoch {epoch}: {metric} (loss={float(loss):.4f})")

    accelerator.end_training()


if __name__ == "__main__":
    main()
