"""Feature example: experiment tracking.

Parity: reference examples/by_feature/tracking.py — ``log_with=...`` on the
Accelerator, ``init_trackers`` with the run config, ``accelerator.log`` per
step (main process only), ``end_training`` to flush.

The JSONL tracker needs no external service, so this runs anywhere; swap
``--log_with tensorboard`` (or wandb/mlflow/comet/aim) when those backends
are configured.

Run:
    python examples/by_feature/tracking.py --project_dir /tmp/run1
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import optax

import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import PairClassificationDataset, accuracy_f1

from accelerate_tpu import Accelerator
from accelerate_tpu.models import Bert
from accelerate_tpu.utils import ProjectConfiguration, set_seed


def main(argv=None):
    parser = argparse.ArgumentParser(description="Tracking example.")
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--log_with", type=str, default="jsonl")
    parser.add_argument("--project_dir", type=str, required=True)
    args = parser.parse_args(argv)

    accelerator = Accelerator(
        log_with=args.log_with,
        project_config=ProjectConfiguration(project_dir=args.project_dir, logging_dir=args.project_dir),
    )
    config = {"lr": args.lr, "num_epochs": args.num_epochs, "batch_size": args.batch_size, "seed": 42}
    accelerator.init_trackers("nlp_example", config)
    set_seed(42)

    model = Bert("bert-tiny")
    dataset = PairClassificationDataset(vocab_size=model.config.vocab_size, max_len=64)
    model, optimizer, train_loader = accelerator.prepare(
        model,
        optax.adamw(args.lr),
        accelerator.prepare_data_loader(dataset, batch_size=args.batch_size, shuffle=True, seed=42),
    )
    loss_fn = Bert.loss_fn(accelerator.unwrap_model(model))

    global_step = 0
    for epoch in range(args.num_epochs):
        train_loader.set_epoch(epoch)
        epoch_loss = 0.0
        for batch in train_loader:
            with accelerator.accumulate(model):
                loss = accelerator.backward(loss_fn, batch)
                optimizer.step()
                optimizer.zero_grad()
            epoch_loss += float(loss)
            accelerator.log({"train_loss": float(loss)}, step=global_step)
            global_step += 1

        predictions, references = [], []
        for batch in train_loader:
            logits = model.apply(
                model.params, batch["input_ids"], batch["attention_mask"], batch["token_type_ids"]
            )
            preds, refs = accelerator.gather_for_metrics((jnp.argmax(logits, -1), batch["labels"]))
            predictions.append(np.asarray(preds))
            references.append(np.asarray(refs))
        metric = accuracy_f1(np.concatenate(predictions), np.concatenate(references))
        accelerator.log(
            {"epoch_loss": epoch_loss / len(train_loader), **metric}, step=global_step
        )
        accelerator.print(f"epoch {epoch}: {metric}")

    accelerator.end_training()  # flushes/closes every tracker


if __name__ == "__main__":
    main()
