"""Feature example: automatic OOM recovery with find_executable_batch_size
(reference examples/by_feature/memory.py, utils/memory.py:87-158).

The decorated inner function re-runs with a halved batch size whenever the
step hits an XLA RESOURCE_EXHAUSTED error, so one script works across chip
generations and model sizes without manual tuning.

Run:
    python examples/by_feature/memory.py --starting_batch_size 256
"""

from __future__ import annotations

import argparse
import os
import sys

import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import PairClassificationDataset, reset_accelerator_state

from accelerate_tpu import Accelerator
from accelerate_tpu.models import Bert
from accelerate_tpu.utils import find_executable_batch_size, set_seed


def main(argv=None):
    parser = argparse.ArgumentParser(description="OOM-retry example.")
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument("--starting_batch_size", type=int, default=256)
    parser.add_argument("--lr", type=float, default=1e-3)
    args = parser.parse_args(argv)

    @find_executable_batch_size(starting_batch_size=args.starting_batch_size)
    def training_function(batch_size):
        reset_accelerator_state()  # a failed attempt must not leak prepared objects
        accelerator = Accelerator()
        set_seed(42)
        bert = Bert("bert-tiny")
        dataset = PairClassificationDataset(vocab_size=bert.config.vocab_size, max_len=64)
        model, optimizer, loader = accelerator.prepare(
            bert,
            optax.adamw(args.lr),
            accelerator.prepare_data_loader(dataset, batch_size=batch_size, shuffle=True, seed=42),
        )
        loss_fn = Bert.loss_fn(bert)
        for epoch in range(args.num_epochs):
            loader.set_epoch(epoch)
            for batch in loader:
                loss = accelerator.backward(loss_fn, batch)
                optimizer.step()
                optimizer.zero_grad()
        accelerator.print(f"trained at batch_size={batch_size}: loss={float(loss):.4f}")
        return batch_size

    used = training_function()
    print(f"executable batch size: {used}")


if __name__ == "__main__":
    main()
