"""Feature example: checkpointing + mid-training resume.

Parity: reference examples/by_feature/checkpointing.py — save the full
training state (model, optimizer, schedule position, RNG) every epoch with
``save_state``, resume with ``load_state`` + ``skip_first_batches``.

Run:
    python examples/by_feature/checkpointing.py --checkpoint_dir /tmp/ckpt
    python examples/by_feature/checkpointing.py --checkpoint_dir /tmp/ckpt \
        --resume_from_checkpoint epoch_1
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import optax

import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import PairClassificationDataset, accuracy_f1

from accelerate_tpu import Accelerator
from accelerate_tpu.models import Bert
from accelerate_tpu.utils import set_seed


def main(argv=None):
    parser = argparse.ArgumentParser(description="Checkpoint/resume example.")
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--checkpoint_dir", type=str, required=True)
    parser.add_argument(
        "--resume_from_checkpoint", type=str, default=None,
        help="Name of a checkpoint under --checkpoint_dir (e.g. epoch_1) to resume from.",
    )
    parser.add_argument(
        "--sharded", action="store_true",
        help="Write per-process sharded checkpoints (for models that only fit sharded).",
    )
    args = parser.parse_args(argv)

    accelerator = Accelerator()
    set_seed(42)

    model = Bert("bert-tiny")
    dataset = PairClassificationDataset(vocab_size=model.config.vocab_size, max_len=64)
    model, optimizer, train_loader = accelerator.prepare(
        model,
        optax.adamw(args.lr),
        accelerator.prepare_data_loader(dataset, batch_size=args.batch_size, shuffle=True, seed=42),
    )
    loss_fn = Bert.loss_fn(accelerator.unwrap_model(model))

    # epoch bookkeeping rides along in the checkpoint as a custom object
    class Progress:
        epoch = 0

        def state_dict(self):
            return {"epoch": self.epoch}

        def load_state_dict(self, state):
            self.epoch = state["epoch"]

    progress = Progress()
    accelerator.register_for_checkpointing(progress)

    start_epoch = 0
    if args.resume_from_checkpoint:
        accelerator.load_state(os.path.join(args.checkpoint_dir, args.resume_from_checkpoint))
        start_epoch = progress.epoch
        accelerator.print(f"resumed from {args.resume_from_checkpoint} at epoch {start_epoch}")

    for epoch in range(start_epoch, args.num_epochs):
        train_loader.set_epoch(epoch)
        for batch in train_loader:
            with accelerator.accumulate(model):
                loss = accelerator.backward(loss_fn, batch)
                optimizer.step()
                optimizer.zero_grad()
        progress.epoch = epoch + 1
        accelerator.save_state(
            os.path.join(args.checkpoint_dir, f"epoch_{epoch}"), sharded=args.sharded
        )
        accelerator.print(f"epoch {epoch}: loss={float(loss):.4f} saved epoch_{epoch}")

    # report train-set accuracy so runs (fresh vs resumed) are comparable
    predictions, references = [], []
    for batch in train_loader:
        logits = model.apply(
            model.params, batch["input_ids"], batch["attention_mask"], batch["token_type_ids"]
        )
        preds, refs = accelerator.gather_for_metrics((jnp.argmax(logits, -1), batch["labels"]))
        predictions.append(np.asarray(preds))
        references.append(np.asarray(refs))
    metric = accuracy_f1(np.concatenate(predictions), np.concatenate(references))
    accelerator.print(f"final: {metric}")
    accelerator.end_training()


if __name__ == "__main__":
    main()
