"""Distributed inference: split a batch of prompts across processes and
gather the generations (reference examples/inference/distributed_inference.py,
which uses PartialState.split_between_processes).

Each host generates only its slice; ``ops.gather_object`` reassembles the
per-rank lists in rank order, so uneven prompt counts need no padding.

Run (single host it degrades to a plain loop):
    python examples/inference/distributed_inference.py --max_new_tokens 8
"""

from __future__ import annotations

import argparse

import numpy as np

import jax
import jax.numpy as jnp

from accelerate_tpu import PartialState, ops
from accelerate_tpu.models import Llama, generate


def main(argv=None):
    parser = argparse.ArgumentParser(description="Distributed inference example.")
    parser.add_argument("--model", type=str, default="llama-tiny")
    parser.add_argument("--max_new_tokens", type=int, default=8)
    args = parser.parse_args(argv)

    state = PartialState()
    model = Llama(args.model)
    params = model.init(jax.random.key(0))

    # five prompts over N processes: uneven split is fine — gather_object is a
    # host-level object gather, so ragged per-rank lists need no padding
    prompts = [[1, 2, 3], [4, 5, 6], [7, 8, 9], [10, 11, 12], [13, 14, 15]]
    local = []
    with state.split_between_processes(prompts) as shard:
        for prompt in shard:
            ids = jnp.asarray([prompt], jnp.int32)
            out = generate(model, params, ids, max_new_tokens=args.max_new_tokens)
            local.append(np.asarray(out)[0].tolist())

    outputs = ops.gather_object(local)
    state.print(f"{state.num_processes} process(es) generated {len(outputs)} sequences:")
    for seq in outputs:
        state.print(f"  {seq}")


if __name__ == "__main__":
    main()
