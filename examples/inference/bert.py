"""Model-parallel BERT inference walkthrough.

Reference analogue: examples/inference/bert.py (pippy stages over BERT).
Here the encoder shards over the tensor axis; with a ``sequence`` axis the
bidirectional ring attention kicks in for long inputs.

Run:
    python examples/inference/bert.py --model bert-tiny --tensor 2
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.models import build_model
from accelerate_tpu.utils import set_seed

import sys as _sys

_sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import cap_parallel_degree as _cap


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", type=str, default="bert-tiny")
    parser.add_argument("--tensor", type=int, default=2)
    parser.add_argument("--sequence", type=int, default=1, help="ring-attention degree")
    parser.add_argument("--seq_len", type=int, default=64)
    args = parser.parse_args(argv)
    set_seed(42)

    accelerator = Accelerator(
        parallelism=ParallelismConfig(tensor=_cap(args.tensor), sequence=_cap(args.sequence))
    )
    model = build_model(args.model)
    prepared = accelerator.prepare_model(model)

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, model.config.vocab_size, (2, args.seq_len)), jnp.int32)
    mask = jnp.ones_like(ids)
    prepared(ids, mask)  # compile
    start = time.perf_counter()
    logits = prepared(ids, mask)
    jax.block_until_ready(logits)
    accelerator.print(f"sharded forward: {time.perf_counter() - start:.4f}s {logits.shape}")
    accelerator.print(f"predictions: {np.asarray(jnp.argmax(logits, -1)).tolist()}")
    accelerator.print("ok")


if __name__ == "__main__":
    main()
