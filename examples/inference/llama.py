"""Model-parallel llama inference walkthrough.

Reference analogue: examples/inference/llama.py (pippy pipeline stages over
LlamaForCausalLM). The TPU-native equivalent shards the SAME stacked weights
over the mesh axes (tensor and/or pipeline) with GSPMD — no fx tracing, no
per-stage processes — and additionally offers KV-cache generation.

Run:
    python examples/inference/llama.py --model llama-tiny --tensor 2
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.models import build_model
from accelerate_tpu.models.generation import generate
from accelerate_tpu.utils import set_seed

import sys as _sys

_sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import cap_parallel_degree as _cap


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", type=str, default="llama-tiny")
    parser.add_argument("--tensor", type=int, default=2, help="tensor-parallel degree")
    parser.add_argument("--pipeline", type=int, default=1, help="pipeline-parallel degree")
    parser.add_argument("--seq_len", type=int, default=64)
    parser.add_argument("--max_new_tokens", type=int, default=8)
    args = parser.parse_args(argv)
    set_seed(42)

    accelerator = Accelerator(
        parallelism=ParallelismConfig(tensor=_cap(args.tensor), pipeline=_cap(args.pipeline))
    )
    model = build_model(args.model)
    prepared = accelerator.prepare_model(model)

    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, model.config.vocab_size, (2, args.seq_len)),
        jnp.int32,
    )
    prepared(ids)  # compile
    start = time.perf_counter()
    logits = prepared(ids)
    jax.block_until_ready(logits)
    accelerator.print(f"sharded forward: {time.perf_counter() - start:.4f}s {logits.shape}")

    # KV-cache generation (two compiled programs: prefill + decode)
    out = generate(model, prepared.params, ids[:, :8], max_new_tokens=args.max_new_tokens)
    accelerator.print(f"generated: {np.asarray(out)[0].tolist()}")
    accelerator.print("ok")


if __name__ == "__main__":
    main()
