"""Big-model inference: load a checkpoint that does not fit in device memory
and generate from it (reference examples/inference/pippy/llama.py and
benchmarks/big_model_inference.py).

The reference materializes the model on the meta device, infers a device map,
and streams offloaded weights through forward hooks. Here the same capability
is three calls — abstract init, sharded-checkpoint load, and dispatch into a
streaming executor whose offloaded layers ride a double-buffered H2D window:

    with init_empty_weights(model):                 # shapes only, no memory
        ...
    lm = load_checkpoint_and_dispatch(model, ckpt, device_map="auto")
    lm.generate(prompt_ids, max_new_tokens=32)

Run (writes a demo checkpoint to --ckpt on first use):
    python examples/inference/big_model_inference.py --model llama-125m \
        --placement cpu --max_new_tokens 16
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from accelerate_tpu import load_checkpoint_and_dispatch
from accelerate_tpu.checkpointing import save_model_weights
from accelerate_tpu.models import build_model


def main(argv=None):
    parser = argparse.ArgumentParser(description="Big-model inference example.")
    parser.add_argument("--model", type=str, default="llama-tiny",
                        help="any registry causal LM (llama-*, gpt2-*)")
    parser.add_argument("--ckpt", type=str, default=None, help="checkpoint dir (demo weights written if absent)")
    parser.add_argument(
        "--placement", type=str, default="cpu", choices=["auto", "device", "cpu", "disk"],
        help="where transformer layers live; embed/head stay on device",
    )
    parser.add_argument("--offload_dir", type=str, default=None)
    parser.add_argument("--max_new_tokens", type=int, default=16)
    parser.add_argument("--temperature", type=float, default=0.0)
    args = parser.parse_args(argv)

    model = build_model(args.model)
    cfg = model.config

    ckpt = args.ckpt or os.path.join("/tmp", f"demo_ckpt_{args.model}")
    if not os.path.isdir(ckpt) or not os.listdir(ckpt):
        print(f"writing demo checkpoint for {args.model} to {ckpt}")
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = jax.device_get(jax.jit(model._init)(jax.random.key(0)))
        save_model_weights(params, ckpt, max_shard_size="512MB")
        del params

    if args.placement == "auto":
        device_map: dict | str = "auto"
    else:
        # transformer layers go to the chosen tier; embeddings/norms/heads
        # (whatever the family calls them) stay on device
        from accelerate_tpu.big_modeling import make_layered_device_map

        device_map = make_layered_device_map(model, args.placement)
    offload_dir = args.offload_dir
    if args.placement == "disk" and offload_dir is None:
        offload_dir = os.path.join("/tmp", f"offload_{args.model}")

    start = time.perf_counter()
    lm = load_checkpoint_and_dispatch(
        model, ckpt, device_map=device_map, offload_dir=offload_dir, dtype=jnp.bfloat16
    )
    print(f"load+dispatch: {time.perf_counter() - start:.2f}s; device_map targets: "
          f"{sorted(set(lm.hf_device_map.values()))}; streaming group={lm.group_size} layers")

    prompt = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    out = lm.generate(prompt, max_new_tokens=args.max_new_tokens, temperature=args.temperature,
                      return_device=True)
    jax.block_until_ready(out)
    start = time.perf_counter()
    out = lm.generate(prompt, max_new_tokens=args.max_new_tokens, temperature=args.temperature,
                      return_device=True)
    jax.block_until_ready(out)
    per_token = (time.perf_counter() - start) / args.max_new_tokens
    print(f"generation: {per_token:.4f} s/token ({args.max_new_tokens} tokens)")
    print("tokens:", np.asarray(out)[0].tolist())


if __name__ == "__main__":
    main()
