"""T5 encoder-decoder inference walkthrough.

Reference analogue: examples/inference/t5.py (pippy stages over
T5ForConditionalGeneration, split on T5Block). The TPU-native path:
(1) tensor-parallel seq2seq forward via GSPMD, (2) big-model streamed
generation — the decoder stack streams through a double-buffered HBM window
while the encoder runs once per sequence (big_modeling.Seq2SeqStreamedModel).

Run:
    python examples/inference/t5.py --model t5-tiny --tensor 2
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, ParallelismConfig, dispatch_model
from accelerate_tpu.big_modeling import make_layered_device_map
from accelerate_tpu.models import build_model
from accelerate_tpu.utils import set_seed

import sys as _sys

_sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from example_utils import cap_parallel_degree as _cap


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", type=str, default="t5-tiny")
    parser.add_argument("--tensor", type=int, default=2)
    parser.add_argument("--seq_len", type=int, default=64)
    parser.add_argument("--max_new_tokens", type=int, default=8)
    parser.add_argument(
        "--placement", type=str, default="cpu", choices=["device", "cpu"],
        help="where the streamed decoder stack lives for generation",
    )
    args = parser.parse_args(argv)
    set_seed(42)

    accelerator = Accelerator(parallelism=ParallelismConfig(tensor=_cap(args.tensor)))
    model = build_model(args.model)
    prepared = accelerator.prepare_model(model)

    rng = np.random.default_rng(0)
    enc_ids = jnp.asarray(rng.integers(0, model.config.vocab_size, (2, args.seq_len)), jnp.int32)
    dec_ids = jnp.asarray(rng.integers(0, model.config.vocab_size, (2, args.seq_len // 2)), jnp.int32)
    prepared(enc_ids, dec_ids)  # compile
    start = time.perf_counter()
    logits = prepared(enc_ids, dec_ids)
    jax.block_until_ready(logits)
    accelerator.print(f"sharded seq2seq forward: {time.perf_counter() - start:.4f}s {logits.shape}")

    # streamed generation: decoder layers offloaded, encoder resident
    params = jax.device_get(prepared.params)
    lm = dispatch_model(model, params, device_map=make_layered_device_map(model, args.placement))
    out = lm.generate(enc_ids[:1, :16], max_new_tokens=args.max_new_tokens)
    accelerator.print(f"generated decoder tokens: {out[0].tolist()}")
    accelerator.print("ok")


if __name__ == "__main__":
    main()
