#!/bin/bash
# Multi-host TPU job under Slurm (reference examples/slurm/submit_multinode.sh).
#
# One task per HOST (a host drives all of its local TPU chips — there is no
# per-chip process fan-out on this stack). Rank 0's node is the JAX
# coordination-service rendezvous point.

#SBATCH --job-name=accelerate-tpu-multinode
#SBATCH -D .
#SBATCH --output=O-%x.%j
#SBATCH --error=E-%x.%j
#SBATCH --nodes=4                   # number of TPU hosts
#SBATCH --ntasks-per-node=1         # exactly one process per host
#SBATCH --cpus-per-task=96
#SBATCH --time=01:59:00

######################
### Set environment ##
######################
# source activate_environment.sh   # your venv with accelerate_tpu installed
######################

######################
#### Set network #####
######################
head_node_ip=$(scontrol show hostnames "$SLURM_JOB_NODELIST" | head -n 1)
export COORDINATOR="${head_node_ip}:29500"
######################

export LAUNCHER="accelerate-tpu launch \
    --num_processes $SLURM_NNODES \
    --process_id \$SLURM_PROCID \
    --coordinator_address $COORDINATOR \
    --fsdp_size $SLURM_NNODES \
    --mixed_precision bf16 \
    "
export SCRIPT="examples/complete_nlp_example.py"
export SCRIPT_ARGS="--num_epochs 3 --output_dir /tmp/run --checkpointing_steps epoch"

# srun expands $SLURM_PROCID per task, giving each host its rank
srun bash -c "$LAUNCHER $SCRIPT $SCRIPT_ARGS"
