#!/bin/bash
# Single-host TPU job under Slurm (reference examples/slurm/submit_multigpu.sh).
#
# One process drives every local TPU chip; the mesh axes are set by flags
# (here: pure data parallelism over all chips).

#SBATCH --job-name=accelerate-tpu-singlenode
#SBATCH -D .
#SBATCH --output=O-%x.%j
#SBATCH --error=E-%x.%j
#SBATCH --nodes=1
#SBATCH --ntasks-per-node=1
#SBATCH --cpus-per-task=96
#SBATCH --time=01:59:00

# source activate_environment.sh   # your venv with accelerate_tpu installed

accelerate-tpu launch \
    --mixed_precision bf16 \
    examples/nlp_example.py --num_epochs 3
