"""The kitchen-sink vision loop (reference examples/complete_cv_example.py):
the cv_example convnet plus tracking, checkpointing with mid-training resume,
LR scheduling, and exact distributed metrics, all behind CLI flags.

Run:
    python examples/complete_cv_example.py --with_tracking \
        --checkpointing_steps epoch --output_dir /tmp/cv_run
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import optax

import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from cv_example import ShapesDataset, SmallConvNet, loss_fn
from example_utils import train_eval_split

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import ProjectConfiguration, set_seed

EVAL_BATCH_SIZE = 16


def main(argv=None):
    parser = argparse.ArgumentParser(description="Complete vision training-loop example.")
    parser.add_argument("--mixed_precision", type=str, default=None, choices=[None, "no", "fp16", "bf16"])
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--lr", type=float, default=3e-3)
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument(
        "--checkpointing_steps", type=str, default=None,
        help='"epoch", or an integer number of batches between checkpoints',
    )
    parser.add_argument("--resume_from_checkpoint", type=str, default=None)
    parser.add_argument("--output_dir", type=str, default=None)
    args = parser.parse_args(argv)
    if args.checkpointing_steps or args.with_tracking:
        assert args.output_dir, "--output_dir is required with tracking/checkpointing"

    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        log_with="jsonl" if args.with_tracking else None,
        project_config=ProjectConfiguration(project_dir=args.output_dir, logging_dir=args.output_dir),
    )
    set_seed(42)
    if args.with_tracking:
        accelerator.init_trackers("complete_cv_example", vars(args))

    train_set, eval_set = train_eval_split(ShapesDataset())

    def schedule(count):
        return args.lr / (1 + 0.05 * count)

    model, optimizer, train_loader, scheduler = accelerator.prepare(
        SmallConvNet(),
        optax.adam(schedule),
        accelerator.prepare_data_loader(train_set, batch_size=args.batch_size, shuffle=True, seed=42),
        schedule,
    )
    eval_loader = accelerator.prepare_data_loader(eval_set, batch_size=EVAL_BATCH_SIZE)

    class Progress:
        step = 0

        def state_dict(self):
            return {"step": self.step}

        def load_state_dict(self, state):
            self.step = state["step"]

    progress = Progress()
    accelerator.register_for_checkpointing(progress)
    batches_per_epoch = max(len(train_loader), 1)
    start_epoch = skip_batches = 0
    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)
        start_epoch = progress.step // batches_per_epoch
        skip_batches = progress.step % batches_per_epoch
        accelerator.print(f"resumed at epoch {start_epoch}, step {progress.step}")

    for epoch in range(start_epoch, args.num_epochs):
        train_loader.set_epoch(epoch)
        loader = train_loader
        if epoch == start_epoch and skip_batches:
            loader = accelerator.skip_first_batches(train_loader, skip_batches)
        for batch in loader:
            with accelerator.accumulate(model):
                loss = accelerator.backward(loss_fn, batch)
                optimizer.step()
                scheduler.step()
                optimizer.zero_grad()
            progress.step += 1
            if args.with_tracking:
                accelerator.log({"train_loss": float(loss)}, step=progress.step)
            if args.checkpointing_steps and args.checkpointing_steps != "epoch":
                if progress.step % int(args.checkpointing_steps) == 0:
                    accelerator.save_state(os.path.join(args.output_dir, f"step_{progress.step}"))
        if args.checkpointing_steps == "epoch":
            accelerator.save_state(os.path.join(args.output_dir, f"epoch_{epoch}"))

        correct = total = 0
        for batch in eval_loader:
            logits = SmallConvNet.apply(model.params, batch["image"])
            preds, refs = accelerator.gather_for_metrics((jnp.argmax(logits, -1), batch["label"]))
            correct += int((np.asarray(preds) == np.asarray(refs)).sum())
            total += len(np.asarray(refs))
        accuracy = correct / total
        accelerator.print(f"epoch {epoch}: accuracy={accuracy:.3f}")
        if args.with_tracking:
            accelerator.log({"accuracy": accuracy}, step=progress.step)

    accelerator.end_training()


if __name__ == "__main__":
    main()
