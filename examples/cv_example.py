"""Vision example: a small convnet on a synthetic shapes dataset
(reference examples/cv_example.py trains ResNet-50 on Oxford pets; this runs
with zero downloads and shows the framework is model-agnostic — any
(init, apply) pair trains, not just the bundled transformers).

Run:
    python examples/cv_example.py --num_epochs 3
"""

from __future__ import annotations

import argparse

import numpy as np
import optax

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import set_seed


class ShapesDataset:
    """28×28 images of one of three shapes (square / cross / diagonal) with
    noise — classifiable, but not linearly trivial."""

    def __init__(self, n: int = 192, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.images = np.zeros((n, 28, 28, 1), np.float32)
        self.labels = rng.integers(0, 3, n).astype(np.int32)
        for i, label in enumerate(self.labels):
            canvas = np.zeros((28, 28), np.float32)
            x, y = rng.integers(4, 16, 2)
            if label == 0:  # square outline
                canvas[y : y + 9, x : x + 9] = 1.0
                canvas[y + 2 : y + 7, x + 2 : x + 7] = 0.0
            elif label == 1:  # cross
                canvas[y + 4, x : x + 9] = 1.0
                canvas[y : y + 9, x + 4] = 1.0
            else:  # diagonal
                for j in range(9):
                    canvas[y + j, x + j] = 1.0
            self.images[i, :, :, 0] = canvas + 0.1 * rng.normal(size=(28, 28))

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        return {"image": self.images[i], "label": self.labels[i]}


class SmallConvNet:
    """conv3x3 ×2 (stride 2) → global pool → linear, as an (init, apply) pair."""

    def init(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        return {
            "conv1": jax.random.normal(k1, (3, 3, 1, 16), jnp.float32) * 0.3,
            "conv2": jax.random.normal(k2, (3, 3, 16, 32), jnp.float32) * 0.1,
            "head_w": jax.random.normal(k3, (32, 3), jnp.float32) * 0.1,
            "head_b": jnp.zeros((3,), jnp.float32),
        }

    @staticmethod
    def apply(params, images):
        h = jax.lax.conv_general_dilated(
            images, params["conv1"], (2, 2), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )
        h = jax.nn.relu(h)
        h = jax.lax.conv_general_dilated(
            h, params["conv2"], (2, 2), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )
        h = jax.nn.relu(h).mean(axis=(1, 2))  # global average pool
        return h @ params["head_w"] + params["head_b"]


def loss_fn(params, batch):
    logits = SmallConvNet.apply(params, batch["image"]).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, batch["label"][:, None], axis=-1).mean()


def main(argv=None):
    parser = argparse.ArgumentParser(description="Vision training example.")
    parser.add_argument("--mixed_precision", type=str, default=None, choices=[None, "no", "fp16", "bf16"])
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=3e-3)
    args = parser.parse_args(argv)

    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    set_seed(42)
    import os, sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from example_utils import train_eval_split

    train_set, eval_set = train_eval_split(ShapesDataset())
    model, optimizer, loader = accelerator.prepare(
        SmallConvNet(),
        optax.adam(args.lr),
        accelerator.prepare_data_loader(train_set, batch_size=args.batch_size, shuffle=True, seed=42),
    )
    eval_loader = accelerator.prepare_data_loader(eval_set, batch_size=args.batch_size)

    for epoch in range(args.num_epochs):
        loader.set_epoch(epoch)
        for batch in loader:
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()

        correct, total = 0, 0
        for batch in eval_loader:
            logits = SmallConvNet.apply(model.params, batch["image"])
            preds, refs = accelerator.gather_for_metrics((jnp.argmax(logits, -1), batch["label"]))
            correct += int((np.asarray(preds) == np.asarray(refs)).sum())
            total += len(np.asarray(refs))
        accelerator.print(f"epoch {epoch}: loss={float(loss):.4f} accuracy={correct / total:.3f}")

    accelerator.end_training()


if __name__ == "__main__":
    main()
