"""The kitchen-sink loop: every feature from by_feature/ in one script
(reference examples/complete_nlp_example.py) — tracking, gradient
accumulation, checkpointing with mid-training resume, LR scheduling, and
exact distributed metrics, all behind CLI flags.

Run:
    python examples/complete_nlp_example.py --with_tracking \
        --checkpointing_steps epoch --output_dir /tmp/run
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import optax

import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from example_utils import PairClassificationDataset, accuracy_f1, train_eval_split

from accelerate_tpu import Accelerator
from accelerate_tpu.models import Bert
from accelerate_tpu.utils import ProjectConfiguration, set_seed

EVAL_BATCH_SIZE = 16


def main(argv=None):
    parser = argparse.ArgumentParser(description="Complete training-loop example.")
    parser.add_argument("--mixed_precision", type=str, default=None, choices=[None, "no", "fp16", "bf16", "fp8"])
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=2)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument(
        "--checkpointing_steps", type=str, default=None,
        help='"epoch", or an integer number of batches between checkpoints',
    )
    parser.add_argument("--resume_from_checkpoint", type=str, default=None)
    parser.add_argument("--output_dir", type=str, default=None)
    args = parser.parse_args(argv)
    if args.checkpointing_steps or args.with_tracking:
        assert args.output_dir, "--output_dir is required with tracking/checkpointing"

    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        log_with="jsonl" if args.with_tracking else None,
        project_config=ProjectConfiguration(project_dir=args.output_dir, logging_dir=args.output_dir),
    )
    set_seed(42)
    if args.with_tracking:
        accelerator.init_trackers("complete_nlp_example", vars(args))

    bert = Bert("bert-tiny")
    dataset = PairClassificationDataset(vocab_size=bert.config.vocab_size, max_len=64)
    train_set, eval_set = train_eval_split(dataset)

    # the schedule is BAKED INTO the optax transformation (that is what moves
    # the LR); the AcceleratedScheduler wrapper tracks its position for
    # get_last_lr/checkpointing
    def schedule(count):
        return args.lr / (1 + 0.05 * count)

    model, optimizer, train_loader, scheduler = accelerator.prepare(
        bert,
        optax.adamw(schedule),
        accelerator.prepare_data_loader(train_set, batch_size=args.batch_size, shuffle=True, seed=42),
        schedule,
    )
    eval_loader = accelerator.prepare_data_loader(eval_set, batch_size=EVAL_BATCH_SIZE)
    loss_fn = Bert.loss_fn(bert)

    class Progress:
        step = 0  # batches seen; epoch/offset derive from it, so epoch AND
        # mid-epoch step checkpoints resume consistently

        def state_dict(self):
            return {"step": self.step}

        def load_state_dict(self, state):
            self.step = state["step"]

    progress = Progress()
    accelerator.register_for_checkpointing(progress)
    batches_per_epoch = max(len(train_loader), 1)
    start_epoch = skip_batches = 0
    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)
        start_epoch = progress.step // batches_per_epoch
        skip_batches = progress.step % batches_per_epoch
        accelerator.print(f"resumed at epoch {start_epoch}, step {progress.step}")

    for epoch in range(start_epoch, args.num_epochs):
        train_loader.set_epoch(epoch)
        loader = train_loader
        if epoch == start_epoch and skip_batches:
            loader = accelerator.skip_first_batches(train_loader, skip_batches)
        for batch in loader:
            with accelerator.accumulate(model):
                loss = accelerator.backward(loss_fn, batch)
                optimizer.step()
                scheduler.step()
                optimizer.zero_grad()
            progress.step += 1
            if args.with_tracking:
                accelerator.log(
                    {"train_loss": float(loss), "lr": float(schedule(optimizer.step_count))},
                    step=progress.step,
                )
            if args.checkpointing_steps and args.checkpointing_steps != "epoch":
                if progress.step % int(args.checkpointing_steps) == 0:
                    accelerator.save_state(os.path.join(args.output_dir, f"step_{progress.step}"))
        if args.checkpointing_steps == "epoch":
            accelerator.save_state(os.path.join(args.output_dir, f"epoch_{epoch}"))

        predictions, references = [], []
        for batch in eval_loader:
            logits = bert.apply(model.params, batch["input_ids"], batch["attention_mask"], batch["token_type_ids"])
            preds, refs = accelerator.gather_for_metrics((jnp.argmax(logits, -1), batch["labels"]))
            predictions.append(np.asarray(preds))
            references.append(np.asarray(refs))
        metric = accuracy_f1(np.concatenate(predictions), np.concatenate(references))
        accelerator.print(f"epoch {epoch}: {metric}")
        if args.with_tracking:
            accelerator.log(dict(metric), step=progress.step)

    accelerator.end_training()


if __name__ == "__main__":
    main()
