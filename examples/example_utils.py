"""Shared helpers for the examples: a self-contained tokenizer and the tiny
bundled MRPC-like dataset (examples/data/mrpc_tiny.csv).

The reference examples tokenize GLUE-MRPC with a pretrained BERT tokenizer
(reference examples/nlp_example.py:46-60); these examples run with zero
network access, so sentences are hash-tokenized into a fixed vocab instead.
Everything else — the pair encoding ([CLS] s1 [SEP] s2 [SEP]), the padding,
the metric flow — mirrors the reference loop.
"""

from __future__ import annotations

import csv
import os

import numpy as np

PAD, CLS, SEP, UNK = 0, 1, 2, 3
_RESERVED = 4

DATA_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data", "mrpc_tiny.csv")


def tokenize(text: str, vocab_size: int) -> list[int]:
    """Deterministic hash tokenizer: word → id in [4, vocab_size)."""
    ids = []
    for word in text.lower().split():
        word = word.strip(".,!?\"'")
        if not word:
            continue
        # FNV-1a, stable across processes (unlike Python's salted hash())
        h = 2166136261
        for ch in word.encode():
            h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
        ids.append(_RESERVED + h % (vocab_size - _RESERVED))
    return ids


def encode_pair(s1: str, s2: str, vocab_size: int, max_len: int) -> dict[str, np.ndarray]:
    """[CLS] s1 [SEP] s2 [SEP] with padding, mask, and segment ids."""
    a, b = tokenize(s1, vocab_size), tokenize(s2, vocab_size)
    ids = [CLS] + a + [SEP] + b + [SEP]
    types = [0] * (len(a) + 2) + [1] * (len(b) + 1)
    ids, types = ids[:max_len], types[:max_len]
    pad = max_len - len(ids)
    return {
        "input_ids": np.asarray(ids + [PAD] * pad, np.int32),
        "attention_mask": np.asarray([1] * len(ids) + [0] * pad, np.int32),
        "token_type_ids": np.asarray(types + [0] * pad, np.int32),
    }


class PairClassificationDataset:
    """Map-style dataset over the bundled CSV (label,sentence1,sentence2)."""

    def __init__(self, path: str = DATA_PATH, vocab_size: int = 1024, max_len: int = 64):
        self.rows = []
        with open(path, newline="") as f:
            for row in csv.DictReader(f):
                self.rows.append(
                    (row["sentence1"], row["sentence2"], 1 if row["label"] == "equivalent" else 0)
                )
        self.vocab_size = vocab_size
        self.max_len = max_len

    def __len__(self) -> int:
        return len(self.rows)

    def __getitem__(self, i: int) -> dict[str, np.ndarray]:
        s1, s2, label = self.rows[i]
        item = encode_pair(s1, s2, self.vocab_size, self.max_len)
        item["labels"] = np.asarray(label, np.int32)
        return item


def accuracy_f1(predictions: np.ndarray, references: np.ndarray) -> dict[str, float]:
    """The MRPC metric pair (accuracy + F1), computed locally."""
    predictions = np.asarray(predictions)
    references = np.asarray(references)
    accuracy = float((predictions == references).mean())
    tp = float(((predictions == 1) & (references == 1)).sum())
    fp = float(((predictions == 1) & (references == 0)).sum())
    fn = float(((predictions == 0) & (references == 1)).sum())
    f1 = 2 * tp / (2 * tp + fp + fn) if (2 * tp + fp + fn) else 0.0
    return {"accuracy": round(accuracy, 4), "f1": round(f1, 4)}


class Subset:
    """Index-view over a map-style dataset (shared by the example scripts)."""

    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __len__(self):
        return len(self.indices)

    def __getitem__(self, i):
        return self.dataset[int(self.indices[i])]


def train_eval_split(dataset, eval_fraction: float = 0.25, seed: int = 0):
    """Deterministic shuffled train/eval split used by every example."""
    n_eval = max(int(len(dataset) * eval_fraction), 1)
    indices = np.random.default_rng(seed).permutation(len(dataset))
    return Subset(dataset, indices[n_eval:]), Subset(dataset, indices[:n_eval])


def reset_accelerator_state():
    """Drop the topology singletons so a fresh Accelerator can be built
    (used by the OOM-retry examples, which rebuild everything per attempt)."""
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def cap_parallel_degree(degree: int) -> int:
    """Clamp a requested parallel degree to the visible topology (walkthroughs
    still run on a single chip; on an 8-device mesh they shard for real)."""
    import jax

    n = jax.device_count()
    while degree > 1 and n % degree:
        degree -= 1
    return min(degree, n)
