"""The canonical training loop: BERT sequence classification on an MRPC-like
paraphrase task, TPU-native.

Parity with the reference's flagship example (examples/nlp_example.py:1): the
user keeps the loop, ``Accelerator`` makes it run unchanged on one chip, a
TPU slice, or a virtual CPU mesh — sharding, precision, and collectives all
come from ``prepare()`` + ``backward()`` + ``gather_for_metrics()``.

Run (single chip or real slice):
    python examples/nlp_example.py --mixed_precision bf16
Run on the 8-device virtual CPU mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/nlp_example.py --num_epochs 2
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import optax

import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from example_utils import PairClassificationDataset, accuracy_f1, train_eval_split

from accelerate_tpu import Accelerator
from accelerate_tpu.models import Bert
from accelerate_tpu.utils import set_seed

EVAL_BATCH_SIZE = 16


def get_dataloaders(accelerator: Accelerator, batch_size: int, max_len: int, vocab_size: int):
    """Train/eval loaders over the bundled dataset (deterministic split)."""
    dataset = PairClassificationDataset(vocab_size=vocab_size, max_len=max_len)
    train_set, eval_set = train_eval_split(dataset)
    train_loader = accelerator.prepare_data_loader(
        train_set, batch_size=batch_size, shuffle=True, seed=42
    )
    eval_loader = accelerator.prepare_data_loader(
        eval_set, batch_size=EVAL_BATCH_SIZE, shuffle=False
    )
    return train_loader, eval_loader


def training_function(config: dict, args: argparse.Namespace) -> dict:
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    set_seed(int(config["seed"]))

    model = Bert("bert-tiny")
    cfg = model.config
    train_loader, eval_loader = get_dataloaders(
        accelerator, int(config["batch_size"]), max_len=64, vocab_size=cfg.vocab_size
    )

    steps_per_epoch = len(train_loader)
    warmup_steps = max(1, steps_per_epoch // 2)
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=config["lr"],
        warmup_steps=warmup_steps,
        decay_steps=max(steps_per_epoch * int(config["num_epochs"]), warmup_steps + 1),
    )
    model, optimizer, scheduler = accelerator.prepare(
        model, optax.adamw(schedule), lambda c: schedule(c)
    )
    loss_fn = Bert.loss_fn(accelerator.unwrap_model(model))

    eval_metric: dict = {}
    for epoch in range(int(config["num_epochs"])):
        train_loader.set_epoch(epoch)
        for batch in train_loader:
            with accelerator.accumulate(model):
                accelerator.backward(loss_fn, batch)
                optimizer.step()
                scheduler.step()
                optimizer.zero_grad()

        predictions, references = [], []
        for batch in eval_loader:
            logits = model.apply(
                model.params, batch["input_ids"], batch["attention_mask"], batch["token_type_ids"]
            )
            preds = jnp.argmax(logits, axis=-1)
            preds, refs = accelerator.gather_for_metrics((preds, batch["labels"]))
            predictions.append(np.asarray(preds))
            references.append(np.asarray(refs))
        eval_metric = accuracy_f1(np.concatenate(predictions), np.concatenate(references))
        accelerator.print(f"epoch {epoch}: {eval_metric}")

    accelerator.end_training()
    return eval_metric


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="Canonical training-loop example.")
    parser.add_argument(
        "--mixed_precision", type=str, default=None, choices=["no", "fp16", "bf16", "fp8"],
        help="Compute precision policy (params stay fp32).",
    )
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=1e-3)
    return parser.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    config = {"lr": args.lr, "num_epochs": args.num_epochs, "seed": 42, "batch_size": args.batch_size}
    training_function(config, args)


if __name__ == "__main__":
    main()
