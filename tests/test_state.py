"""Tests for the topology singletons (reference: tests/test_state_checkpointing etc.)."""

import jax
import numpy as np
import pytest

from accelerate_tpu import ParallelismConfig
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils import DistributedType


def test_virtual_mesh_has_8_devices():
    assert jax.device_count() == 8


def test_partial_state_borg():
    a = PartialState()
    b = PartialState()
    assert a.__dict__ is b.__dict__
    assert a.num_processes == 1
    assert a.process_index == 0
    assert a.is_main_process
    assert a.num_devices == 8


def test_default_mesh_is_pure_data_parallel():
    state = PartialState()
    assert state.mesh.shape["data"] == 8
    assert state.mesh.shape["tensor"] == 1
    assert state.distributed_type == DistributedType.DATA_PARALLEL


def test_parallelism_config_axis_sizes():
    cfg = ParallelismConfig(fsdp=2, tensor=2)
    sizes = cfg.axis_sizes(8)
    assert sizes["data"] == 2
    assert sizes["fsdp"] == 2
    assert sizes["tensor"] == 2
    assert cfg.distributed_type == DistributedType.HYBRID


def test_parallelism_config_invalid():
    with pytest.raises(ValueError):
        ParallelismConfig(tensor=3).axis_sizes(8)
    with pytest.raises(ValueError):
        ParallelismConfig(data=2, tensor=2).axis_sizes(8)


def test_mesh_with_model_axes():
    state = PartialState(parallelism=ParallelismConfig(tensor=4))
    assert state.mesh.shape["tensor"] == 4
    assert state.mesh.shape["data"] == 2
    assert state.distributed_type == DistributedType.TENSOR_PARALLEL


def test_conflicting_reinit_raises():
    PartialState(parallelism=ParallelismConfig(tensor=2))
    with pytest.raises(ValueError):
        PartialState(parallelism=ParallelismConfig(tensor=4))


def test_split_between_processes_single():
    state = PartialState()
    with state.split_between_processes(list(range(10))) as piece:
        assert piece == list(range(10))


def test_on_main_process_decorator():
    state = PartialState()
    calls = []

    @state.on_main_process
    def fn(x):
        calls.append(x)
        return x

    assert fn(3) == 3
    assert calls == [3]


def test_accelerator_state_shares_topology():
    astate = AcceleratorState(mixed_precision="bf16")
    assert astate.num_devices == 8
    assert astate.mixed_precision == "bf16"
    assert astate.precision_policy.compute_dtype == jax.numpy.bfloat16


def test_gradient_state_defaults():
    gs = GradientState()
    assert gs.sync_gradients
    assert gs.num_steps == 1
    assert gs.remainder == -1
    assert not gs.end_of_dataloader


def test_data_sharding_spec():
    state = PartialState(parallelism=ParallelismConfig(fsdp=2))
    sharding = state.data_sharding()
    x = jax.device_put(np.zeros((16, 4), np.float32), sharding)
    # batch axis split over data(4) x fsdp(2) = 8 ways
    assert len(x.sharding.device_set) == 8


def test_distributed_init_kwargs_export_env(monkeypatch):
    """DistributedInitKwargs/InitProcessGroupKwargs reach the bootstrap env."""
    import datetime
    import os

    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import InitProcessGroupKwargs

    # setenv first so monkeypatch records the (absent) original and restores
    # it at teardown — the production write below is plain os.environ
    monkeypatch.setenv("ACCELERATE_INIT_TIMEOUT", "sentinel")
    monkeypatch.delenv("ACCELERATE_INIT_TIMEOUT")
    handler = InitProcessGroupKwargs(timeout=datetime.timedelta(seconds=123))
    Accelerator(kwargs_handlers=[handler])
    assert os.environ["ACCELERATE_INIT_TIMEOUT"] == "123"


def test_init_process_group_kwargs_reference_positional_order(monkeypatch):
    """Reference signature is (backend, init_method, timeout): a migrated
    positional call must not leak 'gloo' into the coordinator address."""
    import datetime
    import os

    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import InitProcessGroupKwargs

    monkeypatch.setenv("ACCELERATE_COORDINATOR_ADDRESS", "sentinel")
    monkeypatch.delenv("ACCELERATE_COORDINATOR_ADDRESS")
    monkeypatch.setenv("ACCELERATE_INIT_TIMEOUT", "60")
    handler = InitProcessGroupKwargs("gloo", None, datetime.timedelta(seconds=7))
    assert handler.backend == "gloo" and handler.timeout.total_seconds() == 7
    Accelerator(kwargs_handlers=[handler])
    assert "ACCELERATE_COORDINATOR_ADDRESS" not in os.environ
    assert os.environ["ACCELERATE_INIT_TIMEOUT"] == "7"


def test_distributed_init_kwargs_after_state_raises(monkeypatch):
    """Coordinator fields after ANY PartialState exists are dead (the
    bootstrap is once-only) — must raise, not silently run single-process."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import DistributedInitKwargs

    PartialState()  # bootstrap already ran (single-process)
    handler = DistributedInitKwargs(
        coordinator_address="host:1234", num_processes=2, process_id=0
    )
    with pytest.raises(ValueError, match="before any"):
        Accelerator(kwargs_handlers=[handler])


def test_timeout_only_kwargs_after_state_is_fine(monkeypatch):
    """A timeout-only handler stays legal after a PartialState: it only
    matters if a rendezvous happens later, and the env still reaches it."""
    import datetime
    import os

    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import InitProcessGroupKwargs

    monkeypatch.setenv("ACCELERATE_INIT_TIMEOUT", "sentinel")
    monkeypatch.delenv("ACCELERATE_INIT_TIMEOUT")
    PartialState()
    Accelerator(kwargs_handlers=[InitProcessGroupKwargs(timeout=datetime.timedelta(seconds=9))])
    assert os.environ["ACCELERATE_INIT_TIMEOUT"] == "9"


def test_distributed_init_kwargs_positional_misuse_raises():
    """Migrated positional call puts the address into `backend` — loud error."""
    from accelerate_tpu.utils import DistributedInitKwargs

    with pytest.raises(ValueError, match="coordinator address"):
        DistributedInitKwargs("host:1234", 4, 0)


def test_init_process_group_kwargs_default_timeout_keeps_env(monkeypatch):
    """A handler with no explicit timeout must not clobber an operator-set
    ACCELERATE_INIT_TIMEOUT."""
    import os

    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import InitProcessGroupKwargs

    monkeypatch.setenv("ACCELERATE_INIT_TIMEOUT", "60")
    Accelerator(kwargs_handlers=[InitProcessGroupKwargs()])
    assert os.environ["ACCELERATE_INIT_TIMEOUT"] == "60"
