"""Ring attention exactness + sequence-parallel llama training."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.models import Llama
from accelerate_tpu.models.attention import dot_product_attention
from accelerate_tpu.parallel.ring_attention import make_ring_attention
from accelerate_tpu.state import PartialState


def _qkv(b=2, s=32, n=4, kv=None, d=16, seed=0):
    rng = np.random.default_rng(seed)
    kv = kv or n
    q = jnp.asarray(rng.normal(size=(b, s, n, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, kv, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, kv, d)), jnp.float32)
    return q, k, v


def test_ring_attention_matches_causal_reference():
    state = PartialState(parallelism=ParallelismConfig(sequence=4))
    q, k, v = _qkv()
    expected = dot_product_attention(q, k, v, causal=True)
    ring = make_ring_attention(state.mesh, causal=True)
    got = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_ring_attention_non_causal():
    state = PartialState(parallelism=ParallelismConfig(sequence=4))
    q, k, v = _qkv(seed=1)
    expected = dot_product_attention(q, k, v, causal=False)
    ring = make_ring_attention(state.mesh, causal=False)
    got = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_ring_attention_gqa():
    state = PartialState(parallelism=ParallelismConfig(sequence=2, tensor=2))
    q, k, v = _qkv(n=4, kv=2, seed=2)
    expected = dot_product_attention(q, k, v, causal=True)
    ring = make_ring_attention(state.mesh, causal=True)
    got = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_ring_attention_padding_mask():
    """Padded batches must match the masked reference (review repro)."""
    state = PartialState(parallelism=ParallelismConfig(sequence=4))
    q, k, v = _qkv(s=32, seed=3)
    kv_mask = np.ones((2, 32), np.int32)
    kv_mask[0, :8] = 0  # left padding on row 0
    kv_mask = jnp.asarray(kv_mask)
    expected = dot_product_attention(q, k, v, mask=kv_mask[:, None, None, :].astype(bool), causal=True)
    ring = make_ring_attention(state.mesh, causal=True)
    got = jax.jit(ring)(q, k, v, kv_mask)
    real = np.asarray(kv_mask, bool)
    np.testing.assert_allclose(
        np.asarray(got)[real], np.asarray(expected)[real], atol=1e-5
    )


def test_ring_attention_indivisible_length_falls_back():
    state = PartialState(parallelism=ParallelismConfig(sequence=4))
    q, k, v = _qkv(s=30, seed=4)  # 30 % 4 != 0
    ring = make_ring_attention(state.mesh, causal=True)
    got = ring(q, k, v)
    expected = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_padded_llama_sequence_parallel_matches():
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(5).integers(0, 1024, (2, 64)), jnp.int32)
    am = np.ones((2, 64), np.int32)
    am[0, :16] = 0
    am = jnp.asarray(am)
    expected = model.apply(params, ids, attention_mask=am)
    model.attention_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(sequence=4))
    prepared = accelerator.prepare_model(model, params=params)
    got = prepared(ids, attention_mask=am)
    real = np.asarray(am, bool)
    np.testing.assert_allclose(
        np.asarray(got)[real], np.asarray(expected)[real], atol=2e-4
    )


def test_sequence_parallel_llama_matches_single_device():
    """Full llama forward with the sequence axis active == plain forward."""
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 1024, (2, 64)), jnp.int32)
    expected = model.apply(params, ids)

    accelerator = Accelerator(parallelism=ParallelismConfig(sequence=4))
    prepared = accelerator.prepare_model(model, params=params)
    assert model.attention_fn is not None  # ring attention was swapped in
    got = prepared(ids)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_sequence_parallel_gpt2_matches_single_device():
    """The attention_fn hook is zoo-wide: gpt2 under a sequence axis."""
    from accelerate_tpu.models import GPT2

    model = GPT2("gpt2-tiny")
    params = model.init(jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(6).integers(0, 1024, (2, 64)), jnp.int32)
    expected = model.apply(params, ids)
    model.attention_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(sequence=4))
    prepared = accelerator.prepare_model(model, params=params)
    assert model.attention_fn is not None
    got = prepared(ids)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_sequence_parallel_bert_matches_single_device():
    """Bert gets the NON-causal ring (causal_attention=False) — bidirectional
    attention must survive the sequence axis, padding included."""
    from accelerate_tpu.models import Bert

    model = Bert("bert-tiny")
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(7)
    ids = jnp.asarray(rng.integers(0, 1024, (2, 64)), jnp.int32)
    am = np.ones((2, 64), np.int32)
    am[1, 48:] = 0
    am = jnp.asarray(am)
    expected = model.apply(params, ids, attention_mask=am)
    model.attention_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(sequence=4))
    prepared = accelerator.prepare_model(model, params=params)
    assert model.attention_fn is not None
    got = prepared(ids, attention_mask=am)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_sequence_parallel_llama_trains():
    accelerator = Accelerator(parallelism=ParallelismConfig(sequence=2, fsdp=2, tensor=2))
    model = Llama("llama-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    loss_fn = Llama.loss_fn(model)
    batch = {"input_ids": jnp.asarray(np.random.default_rng(0).integers(0, 1024, (4, 64)), jnp.int32)}
    losses = []
    for _ in range(6):
        with accelerator.accumulate(prepared):
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()
