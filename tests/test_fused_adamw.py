"""Fused adamw kernel (ops/fused_adamw.py): tolerance-0 equality against
optax.adamw — as a bare transform, through the eager update path, and
through the ZeRO sharded step's update-equivalence harness (the existing
bit-exactness gate of tests/test_zero.py, now with the kernel engaged)."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator
from accelerate_tpu.models import Bert
from accelerate_tpu.ops.fused_adamw import fused_adamw
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils.random import set_seed


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _tree_equal(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def test_fused_matches_optax_bit_exact_over_10_steps():
    """Kernel vs optax.adamw on a mixed tree — tileable matrices, a stacked
    3-D leaf, and a 7-element vector that falls back to the reference
    formula — params AND optimizer state bit-equal after every step."""
    rng = np.random.default_rng(0)
    params = {
        "a": jnp.asarray(rng.normal(size=(16, 64)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(7,)).astype(np.float32)),
        "c": jnp.asarray(rng.normal(size=(4, 8, 32)).astype(np.float32)),
    }
    ref_tx, fused = optax.adamw(3e-3), fused_adamw(3e-3)
    state_r, state_f = ref_tx.init(params), fused.init(params)
    p_r = p_f = params

    @jax.jit
    def ref_step(p, s, g):
        u, s = ref_tx.update(g, s, p)
        return optax.apply_updates(p, u), s

    fused_step = jax.jit(fused.fused_apply)
    for _ in range(10):
        g = jax.tree.map(
            lambda x: jnp.asarray(rng.normal(size=x.shape).astype(np.float32)), params
        )
        p_r, state_r = ref_step(p_r, state_r, g)
        p_f, state_f = fused_step(p_f, state_f, g)
        assert _tree_equal(p_r, p_f)
    assert _tree_equal(state_r, state_f)


def test_fused_state_structure_matches_optax():
    """Same state pytree as optax.adamw (ScaleByAdamState + empties), so
    checkpointing, sharding layouts, and the coupling probe are unchanged."""
    params = {"w": jnp.ones((4, 4))}
    a = jax.tree_util.tree_structure(optax.adamw(1e-3).init(params))
    b = jax.tree_util.tree_structure(fused_adamw(1e-3).init(params))
    assert a == b


def test_fused_rejects_schedules():
    with pytest.raises(ValueError, match="scalar learning_rate"):
        fused_adamw(optax.linear_schedule(1e-3, 0.0, 100))


def _updated_state(tx_factory, n_steps=10):
    """The existing ZeRO update-equivalence harness (tests/test_zero.py):
    identical seeded gradients through the eager update path of a
    default-config accelerator — ZeRO-eligible on the 8-device test mesh,
    so the update runs on the folded 1/N storage layout."""
    _reset()
    set_seed(0)
    accelerator = Accelerator()
    model = Bert("bert-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(tx_factory())
    rng = np.random.default_rng(0)
    host_params = jax.tree.map(np.asarray, prepared.params)
    for _ in range(n_steps):
        grads = jax.tree.map(
            lambda p: jnp.asarray(rng.standard_normal(p.shape), jnp.float32),
            host_params,
        )
        optimizer.accumulate_grads(jax.device_put(grads, prepared.params_shardings))
        optimizer.step()
    return (
        jax.tree.map(np.asarray, prepared.params),
        jax.tree.map(np.asarray, optimizer.opt_state),
    )


def test_fused_passes_zero_update_equivalence_gate():
    """10 steps of identical gradients through the sharded update layout:
    the fused kernel and optax.adamw produce bit-identical params AND
    optimizer state at tolerance 0 — the kernel slots into PR 11's step
    without moving a bit."""
    p_f, o_f = _updated_state(lambda: fused_adamw(3e-4))
    p_r, o_r = _updated_state(lambda: optax.adamw(3e-4))
    assert _tree_equal(p_f, p_r)
    assert _tree_equal(o_f, o_r)


def test_fused_inside_compiled_zero_step():
    """The fused kernel runs INSIDE the manual-shard_map ZeRO step program
    (interpret-mode Pallas in the manual region) and tracks the optax step
    closely — same program structure up to the update, so losses match to
    roundoff over a few steps."""
    init = Bert("bert-tiny").init(jax.random.key(7))
    losses = {}
    for name, tx_factory in (("optax", lambda: optax.adamw(1e-3)),
                             ("fused", lambda: fused_adamw(1e-3))):
        _reset()
        accelerator = Accelerator()
        model = Bert("bert-tiny")
        accelerator.prepare_model(model, params=jax.tree.map(jnp.array, init))
        accelerator.prepare_optimizer(tx_factory())
        assert accelerator._zero_update_sharding
        step = accelerator.compiled_step(Bert.loss_fn(model))
        rng = np.random.default_rng(0)
        batch = {
            "input_ids": jnp.asarray(
                rng.integers(0, model.config.vocab_size, (8, 16)), jnp.int32
            ),
            "attention_mask": jnp.ones((8, 16), jnp.int32),
            "labels": jnp.asarray(rng.integers(0, 2, (8,)), jnp.int32),
        }
        losses[name] = [float(step(batch)) for _ in range(4)]
    np.testing.assert_allclose(losses["fused"], losses["optax"], rtol=1e-5)
    assert all(np.isfinite(losses["fused"]))
